"""Tensor-parallel LLaMA-style decoder-only transformer (RoPE + RMSNorm +
SwiGLU), TPU-native.

Capability parity with `/root/reference/models/model.py` (Transformer /
DecoderLayer / Attention / FFN), re-designed for XLA:

* **Per-shard forward** written for `jax.shard_map` over a ('dp', 'tp') mesh;
  the Megatron fused pattern is preserved exactly — wq/wk/wv are
  column-parallel with `gather_output=False`, wo is row-parallel with
  `split_input=False` (`model.py:57-60`), and likewise gate/up/down for the
  SwiGLU FFN (`model.py:85-87`), giving one all-reduce per sublayer forward
  and one per sublayer backward.

* **Stacked layer params + `lax.scan`** instead of a Python module list
  (`model.py:132-135`): one compiled layer body regardless of depth — faster
  compiles, identical math.

* **One shared RoPE table** instead of one per layer (`model.py:110` keeps 12
  identical copies — SURVEY quirk #10).

* **Full-vocab logits without an explicit gather**: the per-shard forward
  returns the local vocab shard of the logits and the shard_map out-spec
  P('dp', None, 'tp') stitches the global array — the "gather" is the output
  sharding itself. The reference instead all-gathers inside lm_head
  (`model.py:137`); that data path is still available via `loss_mode='gather'`
  (see `loss_shard`), and the comm op is `ops.collectives.gather_from`.

* The vanilla twin the reference's full-model test imports but never shipped
  (`VallinaTransformer`, SURVEY quirk #1) exists here: `models/vanilla.py`.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import IGNORE_INDEX, ModelConfig, resolve_dtype
from ..ops.attention import causal_attention
from ..ops.collectives import copy_to, gather_from, reduce_from
from ..ops.ring_attention import ring_attention, ulysses_attention
from ..ops.rope import apply_rotary, rope_tables
from ..parallel.embedding import VocabParallelEmbedding
from ..parallel.linear import (ColumnParallelLinear, RowParallelLinear,
                               apply_column_ring_fused)
from ..parallel.moe import MoEFFN, aux_losses, aux_zeros
from ..parallel.norm import RMSNorm
from ..runtime.prng import fold

Params = Dict[str, Any]

NEG_INF = -1e9  # mask value for padded vocab logits


def validate_pp(num_layers: int, pp_size: int, pp_microbatches: int,
                pp_schedule: str = "gpipe", pp_virtual: int = 2) -> None:
    """Pipeline construction checks shared by both model families."""
    if pp_size > 1 and num_layers % pp_size != 0:
        raise ValueError(
            f"num_layers {num_layers} not divisible by pp_size "
            f"{pp_size} (stages hold equal layer counts)")
    if pp_microbatches and pp_size == 1:
        raise ValueError(
            "pp_microbatches requires pp_size > 1 (a non-pipelined model "
            "runs no microbatch schedule; the setting would be silently "
            "ignored)")
    if pp_microbatches and pp_microbatches < pp_size:
        raise ValueError(
            f"pp_microbatches {pp_microbatches} < pp_size "
            f"{pp_size} would leave permanent pipeline bubbles")
    if pp_schedule not in ("gpipe", "interleaved"):
        raise ValueError(f"pp_schedule must be 'gpipe' or 'interleaved', "
                         f"got {pp_schedule!r}")
    if pp_schedule == "interleaved":
        if pp_size == 1:
            raise ValueError("pp_schedule='interleaved' requires pp_size > 1")
        if pp_virtual < 2:
            raise ValueError(
                f"pp_virtual {pp_virtual} < 2: one virtual stage per device "
                f"IS the gpipe schedule; use pp_schedule='gpipe'")
        if num_layers % (pp_size * pp_virtual) != 0:
            raise ValueError(
                f"num_layers {num_layers} not divisible by "
                f"pp_size*pp_virtual {pp_size * pp_virtual} (each device "
                f"holds pp_virtual equal round-robin layer blocks)")
        M = pp_microbatches or pp_size
        if M % pp_size != 0:
            raise ValueError(
                f"interleaved schedule needs pp_microbatches {M} divisible "
                f"by pp_size {pp_size} (microbatches circulate the ring in "
                f"groups of pp_size)")


def validate_cp(cfg: ModelConfig, tp: int, cp_size: int, cp_impl: str,
                cp_layout: str) -> None:
    """Context-parallel construction checks shared by both model families
    (llama + gpt2): cp_impl/cp_layout membership, Ulysses head
    divisibility (q AND kv local heads), zigzag-requires-ring."""
    if cp_impl not in ("ring", "ulysses"):
        raise ValueError(f"cp_impl must be 'ring' or 'ulysses', got "
                         f"{cp_impl!r}")
    if (cp_size > 1 and cp_impl == "ulysses"
            and ((cfg.num_heads // tp) % cp_size != 0
                 or (cfg.kv_heads // tp) % cp_size != 0)):
        raise ValueError(
            f"ulysses needs local q heads {cfg.num_heads // tp} and kv "
            f"heads {cfg.kv_heads // tp} divisible by cp_size {cp_size}; "
            f"use cp_impl='ring'")
    if cp_layout not in ("contiguous", "zigzag"):
        raise ValueError(f"cp_layout must be 'contiguous' or 'zigzag', "
                         f"got {cp_layout!r}")
    if cp_layout == "zigzag" and cp_impl != "ring":
        raise ValueError("cp_layout='zigzag' requires cp_impl='ring' "
                         "(Ulysses assumes rank-order contiguous chunks)")


def validate_t_real(attn_t_real, cp_size: int, num_experts: int = 0) -> None:
    """Sequence-bucketing construction checks shared by both families."""
    if attn_t_real is None:
        return
    if attn_t_real < 1:
        raise ValueError(f"attn_t_real must be >= 1, got {attn_t_real}")
    if cp_size > 1:
        raise ValueError(
            "attn_t_real (pad-aware sequence bucketing) requires cp_size "
            "== 1: the ring/ulysses paths shard the sequence over 'cp' and "
            "mask by carried global positions, so a static real-length cut "
            "would land mid-chunk")
    if num_experts:
        raise ValueError(
            "attn_t_real (pad-aware sequence bucketing) does not compose "
            "with MoE: the router sees every position, so pad tokens would "
            "claim expert-capacity slots ahead of later rows' real tokens "
            "and inflate the load-balance/z aux statistics — bucketed MoE "
            "training would silently diverge from unbucketed")


def validate_tp_overlap(tp_overlap: str, sequence_parallel: bool,
                        num_experts: int = 0) -> None:
    """tp_overlap construction checks shared by both model families."""
    if tp_overlap not in ("off", "ring", "ring_q"):
        raise ValueError(f"tp_overlap must be 'off', 'ring' or 'ring_q', "
                         f"got {tp_overlap!r}")
    if tp_overlap in ("ring", "ring_q") and not sequence_parallel:
        raise ValueError(
            f"tp_overlap={tp_overlap!r} requires sequence_parallel: the "
            "ring decomposes the SP all-gather/reduce-scatter pair; the "
            "non-SP path's monolithic all-reduce has no chunk schedule to "
            "overlap (or quantize per hop)")
    if tp_overlap in ("ring", "ring_q") and num_experts:
        raise ValueError(
            f"tp_overlap={tp_overlap!r} does not compose with MoE yet: "
            "the router consumes the full-token gather that the ring "
            "collective matmul deliberately never materialises")


def remat_wrap(layer_fn, remat, static_argnums=()):
    """Apply a per-layer remat policy; shared by every model family.

    'dots' = checkpoint_dots saves matmul outputs; additionally pin the
    flash kernel's o/lse residuals (tagged via checkpoint_name in
    ops/pallas/flash_attention.py) so the backward pass never re-runs the
    forward attention kernel. On the XLA attention path the tags don't
    exist and the policy degrades gracefully.
    """
    if remat == "dots":
        policy = jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.checkpoint_dots,
            jax.checkpoint_policies.save_only_these_names(
                "flash_out", "flash_lse"))
        return jax.checkpoint(layer_fn, static_argnums=static_argnums,
                              policy=policy)
    if remat:
        return jax.checkpoint(layer_fn, static_argnums=static_argnums)
    return layer_fn


@dataclass(frozen=True)
class Transformer:
    """Static model definition; params live in an explicit pytree."""

    cfg: ModelConfig
    tp_size: int = 1
    attn_impl: str = "auto"  # flash kernel on TPU, XLA path on CPU
    # Expert parallelism (with cfg.num_experts > 0): experts are sharded
    # over the mesh axis 'ep', which doubles as an extra data axis for the
    # dense sublayers (the batch shards over dp x ep). parallel/moe.py.
    ep_size: int = 1
    # Pipeline parallelism over the mesh axis 'pp': the stacked layer dim is
    # sharded (each stage owns num_layers/pp layers) and microbatches flow
    # through a GPipe schedule built from ONE lax.scan over pipeline steps
    # with a ppermute between stages. JAX autodiff transposes the schedule
    # into the backward pipeline (reverse ppermute, reverse time) for free.
    # No reference counterpart (SURVEY §2.4 "PP ❌"). Bubble fraction is
    # (pp-1)/(microbatches+pp-1); raise pp_microbatches to amortise it.
    pp_size: int = 1
    pp_microbatches: int = 0  # 0 -> pp_size (the minimum that fills the pipe)
    # Pipeline schedule (VERDICT r3 #7):
    #   'gpipe'       — contiguous layer blocks, bubble (pp-1)/(M+pp-1).
    #   'interleaved' — Megatron-style virtual stages: each device owns
    #     pp_virtual NON-contiguous layer blocks assigned round-robin
    #     (device p runs virtual stages p, pp+p, 2pp+p, ...), and every
    #     microbatch circulates pp_virtual times around the same ring.
    #     Bubble shrinks to (pp-1)/(pp_virtual*M + pp-1) — the fill/drain
    #     cost amortises over pp_virtual x more ring steps — at the price
    #     of pp_virtual x more ppermute hops of the (mb, t, d) carry (the
    #     standard interleaved trade-off: less bubble, more wire).
    pp_schedule: str = "gpipe"
    pp_virtual: int = 2  # virtual stages per device ('interleaved' only)
    # Rematerialise each pipeline STEP: backward-pipeline residuals shrink
    # to the (mb, t, d) step carries (layer internals recompute), cutting
    # the M-proportional activation footprint — the practical core of a
    # 1F1B schedule's memory advantage, expressed scan-side (the schedule
    # itself stays GPipe; autodiff derives the reverse pipeline).
    pp_remat_steps: bool = False
    # Context parallelism: shard the sequence dim over the mesh axis 'cp'
    # (absent from the reference — SURVEY §5.7 documents it has no
    # long-context story at all). cp_impl: 'ring' rotates KV chunks around
    # the cp ring with online-softmax combination; 'ulysses' all-to-alls
    # heads<->sequence and runs the dense kernel on the full sequence.
    cp_size: int = 1
    cp_impl: str = "ring"
    # cp_layout='zigzag' feeds each cp shard an equally early+late pair of
    # sequence sub-chunks (ops/ring_attention.zigzag_perm), balancing the
    # causal ring's per-step work ~2x vs contiguous chunks. Pure input
    # permutation: ring attention masks by the carried global positions, so
    # both layouts are exact. Ring-only — Ulysses gathers the sequence in
    # rank order and runs a position-oblivious triangular mask, which a
    # permuted layout would silently break.
    cp_layout: str = "contiguous"
    # Megatron-style sequence parallelism over 'tp' (absent from the
    # reference: its norms are replicated and inter-block activations are
    # full-size on every rank — SURVEY §2.4 "SP ❌"). When on, activations
    # between sublayers are sequence-sharded over tp: the per-sublayer
    # all-reduce splits into a reduce-scatter (row-linear output) and an
    # all-gather (next column-linear input) — same bytes on the wire, but
    # norms/residuals compute on t/tp tokens and inter-block activation
    # memory drops by 1/tp. Composes with cp (t is sharded over cp first,
    # then tp).
    sequence_parallel: bool = False
    # Communication overlap for the tp collectives (requires
    # sequence_parallel): 'ring' swaps the monolithic per-sublayer
    # all-gather/reduce-scatter for ring-decomposed collective matmuls
    # (ops/overlap.py) — each ppermute hop hides under the partial dot of
    # the chunk already in hand, fwd and bwd. 'off' (default) stays
    # bit-identical to today's path. Composes with dp/cp/pp; under a pp
    # mesh the ring's ppermutes must execute on EVERY pipeline step
    # (collective-permute lowers with a global participant list), so the
    # dense segments run ungated and bubble steps burn their FLOPs —
    # garbage flows only into garbage (see _pipeline_layers) — trading
    # bubble compute for hidden wire. Not yet composed with MoE (the
    # router needs the full-token gather the ring never materialises).
    tp_overlap: str = "off"
    # Rematerialise each decoder layer in the backward pass instead of saving
    # its activations (the naive O(T^2) attention otherwise stores
    # (L, b, heads, t, t) softmax residuals — 11.7 GiB for the reference's
    # 45M config at b=32, t=1000, which OOMs a 16G v5e chip). Trading these
    # HBM residuals for recompute FLOPs is the standard TPU playbook
    # (SURVEY §0 / scaling-book); the reference has no analogue (PyTorch
    # keeps all residuals and simply needs a bigger GPU).
    #   True   — full per-layer remat (lowest memory, ~33% recompute FLOPs)
    #   "dots" — jax.checkpoint_policies.checkpoint_dots: matmul outputs are
    #            saved, only elementwise ops recompute (best speed that still
    #            bounds residuals; needs flash attention or short t, since
    #            the XLA attention path's softmax residual is O(t^2))
    #   False  — no remat (reference behaviour; OOMs the 45M b32xt1000 run
    #            on a 16G chip)
    remat: "bool | str" = True
    # Pad-aware sequence bucketing: when the caller pads its (b, t) batch up
    # to a bucket boundary (e.g. t=1000 real tokens in a t=1024 buffer so
    # every matmul tiles cleanly on the 8x128 vector lanes AND the flash
    # kernel's internal padding vanishes), set attn_t_real to the REAL
    # token count. Attention then does only ~t_real work (the kernels skip
    # fully-dead tiles and emit exact zeros/zero-grads for pad rows), and
    # the CE loss masks the pad targets via IGNORE_INDEX as usual. None =
    # every position is real (the default, and the only mode under cp > 1 —
    # the ring/ulysses paths shard the sequence and carry their own
    # position masking).
    attn_t_real: "int | None" = None
    # ZeRO-3 (training/zero.py): when set to a mesh axis name (normally
    # 'dp'), the layer body ring-all-gathers each layer's dp-sharded param
    # leaves on entry — INSIDE the remat boundary, so the gathered weights
    # are recomputed (never saved as backward residuals) and peak param
    # HBM stays full/dp + one layer. Only `build_zero3_grad_fn` sets this
    # (via dataclasses.replace on its private model copy); every other
    # path keeps params at model.specs() layouts and must leave it None.
    zero3_axis: "str | None" = None

    def __post_init__(self):
        cfg, tp = self.cfg, self.tp_size
        if self.remat not in (True, False, "dots"):
            raise ValueError(
                f"remat must be True, False or 'dots', got {self.remat!r}")
        if cfg.num_heads % tp != 0:
            raise ValueError(f"num_heads {cfg.num_heads} not divisible by tp_size {tp}")
        if cfg.attn_dim % tp != 0 or cfg.ffn_dim % tp != 0:
            raise ValueError(
                f"attn_dim {cfg.attn_dim} and ffn_dim {cfg.ffn_dim} must be "
                f"divisible by tp_size {tp}")
        if cfg.num_heads % cfg.kv_heads != 0:
            raise ValueError(f"num_heads {cfg.num_heads} must be a multiple "
                             f"of num_kv_heads {cfg.kv_heads}")
        if cfg.kv_heads % tp != 0:
            raise ValueError(f"num_kv_heads {cfg.kv_heads} not divisible by "
                             f"tp_size {tp}")
        validate_cp(cfg, tp, self.cp_size, self.cp_impl, self.cp_layout)
        validate_tp_overlap(self.tp_overlap, self.sequence_parallel,
                            cfg.num_experts)
        if not cfg.num_experts and self.ep_size > 1:
            raise ValueError("ep_size > 1 requires cfg.num_experts > 0 "
                             "(a dense model has nothing to shard over 'ep'; "
                             "use dp for a pure data axis)")
        validate_pp(cfg.num_layers, self.pp_size, self.pp_microbatches,
                    self.pp_schedule, self.pp_virtual)
        validate_t_real(self.attn_t_real, self.cp_size, cfg.num_experts)

    # ---- sub-module definitions (static, cheap to rebuild) ----

    # family hooks the generic KV decoder consults (models/decode.py);
    # the gpt2 family overrides all three
    uses_rope = True          # RoPE on q/k (vs learned position embeddings)
    attn_norm_key = "norm1"   # pre-attention norm's module-dict key
    ffn_norm_key = "norm2"    # pre-FFN norm's key

    @property
    def d(self) -> int:
        return self.cfg.attn_dim

    @property
    def vocab_padded(self) -> int:
        return self.cfg.padded_vocab_size(self.tp_size)

    @property
    def num_local_heads(self) -> int:
        assert self.cfg.num_heads % self.tp_size == 0, (
            f"num_heads {self.cfg.num_heads} not divisible by tp {self.tp_size}")
        return self.cfg.num_heads // self.tp_size

    @property
    def num_local_kv_heads(self) -> int:
        return self.cfg.kv_heads // self.tp_size

    @functools.cached_property
    def embedding(self) -> VocabParallelEmbedding:
        return VocabParallelEmbedding(self.cfg.vocab_size, self.d, tp_size=self.tp_size)

    @property
    def is_moe(self) -> bool:
        return self.cfg.num_experts > 0

    @functools.cached_property
    def _mods(self) -> Dict[str, Any]:
        d, f = self.d, self.cfg.ffn_dim
        kd = self.cfg.kv_dim  # < d under grouped-query attention
        ov = self.tp_overlap
        mods = {
            # wq/wk/wv (and gate/up) stay overlap='off': under ring overlap
            # the fused multi-weight ring in _layer_body covers them (one
            # ring shared per sublayer = the shared-gather byte parity)
            "wq": ColumnParallelLinear(d, d, gather_output=False),
            "wk": ColumnParallelLinear(d, kd, gather_output=False),
            "wv": ColumnParallelLinear(d, kd, gather_output=False),
            "wo": RowParallelLinear(d, d, split_input=False, overlap=ov),
            "norm1": RMSNorm(d),
            "norm2": RMSNorm(d),
        }
        if self.is_moe:
            mods["moe"] = MoEFFN(
                d, f, self.cfg.num_experts, top_k=self.cfg.moe_top_k,
                capacity_factor=self.cfg.moe_capacity_factor,
                ep_size=self.ep_size, tp_size=self.tp_size)
        else:
            mods.update({
                "gate_proj": ColumnParallelLinear(d, f, gather_output=False),
                "up_proj": ColumnParallelLinear(d, f, gather_output=False),
                "down_proj": RowParallelLinear(f, d, split_input=False,
                                               overlap=ov),
            })
        return mods

    @functools.cached_property
    def final_norm(self) -> RMSNorm:
        return RMSNorm(self.d)

    @functools.cached_property
    def lm_head(self) -> ColumnParallelLinear:
        # gather_output handled at the shard_map boundary; see module docstring.
        return ColumnParallelLinear(self.d, self.vocab_padded,
                                    gather_output=False,
                                    overlap=self.tp_overlap)

    # ---- init ----

    def init(self, key: jax.Array) -> Params:
        """Full (global) parameter pytree, float32.

        Layer params are stacked along a leading num_layers axis for scan.
        """
        L = self.cfg.num_layers
        layer_keys = jax.random.split(fold(key, "layers"), L)

        def one_layer(k: jax.Array) -> Params:
            return {name: mod.init(fold(k, name)) for name, mod in self._mods.items()}

        layers = jax.vmap(one_layer)(layer_keys)
        if self._interleaved:
            layers = self._layers_to_schedule(layers)
        lm_head = self.lm_head.init(fold(key, "lm_head"))
        if self.vocab_padded != self.cfg.vocab_size:
            # zero the padded output columns so checkpoints stay
            # permutation-stable; padded logits are masked to NEG_INF anyway.
            w = lm_head["weight"]
            mask = (jnp.arange(self.vocab_padded) < self.cfg.vocab_size)[None, :]
            lm_head["weight"] = jnp.where(mask, w, 0.0)
            if "bias" in lm_head:
                lm_head["bias"] = jnp.where(mask[0], lm_head["bias"], 0.0)
        return {
            "embedding": self.embedding.init(fold(key, "embedding")),
            "layers": layers,
            "norm": self.final_norm.init(fold(key, "norm")),
            "lm_head": lm_head,
        }

    @property
    def _interleaved(self) -> bool:
        return self.pp_size > 1 and self.pp_schedule == "interleaved"

    def _layers_to_schedule(self, layers: Params) -> Params:
        """Canonical stacked layers (L, ...) -> the interleaved layout
        (V, pp, Lv, ...). Row-major flatten of (v, p, l) is
        (v*pp + p)*Lv + l — exactly the execution order of virtual stage
        v*pp + p — so the two layouts are plain reshapes of each other and
        checkpoints stay schedule-independent (`to_canonical`)."""
        V, pp = self.pp_virtual, self.pp_size
        Lv = self.cfg.num_layers // (V * pp)
        return jax.tree.map(
            lambda a: a.reshape(V, pp, Lv, *a.shape[1:]), layers)

    def _layers_to_canonical(self, layers: Params) -> Params:
        L = self.cfg.num_layers
        return jax.tree.map(lambda a: a.reshape(L, *a.shape[3:]), layers)

    def to_canonical(self, params: Params) -> Params:
        """Params with layers in the canonical (num_layers, ...) stack —
        identity unless this model is interleaved-pipelined. Checkpoints
        are always saved canonical so any mesh/schedule can reload them."""
        if not self._interleaved:
            return params
        out = dict(params)
        out["layers"] = self._layers_to_canonical(params["layers"])
        return out

    def from_canonical(self, params: Params) -> Params:
        """Inverse of `to_canonical` (e.g. a checkpoint or an oracle's
        params entering an interleaved model)."""
        if not self._interleaved:
            return params
        out = dict(params)
        out["layers"] = self._layers_to_schedule(params["layers"])
        return out

    def canonical_specs(self) -> Params:
        """PartitionSpec tree for the canonical layout — what checkpoints
        are saved/loaded with (the gpipe specs of this same model)."""
        if not self._interleaved:
            return self.specs()
        import dataclasses
        return dataclasses.replace(self, pp_schedule="gpipe").specs()

    def specs(self) -> Params:
        """PartitionSpec pytree matching `init`'s structure."""
        lead = "pp" if self.pp_size > 1 else None

        def stack(spec_dict: Params) -> Params:
            # stacked num_layers axis: sharded over 'pp' when pipelining
            # (each stage owns its num_layers/pp slice — contiguous for
            # gpipe; the (V, pp, Lv) dim-1 slice = V round-robin virtual
            # blocks for the interleaved schedule), else unsharded
            if self._interleaved:
                return jax.tree.map(lambda s: P(None, "pp", None, *s),
                                    spec_dict,
                                    is_leaf=lambda x: isinstance(x, P))
            return jax.tree.map(lambda s: P(lead, *s), spec_dict,
                                is_leaf=lambda x: isinstance(x, P))
        return {
            "embedding": self.embedding.specs(),
            "layers": {name: stack(mod.specs()) for name, mod in self._mods.items()},
            "norm": self.final_norm.specs(),
            "lm_head": self.lm_head.specs(),
        }

    def shardings(self, mesh: Mesh) -> Params:
        return jax.tree.map(lambda s: NamedSharding(mesh, s), self.specs(),
                            is_leaf=lambda x: isinstance(x, P))

    # ---- per-shard forward (call inside shard_map) ----

    def _layer_body(self, x: jax.Array, layer_params: Params,
                    cos: jax.Array, sin: jax.Array, pos: jax.Array,
                    dtype, live=None) -> jax.Array:
        """One decoder layer. `live` (optional scalar bool) is the
        pipeline-bubble gate used ONLY on pp meshes with ring CP: the dense
        segments (projections / attention epilogue / FFN) wrap in
        `lax.cond(live, ...)` — their collectives (tp psums/gathers, ep
        all_to_alls) lower with per-group participant lists, and every
        member of those groups shares the pp stage, so the branch is
        uniform — while the ring's ppermutes run UNCONDITIONALLY (XLA
        collective-permute lists every device as a participant; a measured
        deadlock otherwise) with the per-block MXU work gated inside the
        ring (ops/ring_attention.py). Bubble steps therefore cost only the
        ring's wire traffic, not layer FLOPs (VERDICT r3 #3)."""
        if self.zero3_axis:
            # ZeRO-3: this layer's dp-sharded leaves gather here, inside
            # the remat boundary, so the gathered weights are transient in
            # the forward and REPLAYED (not saved) for the backward; the
            # gather's transpose reduce-scatters the weight grads back to
            # this rank's shard. training/zero.py owns the layout rule.
            from ..training.zero import zero3_layer_gather
            layer_params = zero3_layer_gather(self, layer_params,
                                              self.zero3_axis)
        m = self._mods
        h = self.cfg.head_dim
        # In sequence-parallel mode x is (b, t/tp, d) between sublayers; the
        # column-linears all-gather it back to the full local sequence t and
        # the row-linears reduce-scatter their outputs.
        sp = self.sequence_parallel
        # tp_overlap='ring': the per-sublayer gather never materialises —
        # the fused ring collective matmul (one ring SHARED by wq/wk/wv,
        # resp. gate/up — same bytes as the shared gather) consumes the
        # seq-sharded activation directly, and its custom VJP sums the
        # fan-out cotangents on one reverse ring (the same one-psum_scatter
        # -per-sublayer traffic as the shared gather's transpose).
        ring_ov = sp and self.tp_overlap in ("ring", "ring_q")
        ring_quant = self.tp_overlap == "ring_q"
        # Otherwise gather the normed activation ONCE per sublayer and share
        # it between the projections (wq/wk/wv, gate/up): the fan-out
        # cotangents sum at the single gather, whose transpose is one
        # psum_scatter per sublayer (canonical Megatron SP traffic), not one
        # per projection.
        maybe_gather = ((lambda z: gather_from(z, "tp", tiled_axis=-2))
                        if sp and not ring_ov else (lambda z: z))
        in_layout = "gathered" if sp else "replicated"
        out_layout = "seq_sharded" if sp else "replicated"
        b = x.shape[0]
        t = cos.shape[1]  # full (cp-local) sequence length, not x.shape[1]

        # Attention sublayer: x + attn(norm1(x))   (model.py:119)
        def qkv(x):
            y = maybe_gather(m["norm1"].apply(layer_params["norm1"], x))
            if ring_ov:
                q, k, v = apply_column_ring_fused(
                    (layer_params["wq"], layer_params["wk"],
                     layer_params["wv"]), y, dtype,
                    quantized=ring_quant)
            else:
                q = m["wq"].apply(layer_params["wq"], y, dtype,
                                  input_layout=in_layout)
                k = m["wk"].apply(layer_params["wk"], y, dtype,
                                  input_layout=in_layout)
                v = m["wv"].apply(layer_params["wv"], y, dtype,
                                  input_layout=in_layout)
            # (b, t, heads*h) -> (b, heads, t, h); under grouped-query
            # attention wk/wv produce fewer heads and k/v STAY at the
            # kv-head count — every attention impl handles the grouping
            # itself (the flash kernel and ring path route query-head
            # blocks onto kv rows with no HBM repeat; the XLA fallback
            # expands at its own boundary, ops/attention.py).
            split = lambda z, nh: z.reshape(b, t, nh, h).transpose(0, 2, 1, 3)
            q = split(q, self.num_local_heads)
            k = split(k, self.num_local_kv_heads)
            v = split(v, self.num_local_kv_heads)
            return apply_rotary(q, k, cos, sin) + (v,)

        def attn_out(args):
            x, o = args
            o = o.transpose(0, 2, 1, 3).reshape(b, t,
                                                self.num_local_heads * h)
            x = x + m["wo"].apply(layer_params["wo"], o, dtype,
                                  output_layout=out_layout)

            # FFN sublayer: x + down(silu(gate(x)) * up(x))
            # (model.py:94-95,120) — or, with cfg.num_experts > 0,
            # x + MoE(norm2(x)) (parallel/moe.py)
            y = maybe_gather(m["norm2"].apply(layer_params["norm2"], x))
            if self.is_moe:
                ff, aux = m["moe"].apply(layer_params["moe"], y, dtype)
                if sp:
                    # The router saw the tp-gathered full tokens (identical
                    # on every tp rank, so routing agrees) and the expert
                    # internals already all-reduced over tp — ff is the
                    # full-value FFN output on every rank. Keep only this
                    # rank's sequence slice so the residual stays
                    # seq-sharded; the slice's transpose zero-pads,
                    # composing with the gather's psum_scatter.
                    tl = ff.shape[1] // self.tp_size
                    ff = lax.dynamic_slice_in_dim(
                        ff, lax.axis_index("tp") * tl, tl, axis=1)
                return x + ff, aux
            if ring_ov:
                g, u = apply_column_ring_fused(
                    (layer_params["gate_proj"], layer_params["up_proj"]),
                    y, dtype, quantized=ring_quant)
            else:
                g = m["gate_proj"].apply(layer_params["gate_proj"], y, dtype,
                                         input_layout=in_layout)
                u = m["up_proj"].apply(layer_params["up_proj"], y, dtype,
                                       input_layout=in_layout)
            x = x + m["down_proj"].apply(layer_params["down_proj"],
                                         jax.nn.silu(g) * u, dtype,
                                         output_layout=out_layout)
            return x, None

        # Under ring overlap the dense segments run even on pipeline-bubble
        # steps (live is ignored except by ring attention): their tp
        # ppermutes lower with a GLOBAL participant list, so hiding them in
        # a stage-divergent lax.cond would deadlock — the same constraint
        # the cp ring documents below. Bubble steps burn the layer FLOPs;
        # their outputs are structurally discarded (garbage flows only into
        # garbage — see _pipeline_layers).
        if live is None or ring_ov:
            q, k, v = qkv(x)
            if self.cp_size > 1:
                if self.cp_impl == "ring":
                    o = ring_attention(q, k, v, pos, axis="cp",
                                       impl=self.attn_impl, live=live)
                else:
                    o = ulysses_attention(q, k, v, axis="cp",
                                          impl=self.attn_impl)
            else:
                o = causal_attention(q, k, v, impl=self.attn_impl,
                                     t_real=self._t_real(t))
            return attn_out((x, o))
        return self._live_gated_ring(x, qkv, attn_out, pos, live)

    def _t_real(self, t: int) -> "int | None":
        """attn_t_real clamped to the runtime sequence length (a shorter
        batch than the bucket simply has no pad rows to skip)."""
        if self.attn_t_real is None or self.attn_t_real >= t:
            return None
        return self.attn_t_real

    @property
    def _pp_vary_axes(self) -> Tuple[str, ...]:
        """Axes the pipeline's step carry varies over: the stage-dependent
        'pp', the batch axes, and 'tp' when sequence parallelism shards t."""
        return (("pp", "dp", "ep", "cp")
                + (("tp",) if self.sequence_parallel else ()))

    def _live_gated_ring(self, x, qkv, attn_out, pos, live):
        """Live-gated layer execution for pp x ring-CP meshes — shared by
        both model families (see `_layer_body`'s docstring for why the ring
        runs unconditionally while the dense segments take `lax.cond`).

        `qkv(x) -> (q, k, v)` is the pre-attention segment and
        `attn_out((x, o)) -> (x', aux)` the epilogue; both run only on live
        steps. Bubble steps permute zeros around the ring (wire traffic
        only — every block's MXU work is skipped inside `ring_attention`
        by the same `live` scalar) and pass the carry through unchanged.

        vma discipline: `lax.cond` branches must produce identical avals
        INCLUDING varying-manual-axes tags, so both branches lift their
        outputs to a common tag set with `copy_to` (idempotent pvary —
        only ever ADDS tags, a semantically weaker claim that is always
        sound). q/k/v carry 'tp' on top of the pipeline vary axes (the
        projection weights are tp-sharded); the epilogue's outputs carry
        exactly the pipeline carry's axes.
        """
        qkv_tag = ("pp", "dp", "ep", "cp", "tp")
        out_tag = self._pp_vary_axes
        b, t = pos.shape
        h = self.cfg.head_dim

        def qkv_live(x):
            return tuple(copy_to(z, qkv_tag) for z in qkv(x))

        def qkv_zero(x):
            dtype = resolve_dtype(self.cfg.compute_dtype)
            shapes = [(b, self.num_local_heads, t, h),
                      (b, self.num_local_kv_heads, t, h),
                      (b, self.num_local_kv_heads, t, h)]
            return tuple(copy_to(jnp.zeros(s, dtype), qkv_tag)
                         for s in shapes)

        q, k, v = lax.cond(live, qkv_live, qkv_zero, x)
        o = ring_attention(q, k, v, pos, axis="cp", impl=self.attn_impl,
                           live=live)

        def post_live(args):
            x2, aux = attn_out(args)
            if self.is_moe:
                aux = jax.tree.map(lambda a: copy_to(a, out_tag), aux)
            return copy_to(x2, out_tag), aux

        def post_skip(args):
            x2, _ = args
            aux = (jax.tree.map(lambda a: copy_to(a, out_tag),
                                aux_zeros(self.cfg.num_experts))
                   if self.is_moe else None)
            return copy_to(x2, out_tag), aux

        return lax.cond(live, post_live, post_skip, (x, o))

    def forward_shard(self, params: Params, input_ids: jax.Array,
                      position_ids: jax.Array) -> jax.Array:
        """(b_local, t) ids -> (b_local, t, vocab_padded / tp) LOCAL logits.

        Runs per-shard inside shard_map. The caller chooses whether to stitch
        (out_spec P('dp', None, 'tp')) or explicitly `gather_from` the result.
        """
        logits, _ = self._forward_with_aux(params, input_ids, position_ids)
        return logits

    def _forward_with_aux(self, params: Params, input_ids: jax.Array,
                          position_ids: jax.Array,
                          head_layout: str = "replicated"):
        """forward_shard + the MoE aux-stat sums (None for dense models),
        summed over layers but still LOCAL to this shard — loss_shard psums
        them over the batch axes before forming the aux losses.

        `head_layout` (pipeline only): 'pp_scatter' hands each pp stage a
        disjoint 1/pp batch chunk for norm/lm_head (see _pipeline_layers);
        the returned logits then have b/pp rows."""
        dtype = resolve_dtype(self.cfg.compute_dtype)
        sp = self.sequence_parallel
        if sp and input_ids.shape[1] % self.tp_size != 0:
            raise ValueError(
                f"sequence_parallel needs the (cp-local) sequence length "
                f"{input_ids.shape[1]} divisible by tp_size {self.tp_size}")
        x = self.embedding.apply(params["embedding"], input_ids,
                                 output_layout="seq_sharded" if sp else "replicated")
        x = x.astype(dtype)  # explicit cast, mirrors model.py:153-154

        cos_t, sin_t = rope_tables(self.cfg.maxlen, self.cfg.head_dim,
                                   self.cfg.rope_theta)
        # mode="clip": out-of-range positions clamp to the last table row
        # instead of jnp.take's default NaN fill (the reference would index
        # out of bounds, model.py:117-118).
        cos = jnp.take(cos_t, position_ids, axis=0, mode="clip")  # (b, t, head_dim)
        sin = jnp.take(sin_t, position_ids, axis=0, mode="clip")

        layer_fn = remat_wrap(self._layer_body, self.remat, static_argnums=(5,))

        if self.pp_size > 1:
            def stage_fn(z, layers, cos_m, sin_m, pos_m, live=None):
                def body(carry, lp):
                    return layer_fn(carry, lp, cos_m, sin_m, pos_m, dtype,
                                    live)
                z, auxs = lax.scan(body, z, layers)
                aux = (jax.tree.map(lambda a: jnp.sum(a, axis=0), auxs)
                       if self.is_moe else None)
                return z, aux

            x, aux = self._pipeline_layers(stage_fn, x, params["layers"],
                                           (cos, sin, position_ids),
                                           head_layout=head_layout)
        else:
            def body(carry, layer_params):
                return layer_fn(carry, layer_params, cos, sin, position_ids,
                                dtype)

            x, auxs = lax.scan(body, x, params["layers"])
            # auxs: None for dense; for MoE a dict of (L,...) stacked sums
            aux = (jax.tree.map(lambda a: jnp.sum(a, axis=0), auxs)
                   if self.is_moe else None)
        x = self.final_norm.apply(params["norm"], x)
        logits = self.lm_head.apply(
            params["lm_head"], x, dtype,
            input_layout="seq_sharded" if sp else "replicated")

        # Mask padded vocab entries so they carry no probability mass.
        if self.vocab_padded != self.cfg.vocab_size:
            local_v = self.vocab_padded // self.tp_size
            start = lax.axis_index("tp") * local_v
            col = start + jnp.arange(local_v)
            logits = jnp.where(col[None, None, :] < self.cfg.vocab_size,
                               logits, jnp.asarray(NEG_INF, logits.dtype))
        return logits, aux

    def _pipeline_layers(self, stage_fn, x: jax.Array, layers: Params,
                         mb_arrays: Tuple[jax.Array, ...],
                         head_layout: str = "replicated"):
        """GPipe microbatch pipeline over the 'pp' mesh axis — family-
        agnostic: `stage_fn(z, layers, *mb) -> (z', aux_or_None)` runs this
        stage's layer stack on one microbatch, and `mb_arrays` are the
        per-microbatch auxiliary inputs (leading dim = local batch b) each
        family needs (llama: cos/sin/position_ids; gpt2: position_ids).

        `layers` arrive ALREADY sliced by shard_map to this stage's block:
        gpipe — the contiguous (num_layers/pp, ...) slice (specs() shards
        the stacked layer dim over 'pp'); interleaved — the (V, 1, Lv, ...)
        slice of the (V, pp, Lv, ...) layout, i.e. this device's V
        round-robin virtual blocks. The gpipe schedule is one lax.scan over
        M + pp - 1 pipeline steps; at step s, stage p runs microbatch s - p
        through its local layers and ppermutes the activation to stage
        p + 1. The interleaved schedule scans V*M + pp - 1 steps over the
        SAME ring: with r = s - p, stage p runs virtual block
        (r // pp) % V on microbatch (r // (V*pp))*pp + r % pp — each
        microbatch circulates V times, stage 0 consuming the ring wrap for
        blocks > 0 and fresh injections for block 0. Autodiff transposes
        either schedule into the reverse-time backward pipeline.

        Bubble steps take a `lax.cond` identity branch — no layer FLOPs are
        burned on discarded microbatches (VERDICT r2 weak #2a). The
        predicate depends only on (step, stage), so every member of a
        tp/ep/dp/cp group agrees on the branch and the collectives inside
        the live branch stay uniform.

        MoE router aux sums ride the scan carry, gated to live steps, so
        expert models pipeline too (VERDICT r2 #4); each stage returns the
        aux sums for ITS layers x all microbatches (psum over 'pp' in
        loss_shard totals them).

        Returns (x_final, aux):
          head_layout='replicated' — x_final is the final-layer activation
            for the FULL local batch, replicated over 'pp' (psum broadcast)
            so norm/lm_head code is pipeline-oblivious; callers must mask
            per-stage duplicates (make_forward's contract).
          head_layout='pp_scatter' (requires b % pp == 0) — x_final is this
            stage's 1/pp batch chunk (psum_scatter): norm + lm_head + CE
            then run pp-way parallel on disjoint chunks instead of
            pp-way replicated (VERDICT r2 weak #2c — no duplicated lm_head
            FLOPs, and the broadcast's (b,t,d) wire bytes drop by 1/pp).
        """
        pp = self.pp_size
        M = self.pp_microbatches or pp
        b, t, d = x.shape
        if b % M != 0:
            raise ValueError(f"local batch {b} not divisible by "
                             f"pp_microbatches {M}")
        mb = b // M
        stage = lax.axis_index("pp")
        last = pp - 1

        # (M, mb, ...) microbatch views; the mb_arrays are replicated over
        # pp so every stage can index its current microbatch locally.
        xs = x.reshape(M, mb, t, d)
        mb_views = [a.reshape(M, mb, *a.shape[1:]) for a in mb_arrays]

        vary_axes = self._pp_vary_axes

        def pvary(z):
            # copy_to is the tag-aware (idempotent) varying cast: router aux
            # leaves mix constants — invariant — with token-derived values,
            # and cond branches must agree exactly
            return copy_to(z, vary_axes)

        def local_layers(z, lyrs, *mb_in, **kw):
            z, aux = stage_fn(z, lyrs, *mb_in, **kw)
            if self.is_moe:
                aux = jax.tree.map(pvary, aux)
            return z, aux

        aux0 = (jax.tree.map(pvary, aux_zeros(self.cfg.num_experts))
                if self.is_moe else None)
        # Bubble-step execution mode: a whole-stage lax.cond is only sound
        # when the layer body contains no ppermute (see pipe_step below).
        # Two features put ppermutes in the body: the cp ring, and the
        # tp_overlap ring collective matmuls — either forces the
        # run-unconditionally mode, where the layer body itself decides what
        # to gate (the cp ring gates per-block MXU work on `live`; the tp
        # rings run in full, burning bubble FLOPs whose outputs are
        # structurally discarded).
        ring_cp = (self.cp_size > 1 and self.cp_impl == "ring") or (
            self.sequence_parallel
            and self.tp_overlap in ("ring", "ring_q"))

        if self.pp_schedule == "interleaved":
            return self._pipeline_interleaved(
                xs, mb_views, layers, local_layers, aux0, pvary, ring_cp,
                head_layout)

        def pipe_step(carry, s):
            z_prev, aux_acc = carry
            # which microbatch this stage works on; bubble steps (before the
            # pipe fills / after this stage drains) skip compute entirely
            m = jnp.clip(s - stage, 0, M - 1)
            live = (s >= stage) & (s - stage <= M - 1)
            inject = lax.dynamic_index_in_dim(xs, jnp.clip(s, 0, M - 1), 0,
                                              keepdims=False)
            z = jnp.where(stage == 0, inject, z_prev)
            take = lambda a: lax.dynamic_index_in_dim(a, m, 0,
                                                      keepdims=False)

            def run(z):
                return local_layers(z, layers, *[take(v) for v in mb_views])

            def skip(z):
                return z, aux0

            # Bubble skip: a whole-stage `lax.cond` is only sound when the
            # layer body contains no ppermute — XLA lowers
            # collective-permute with a GLOBAL participant list (every
            # device must execute it; measured: the cp ring inside a
            # stage-divergent cond deadlocks the CPU rendezvous), while
            # psum/all_gather/psum_scatter/all_to_all lower with proper
            # per-group participant lists (tp/ep/sp members share a pp
            # stage, so they agree on the branch). The ring-CP path
            # therefore gates at FINER granularity instead: `live` flows
            # into every layer body, the ring's ppermutes execute
            # unconditionally on every step (zeros on bubbles), and the
            # dense segments + per-block MXU work skip inside the layer
            # (_live_gated_ring / ring_attention's live gate) — bubble
            # steps cost wire traffic only, the same M-layer-passes FLOPs
            # accounting as the cond path (VERDICT r3 #3).
            if ring_cp:
                y, aux_step = local_layers(
                    z, layers, *[take(v) for v in mb_views], live=live)
            else:
                y, aux_step = lax.cond(live, run, skip, z)
            if self.is_moe:
                aux_acc = jax.tree.map(lambda acc, a: acc + a, aux_acc,
                                       aux_step)
            out = jnp.where(stage == last, y, jnp.zeros_like(y))
            # stage p -> p + 1; the wrap to stage 0 is overwritten by inject
            y_send = lax.ppermute(y, "pp",
                                  [(i, (i + 1) % pp) for i in range(pp)])
            return (y_send, aux_acc), out

        if self.pp_remat_steps:
            # Per-step remat: residuals for the backward pipeline are the
            # (mb, t, d) step carries only; each step's layer internals
            # recompute. Cuts the M-proportional layer-activation footprint
            # (the practical core of a 1F1B schedule's memory win) at ~33%
            # extra FLOPs.
            pipe_step = jax.checkpoint(pipe_step)

        # vma: the carried activation varies over 'pp' (stage-dependent) and
        # over the batch axes (x is batch-sharded) — and over 'tp' when
        # sequence parallelism shards t.
        carry0 = pvary(jnp.zeros((mb, t, d), x.dtype))
        (_, aux), outs = lax.scan(pipe_step, (carry0, aux0),
                                  jnp.arange(M + pp - 1, dtype=jnp.int32))
        # outs[last + m] is microbatch m off the last stage (zeros on every
        # other stage).
        x_final = outs[last:].reshape(b, t, d)
        if head_layout == "pp_scatter":
            x_final = lax.psum_scatter(x_final, "pp", scatter_dimension=0,
                                       tiled=True)        # (b/pp, t, d)
        else:
            x_final = lax.psum(x_final, "pp")
        return x_final, aux

    def _pipeline_interleaved(self, xs, mb_views, layers, local_layers,
                              aux0, pvary, ring_cp, head_layout):
        """Interleaved (virtual-stage) schedule body — see _pipeline_layers'
        docstring for the step/stage/block algebra. Completed microbatches
        accumulate into an (M, mb, t, d) carry buffer on the last stage
        (with V circulations their completion steps are no longer one
        contiguous outs slice)."""
        pp, V = self.pp_size, self.pp_virtual
        M, mb, t, d = xs.shape
        stage = lax.axis_index("pp")
        last = pp - 1
        # (V, 1, Lv, ...) shard_map slice -> (V, Lv, ...)
        layers = jax.tree.map(lambda a: a.reshape(a.shape[0], *a.shape[2:]),
                              layers)

        def pipe_step(carry, s):
            z_prev, aux_acc, out_buf = carry
            r = s - stage
            live = (r >= 0) & (r <= V * M - 1)
            j = (r // pp) % V                      # this device's block
            m = jnp.clip((r // (V * pp)) * pp + (r % pp), 0, M - 1)
            # stage 0 injects fresh microbatches into virtual block 0 and
            # consumes the ring wrap (stage pp-1's output entering block
            # j) otherwise; the wrap arriving during block-0 steps carries
            # FINAL outputs, already banked into out_buf below.
            inject = lax.dynamic_index_in_dim(xs, m, 0, keepdims=False)
            z = jnp.where((stage == 0) & (j == 0), inject, z_prev)
            lyrs = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, jnp.clip(j, 0, V - 1),
                                                   0, keepdims=False),
                layers)
            take = lambda a: lax.dynamic_index_in_dim(a, m, 0,
                                                      keepdims=False)

            def run(z):
                return local_layers(z, lyrs, *[take(v) for v in mb_views])

            def skip(z):
                return z, aux0

            if ring_cp:  # same finer-grained gating as the gpipe path
                y, aux_step = local_layers(
                    z, lyrs, *[take(v) for v in mb_views], live=live)
            else:
                y, aux_step = lax.cond(live, run, skip, z)
            if self.is_moe:
                aux_acc = jax.tree.map(lambda acc, a: acc + a, aux_acc,
                                       aux_step)
            done = live & (stage == last) & (j == V - 1)
            upd = lax.dynamic_update_slice(out_buf, y[None],
                                           (m, 0, 0, 0))
            out_buf = jnp.where(done, upd, out_buf)
            y_send = lax.ppermute(y, "pp",
                                  [(i, (i + 1) % pp) for i in range(pp)])
            return (y_send, aux_acc, out_buf), None

        if self.pp_remat_steps:
            pipe_step = jax.checkpoint(pipe_step)

        carry0 = (pvary(jnp.zeros((mb, t, d), xs.dtype)), aux0,
                  pvary(jnp.zeros((M, mb, t, d), xs.dtype)))
        (_, aux, out_buf), _ = lax.scan(
            pipe_step, carry0,
            jnp.arange(V * M + pp - 1, dtype=jnp.int32))
        x_final = out_buf.reshape(M * mb, t, d)
        if head_layout == "pp_scatter":
            x_final = lax.psum_scatter(x_final, "pp", scatter_dimension=0,
                                       tiled=True)
        else:
            x_final = lax.psum(x_final, "pp")
        return x_final, aux

    # ---- losses (per-shard, inside shard_map) ----

    def _token_ce(self, logits: jax.Array, target_ids: jax.Array,
                  mode: str) -> Tuple[jax.Array, jax.Array]:
        """Per-token CE from the LOCAL vocab-shard logits: (token_loss f32,
        valid mask), both (..., t). Shared by the training loss and the
        per-document eval loss."""
        logits = logits.astype(jnp.float32)
        valid = target_ids != IGNORE_INDEX
        tgt = jnp.where(valid, target_ids, 0)

        if mode == "gather":
            # Reference data path: materialise full logits (lm_head
            # gather_output=True, model.py:137), CE on every shard, then
            # average the tp-identical copies so the result is tp-invariant.
            full = gather_from(logits, "tp")
            lse = jax.nn.logsumexp(full, axis=-1)
            tgt_logit = jnp.take_along_axis(full, tgt[..., None], axis=-1)[..., 0]
            # average the tp-identical copies: makes the value tp-invariant
            token_loss = reduce_from(lse - tgt_logit, "tp") / self.tp_size
        elif mode == "vocab_parallel":
            # Megatron-style vocab-parallel CE: never materialise the full
            # (b, t, vocab) tensor — two scalar-field psums instead of an
            # all-gather. Wins when vocab is large (BASELINE config 4).
            local_v = logits.shape[-1]
            start = lax.axis_index("tp") * local_v
            # softmax is shift-invariant, so the max subtraction carries no
            # gradient (and pmax has no differentiation rule anyway).
            local_max = jnp.max(lax.stop_gradient(logits), axis=-1)
            gmax = lax.stop_gradient(lax.pmax(local_max, "tp"))
            sumexp = reduce_from(
                jnp.sum(jnp.exp(logits - gmax[..., None]), axis=-1), "tp")
            lse = jnp.log(sumexp) + gmax
            local_tgt = tgt - start
            owned = (local_tgt >= 0) & (local_tgt < local_v)
            safe_tgt = jnp.where(owned, local_tgt, 0)
            tgt_logit = jnp.take_along_axis(logits, safe_tgt[..., None], axis=-1)[..., 0]
            tgt_logit = reduce_from(jnp.where(owned, tgt_logit, 0.0), "tp")
            token_loss = lse - tgt_logit
        else:
            raise ValueError(f"unknown loss mode {mode!r}")
        return token_loss, valid

    def loss_shard(self, params: Params, input_ids: jax.Array,
                   target_ids: jax.Array, position_ids: jax.Array,
                   mode: str = "vocab_parallel",
                   batch_axes: Tuple[str, ...] = ("dp", "ep", "cp")) -> jax.Array:
        """Mean cross-entropy over non-ignored tokens, global over the mesh.

        f32 loss with ignore-index masking, matching the reference's
        `F.cross_entropy(logits.float(), ..., ignore_index=-1, 'mean')`
        (`/root/reference/train.py:101-104`).
        """
        # Pipeline head layout: with a pp-divisible batch each stage computes
        # norm/lm_head/CE on a DISJOINT 1/pp chunk (no duplicated head FLOPs
        # — VERDICT r2 weak #2c); otherwise every stage sees the broadcast
        # full batch and the sums are masked to the last stage below.
        pp_scatter = (self.pp_size > 1
                      and input_ids.shape[0] % self.pp_size == 0)
        logits, aux = self._forward_with_aux(
            params, input_ids, position_ids,
            head_layout="pp_scatter" if pp_scatter else "replicated")
        if pp_scatter:
            chunk = input_ids.shape[0] // self.pp_size
            target_ids = lax.dynamic_slice_in_dim(
                target_ids, lax.axis_index("pp") * chunk, chunk, axis=0)
        token_loss, valid = self._token_ce(logits, target_ids, mode)
        loss_sum = jnp.sum(jnp.where(valid, token_loss, 0.0))
        count = jnp.sum(valid.astype(jnp.float32))
        if self.pp_size > 1:
            if not pp_scatter:
                # Fallback (batch not pp-divisible): every stage computed
                # the same CE from the psum-broadcast x_final, so count it
                # ONCE: mask to the last stage. This also zeroes the CE
                # cotangent on the other stages — without it, shard_map's
                # transpose would psum pp_size identical lm_head/embedding
                # cotangents (they are replicated over 'pp') and scale
                # their gradients by pp_size. (The scatter path needs no
                # mask: the chunks are disjoint, so the psum over 'pp' IS
                # the batch total and per-stage cotangents are per-chunk.)
                is_last = (lax.axis_index("pp") == self.pp_size - 1)
                is_last = is_last.astype(jnp.float32)
                loss_sum = loss_sum * is_last
                count = count * is_last
            batch_axes = tuple(batch_axes) + ("pp",)
        loss_sum = lax.psum(loss_sum, batch_axes)
        count = lax.psum(count, batch_axes)
        loss = loss_sum / jnp.maximum(count, 1.0)
        if self.is_moe:
            # Globally-summed router stats -> sharding-invariant aux losses
            # (load balance + z), added with their Switch/ST-MoE weights.
            if self.sequence_parallel:
                # Under SP the router ran on the tp-GATHERED tokens: every
                # tp rank holds identical aux sums, but they carry the
                # gather's tp-varying tag. pmean is a value-identity that
                # clears the tag (and its transpose splits the cotangent
                # 1/tp per rank, whose contributions re-sum downstream).
                aux = jax.tree.map(lambda a: lax.pmean(a, "tp"), aux)
            aux_g = jax.tree.map(lambda a: lax.psum(a, batch_axes), aux)
            lb, z = aux_losses(aux_g, self.cfg.num_experts,
                               self.cfg.moe_top_k)
            loss = (loss + self.cfg.moe_aux_coef * lb
                    + self.cfg.moe_z_coef * z)
        return loss

    # ---- global (jitted) entry points ----

    @property
    def _zigzag(self) -> bool:
        return self.cp_layout == "zigzag" and self.cp_size > 1

    def make_forward(self, mesh: Mesh):
        """Jitted global forward: (params, input_ids, position_ids) -> full
        logits (b, t, vocab_padded), vocab dim sharded over 'tp'.

        With cp_layout='zigzag', inputs are permuted into the zig-zag order
        before the shard_map and the logits inverse-permuted after, so the
        caller sees natural token order either way."""
        from ..ops.ring_attention import zigzag_perm

        fwd = jax.shard_map(
            self.forward_shard, mesh=mesh,
            in_specs=(self.specs(), P(("dp", "ep"), "cp"),
                      P(("dp", "ep"), "cp")),
            out_specs=P(("dp", "ep"), "cp", "tp"),
        )
        if not self._zigzag:
            return jax.jit(fwd)

        def zz(params, input_ids, position_ids):
            perm = zigzag_perm(input_ids.shape[1], self.cp_size)
            inv = perm.argsort()
            logits = fwd(params, input_ids[:, perm], position_ids[:, perm])
            return logits[:, inv]

        return jax.jit(zz)

    def make_loss(self, mesh: Mesh, mode: str = "vocab_parallel"):
        from ..ops.ring_attention import zigzag_perm

        loss = functools.partial(self.loss_shard, mode=mode)
        fn = jax.shard_map(
            loss, mesh=mesh,
            in_specs=(self.specs(), P(("dp", "ep"), "cp"),
                      P(("dp", "ep"), "cp"), P(("dp", "ep"), "cp")),
            out_specs=P(),
        )
        if not self._zigzag:
            return jax.jit(fn)

        def zz(params, input_ids, target_ids, position_ids):
            # masked token-mean CE is permutation-invariant: permute all
            # three together, no unpermute needed
            perm = zigzag_perm(input_ids.shape[1], self.cp_size)
            return fn(params, input_ids[:, perm], target_ids[:, perm],
                      position_ids[:, perm])

        return jax.jit(zz)

    def doc_loss_shard(self, params: Params, input_ids: jax.Array,
                       target_ids: jax.Array, position_ids: jax.Array,
                       mode: str = "vocab_parallel"):
        """Per-DOCUMENT mean CE: ((b_local,) means f32, (b_local,) real-row
        mask). Uses the same vocab-parallel CE as training — no (b, t, V)
        logits gather. Padding rows (all IGNORE_INDEX) report mask False.

        Eval-only (forward under no grad); pp meshes are not supported here
        (evaluation runs dp x cp x tp, like the reference's)."""
        if self.pp_size > 1:
            raise ValueError("doc_loss runs on a pp=1 eval mesh")
        logits, _ = self._forward_with_aux(params, input_ids, position_ids)
        token_loss, valid = self._token_ce(logits, target_ids, mode)
        # per-row sums over this shard's sequence chunk, then totals over cp
        row_sum = lax.psum(jnp.sum(jnp.where(valid, token_loss, 0.0), axis=-1),
                           "cp")
        row_cnt = lax.psum(jnp.sum(valid.astype(jnp.float32), axis=-1), "cp")
        return row_sum / jnp.maximum(row_cnt, 1.0), row_cnt > 0

    def make_doc_loss(self, mesh: Mesh, mode: str = "vocab_parallel"):
        """Jitted per-document eval loss (see doc_loss_shard); the row dim
        stays sharded over ('dp', 'ep') like the batch."""
        from ..ops.ring_attention import zigzag_perm

        fn = jax.shard_map(
            functools.partial(self.doc_loss_shard, mode=mode), mesh=mesh,
            in_specs=(self.specs(), P(("dp", "ep"), "cp"),
                      P(("dp", "ep"), "cp"), P(("dp", "ep"), "cp")),
            out_specs=(P(("dp", "ep")), P(("dp", "ep"))),
        )
        if not self._zigzag:
            return jax.jit(fn)

        def zz(params, input_ids, target_ids, position_ids):
            # per-document masked means are token-permutation-invariant
            perm = zigzag_perm(input_ids.shape[1], self.cp_size)
            return fn(params, input_ids[:, perm], target_ids[:, perm],
                      position_ids[:, perm])

        return jax.jit(zz)
