"""KV-cache autoregressive decoding — one XLA program per generation.

The reference has NO KV cache: its greedy decode re-runs the full growing
sequence through the model for every generated token
(`/root/reference/test.py:141-161`; SURVEY §7 lists "KV cache" as a
reference non-goal). This module is the TPU-native upgrade, two levels deep:

1. **KV cache**: a prefill pass over the padded prompt buffer produces
   per-layer K/V tensors; each generated token then costs a single-token
   forward against the cache — O(t) per token instead of O(t^2).
2. **On-device generation loop**: prefill + a `lax.while_loop` of
   single-token steps + greedy argmax + per-row EOS early-exit all compile
   into ONE dispatch (`make_generate`). A host-driven token loop pays a full
   host->device round-trip per token (~80 ms over the axon tunnel — measured
   to dwarf the 45M model's ~1.7 ms of per-token compute); the fused loop
   runs at device speed and returns once per prompt.

Layout: caches are (num_layers, b, local_KV_heads, buf_len, head_dim),
sharded over 'tp' on the heads dim — the same head partitioning as training,
so the same checkpoint params work unchanged; under grouped-query attention
the caches are num_heads/num_kv_heads x smaller than the query-head count
(the GQA decode memory win). With a cp-sharded model (ring + contiguous
layout) the PREFILL also shards the prompt over 'cp' and runs ring
attention — long-context generation — while the per-token loop stays
replicated on the gathered caches (`_prefill_cp`).

The decoder is generic over the model FAMILY via three hooks each family
class declares (`uses_rope`, `attn_norm_key`, `ffn_norm_key`) plus duck
typing on the module dict: the gpt2 family (learned position embeddings
added at the input, LayerNorm, gelu MLP, TIED lm_head) decodes through the
same prefill + fused-loop machinery as llama (VERDICT r2 #6). Families with
learned positions expose `max_decode_positions`; the buffer must fit it.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..config import resolve_dtype
from ..ops.attention import MASK_VALUE, causal_attention
from ..ops.collectives import gather_from, ring_permute
from ..ops.quant import quantize_rows
from ..ops.ring_attention import _BIG_NEG, _block_attn_xla, ring_attention
from ..ops.rope import apply_rotary, rope_tables
from .transformer import NEG_INF, Transformer

Params = Dict[str, Any]


def _qkv(model: Transformer, lp: Params, y: jax.Array, dtype):
    """Project y (b, t, d) -> q (b, local_heads, t, hd) and k, v at
    (b, local_KV_heads, t, hd).

    Under grouped-query attention k/v stay at the (smaller) kv-head count —
    the caches then hold kv_heads entries, which is the GQA decode memory
    win (num_heads/num_kv_heads x smaller KV cache). Query head i reads kv
    head i // group, matching training's `jnp.repeat(k, group, axis=1)`
    layout (models/transformer.py)."""
    m = model._mods
    b, t, _ = y.shape
    h = model.cfg.head_dim
    split = lambda z, nh: z.reshape(b, t, nh, h).transpose(0, 2, 1, 3)
    q = split(m["wq"].apply(lp["wq"], y, dtype), model.num_local_heads)
    k = split(m["wk"].apply(lp["wk"], y, dtype), model.num_local_kv_heads)
    v = split(m["wv"].apply(lp["wv"], y, dtype), model.num_local_kv_heads)
    return q, k, v


def _embed(model, params: Params, ids: jax.Array, pos: jax.Array, dtype):
    """Token embedding (+ the learned position embedding for families
    without RoPE — gpt2's positions enter HERE, mirroring
    `GPT2Transformer.forward_shard`)."""
    x = model.embedding.apply(params["embedding"], ids)
    if not model.uses_rope:
        x = x + jnp.take(params["pos_embedding"]["weight"], pos, axis=0,
                         mode="clip")
    return x.astype(dtype)


def _finish_block(model: Transformer, lp: Params, x: jax.Array,
                  o: jax.Array, dtype) -> jax.Array:
    """Residual + wo, then the FFN sublayer (shared by prefill and decode)."""
    m = model._mods
    b, t = x.shape[0], x.shape[1]
    o = o.transpose(0, 2, 1, 3).reshape(b, t, model.num_local_heads * model.cfg.head_dim)
    x = x + m["wo"].apply(lp["wo"], o, dtype)
    nk = model.ffn_norm_key
    y = m[nk].apply(lp[nk], x)
    if model.is_moe:
        ff, _ = m["moe"].apply(lp["moe"], y, dtype)  # aux unused at decode
        # Decode replicates the batch over 'ep' (in_specs P(None, None))
        # while expert weights stay ep-sharded, so every ep shard computes
        # the same ff values under an ep-varying vma tag. pmean averages
        # the identical copies: value-identity, clears the tag so the scan
        # carry and the P(None, None) out_specs stay ep-invariant.
        return x + lax.pmean(ff, "ep")
    if "fc" in m:  # gpt2 family: gelu MLP
        h = jax.nn.gelu(m["fc"].apply(lp["fc"], y, dtype), approximate=True)
        return x + m["proj"].apply(lp["proj"], h, dtype)
    g = m["gate_proj"].apply(lp["gate_proj"], y, dtype)
    u = m["up_proj"].apply(lp["up_proj"], y, dtype)
    return x + m["down_proj"].apply(lp["down_proj"], jax.nn.silu(g) * u, dtype)


def _logits_tokens(model: Transformer, params: Params, x: jax.Array,
                   dtype) -> jax.Array:
    """Final norm + head on (b, t, d); returns the LOCAL vocab shard
    (b, t, vocab_padded/tp) with padded columns masked (mirrors
    forward_shard). Families without an lm_head module tie the head to the
    vocab-parallel token embedding (gpt2) — same local-logits layout either
    way. t = 1 is the single-position decode step; the speculative verify
    step asks for all k+1 positions at once."""
    x = model.final_norm.apply(params["norm"], x)
    if hasattr(model, "lm_head"):
        logits = model.lm_head.apply(params["lm_head"], x, dtype)
    else:
        w = params["embedding"]["weight"].astype(dtype)   # (vp/tp, d)
        logits = x.astype(dtype) @ w.T
    if model.vocab_padded != model.cfg.vocab_size:
        local_v = logits.shape[-1]
        start = lax.axis_index("tp") * local_v
        col = start + jnp.arange(local_v)
        logits = jnp.where(col[None, None, :] < model.cfg.vocab_size, logits,
                           jnp.asarray(NEG_INF, logits.dtype))
    return logits


def _logits_last(model: Transformer, params: Params, x_last: jax.Array,
                 dtype) -> jax.Array:
    """`_logits_tokens` at t = 1: (b, 1, d) -> (b, vocab_padded/tp)."""
    return _logits_tokens(model, params, x_last, dtype)[:, 0, :]


def _prefill(model: Transformer, params: Params, buf: jax.Array,
             prompt_len: jax.Array, cos_t, sin_t, dtype):
    """Causal full-buffer forward: returns (ks, vs) stacked per layer and the
    PER-ROW logits at position prompt_len[i]-1 (prompt_len: (b,)). Same
    `causal_attention` kernel as training (flash on TPU). K/V of positions
    >= prompt_len hold padding — they are re-written by decode steps before
    any query can attend to them."""
    b, t = buf.shape
    pos = jnp.tile(jnp.arange(t, dtype=jnp.int32)[None, :], (b, 1))
    x = _embed(model, params, buf, pos, dtype)
    if model.uses_rope:
        cos = jnp.take(cos_t, pos, axis=0, mode="clip")
        sin = jnp.take(sin_t, pos, axis=0, mode="clip")

    def body(x, lp):
        nk = model.attn_norm_key
        y = model._mods[nk].apply(lp[nk], x)
        q, k, v = _qkv(model, lp, y, dtype)
        if model.uses_rope:
            q, k = apply_rotary(q, k, cos, sin)
        # grouped k/v pass straight through: every causal_attention impl
        # routes query-head groups onto the kv heads itself (ops/attention.py)
        o = causal_attention(q, k, v, impl=model.attn_impl)
        x = _finish_block(model, lp, x, o, dtype)
        return x, (k, v)  # caches stay at kv_heads (see _qkv)

    x, (ks, vs) = lax.scan(body, x, params["layers"])
    last = jnp.take_along_axis(
        x, (prompt_len - 1)[:, None, None].astype(jnp.int32), axis=1)
    return ks.astype(dtype), vs.astype(dtype), _logits_last(model, params, last, dtype)


def _prefill_cp(model: Transformer, params: Params, buf: jax.Array,
                prompt_len: jax.Array, cos_t, sin_t, dtype):
    """Context-parallel prefill: the buffer's sequence dim shards over the
    'cp' mesh axis (contiguous chunks) and every layer's attention runs the
    ring (`ops/ring_attention.ring_attention`) — the same long-context path
    training uses, so a prompt far longer than one chip's O(t^2) budget
    prefills across the cp group. The per-layer K/V chunks are then
    `lax.all_gather`ed back to full length: the decode LOOP stays
    replicated over cp (each single-token step is cheap and identical on
    every shard), which keeps cache-write indexing trivial while the
    quadratic prefill work and its activations split cp-ways.

    `buf` here is the REPLICATED (b, buf_len) buffer; each shard slices its
    contiguous chunk by `axis_index('cp')`. Returns full-length (ks, vs)
    and the per-row logits at prompt_len-1, exactly like `_prefill` — the
    outputs are cp-INVARIANT (the chunk psum below clears the tag), so
    the caller's decode loop runs unchanged."""
    b, t = buf.shape
    cp = lax.axis_size("cp")
    tl = t // cp
    i = lax.axis_index("cp")
    local = lax.dynamic_slice_in_dim(buf, i * tl, tl, axis=1)
    pos = i * tl + jnp.tile(jnp.arange(tl, dtype=jnp.int32)[None, :], (b, 1))
    x = _embed(model, params, local, pos, dtype)
    if model.uses_rope:
        cos = jnp.take(cos_t, pos, axis=0, mode="clip")
        sin = jnp.take(sin_t, pos, axis=0, mode="clip")

    def body(x, lp):
        nk = model.attn_norm_key
        y = model._mods[nk].apply(lp[nk], x)
        q, k, v = _qkv(model, lp, y, dtype)
        if model.uses_rope:
            q, k = apply_rotary(q, k, cos, sin)
        o = ring_attention(q, k, v, q_pos=pos, axis="cp",
                           impl=model.attn_impl).astype(x.dtype)
        x = _finish_block(model, lp, x, o, dtype)
        return x, (k, v)

    x, (ks, vs) = lax.scan(body, x, params["layers"])

    # Chunks -> full length in ONE collective that also clears the
    # cp-varying tag: each shard scatters its chunk into a zeros
    # full-length buffer and the psum of the disjoint chunks IS the
    # concatenation (psum output is cp-invariant, so the decode loop
    # below runs identically on every shard with no extra casts).
    def to_full(z, seq_axis):
        shape = z.shape[:seq_axis] + (t,) + z.shape[seq_axis + 1:]
        full = lax.dynamic_update_slice_in_dim(
            jnp.zeros(shape, z.dtype), z, i * tl, axis=seq_axis)
        return lax.psum(full, "cp")

    ks = to_full(ks, 3)                      # (L, b, kvh, t, hd)
    vs = to_full(vs, 3)
    # The logits need ONE position per row (prompt_len-1): the shard whose
    # chunk holds it contributes the (b, 1, d) slice and the psum selects
    # it — no full-length (b, t, d) gather on the long-context path.
    idx = (prompt_len - 1).astype(jnp.int32)             # (b,) global
    in_chunk = (idx >= i * tl) & (idx < (i + 1) * tl)    # (b,)
    sel = jnp.take_along_axis(
        x, jnp.clip(idx - i * tl, 0, tl - 1)[:, None, None], axis=1)
    last = lax.psum(jnp.where(in_chunk[:, None, None], sel, 0), "cp")
    return ks.astype(dtype), vs.astype(dtype), _logits_last(
        model, params, last, dtype)


def _decode_one(model: Transformer, params: Params, cache_k, cache_v,
                token: jax.Array, cur: jax.Array, buf_len: int,
                cos_t, sin_t, dtype):
    """One single-token step: writes each row's token K/V into the caches at
    that row's position, attends over cache[0..cur_row], returns
    (k', v', logits).

    `cur` may be a scalar (the fused whole-generation loop's shared cursor)
    or a (b,) vector (the serving engine's per-slot cursors — every live
    slot sits at its own position). Per-row math is identical either way:
    the scalar case is just the broadcast vector, so both drivers share
    this one lowering."""
    b = token.shape[0]
    shared_cur = jnp.ndim(cur) == 0   # static: the fused loop's scalar case
    cur_scalar = cur
    cur = jnp.broadcast_to(jnp.asarray(cur, jnp.int32), (b,))
    p1 = cur[:, None]
    x = _embed(model, params, token[:, None], p1, dtype)
    if model.uses_rope:
        cos = jnp.take(cos_t, p1, axis=0, mode="clip")
        sin = jnp.take(sin_t, p1, axis=0, mode="clip")
    visible = (jnp.arange(buf_len)[None, :] <= cur[:, None])[:, None, None, :]
    rows = jnp.arange(b)

    def write_cache(cache, z):
        # per-row scatter (row i writes position cur[i]); a SHARED scalar
        # cursor keeps the old dynamic-update-slice lowering — cheaper on
        # TPU than trusting XLA to pattern-match the all-equal scatter —
        # with identical written values either way
        if shared_cur:
            return lax.dynamic_update_slice_in_dim(
                cache, z.astype(cache.dtype), cur_scalar, axis=2)
        return cache.at[rows, :, cur, :].set(z[:, :, 0, :].astype(cache.dtype))

    def body(x, layer_in):
        lp, k_cache, v_cache = layer_in
        nk = model.attn_norm_key
        y = model._mods[nk].apply(lp[nk], x)
        q, k, v = _qkv(model, lp, y, dtype)   # q: (b, h, 1, hd); kv: kvh
        if model.uses_rope:
            q, k = apply_rotary(q, k, cos, sin)
        k_cache = write_cache(k_cache, k)
        v_cache = write_cache(v_cache, v)
        # grouped attention against the kv-head caches: query head
        # kv_idx*g + g_idx reads kv head kv_idx (g == 1 reduces to plain
        # MHA — the reshapes are identities)
        kvh = model.num_local_kv_heads
        g = model.num_local_heads // kvh
        hd = model.cfg.head_dim
        qg = q[:, :, 0, :].reshape(b, kvh, g, hd)
        s = jnp.einsum("bkgd,bktd->bkgt", qg, k_cache,
                       preferred_element_type=jnp.float32)
        s = s / jnp.sqrt(jnp.asarray(hd, jnp.float32))
        s = jnp.where(visible, s, MASK_VALUE)
        p = jax.nn.softmax(s, axis=-1).astype(dtype)
        o = jnp.einsum("bkgt,bktd->bkgd", p, v_cache)
        o = o.reshape(b, kvh * g, hd)[:, :, None, :]   # (b, h, 1, hd)
        x = _finish_block(model, lp, x, o, dtype)
        return x, (k_cache, v_cache)

    x, (k_new, v_new) = lax.scan(body, x, (params["layers"], cache_k, cache_v))
    return k_new, v_new, _logits_last(model, params, x, dtype)


def _paged_cache_write(cache, zi, dst_page, dst_off):
    """Scatter head-vectors into a page-pool layer slice. `zi` is shaped
    like the advanced-index result of `cache[dst_page, :, dst_off]` —
    (b, kvh, hd) for the single-token step, (b, cw, kvh, hd) for a chunk.

    A quantized pool arrives as a (codes int8, scales f32) tuple: the
    incoming vectors quantize HERE (one symmetric scale per head-vector,
    ops/quant.quantize_rows) and codes + scales scatter through the same
    index maps — append-only, so no earlier position ever requantizes."""
    if isinstance(cache, tuple):
        codes, sc = cache
        q, s = quantize_rows(zi)
        return (codes.at[dst_page, :, dst_off, :].set(q),
                sc.at[dst_page, :, dst_off].set(s))
    return cache.at[dst_page, :, dst_off, :].set(zi.astype(cache.dtype))


def _gather_page_view(cache, page_tbl: jax.Array, dtype) -> jax.Array:
    """Page pool layer slice (pages, kvh, page, hd) + per-row page lists
    (b, max_pages) -> the dense logical cache view (b, kvh, max_pages*page,
    hd) the attention einsums consume.

    The gathered view is VALUE-identical to a slot-granular cache row at
    every position a request has written (pages hold exactly the K/V the
    prefill/decode scatters put there); positions beyond the cursor gather
    whatever the mapped page holds (a freshly allocated page's zeros, the
    scratch page, or a COW donor's later tokens) — all finite, all masked
    to exact-zero attention weight before anything reads them, the same
    garbage-flows-only-into-garbage argument as the slot engine's free
    rows.

    A quantized pool (codes, scales) dequantizes INSIDE the gather — the
    attend math downstream is byte-for-byte the same einsum block, only
    the view operand changed (kv_dtype='int8', ISSUE 8).

    This materialized copy is the paged decode path's HBM floor, and
    since ISSUE 14 it is the ORACLE impl (`paged_attn_impl='gather'`):
    `ops.pallas.paged_attention` attends over the paged layout in place
    — same tokens, no dense view — and is what a TPU serving config
    should run (`--paged_attn pallas`)."""
    b, mp = page_tbl.shape
    if isinstance(cache, tuple):
        codes, sc = cache
        _, kvh, ps, hd = codes.shape
        view = (codes[page_tbl].astype(jnp.float32)
                * sc[page_tbl][..., None]).astype(dtype)
    else:
        _, kvh, ps, hd = cache.shape
        view = cache[page_tbl]                  # (b, mp, kvh, ps, hd)
    return view.transpose(0, 2, 1, 3, 4).reshape(b, kvh, mp * ps, hd)


def _cp_pool_view(pool_k, page_tbl, page_size: int, cp: int):
    """This cp rank's slice of the paged world (call inside shard_map with
    a cp-sharded pool, ISSUE 18): the rank's `max_pages/cp` page-table
    columns translated to LOCAL pool indices, the global position of its
    first column, and the local real-page count.

    Layout (kv_manager.PagedKVPool, cp > 1): page-table column j belongs
    to rank j // (max_pages/cp) — contiguous position spans — and rank r's
    local pool slab holds global pages [r*ppr, (r+1)*ppr) plus one local
    scratch at index ppr; any id the rank does not own translates to that
    scratch (`local_page_ids`), which visibility masks to zero weight."""
    from ..serving.kv_manager import local_page_ids

    mp = page_tbl.shape[1]
    mpp = mp // cp                     # page-table columns per rank
    ppr = (pool_k[0] if isinstance(pool_k, tuple)
           else pool_k).shape[1] - 1   # local real pages (+1 = scratch)
    r = lax.axis_index("cp")
    tbl_r = lax.dynamic_slice_in_dim(page_tbl, r * mpp, mpp, axis=1)
    to_local = lambda ids: local_page_ids(ids, ppr)
    base = r * (mpp * page_size)       # global position of local column 0
    return to_local(tbl_r), base, to_local


def _cp_combine(o, lse, axis: str = "cp"):
    """Merge per-rank partial attention (o f32-normalized within the rank,
    lse over the rank's visible scores) into the exact global softmax —
    ONE pmax + two psums of decode-step-sized tensors, never pages.

    o_r = acc_r / l_r and lse_r = m_r + log l_r give
    sum_r o_r * exp(lse_r - m) / sum_r exp(lse_r - m)
      = sum_r acc_r * exp(m_r) / sum_r l_r * exp(m_r): the single-pool
    softmax bit for bit up to float reassociation. Dead ranks (lse at the
    -1e30 sentinel) underflow to exactly zero weight; an all-dead row
    (free slot) returns 0 like the cp=1 path. The psum outputs are
    cp-invariant, so the caller's residual stream stays replicated."""
    m = lax.pmax(lse, axis)
    w = jnp.exp(lse - m)               # all-dead rows: w = 1 on every rank
    denom = lax.psum(w, axis)
    return lax.psum(o * w[..., None], axis) / denom[..., None]


def _paged_decode_one(model: Transformer, params: Params, pool_k, pool_v,
                      token: jax.Array, cur: jax.Array, page_tbl: jax.Array,
                      page_size: int, cos_t, sin_t, dtype,
                      attn_impl: str = "gather",
                      attn_interpret: bool = False, cp: int = 1):
    """`_decode_one` through a page table: one single-token step where each
    row's K/V write lands in the PAGE mapped for its cursor position
    (pool.at[page, :, offset, :]) and the attention reads the row's page
    list. Two attend impls, token-identical by contract:

    * `attn_impl='gather'` (the oracle): materialize the dense logical
      view (`_gather_page_view`) and run the same einsum block
      `_decode_one` lowers — MASK_VALUE mask, f32 scores.
    * `attn_impl='pallas'` (ISSUE 14): `ops.pallas.paged_attention` walks
      the page table in place — per-row cursor masking, online softmax
      across page blocks, int8 dequant fused into the block loop — so the
      per-step HBM copy of every slot's whole context never happens.
      `attn_interpret` runs the kernel under the Pallas interpreter (the
      CPU identity tests); callers resolve the impl up front via
      `ops.pallas.paged_attention.resolve_paged_attn_impl`.

    pool_k/pool_v: (L, num_pages+1, kvh, page_size, hd); page_tbl:
    (b, max_pages) int32 page ids (free rows map every entry at the scratch
    page, whose content is never attended).

    `cp > 1` (ISSUE 18): the pool is page-sharded over the 'cp' mesh axis
    (kv_manager.CP_POOL_SPEC) and this function runs per-rank inside the
    engine's shard_map. Each rank writes the token's K/V only if it owns
    the cursor's page (everyone else scatters to their LOCAL scratch),
    attends over its own `max_pages/cp` page-table columns with the rank's
    global base as `pos_offset`, and the per-rank partial (out, lse) pairs
    merge through `_cp_combine` — the step's only cp collective is that
    decode-sized reduction; page data never moves."""
    b = token.shape[0]
    mp = page_tbl.shape[1]
    buf_len = mp * page_size
    cur = jnp.asarray(cur, jnp.int32)
    p1 = cur[:, None]
    x = _embed(model, params, token[:, None], p1, dtype)
    if model.uses_rope:
        cos = jnp.take(cos_t, p1, axis=0, mode="clip")
        sin = jnp.take(sin_t, p1, axis=0, mode="clip")
    visible = (jnp.arange(buf_len)[None, :] <= cur[:, None])[:, None, None, :]
    rows = jnp.arange(b)
    # the physical destination of each row's write: its cursor's page + the
    # offset inside that page (free rows' tables aim at the scratch page)
    dst_page = page_tbl[rows, cur // page_size]        # (b,)
    dst_off = cur % page_size                          # (b,)
    if cp > 1:
        tbl_cp, base_cp, to_local = _cp_pool_view(pool_k, page_tbl,
                                                  page_size, cp)
        # rows whose cursor page lives on another rank write their token's
        # K/V to the local scratch — exactly one rank lands the real write
        dst_page = to_local(dst_page)
        t_cp = tbl_cp.shape[1] * page_size
        kv_pos_cp = jnp.broadcast_to(
            base_cp + jnp.arange(t_cp, dtype=jnp.int32), (b, t_cp))

    def write_cache(cache, z):
        # per-row scatter into the page pool (row i writes page dst_page[i]
        # at offset dst_off[i]); duplicate scratch targets are harmless —
        # the scratch page is never read. Quantized pools code the vector
        # on the way in (_paged_cache_write).
        return _paged_cache_write(cache, z[:, :, 0, :], dst_page, dst_off)

    def body(x, layer_in):
        lp, k_cache, v_cache = layer_in
        nk = model.attn_norm_key
        y = model._mods[nk].apply(lp[nk], x)
        q, k, v = _qkv(model, lp, y, dtype)   # q: (b, h, 1, hd); kv: kvh
        if model.uses_rope:
            q, k = apply_rotary(q, k, cos, sin)
        k_cache = write_cache(k_cache, k)
        v_cache = write_cache(v_cache, v)
        if attn_impl == "pallas":
            # walk the page table in place (writes above land in the pool
            # first, so the pending token is visible like the gather path)
            from ..ops.pallas.paged_attention import paged_attention
            if cp > 1:
                # local columns only; pos_offset anchors this rank's pages
                # at their global positions, so the kernel's causal mask and
                # block-skip logic run unchanged against the local slab
                o, olse = paged_attention(q, k_cache, v_cache, tbl_cp, cur,
                                          page_size=page_size,
                                          pos_offset=base_cp,
                                          return_lse=True,
                                          interpret=attn_interpret)
                o = _cp_combine(o.astype(jnp.float32), olse).astype(dtype)
            else:
                o = paged_attention(q, k_cache, v_cache, page_tbl, cur,
                                    page_size=page_size,
                                    interpret=attn_interpret).astype(dtype)
            x = _finish_block(model, lp, x, o, dtype)
            return x, (k_cache, v_cache)
        if cp > 1:
            # per-rank partial over the local gathered view; the causal
            # mask is positional (kv_pos carries the global base), dead
            # ranks (cursor before their span) emit the lse sentinel and
            # vanish in the combine
            k_view = _gather_page_view(k_cache, tbl_cp, dtype)
            v_view = _gather_page_view(v_cache, tbl_cp, dtype)
            o, olse = _block_attn_xla(q, k_view, v_view, cur[:, None],
                                      kv_pos_cp,
                                      model.cfg.head_dim ** -0.5)
            o = _cp_combine(o, olse).astype(dtype)     # (b, h, 1, hd)
            x = _finish_block(model, lp, x, o, dtype)
            return x, (k_cache, v_cache)
        k_view = _gather_page_view(k_cache, page_tbl, dtype)
        v_view = _gather_page_view(v_cache, page_tbl, dtype)
        # identical attend block to _decode_one (same einsums, same mask,
        # same f32 scores) — only the cache OPERAND is gathered, not sliced
        kvh = model.num_local_kv_heads
        g = model.num_local_heads // kvh
        hd = model.cfg.head_dim
        qg = q[:, :, 0, :].reshape(b, kvh, g, hd)
        s = jnp.einsum("bkgd,bktd->bkgt", qg, k_view,
                       preferred_element_type=jnp.float32)
        s = s / jnp.sqrt(jnp.asarray(hd, jnp.float32))
        s = jnp.where(visible, s, MASK_VALUE)
        p = jax.nn.softmax(s, axis=-1).astype(dtype)
        o = jnp.einsum("bkgt,bktd->bkgd", p, v_view)
        o = o.reshape(b, kvh * g, hd)[:, :, None, :]   # (b, h, 1, hd)
        x = _finish_block(model, lp, x, o, dtype)
        return x, (k_cache, v_cache)

    x, (k_new, v_new) = lax.scan(body, x, (params["layers"], pool_k, pool_v))
    return k_new, v_new, _logits_last(model, params, x, dtype)


def _cp_ring_attend(q, k_cache, v_cache, tbl_cp, base_cp, kv_pos_cp,
                    start, qlen, pos, page_size: int, cp: int, dtype,
                    attn_impl: str, attn_interpret: bool):
    """Ring the chunk's QUERIES around the cp axis over a page-sharded pool
    (one layer's attend inside `_paged_prefill_chunk`, ISSUE 18).

    Rank r starts with sub-block r of the chunk (cw/cp queries) and walks
    cp hops: attend the carried sub-block against the rank's LOCAL pages —
    partial out f32-normalized within the hop plus its lse — merge into the
    carry by the logaddexp recurrence (ops/ring_attention.block_into), and
    collective-permute the carry (queries, their global positions, out,
    lse, chunk offset) one rank forward. After cp hops every sub-block has
    visited every slab; a position-scatter + psum('cp') reassembles the
    full (b, h, cw, hd) output, cp-invariant for the replicated residual
    stream. Communication: (cp-1) ppermute hops of sub-block-sized carry +
    one chunk-sized psum — pages never move.

    Dead hops (no local position visible to a query) emit the -1e30 lse
    sentinel and merge at exactly zero weight; a query dead on EVERY hop is
    a pad column (>= qlen), whose finite garbage flows only into pad
    logits, same as the cp=1 chunk."""
    b, h, cw, hd = q.shape
    cws = cw // cp
    r = lax.axis_index("cp")
    off = jnp.asarray(r * cws, jnp.int32)[None]          # (1,) carried
    qh = lax.dynamic_slice_in_dim(q, r * cws, cws, axis=2)
    qph = lax.dynamic_slice_in_dim(pos, r * cws, cws, axis=1)
    zero = qh.astype(jnp.float32).sum() * 0.0            # cp-varying 0
    o = jnp.zeros((b, h, cws, hd), jnp.float32) + zero
    lse = jnp.full((b, h, cws), _BIG_NEG, jnp.float32) + zero
    if attn_impl != "pallas":
        k_view = _gather_page_view(k_cache, tbl_cp, dtype)
        v_view = _gather_page_view(v_cache, tbl_cp, dtype)
    for hop in range(cp):
        if attn_impl == "pallas":
            from ..ops.pallas.paged_attention import paged_attention
            # the carried sub-block's queries sit at chunk offset off:
            # global start start+off, per-row real length qlen-off (clipped
            # to the sub-block); dead rows surface the lse sentinel
            bo, blse = paged_attention(
                qh, k_cache, v_cache, tbl_cp, start + off[0],
                page_size=page_size,
                qlen=jnp.clip(qlen - off[0], 0, cws),
                pos_offset=base_cp, return_lse=True,
                interpret=attn_interpret)
            bo = bo.astype(jnp.float32)
        else:
            bo, blse = _block_attn_xla(qh, k_view, v_view, qph, kv_pos_cp,
                                       hd ** -0.5)
        lse_new = jnp.logaddexp(lse, blse)
        o = (o * jnp.exp(lse - lse_new)[..., None]
             + bo * jnp.exp(blse - lse_new)[..., None])
        lse = lse_new
        if hop < cp - 1:
            qh, qph, o, lse, off = [ring_permute(t, "cp")
                                    for t in (qh, qph, o, lse, off)]
    # rank r now holds sub-block (r - cp + 1) mod cp fully attended; put
    # every sub-block back at its chunk offset and sum the disjoint slots
    full = jnp.zeros((b, h, cw, hd), jnp.float32) + zero
    full = lax.dynamic_update_slice_in_dim(full, o, off[0], axis=2)
    return lax.psum(full, "cp")


def _paged_prefill_chunk(model: Transformer, params: Params, pool_k, pool_v,
                         chunk: jax.Array, start: jax.Array,
                         qlen: jax.Array, page_tbl: jax.Array,
                         dst_page: jax.Array, dst_off: jax.Array,
                         page_size: int, cos_t, sin_t, dtype,
                         all_logits: bool = False,
                         attn_impl: str = "gather",
                         attn_interpret: bool = False, cp: int = 1):
    """One CHUNK of an incremental prefill: process `chunk` (b, cw) tokens
    occupying absolute positions start..start+qlen-1 (columns >= qlen are
    pad), write their K/V into the pages `dst_page`/`dst_off` (b, cw) map
    (pad columns aim at the scratch page), and attend each chunk query over
    the row's FULL gathered page view — prior chunks, a COW-shared prefix
    prefilled by another request, and the chunk's own earlier positions all
    arrive through the same page table. Returns the per-row logits at the
    chunk's LAST real position (qlen-1), which for the final chunk of a
    prompt are the first-token sampling logits.

    This is `_paged_decode_one` generalised from 1 query to cw queries:
    position p's activations depend only on positions <= p (causality), so
    chunk-at-a-time prefill is value-identical to the whole-buffer
    `_prefill` — chunking changes cost and stall, never tokens.

    `all_logits=True` (build-time) returns the logits at EVERY chunk
    position (b, cw, local_v) instead of only the last — the speculative
    VERIFY step (serving/speculative.py): the target model scores all k+1
    draft positions in this one dispatch, each row starting at its own
    cursor (`start` is per-row), with page growth/COW already resolved by
    the host through the same `dst_page`/`dst_off` maps a prefill chunk
    uses.

    `cp > 1` (ISSUE 18): the pool is page-sharded over 'cp' and the chunk's
    QUERIES ring around the cp axis instead of the pages. Every rank runs
    the full-chunk qkv/norm/MLP math replicated (no collectives — the
    residual stream stays cp-invariant), writes only the K/V of chunk
    columns whose destination pages it owns (the rest aim at the local
    scratch), then splits the chunk into cp sub-blocks of cw/cp queries:
    rank r starts with sub-block r, attends it against its LOCAL pages
    (online-softmax partial + lse), and collective-permutes the carry
    (queries, positions, partial out, lse, offset) one rank forward, cp
    hops total. Each hop's attend covers cw/cp queries x T/cp keys, so the
    per-rank attend FLOPs are 1/cp of the dense chunk attend — the
    long-prompt full-mesh-FLOPs win. The hop merge is the same logaddexp
    recurrence ring_attention uses; a final position-scatter + psum
    reassembles the full (b, h, cw, hd) output replicated, bit-for-bit the
    single-pool softmax up to float reassociation. Requires cw % cp == 0
    (the engine rounds chunk widths up to a cp multiple)."""
    b, cw = chunk.shape
    mp = page_tbl.shape[1]
    buf_len = mp * page_size
    pos = start[:, None] + jnp.arange(cw, dtype=jnp.int32)[None, :]  # (b, cw)
    x = _embed(model, params, chunk, pos, dtype)
    if model.uses_rope:
        cos = jnp.take(cos_t, pos, axis=0, mode="clip")
        sin = jnp.take(sin_t, pos, axis=0, mode="clip")
    # query at (row, i) sees cache position t iff t <= start[row] + i;
    # everything later (incl. garbage pages) masks to exact-zero weight
    visible = (jnp.arange(buf_len)[None, None, :]
               <= pos[:, :, None])[:, None, None, :, :]  # (b,1,1,cw,T)
    if cp > 1:
        if cw % cp:
            raise ValueError(f"cp prefill needs chunk width {cw} divisible "
                             f"by cp={cp}")
        cws = cw // cp
        tbl_cp, base_cp, to_local = _cp_pool_view(pool_k, page_tbl,
                                                  page_size, cp)
        dst_page = to_local(dst_page)       # non-owned columns -> scratch
        t_cp = tbl_cp.shape[1] * page_size
        kv_pos_cp = jnp.broadcast_to(
            base_cp + jnp.arange(t_cp, dtype=jnp.int32), (b, t_cp))

    def write_cache(cache, z):
        # z: (b, kvh, cw, hd) -> scatter token i of row r to
        # cache[dst_page[r, i], :, dst_off[r, i], :] (quantized pools code
        # each head-vector on the way in)
        return _paged_cache_write(cache, z.transpose(0, 2, 1, 3),
                                  dst_page, dst_off)

    def body(x, layer_in):
        lp, k_cache, v_cache = layer_in
        nk = model.attn_norm_key
        y = model._mods[nk].apply(lp[nk], x)
        q, k, v = _qkv(model, lp, y, dtype)   # q: (b, h, cw, hd)
        if model.uses_rope:
            q, k = apply_rotary(q, k, cos, sin)
        k_cache = write_cache(k_cache, k)
        v_cache = write_cache(v_cache, v)
        if cp > 1:
            o = _cp_ring_attend(q, k_cache, v_cache, tbl_cp, base_cp,
                                kv_pos_cp, start, qlen, pos, page_size,
                                cp, dtype, attn_impl, attn_interpret)
            x = _finish_block(model, lp, x, o.astype(dtype), dtype)
            return x, (k_cache, v_cache)
        if attn_impl == "pallas":
            # the chunk's own K/V are in the pool (writes above), so the
            # kernel's start+i causality reproduces `visible` exactly;
            # pad columns (>= qlen) stay garbage-into-garbage like the
            # gather path, and their page walk is skipped
            from ..ops.pallas.paged_attention import paged_attention
            o = paged_attention(q, k_cache, v_cache, page_tbl, start,
                                page_size=page_size, qlen=qlen,
                                interpret=attn_interpret).astype(dtype)
            x = _finish_block(model, lp, x, o, dtype)
            return x, (k_cache, v_cache)
        k_view = _gather_page_view(k_cache, page_tbl, dtype)
        v_view = _gather_page_view(v_cache, page_tbl, dtype)
        kvh = model.num_local_kv_heads
        g = model.num_local_heads // kvh
        hd = model.cfg.head_dim
        qg = q.reshape(b, kvh, g, cw, hd)
        s = jnp.einsum("bkgqd,bktd->bkgqt", qg, k_view,
                       preferred_element_type=jnp.float32)
        s = s / jnp.sqrt(jnp.asarray(hd, jnp.float32))
        s = jnp.where(visible, s, MASK_VALUE)
        p = jax.nn.softmax(s, axis=-1).astype(dtype)
        o = jnp.einsum("bkgqt,bktd->bkgqd", p, v_view)
        o = o.reshape(b, kvh * g, cw, hd)
        x = _finish_block(model, lp, x, o, dtype)
        return x, (k_cache, v_cache)

    x, (k_new, v_new) = lax.scan(body, x, (params["layers"], pool_k, pool_v))
    if all_logits:
        return k_new, v_new, _logits_tokens(model, params, x, dtype)
    last = jnp.take_along_axis(
        x, jnp.maximum(qlen - 1, 0)[:, None, None].astype(jnp.int32), axis=1)
    return k_new, v_new, _logits_last(model, params, last, dtype)


def validate_sampling(cfg, temperature: float, top_k: int,
                      top_p: float) -> None:
    """Build-time sampling-knob validation shared by `make_generate` and the
    serving engine (serving/engine.py) — one contract, one error text."""
    if temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if top_k < 0 or top_k > cfg.vocab_size:
        raise ValueError(f"top_k must be in [0, vocab_size], got {top_k}")
    if not 0.0 <= top_p <= 1.0:
        raise ValueError(f"top_p must be in [0, 1] (0 = off), got {top_p}")


def _full_vocab_logits(model: Transformer, logits: jax.Array) -> jax.Array:
    """Local vocab-shard logits -> full (..., vocab_size) f32 logits
    (gathers the tp shards along the LAST dim; every shard holds the same
    values afterwards). Works on the (b, local_v) single-position case and
    the verify step's (b, k+1, local_v) block alike."""
    full = gather_from(logits.astype(jnp.float32), "tp")
    return full[..., : model.cfg.vocab_size]


def _filter_logits(scaled: jax.Array, top_k: int, top_p: float) -> jax.Array:
    """top-k then top-p (nucleus) filtering on temperature-scaled logits;
    filtered-out entries become -inf. Both filters compose: top-k prunes
    first, then top-p."""
    if top_k:
        # kth-largest threshold via top_k, not a full V-sort — this runs
        # once per generated token
        kth = lax.top_k(scaled, top_k)[0][:, -1][:, None]
        scaled = jnp.where(scaled >= kth, scaled, -jnp.inf)
    if top_p and top_p < 1.0:
        # nucleus: keep the smallest descending-prob prefix whose mass
        # reaches top_p (the top token always survives: its own
        # exclusive-cumsum is 0 < top_p)
        sorted_l = jnp.sort(scaled, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_l, axis=-1)
        cum = jnp.cumsum(probs, axis=-1) - probs  # exclusive
        keep = cum < top_p                        # (b, V) sorted
        # threshold = smallest kept logit, mapped back to the unsorted
        # layout by value comparison
        thresh = jnp.min(jnp.where(keep, sorted_l, jnp.inf), axis=-1,
                         keepdims=True)
        scaled = jnp.where(scaled >= thresh, scaled, -jnp.inf)
    return scaled


def make_token_sampler(model: Transformer, temperature: float = 0.0,
                       top_k: int = 0, top_p: float = 0.0):
    """Per-ROW-seeded sampler for the serving engine: `sample(logits,
    seeds, positions)` -> (b,) token ids, called INSIDE shard_map.

    Greedy (temperature 0) ignores seeds/positions. Sampled rows draw with
    key = fold_in(fold_in(key(0), seed_row), position_row): the draw is a
    pure function of the REQUEST's seed and the absolute position the
    token will occupy — independent of which slot the request landed in,
    what else shares the batch, and when it was admitted, which is exactly
    the reproducibility contract continuous batching needs. (The fused
    `make_generate` keeps its own caller-key schedule; the filter and
    gather lowerings are shared.)"""
    validate_sampling(model.cfg, temperature, top_k, top_p)

    def sample(logits: jax.Array, seeds: jax.Array,
               positions: jax.Array) -> jax.Array:
        full = _full_vocab_logits(model, logits)
        if temperature == 0.0:
            idx = jnp.argmax(full, axis=-1).astype(jnp.int32)
        else:
            scaled = _filter_logits(full / temperature, top_k, top_p)

            def draw(seed, pos, row):
                key = jax.random.fold_in(
                    jax.random.fold_in(jax.random.key(0), seed), pos)
                return jax.random.categorical(key, row, axis=-1)

            idx = jax.vmap(draw)(seeds.astype(jnp.uint32),
                                 positions.astype(jnp.int32),
                                 scaled).astype(jnp.int32)
        # every tp shard computed the same choice; pmax clears the
        # varying tag so downstream carries stay tp-invariant
        return lax.pmax(idx, "tp")

    return sample


def host_sample_tokens(model: Transformer, padded_logits, seeds, positions,
                       temperature: float = 0.0, top_k: int = 0,
                       top_p: float = 0.0):
    """DEBUG-ONLY host-side sampler over materialised full-vocab logits —
    the path the engines deliberately do NOT ship (in-program sampling via
    `make_token_sampler` has been the only production path since PR 5),
    reachable behind their `debug_host_sampler` flag so the equivalence
    tests can pin that the fused sampler draws the SAME tokens, and so the
    r10 ablation can price the per-step full-vocab host transfer the fused
    design avoids.

    `padded_logits` is the host copy of the tp-concatenated (b,
    vocab_padded) logits a debug step program returns; the filter/argmax/
    fold_in(seed, position) schedule mirrors `make_token_sampler` exactly,
    so fused vs host tokens must agree bit-for-bit. Production engines
    never take this path: it moves b x vocab floats to the host every
    step where the fused path moves b int32 tokens."""
    import numpy as np

    full = jnp.asarray(padded_logits,
                       jnp.float32)[:, : model.cfg.vocab_size]
    if temperature == 0.0:
        return np.asarray(jnp.argmax(full, axis=-1).astype(jnp.int32))
    scaled = _filter_logits(full / temperature, top_k, top_p)

    def draw(seed, pos, row):
        key = jax.random.fold_in(
            jax.random.fold_in(jax.random.key(0), seed), pos)
        return jax.random.categorical(key, row, axis=-1)

    idx = jax.vmap(draw)(jnp.asarray(seeds, jnp.uint32),
                         jnp.asarray(positions, jnp.int32), scaled)
    return np.asarray(idx.astype(jnp.int32))


def make_generate(model: Transformer, mesh: Mesh, buf_len: int,
                  temperature: float = 0.0, top_k: int = 0,
                  top_p: float = 0.0):
    """Whole-generation XLA program: jitted
    (params, buf(b, buf_len), prompt_len, eos_id, max_total_len, key)
      -> (buf with generated tokens written, per-row total length (b,)).

    `prompt_len` and `max_total_len` may each be a scalar (shared) or a
    (b,) vector — mixed-length prompt batches decode in ONE dispatch, and
    each row stops at ITS total-length limit (pass
    `prompt_len + max_new` for per-prompt new-token budgets). The loop cursor is
    shared across rows ("teacher-forced catch-up"): it starts at
    min(prompt_len), and a row whose prompt extends past the cursor re-feeds
    its own prompt token (recomputing the K/V the prefill already wrote —
    per-position activations under causal attention are context-past-only,
    so the values are identical) until the cursor clears its prompt, after
    which its sampled tokens are appended like the single-row case.

    `temperature` 0 = greedy argmax (the reference's only decoding rule,
    `test.py:149`); > 0 samples from softmax(logits / temperature), with
    `top_k > 0` restricting to the k most likely tokens first and/or
    `top_p in (0, 1]` to the smallest nucleus whose probability mass
    reaches p (both filters compose: top-k prunes first, then top-p) —
    the standard sampling surface the reference lacks. Sampling keys fold
    in the cursor, so every position draws fresh randomness while staying
    a pure function of the caller's `key`. Rows that emit EOS stop
    contributing to their length and are padded with eos_id while other
    rows finish. One compile serves every prompt (prompt_len/eos/limit are
    traced; temperature/top_k/top_p are build-time constants)."""
    cfg = model.cfg
    dtype = resolve_dtype(cfg.compute_dtype)
    # RoPE tables cover the whole decode buffer even past the model's
    # trained maxlen (positions used to silently clip to the last table row
    # when buf_len > maxlen — ADVICE r1). Families with learned positions
    # instead hard-cap the buffer (GreedyDecoder validates).
    table_len = max(cfg.maxlen, buf_len)
    validate_sampling(cfg, temperature, top_k, top_p)

    def shard_fn(params, buf, prompt_len, eos_id, max_total_len, key):
        b, _ = buf.shape
        cos_t = sin_t = None
        if model.uses_rope:
            cos_t, sin_t = rope_tables(table_len, cfg.head_dim,
                                       cfg.rope_theta)
        if model.cp_size > 1:
            # cp-sharded ring prefill; the decode loop below stays
            # replicated over cp (outputs carry identical values, pmax
            # clears the varying tag)
            ks, vs, logits = _prefill_cp(model, params, buf, prompt_len,
                                         cos_t, sin_t, dtype)
        else:
            ks, vs, logits = _prefill(model, params, buf, prompt_len,
                                      cos_t, sin_t, dtype)

        def next_token(logits, cur):
            # gather the tp vocab shards; every shard then computes the
            # same choice (same key), and pmax clears the varying tag so
            # the buf carry stays tp-invariant
            full = _full_vocab_logits(model, logits)
            if temperature == 0.0:
                idx = jnp.argmax(full, axis=-1).astype(jnp.int32)
            else:
                scaled = _filter_logits(full / temperature, top_k, top_p)
                idx = jax.random.categorical(
                    jax.random.fold_in(key, cur), scaled, axis=-1
                ).astype(jnp.int32)
            return lax.pmax(idx, "tp")

        # per-ROW total-length cap: max_total_len may be a scalar (shared)
        # or a (b,) vector — a row finishes once prompt_len + generated
        # reaches ITS limit, so short prompts in a mixed batch don't keep
        # generating until the longest row's limit (the global cursor only
        # bounds the loop)
        row_limit = jnp.minimum(
            jnp.broadcast_to(jnp.asarray(max_total_len, jnp.int32), (b,)),
            buf_len)
        cur0 = jnp.min(prompt_len)
        nxt = next_token(logits, cur0)               # (b,) per-row first token
        done0 = ((prompt_len == cur0) & (nxt == eos_id)) | (
            prompt_len >= row_limit)
        gen0 = jnp.zeros((b,), jnp.int32)
        carry0 = (buf, ks, vs, nxt, done0, gen0, cur0)

        def cond(c):
            _, _, _, _, done, _, cur = c
            return jnp.logical_and(cur < jnp.max(row_limit), ~jnp.all(done))

        def body(c):
            buf, ck, cv, nxt, done, gen, cur = c
            in_prompt = cur < prompt_len             # (b,)
            cur_tok = lax.dynamic_slice_in_dim(buf, cur, 1, axis=1)[:, 0]
            tok = jnp.where(in_prompt, cur_tok,
                            jnp.where(done, eos_id, nxt))
            gen = gen + jnp.where(in_prompt | done, 0, 1)
            buf = lax.dynamic_update_slice(buf, tok[:, None], (0, cur))
            ck, cv, logits = _decode_one(model, params, ck, cv, tok, cur,
                                         buf_len, cos_t, sin_t, dtype)
            cand = next_token(logits, cur + 1)
            # cand is consumed at position cur+1; it counts as a GENERATED
            # token for a row only once the cursor has cleared its prompt
            starts_gen = (cur + 1) >= prompt_len
            done = done | (starts_gen & (cand == eos_id))
            done = done | (prompt_len + gen >= row_limit)
            return (buf, ck, cv, cand, done, gen, cur + 1)

        buf, _, _, _, _, gen, _ = lax.while_loop(cond, body, carry0)
        return buf, prompt_len + gen  # per-row total length

    fn = jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(model.specs(), P(None, None), P(None), P(), P(), P()),
        out_specs=(P(None, None), P(None)))

    def wrapper(params, buf, prompt_len, eos_id, max_total_len, key):
        prompt_len = jnp.broadcast_to(
            jnp.asarray(prompt_len, jnp.int32), (buf.shape[0],))
        return fn(params, buf, prompt_len, eos_id, max_total_len, key)

    return jax.jit(wrapper)


class GreedyDecoder:
    """KV-cache decoder: compile the whole-generation program ONCE, reuse
    across prompts (the reference re-runs O(t^2) work per token,
    `test.py:145-152`; the no-cache jitted path in evaluate.py is
    O(buf_len^2) per token AND pays one dispatch per token).

    Greedy by default (the name survives from that contract); pass
    `temperature` / `top_k` for sampled decoding and a `seed` to
    decode_batch for reproducible draws."""

    def __init__(self, model: Transformer, mesh: Mesh, buf_len: int,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 0.0):
        if model.cp_size > 1:
            # Long-context decode: the PREFILL runs the same ring-attention
            # path as training (sequence sharded over 'cp'), so prompts far
            # beyond one chip's attention budget prefill across the group;
            # the per-token loop then runs on the gathered caches,
            # replicated over cp. Contiguous layout + ring only (zigzag
            # would permute the cache order; ulysses needs head headroom).
            if model.cp_impl != "ring" or model.cp_layout != "contiguous":
                raise ValueError(
                    "cp decode supports cp_impl='ring' with the contiguous "
                    f"layout (got impl={model.cp_impl!r}, "
                    f"layout={model.cp_layout!r})")
            if buf_len % model.cp_size:
                raise ValueError(f"buf_len {buf_len} must be divisible by "
                                 f"cp_size {model.cp_size} (contiguous "
                                 f"chunks)")
        cap = getattr(model, "max_decode_positions", None)
        if cap is not None and buf_len > cap:
            raise ValueError(
                f"buf_len {buf_len} exceeds the model's learned position "
                f"table ({cap}); clamp the buffer (evaluate.greedy_decode "
                f"does) or retrain with a larger maxlen")
        self.model = model
        self.mesh = mesh
        self.buf_len = buf_len
        self.generate = make_generate(model, mesh, buf_len,
                                      temperature=temperature, top_k=top_k,
                                      top_p=top_p)

    def decode(self, params, prompt_ids, eos_id: int,
               max_total_len: int, seed: int = 0) -> list:
        """Decode one prompt (ids incl. BOS); returns generated ids
        (prompt excluded), stopping at EOS or `max_total_len` total tokens.
        One device dispatch for the whole generation."""
        return self.decode_batch(params, [prompt_ids], eos_id,
                                 max_total_len, seed=seed)[0]

    def decode_batch(self, params, prompts, eos_id: int,
                     max_total_len: int, seed: int = 0) -> list:
        """Decode a LIST of prompts (mixed lengths fine) in a single
        device dispatch; returns one generated-ids list per prompt. The
        reference dispatches per prompt AND per token (`test.py:141-161`).
        `seed` matters only for sampled decoders (temperature > 0)."""
        import numpy as np

        b = len(prompts)
        for p in prompts:
            assert len(p) < self.buf_len, (
                f"prompt length {len(p)} must leave room in buf_len "
                f"{self.buf_len}")
        buf = np.full((b, self.buf_len), eos_id, dtype=np.int32)
        for i, p in enumerate(prompts):
            buf[i, : len(p)] = p
        plens = np.asarray([len(p) for p in prompts], np.int32)
        buf, flen = self.generate(params, jnp.asarray(buf),
                                  jnp.asarray(plens),
                                  jnp.asarray(eos_id, jnp.int32),
                                  jnp.asarray(max_total_len, jnp.int32),
                                  jax.random.key(seed))
        buf, flen = np.asarray(buf), np.asarray(flen)
        return [buf[i, len(prompts[i]) : int(flen[i])].tolist()
                for i in range(b)]
