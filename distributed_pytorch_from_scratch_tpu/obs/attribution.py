"""Analytic roofline + step-time attribution: where do the milliseconds go?

VERDICT r5 #1: the flagship 45M config ran at 33.7% MFU while gpt2-124m hit
55.7% on the same chip, and nothing in the repo could say WHY. This module
answers that question without needing the chip: it prices every phase of a
train step analytically (FLOPs and HBM bytes -> a roofline ms estimate) and
ranks the known waste suspects — flash-kernel tile/padding waste at the
actual block shapes, remat recompute, dispatch amortisation, the lm_head —
so `bench.py --breakdown` can print an attribution table on CPU and
cross-check it against measured phase times and XLA's cost_analysis when a
backend is present.

Everything here is pure host math (no jax arrays, no backend init): the
tile accounting mirrors the flash kernels' `block_live` grid predicates
(ops/pallas/flash_attention.py) and the phase FLOPs mirror
`training.metrics.model_flops_per_step`'s conventions, itemised per phase.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

# Per-chip peak bf16 FLOP/s and HBM bandwidth (bytes/s). The FLOPS side
# must agree with training.metrics.PEAK_FLOPS; bandwidth is the roofline's
# other axis. Unknown chips assume v5e, clearly labelled in the report.
CHIP_SPECS = {
    "v5e": (197e12, 819e9),
    "v5p": (459e12, 2765e9),
    "v4": (275e12, 1228e9),
    "v6e": (918e12, 1640e9),
}


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def resolve_flash_tiling(t: int, block_q: Optional[int] = None,
                         block_k: Optional[int] = None,
                         head_dim: int = 64,
                         dtype: str = "bfloat16") -> Dict[str, int]:
    """The (t_pad, bq, bk) the flash kernel would actually run — mirrors
    `flash_attention`'s pow2 clamp. Blocks default to the autotuner table
    (which needs no backend for a pure lookup when the key names one)."""
    if block_q is None or block_k is None:
        # lazy import: the kernel module imports jax, but a table lookup
        # does not initialise a backend beyond jax.default_backend()
        from ..ops.pallas.flash_attention import get_block_config
        tuned = get_block_config(t, head_dim, dtype)
        block_q = block_q or tuned.block_q
        block_k = block_k or tuned.block_k
    pow2 = max(128, 1 << (t - 1).bit_length())
    bq, bk = min(block_q, pow2), min(block_k, pow2)
    t_pad = _round_up(t, max(bq, bk))
    return {"t_pad": t_pad, "block_q": bq, "block_k": bk}


def flash_tile_stats(t: int, block_q: Optional[int] = None,
                     block_k: Optional[int] = None,
                     t_real: Optional[int] = None,
                     head_dim: int = 64,
                     dtype: str = "bfloat16") -> Dict[str, float]:
    """MXU work the fwd flash kernel performs at this (t, blocks) vs the
    causal ideal — the quantified 't=1000 -> 1024 padding waste' suspect.

    Counts live (q-block, k-block) tiles with the kernel's own
    `block_live` predicate; work = live tiles x bq x bk score elements.
    `waste_ratio` = work / ideal (1.0 = perfect causal skip; the shipped
    1024x1024 default at t=1000 computes the FULL padded square = ~2.1x).
    `t_real` < t prices the pad-aware bucketed path (attn_t_real).
    """
    tiling = resolve_flash_tiling(t, block_q, block_k, head_dim, dtype)
    t_pad, bq, bk = tiling["t_pad"], tiling["block_q"], tiling["block_k"]
    tr = t if t_real is None else t_real
    num_qb, num_kb = t_pad // bq, t_pad // bk
    live = 0
    for qi in range(num_qb):
        for ki in range(num_kb):
            if (ki * bk <= qi * bq + bq - 1 and ki * bk < tr
                    and qi * bq < tr):
                live += 1
    work = live * bq * bk
    ideal = tr * (tr + 1) / 2
    return {"t_pad": t_pad, "block_q": bq, "block_k": bk,
            "live_tiles": live, "total_tiles": num_qb * num_kb,
            "work_elems": work, "ideal_elems": ideal,
            "waste_ratio": work / ideal}


@dataclasses.dataclass
class PhaseCost:
    """One phase's analytic price. ms_est = roofline max(compute, memory)."""

    name: str
    flops: float
    bytes: float
    note: str = ""

    def ms(self, peak_flops: float, hbm_bw: float) -> float:
        return max(self.flops / peak_flops, self.bytes / hbm_bw) * 1e3


def analytic_phases(cfg, batch: int, t: int, remat: str = "dots",
                    t_real: Optional[int] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    family: str = "llama") -> List[PhaseCost]:
    """Per-phase FLOPs + HBM bytes for ONE fwd+bwd+adam train step (global,
    all devices), itemised so shares can be compared against measured
    fwd/bwd/adam times. remat in {'false','dots','true'} (CLI strings)."""
    d, f, L = cfg.attn_dim, cfg.ffn_dim, cfg.num_layers
    h, hd, kd = cfg.num_heads, cfg.head_dim, cfg.kv_dim
    v = cfg.padded_vocab_size(1)
    N = batch * t            # tokens incl. any bucket padding
    A = 2                    # activation bytes (bf16); f32 would be 4
    P = cfg.num_params()
    # llama: SwiGLU = gate/up/down, 3 matmuls; gpt2: fc/proj gelu MLP, 2
    ffn_mats = 2 if family == "gpt2" else 3

    stats = flash_tile_stats(t, block_q, block_k, t_real, hd,
                             cfg.compute_dtype)
    attn_elems = batch * h * stats["work_elems"]

    fwd = [
        PhaseCost("embed", 0.0, N * d * 4 + N * 4,
                  "gather; bytes-bound"),
        PhaseCost("qkv_proj", L * 2 * N * d * (d + 2 * kd),
                  L * (N * (d + (d + 2 * kd)) * A + d * (d + 2 * kd) * A)),
        PhaseCost("attention", attn_elems * 4 * hd,
                  L * (N * (2 * d + 2 * kd) * A + N * h * 4),
                  f"{stats['live_tiles']}/{stats['total_tiles']} live "
                  f"{stats['block_q']}x{stats['block_k']} tiles, "
                  f"{stats['waste_ratio']:.2f}x causal-ideal work"),
        PhaseCost("wo_proj", L * 2 * N * d * d,
                  L * (2 * N * d * A + d * d * A)),
        PhaseCost("ffn", L * 2 * ffn_mats * N * d * f,
                  L * (2 * N * (d + (ffn_mats - 1) * f) * A
                       + ffn_mats * d * f * A)),
        PhaseCost("norms_rope", L * 16 * N * d, L * 6 * N * d * A,
                  "elementwise; bytes-bound"),
        PhaseCost("lm_head", 2 * N * d * v, N * d * A + N * v * 4),
        PhaseCost("ce_loss", 8 * N * v, 2 * N * v * 4,
                  "f32 logits read+reduce"),
    ]
    # attention FLOPs scale by L too (itemised per layer above except attn)
    fwd[2] = dataclasses.replace(fwd[2], flops=fwd[2].flops * L)

    # Backward: matmul phases cost 2x forward (dgrad + wgrad); the flash
    # backward runs 5 MXU dots where the forward runs 2 (fused path) ->
    # 2.5x; elementwise ~2x. Remat adds recompute on top:
    #   'true' — the whole layer forward replays (+1x layer fwd FLOPs)
    #   'dots' — matmul outputs + flash o/lse are saved; only elementwise
    #            replays (norms/rope/silu)
    #   'false' — nothing replays
    layer_fwd_flops = sum(p.flops for p in fwd[1:6])
    layer_fwd_bytes = sum(p.bytes for p in fwd[1:6])
    recompute = {"true": layer_fwd_flops,
                 "dots": fwd[5].flops,
                 "false": 0.0}[str(remat)]
    recompute_bytes = (layer_fwd_bytes * recompute / layer_fwd_flops
                       if layer_fwd_flops else 0.0)
    bwd_flops = (2 * (fwd[1].flops + fwd[3].flops + fwd[4].flops
                      + fwd[6].flops + fwd[7].flops)
                 + 2.5 * fwd[2].flops + 2 * fwd[5].flops)
    bwd_bytes = 2 * sum(p.bytes for p in fwd[1:])
    phases = fwd + [
        PhaseCost("backward", bwd_flops, bwd_bytes,
                  "2x matmuls, 2.5x flash kernel"),
        PhaseCost("remat_recompute", recompute, recompute_bytes,
                  f"remat={remat}"),
        PhaseCost("adam", 12 * P, 28 * P,
                  "f32 params/moments read+write; bytes-bound"),
    ]
    return phases


def attribution(cfg, batch: int, t: int, remat: str = "dots", spd: int = 8,
                t_real: Optional[int] = None,
                block_q: Optional[int] = None,
                block_k: Optional[int] = None,
                measured: Optional[Dict[str, float]] = None,
                chip: str = "v5e", world: int = 1,
                family: str = "llama") -> Dict:
    """The full report structure: analytic phase table, fwd/bwd/adam bucket
    sums, ranked waste suspects, and (when `measured` carries bench.py
    --breakdown components) analytic-vs-measured share columns.

    measured keys (all optional, ms): fwd_ms, fwdbwd_ms, step_ms,
    h2d_ms, and any 'step_ms_spdN'.
    """
    peak_flops, hbm_bw = CHIP_SPECS.get(chip, CHIP_SPECS["v5e"])
    peak_flops *= world
    hbm_bw *= world
    phases = analytic_phases(cfg, batch, t, remat, t_real, block_q, block_k,
                             family)
    by = {p.name: p for p in phases}
    ms = {p.name: p.ms(peak_flops, hbm_bw) for p in phases}
    fwd_names = ["embed", "qkv_proj", "attention", "wo_proj", "ffn",
                 "norms_rope", "lm_head", "ce_loss"]
    buckets = {
        "fwd_ms": sum(ms[n] for n in fwd_names),
        "bwd_ms": ms["backward"] + ms["remat_recompute"],
        "adam_ms": ms["adam"],
    }
    analytic_step = sum(buckets.values())

    measured = measured or {}
    spd_keys = [k for k in measured if k.startswith("step_ms_spd")]
    measured_amortised = measured.get(spd_keys[0]) if spd_keys else None
    measured_step = measured.get("step_ms")
    dispatch_ms = (measured_step - measured_amortised
                   if measured_step and measured_amortised else None)
    # the yardstick every suspect's share is quoted against
    step_ms = measured_amortised or measured_step or analytic_step

    stats = flash_tile_stats(t, block_q, block_k, t_real, cfg.head_dim,
                             cfg.compute_dtype)
    attn_ms = ms["attention"] * (1 + 2.5)  # fwd + its share of backward
    waste = stats["waste_ratio"]
    suspects = [{
        "name": "attention tile/pad waste",
        "est_ms": attn_ms * (1 - 1 / waste),
        "note": (f"t={t_real or t}->t_pad {stats['t_pad']} @ "
                 f"{stats['block_q']}x{stats['block_k']} blocks: "
                 f"{waste:.2f}x causal-ideal MXU work (fix: bucketing/"
                 f"attn_t_real + tuned blocks)"),
    }, {
        "name": "remat recompute",
        "est_ms": ms["remat_recompute"],
        "note": f"remat={remat} (fix: --remat auto picks false when "
                f"activations fit)",
    }, {
        "name": "dispatch overhead",
        "est_ms": dispatch_ms if dispatch_ms is not None else 0.0,
        "note": (f"measured step - spd-amortised step at spd={spd}"
                 if dispatch_ms is not None else
                 f"unmeasured (needs --breakdown on a backend); spd={spd} "
                 f"amortises host round-trips"),
    }, {
        "name": "lm_head+CE (vocab %d)" % cfg.vocab_size,
        "est_ms": ms["lm_head"] + ms["ce_loss"],
        "note": "unsharded head pass + f32 CE over the full vocab",
    }, {
        "name": "optimizer (bytes-bound)",
        "est_ms": ms["adam"],
        "note": "28 bytes/param HBM traffic",
    }]
    if step_ms > analytic_step:
        # The most important row when a measurement exists: whatever the
        # itemised suspects do NOT cover. A large value here means the gap
        # is kernel efficiency / launch overhead / pipeline stalls — small
        # matmuls far off peak — not algorithmic waste; --breakdown's
        # fwd/bwd/adam splits localise which phase is off its roofline.
        gap = step_ms - analytic_step - (dispatch_ms or 0.0)
        if gap > 0:
            suspects.append({
                "name": "roofline gap (kernel efficiency)",
                "est_ms": gap,
                "note": ("measured minus analytic roofline: time the "
                         "itemised suspects cannot explain — small-matmul "
                         "MXU underutilisation and per-kernel overhead at "
                         f"d={cfg.attn_dim}"),
            })
    suspects.sort(key=lambda s: -s["est_ms"])
    for rank, s in enumerate(suspects, 1):
        s["rank"] = rank
        s["share"] = s["est_ms"] / step_ms if step_ms else 0.0

    return {"phases": [dataclasses.asdict(p) | {"ms_est": ms[p.name]}
                       for p in phases],
            "buckets": buckets,
            "analytic_step_ms": analytic_step,
            "measured_step_ms": measured_step,
            "measured_amortised_ms": measured_amortised,
            "dispatch_ms": dispatch_ms,
            "step_ms_basis": step_ms,
            "tile_stats": stats,
            "suspects": suspects,
            "chip": chip, "world": world,
            "assumptions": (f"{chip} roofline ({peak_flops/1e12:.0f} "
                            f"TFLOP/s, {hbm_bw/1e9:.0f} GB/s) x {world} "
                            f"device(s); bf16 activations, f32 optimizer")}


def format_attribution(report: Dict,
                       measured: Optional[Dict[str, float]] = None) -> str:
    """Human table: ranked suspects + analytic-vs-measured bucket shares."""
    lines = ["step-time attribution (" + report["assumptions"] + ")"]
    basis = report["step_ms_basis"]
    src = ("measured" if report.get("measured_amortised_ms")
           or report.get("measured_step_ms") else "analytic")
    lines.append(f"  step basis: {basis:.1f} ms ({src})")

    measured = measured or {}
    mfwd = measured.get("fwd_ms")
    mbwd = (measured["fwdbwd_ms"] - measured["fwd_ms"]
            if "fwdbwd_ms" in measured and "fwd_ms" in measured else None)
    madam = (measured["step_ms"] - measured["fwdbwd_ms"]
             if "step_ms" in measured and "fwdbwd_ms" in measured else None)
    b = report["buckets"]
    lines.append("  bucket       analytic_ms   measured_ms")
    for name, analytic, meas in [("fwd", b["fwd_ms"], mfwd),
                                 ("bwd(+remat)", b["bwd_ms"], mbwd),
                                 ("adam", b["adam_ms"], madam)]:
        m = f"{meas:11.2f}" if meas is not None else "          —"
        lines.append(f"  {name:<12} {analytic:11.2f}   {m}")

    lines.append("  rank  suspect                        est_ms  share  note")
    for s in report["suspects"]:
        lines.append(f"  {s['rank']:>4}  {s['name']:<29} {s['est_ms']:7.2f}"
                     f"  {s['share']*100:4.1f}%  {s['note']}")
    return "\n".join(lines)
