"""Analytic roofline + step-time attribution: where do the milliseconds go?

VERDICT r5 #1: the flagship 45M config ran at 33.7% MFU while gpt2-124m hit
55.7% on the same chip, and nothing in the repo could say WHY. This module
answers that question without needing the chip: it prices every phase of a
train step analytically (FLOPs and HBM bytes -> a roofline ms estimate) and
ranks the known waste suspects — flash-kernel tile/padding waste at the
actual block shapes, remat recompute, dispatch amortisation, the lm_head —
so `bench.py --breakdown` can print an attribution table on CPU and
cross-check it against measured phase times and XLA's cost_analysis when a
backend is present.

Everything here is pure host math (no jax arrays, no backend init): the
tile accounting mirrors the flash kernels' `block_live` grid predicates
(ops/pallas/flash_attention.py) and the phase FLOPs mirror
`training.metrics.model_flops_per_step`'s conventions, itemised per phase.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

# Per-chip peak bf16 FLOP/s and HBM bandwidth (bytes/s). The FLOPS side
# must agree with training.metrics.PEAK_FLOPS; bandwidth is the roofline's
# other axis. Unknown chips assume v5e, clearly labelled in the report.
CHIP_SPECS = {
    "v5e": (197e12, 819e9),
    "v5p": (459e12, 2765e9),
    "v4": (275e12, 1228e9),
    "v6e": (918e12, 1640e9),
}

# Per-chip ICI terms: (one-way per-link ring bandwidth bytes/s, per-hop
# latency s). These are the alpha-beta model's two knobs per collective —
# nominal values from the published interconnect specs; `calibrate_ici`
# LEARNS the effective bandwidth from a measured all-reduce p50 when the
# bench took one on real hardware (the 4 MiB probe bench.py already runs),
# so the comm attribution tracks the chip actually attached rather than
# the datasheet.
ICI_SPECS = {
    "v5e": (4.5e10, 1e-6),
    "v5p": (9.0e10, 1e-6),
    "v4": (4.5e10, 1e-6),
    "v6e": (9.0e10, 1e-6),
}

ALLREDUCE_PROBE_BYTES = 4 * 2**20  # metrics.allreduce_p50_us's payload


def chip_key_for(device_kind: str) -> str:
    """CHIP_SPECS key for a jax `device_kind` string ('TPU v6 lite' ->
    'v6e'; unknown kinds assume v5e — reports label the assumption).
    The one copy of the lite->e normalization: bench.py's chip_key and
    train.py's duty-profiler chip detection both route through here."""
    kind = device_kind.lower().replace(" ", "").replace("lite", "e")
    for key in sorted(CHIP_SPECS, key=len, reverse=True):
        if key in kind:
            return key
    return "v5e"


def calibrate_ici(chip: str, n: int,
                  measured_allreduce_us: Optional[float] = None,
                  probe_bytes: int = ALLREDUCE_PROBE_BYTES):
    """(ici_bw, ici_lat) for `chip` — the ICI_SPECS entry, with the
    bandwidth term re-fit from a measured ring all-reduce p50 when one is
    available: t = 2(n-1)/n * bytes / bw + 2(n-1) * lat  =>  bw. The
    latency model (2(n-1) hops: reduce-scatter phase + all-gather phase)
    matches how `comm_attribution` prices all-reduce records, so
    re-pricing the probe collective with the fitted terms reproduces the
    measurement. This is the 'learned ICI term': one measured collective
    pins the line the whole comm attribution is priced on."""
    bw, lat = ICI_SPECS.get(chip, ICI_SPECS["v5e"])
    if measured_allreduce_us and n > 1:
        wire = measured_allreduce_us * 1e-6 - 2 * (n - 1) * lat
        if wire > 0:
            bw = 2 * (n - 1) / n * probe_bytes / wire
    return bw, lat


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def resolve_flash_tiling(t: int, block_q: Optional[int] = None,
                         block_k: Optional[int] = None,
                         head_dim: int = 64,
                         dtype: str = "bfloat16") -> Dict[str, int]:
    """The (t_pad, bq, bk) the flash kernel would actually run — mirrors
    `flash_attention`'s pow2 clamp. Blocks default to the autotuner table
    (which needs no backend for a pure lookup when the key names one)."""
    if block_q is None or block_k is None:
        # lazy import: the kernel module imports jax, but a table lookup
        # does not initialise a backend beyond jax.default_backend()
        from ..ops.pallas.flash_attention import get_block_config
        tuned = get_block_config(t, head_dim, dtype)
        block_q = block_q or tuned.block_q
        block_k = block_k or tuned.block_k
    pow2 = max(128, 1 << (t - 1).bit_length())
    bq, bk = min(block_q, pow2), min(block_k, pow2)
    t_pad = _round_up(t, max(bq, bk))
    return {"t_pad": t_pad, "block_q": bq, "block_k": bk}


def flash_tile_stats(t: int, block_q: Optional[int] = None,
                     block_k: Optional[int] = None,
                     t_real: Optional[int] = None,
                     head_dim: int = 64,
                     dtype: str = "bfloat16") -> Dict[str, float]:
    """MXU work the fwd flash kernel performs at this (t, blocks) vs the
    causal ideal — the quantified 't=1000 -> 1024 padding waste' suspect.

    Counts live (q-block, k-block) tiles with the kernel's own
    `block_live` predicate; work = live tiles x bq x bk score elements.
    `waste_ratio` = work / ideal (1.0 = perfect causal skip; the shipped
    1024x1024 default at t=1000 computes the FULL padded square = ~2.1x).
    `t_real` < t prices the pad-aware bucketed path (attn_t_real).
    """
    tiling = resolve_flash_tiling(t, block_q, block_k, head_dim, dtype)
    t_pad, bq, bk = tiling["t_pad"], tiling["block_q"], tiling["block_k"]
    tr = t if t_real is None else t_real
    num_qb, num_kb = t_pad // bq, t_pad // bk
    live = 0
    for qi in range(num_qb):
        for ki in range(num_kb):
            if (ki * bk <= qi * bq + bq - 1 and ki * bk < tr
                    and qi * bq < tr):
                live += 1
    work = live * bq * bk
    ideal = tr * (tr + 1) / 2
    return {"t_pad": t_pad, "block_q": bq, "block_k": bk,
            "live_tiles": live, "total_tiles": num_qb * num_kb,
            "work_elems": work, "ideal_elems": ideal,
            "waste_ratio": work / ideal}


@dataclasses.dataclass
class PhaseCost:
    """One phase's analytic price. ms_est = roofline max(compute, memory)."""

    name: str
    flops: float
    bytes: float
    note: str = ""

    def ms(self, peak_flops: float, hbm_bw: float) -> float:
        return max(self.flops / peak_flops, self.bytes / hbm_bw) * 1e3


def analytic_phases(cfg, batch: int, t: int, remat: str = "dots",
                    t_real: Optional[int] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    family: str = "llama") -> List[PhaseCost]:
    """Per-phase FLOPs + HBM bytes for ONE fwd+bwd+adam train step (global,
    all devices), itemised so shares can be compared against measured
    fwd/bwd/adam times. remat in {'false','dots','true'} (CLI strings)."""
    d, f, L = cfg.attn_dim, cfg.ffn_dim, cfg.num_layers
    h, hd, kd = cfg.num_heads, cfg.head_dim, cfg.kv_dim
    v = cfg.padded_vocab_size(1)
    N = batch * t            # tokens incl. any bucket padding
    A = 2                    # activation bytes (bf16); f32 would be 4
    P = cfg.num_params()
    # llama: SwiGLU = gate/up/down, 3 matmuls; gpt2: fc/proj gelu MLP, 2
    ffn_mats = 2 if family == "gpt2" else 3

    stats = flash_tile_stats(t, block_q, block_k, t_real, hd,
                             cfg.compute_dtype)
    attn_elems = batch * h * stats["work_elems"]

    fwd = [
        PhaseCost("embed", 0.0, N * d * 4 + N * 4,
                  "gather; bytes-bound"),
        PhaseCost("qkv_proj", L * 2 * N * d * (d + 2 * kd),
                  L * (N * (d + (d + 2 * kd)) * A + d * (d + 2 * kd) * A)),
        PhaseCost("attention", attn_elems * 4 * hd,
                  L * (N * (2 * d + 2 * kd) * A + N * h * 4),
                  f"{stats['live_tiles']}/{stats['total_tiles']} live "
                  f"{stats['block_q']}x{stats['block_k']} tiles, "
                  f"{stats['waste_ratio']:.2f}x causal-ideal work"),
        PhaseCost("wo_proj", L * 2 * N * d * d,
                  L * (2 * N * d * A + d * d * A)),
        PhaseCost("ffn", L * 2 * ffn_mats * N * d * f,
                  L * (2 * N * (d + (ffn_mats - 1) * f) * A
                       + ffn_mats * d * f * A)),
        PhaseCost("norms_rope", L * 16 * N * d, L * 6 * N * d * A,
                  "elementwise; bytes-bound"),
        PhaseCost("lm_head", 2 * N * d * v, N * d * A + N * v * 4),
        PhaseCost("ce_loss", 8 * N * v, 2 * N * v * 4,
                  "f32 logits read+reduce"),
    ]
    # attention FLOPs scale by L too (itemised per layer above except attn)
    fwd[2] = dataclasses.replace(fwd[2], flops=fwd[2].flops * L)

    # Backward: matmul phases cost 2x forward (dgrad + wgrad); the flash
    # backward runs 5 MXU dots where the forward runs 2 (fused path) ->
    # 2.5x; elementwise ~2x. Remat adds recompute on top:
    #   'true' — the whole layer forward replays (+1x layer fwd FLOPs)
    #   'dots' — matmul outputs + flash o/lse are saved; only elementwise
    #            replays (norms/rope/silu)
    #   'false' — nothing replays
    layer_fwd_flops = sum(p.flops for p in fwd[1:6])
    layer_fwd_bytes = sum(p.bytes for p in fwd[1:6])
    recompute = {"true": layer_fwd_flops,
                 "dots": fwd[5].flops,
                 "false": 0.0}[str(remat)]
    recompute_bytes = (layer_fwd_bytes * recompute / layer_fwd_flops
                       if layer_fwd_flops else 0.0)
    bwd_flops = (2 * (fwd[1].flops + fwd[3].flops + fwd[4].flops
                      + fwd[6].flops + fwd[7].flops)
                 + 2.5 * fwd[2].flops + 2 * fwd[5].flops)
    bwd_bytes = 2 * sum(p.bytes for p in fwd[1:])
    phases = fwd + [
        PhaseCost("backward", bwd_flops, bwd_bytes,
                  "2x matmuls, 2.5x flash kernel"),
        PhaseCost("remat_recompute", recompute, recompute_bytes,
                  f"remat={remat}"),
        PhaseCost("adam", 12 * P, 28 * P,
                  "f32 params/moments read+write; bytes-bound"),
    ]
    return phases


def ring_chunk_bytes(cfg, batch: int, t: int, tp: int) -> Dict[str, float]:
    """The ring collective-matmul chunk schedule's ppermute bytes per
    DEVICE (tp_overlap='ring'), itemised so `--introspect` can cross-check
    the HLO's collective-permute byte count against it.

    Per ring instance the wire carries (n-1) hops of one (b, t/n, d) chunk
    = (n-1)/n * b*t*d*A bytes. Per layer: fwd = 4 instances (qkv ring, wo
    reduce ring, ffn ring, down reduce ring); bwd = 6 (each ag VJP runs a
    re-gather ring + a reduce ring; each rs VJP one gather ring). The head
    adds 1 fwd + 2 bwd. Both families share the schedule (gpt2's fc/proj
    pair rings exactly like gate-up/down). NOTE for the HLO cross-check:
    the layer stack is a lax.scan, so the compiled program TEXT contains
    one layer's ring ops (executed num_layers times) — compare
    `per_layer_*` against the HLO count, not `total_bytes`."""
    A = 2 if "bf16" in str(cfg.compute_dtype) or "bfloat16" in str(
        cfg.compute_dtype) else 4
    u = (tp - 1) / tp * batch * t * cfg.attn_dim * A
    return {"unit_bytes": u,
            "per_layer_fwd_bytes": 4 * u,
            "per_layer_bwd_bytes": 6 * u,
            "head_fwd_bytes": u,
            "head_bwd_bytes": 2 * u,
            "total_bytes": cfg.num_layers * 10 * u + 3 * u}


def cp_ring_attribution(cfg, batch: int, chunk: int, context: int,
                        cp: int, chip: str = "v5e",
                        decode_steps: int = 0,
                        measured_allreduce_us: Optional[float] = None) -> Dict:
    """Price the cp-serving wire (ISSUE 18): the chunked-prefill query
    ring's ppermute hops against the per-hop attend compute they
    interleave with, plus the two small psum families (chunk reassembly,
    decode's (out, lse) combine).

    The ring moves the QUERY carry, never page data: per hop each rank
    rotates its (b, h, chunk/cp, hd) query sub-block (compute dtype), the
    f32 (o, lse) accumulators and two int32 position fields to its
    neighbour, then attends the arrived queries against its LOCAL pool
    slab (~context/cp keys). The schedule is profitable while
    `per_hop.wire_ms` < `per_hop.attend_ms` — the ratio this report
    carries — because at steady state each hop's rotation hides under the
    next hop's attend (classic ring-attention overlap); the reassembling
    psum and the decode combine are latency-bound small collectives
    either way, priced fully exposed.

    Prefill records price ONE chunk dispatch x num_layers (the layer
    stack is a scan — multiply by ceil(context/chunk) dispatches for a
    full prompt); `decode_steps` > 0 additionally prices that many
    (out, lse) combines."""
    cp = max(1, cp)
    bw, lat = calibrate_ici(chip, cp,
                            measured_allreduce_us if cp > 1 else None)
    peak_flops, _ = CHIP_SPECS.get(chip, CHIP_SPECS["v5e"])
    A = 2 if "bf16" in str(cfg.compute_dtype) or "bfloat16" in str(
        cfg.compute_dtype) else 4
    L, h, hd = cfg.num_layers, cfg.num_heads, cfg.head_dim
    cws = max(1, chunk // cp)  # per-rank query sub-block width
    # per-hop carry: compute-dtype query sub-block + f32 (o, lse)
    # accumulators + int32 positions/offset
    hop_bytes = (batch * h * cws * hd * (A + 4)   # qh + o
                 + batch * h * cws * 4            # lse
                 + batch * cws * 4 + 4)           # qph + off
    # per-hop attend: cws queries vs the local slab, qk + av matmuls
    attend_flops = 4 * batch * h * cws * max(1, context // cp) * hd
    hop_ms = (hop_bytes / bw + lat) * 1e3
    attend_ms = attend_flops / peak_flops * 1e3

    records = []

    def add(name, kind, count, nbytes, hops, budget_ms, note=""):
        total = count * (nbytes / bw + hops * lat) * 1e3
        hidden = min(total, budget_ms) if budget_ms > 0 else 0.0
        records.append({
            "name": name, "kind": kind, "count": count,
            "bytes_each": nbytes, "serialized_ms": total,
            "hidden_ms": hidden, "exposed_ms": total - hidden, "note": note})

    if cp > 1:
        ratio = hop_ms / attend_ms if attend_ms > 0 else float("inf")
        add("cp prefill query ring", "collective-permute",
            L * (cp - 1), hop_bytes, 1, L * (cp - 1) * attend_ms,
            f"per-hop carry {hop_bytes / 1e3:.1f} kB vs "
            f"{attend_flops / 1e9:.3f} GFLOP attend "
            f"(wire/compute {ratio:.2f}): hops hide under the next "
            f"hop's attend while the ratio stays < 1")
        add("cp prefill chunk reassembly", "all-reduce", L,
            2 * (cp - 1) / cp * batch * h * chunk * hd * 4,
            2 * (cp - 1), 0.0,
            "psum of the rotated (out) sub-blocks back into chunk order; "
            "small and latency-bound")
        if decode_steps > 0:
            add("cp decode (out, lse) combine", "all-reduce",
                decode_steps * L,
                2 * (cp - 1) / cp * (batch * h * hd * 4 + 2 * batch * h * 4),
                2 * (cp - 1), 0.0,
                "per-step psums of the per-rank partial output and softmax "
                "weights; pure latency")

    total = sum(r["serialized_ms"] for r in records)
    hidden = sum(r["hidden_ms"] for r in records)
    return {"records": records,
            "comm_total_ms": total,
            "comm_hidden_ms": hidden,
            "comm_exposed_ms": total - hidden,
            "per_hop": {"wire_bytes": int(hop_bytes), "wire_ms": hop_ms,
                        "attend_flops": int(attend_flops),
                        "attend_ms": attend_ms,
                        "wire_to_compute": (hop_ms / attend_ms
                                            if attend_ms > 0 else None)},
            "config": {"cp": cp, "chunk": chunk, "context": context,
                       "decode_steps": decode_steps, "chip": chip}}


def comm_attribution(cfg, batch: int, t: int, tp: int = 1, sp: bool = False,
                     tp_overlap: str = "off", dp: int = 1,
                     dp_bucket_mb: float = 0.0, dp_reduce_dtype: str = "f32",
                     chip: str = "v5e", family: str = "llama",
                     remat: str = "dots",
                     measured_allreduce_us: Optional[float] = None,
                     phase_ms: Optional[Dict[str, float]] = None,
                     zero_stage: int = 0, cp: int = 1,
                     cp_prefill_chunk: int = 0,
                     cp_context: int = 0) -> Dict:
    """Per-collective comm attribution with an overlap model: how many ms
    of ICI time the step spends, and how much of it HIDES under the matmul
    each collective is (or could be) fused with.

    Each record prices serialized_ms = bytes/ici_bw + hops*lat from the
    learned ICI terms (`calibrate_ici`), then splits hidden vs exposed:

    * tp act collectives, tp_overlap='ring' — hidden up to the ms of the
      matmul sharing the ring (ag_matmul/matmul_rs overlap exactly that
      pair); 'ring_q' additionally HALVES the priced chunk bytes (int8
      codes + per-row scales replace the bf16 payload); 'off' — the
      monolithic collective serialises fully.
    * DP grad reduce, dp_bucket_mb > 0 — buckets issue during the
      backward, hidden up to the backward's compute ms; 0 — the
      end-of-step blob is fully exposed. The WIRE dtype prices the bytes:
      bf16 halves them, int8 quarters them (the quantized ring's scale
      overhead, 4/WIRE_GROUP < 1%, is deliberately ignored) — a record
      that kept pricing the compute dtype would silently misreport the
      quantized wire as hidden/exposed ms it no longer spends.
    * `zero_stage` reshapes the DP schedule (training/zero.py). <= 1: one
      grad ALL-REDUCE, 2(dp-1)/dp x P x wire bytes. 2: a grad
      REDUCE-SCATTER at HALF those bytes ((dp-1)/dp x P — each rank
      receives only its shard) plus the end-of-step f32 param all-gather
      XLA inserts for the replicated params. 3: no explicit grad
      collective at all — per-layer param all-gathers (fwd, and again in
      the remat'd backward) whose TRANSPOSE is the grad reduce-scatter,
      all f32 ppermute rings hidden up to the adjacent compute. A record
      that kept pricing the stage-1 all-reduce would assert the halved
      wire instead of showing it.

    `phase_ms` (name -> analytic ms from `analytic_phases`) supplies the
    overlap budgets; computed here when omitted.
    """
    # the 4 MiB probe (`metrics.allreduce_p50_us`) rings over the tp axis,
    # so the re-fit must solve for n = tp; the fitted per-link bandwidth
    # then prices every axis's collectives
    bw, lat = calibrate_ici(chip, tp,
                            measured_allreduce_us if tp > 1 else None)
    if phase_ms is None:
        peak_flops, hbm_bw = CHIP_SPECS.get(chip, CHIP_SPECS["v5e"])
        world = max(1, tp * dp)
        phases = analytic_phases(cfg, batch, t, remat, family=family)
        phase_ms = {p.name: p.ms(peak_flops * world, hbm_bw * world)
                    for p in phases}

    A = 2  # bf16 activation bytes, matching analytic_phases
    L = cfg.num_layers
    act = batch * t * cfg.attn_dim * A  # one full layer-boundary activation

    def ms_of(nbytes: float, hops: int) -> float:
        return (nbytes / bw + hops * lat) * 1e3

    records = []

    def add(name, kind, count, nbytes, hops, budget_ms, note=""):
        total = count * ms_of(nbytes, hops)
        hidden = min(total, budget_ms) if budget_ms > 0 else 0.0
        records.append({
            "name": name, "kind": kind, "count": count,
            "bytes_each": nbytes, "serialized_ms": total,
            "hidden_ms": hidden, "exposed_ms": total - hidden, "note": note})

    if tp > 1:
        ring = tp_overlap in ("ring", "ring_q")
        # ring_q: int8 codes on every hop — half the bf16 activation
        # bytes (per-row scales add 4/head_dim-ish; ignored like the DP
        # wire's group scales)
        wire_scale = 0.5 if tp_overlap == "ring_q" else 1.0
        shard = (tp - 1) / tp * act * wire_scale  # ag / rs wire bytes
        ar = 2 * (tp - 1) / tp * act    # all-reduce wire bytes (non-ring)
        hops = tp - 1
        # budgets: the matmul each collective's ring is fused with (fwd),
        # and its ~2x backward counterpart for the conjugate direction
        fwd_note = ("ring: hops hide under the partial dots"
                    + (", int8 payloads" if tp_overlap == "ring_q" else "")
                    if ring else "monolithic: fully exposed")
        if sp:
            # ring-mode counts follow `ring_chunk_bytes`'s chunk schedule:
            # each ag VJP runs TWO reverse rings (re-gather + reduce) where
            # the monolithic transpose is one conjugate collective, so the
            # ring moves MORE chunk-instances per layer (4 fwd + 6 bwd vs
            # 4 + 4) — all of them overlappable, but priced honestly
            add("qkv all-gather (fwd+bwd)", "all-gather",
                (3 if ring else 2) * L, shard, hops,
                (phase_ms.get("qkv_proj", 0) * 3 if ring else 0), fwd_note)
            add("wo reduce-scatter (fwd+bwd)", "reduce-scatter", 2 * L,
                shard, hops,
                (phase_ms.get("wo_proj", 0) * 3 if ring else 0), fwd_note)
            add("ffn all-gather+reduce-scatter (fwd+bwd)", "all-gather",
                (5 if ring else 4) * L, shard, hops,
                (phase_ms.get("ffn", 0) * 3 if ring else 0), fwd_note)
            add("lm_head all-gather (fwd+bwd)", "all-gather",
                3 if ring else 2, shard, hops,
                (phase_ms.get("lm_head", 0) * 3 if ring else 0), fwd_note)
            add("embed reduce-scatter (fwd+bwd)", "reduce-scatter", 2,
                shard, hops, 0.0, "bytes-bound producer; not ringed")
        else:
            add("per-sublayer all-reduce (fwd+bwd)", "all-reduce", 4 * L,
                ar, 2 * hops, 0.0,
                "no SP: monolithic psum per sublayer per direction")
            add("lm_head input all-reduce (bwd)", "all-reduce", 1, ar,
                2 * hops, 0.0, "copy_to transpose")
        # vocab-parallel CE scalar-field psums: two (b, t) f32 fields
        add("CE scalar psums (fwd+bwd)", "all-reduce", 2,
            2 * (tp - 1) / tp * batch * t * 4, 2 * hops, 0.0,
            "tiny; never worth overlapping")

    if dp > 1:
        P_count = cfg.num_params()
        wire_itemsize = {"bf16": 2, "bfloat16": 2,
                         "int8": 1}.get(dp_reduce_dtype, 4)
        shard_bytes = (dp - 1) / dp * P_count  # RS or AG wire, per element
        bucketed = dp_bucket_mb > 0
        bwd_budget = phase_ms.get("backward", 0.0)
        if zero_stage >= 3:
            # ZeRO-3: params gather per layer inside the scan (fwd, and
            # again in the remat'd backward replay); the gathers'
            # transposes ARE the grad reduce-scatter. All three rings are
            # f32 (params/cotangents), per-layer, overlappable.
            fwd_budget = sum(phase_ms.get(n, 0.0)
                             for n in ("qkv_proj", "wo_proj", "ffn"))
            add("ZeRO-3 param all-gather (fwd)", "all-gather", 1,
                shard_bytes * 4, dp - 1, fwd_budget,
                "per-layer ring inside the scan: hops hide under the "
                "layer's matmuls")
            add("ZeRO-3 param all-gather (bwd remat)", "all-gather", 1,
                shard_bytes * 4, dp - 1, bwd_budget,
                "the remat replay re-gathers each layer during the "
                "backward")
            add("ZeRO-3 grad reduce-scatter (bwd)", "reduce-scatter", 1,
                shard_bytes * 4, dp - 1, bwd_budget,
                "the gather's transpose: each rank receives only its "
                "dp-summed shard (f32 wire)")
        elif zero_stage == 2:
            note = (f"bucketed ({dp_bucket_mb:g} MiB, {dp_reduce_dtype} "
                    f"wire): half the all-reduce bytes — each rank "
                    f"receives only its 1/dp grad shard"
                    if bucketed else
                    f"{dp_reduce_dtype} wire; half the all-reduce bytes")
            add("DP grad reduce-scatter", "reduce-scatter", 1,
                shard_bytes * wire_itemsize, dp - 1,
                bwd_budget if bucketed else 0.0, note)
            add("ZeRO-2 param all-gather", "all-gather", 1,
                shard_bytes * 4, dp - 1, 0.0,
                "end-of-step gather of the freshly updated params (f32); "
                "--zero 3 gathers per-layer under compute instead")
        else:
            nbytes = 2 * shard_bytes * wire_itemsize
            budget = bwd_budget if bucketed else 0.0
            note = (f"bucketed ({dp_bucket_mb:g} MiB, {dp_reduce_dtype} "
                    f"wire): buckets overlap the remaining backward"
                    if bucketed else
                    "end-of-step whole-tree blob: fully exposed "
                    "(--dp_reduce_bucket_mb to overlap)")
            add("DP grad reduce", "all-reduce", 1, nbytes, 2 * (dp - 1),
                budget, note)

    if cp > 1 and cp_prefill_chunk > 0:
        # serving-side cp ring (ISSUE 18): priced by cp_ring_attribution
        # and folded into the same record table so one report covers the
        # whole wire
        ring = cp_ring_attribution(
            cfg, batch, cp_prefill_chunk,
            max(cp_context, cp_prefill_chunk), cp, chip=chip,
            measured_allreduce_us=measured_allreduce_us)
        records.extend(ring["records"])

    total = sum(r["serialized_ms"] for r in records)
    hidden = sum(r["hidden_ms"] for r in records)
    return {"records": records,
            "comm_total_ms": total,
            "comm_hidden_ms": hidden,
            "comm_exposed_ms": total - hidden,
            "ici": {"bw_bytes_per_s": bw, "latency_s": lat,
                    "calibrated": bool(measured_allreduce_us)},
            "config": {"tp": tp, "sp": sp, "tp_overlap": tp_overlap,
                       "dp": dp, "dp_bucket_mb": dp_bucket_mb,
                       "dp_reduce_dtype": dp_reduce_dtype,
                       # the ZeRO stage the DP schedule was priced at
                       # (ISSUE 9): <=1 all-reduce, 2 RS+param-AG, 3
                       # per-layer AG + transpose RS
                       "zero_stage": zero_stage,
                       # the attributable wire dtypes (ISSUE 8): what the
                       # DP reduce and the tp ring payloads actually carry
                       "wire_dtype": (dp_reduce_dtype if zero_stage < 3
                                      else "f32"),
                       "tp_wire_dtype": ("int8" if tp_overlap == "ring_q"
                                         else "bf16"),
                       # serving-side cp ring inputs (ISSUE 18); 1/0 when
                       # the report prices a pure training step
                       "cp": cp, "cp_prefill_chunk": cp_prefill_chunk,
                       "cp_context": cp_context}}


def attribution(cfg, batch: int, t: int, remat: str = "dots", spd: int = 8,
                t_real: Optional[int] = None,
                block_q: Optional[int] = None,
                block_k: Optional[int] = None,
                measured: Optional[Dict[str, float]] = None,
                chip: str = "v5e", world: int = 1,
                family: str = "llama", tp: int = 1, sp: bool = False,
                tp_overlap: str = "off", dp: int = 1,
                dp_bucket_mb: float = 0.0, dp_reduce_dtype: str = "f32",
                measured_allreduce_us: Optional[float] = None,
                zero_stage: int = 0) -> Dict:
    """The full report structure: analytic phase table, fwd/bwd/adam bucket
    sums, the per-collective COMM attribution (serialized vs hidden vs
    exposed ICI ms under the configured overlap knobs), ranked waste
    suspects, and (when `measured` carries bench.py --breakdown
    components) analytic-vs-measured share columns.

    measured keys (all optional, ms): fwd_ms, fwdbwd_ms, step_ms,
    h2d_ms, and any 'step_ms_spdN'.
    """
    peak_flops, hbm_bw = CHIP_SPECS.get(chip, CHIP_SPECS["v5e"])
    peak_flops *= world
    hbm_bw *= world
    phases = analytic_phases(cfg, batch, t, remat, t_real, block_q, block_k,
                             family)
    by = {p.name: p for p in phases}
    ms = {p.name: p.ms(peak_flops, hbm_bw) for p in phases}
    comm = comm_attribution(cfg, batch, t_real or t, tp=tp, sp=sp,
                            tp_overlap=tp_overlap, dp=dp,
                            dp_bucket_mb=dp_bucket_mb,
                            dp_reduce_dtype=dp_reduce_dtype, chip=chip,
                            family=family, remat=remat,
                            measured_allreduce_us=measured_allreduce_us,
                            phase_ms=ms, zero_stage=zero_stage)
    fwd_names = ["embed", "qkv_proj", "attention", "wo_proj", "ffn",
                 "norms_rope", "lm_head", "ce_loss"]
    buckets = {
        "fwd_ms": sum(ms[n] for n in fwd_names),
        "bwd_ms": ms["backward"] + ms["remat_recompute"],
        "adam_ms": ms["adam"],
    }
    analytic_step = sum(buckets.values())

    measured = measured or {}
    spd_keys = [k for k in measured if k.startswith("step_ms_spd")]
    measured_amortised = measured.get(spd_keys[0]) if spd_keys else None
    measured_step = measured.get("step_ms")
    dispatch_ms = (measured_step - measured_amortised
                   if measured_step and measured_amortised else None)
    # the yardstick every suspect's share is quoted against
    step_ms = measured_amortised or measured_step or analytic_step

    stats = flash_tile_stats(t, block_q, block_k, t_real, cfg.head_dim,
                             cfg.compute_dtype)
    attn_ms = ms["attention"] * (1 + 2.5)  # fwd + its share of backward
    waste = stats["waste_ratio"]
    suspects = [{
        "name": "attention tile/pad waste",
        "est_ms": attn_ms * (1 - 1 / waste),
        "note": (f"t={t_real or t}->t_pad {stats['t_pad']} @ "
                 f"{stats['block_q']}x{stats['block_k']} blocks: "
                 f"{waste:.2f}x causal-ideal MXU work (fix: bucketing/"
                 f"attn_t_real + tuned blocks)"),
    }, {
        "name": "remat recompute",
        "est_ms": ms["remat_recompute"],
        "note": f"remat={remat} (fix: --remat auto picks false when "
                f"activations fit)",
    }, {
        "name": "dispatch overhead",
        "est_ms": dispatch_ms if dispatch_ms is not None else 0.0,
        "note": (f"measured step - spd-amortised step at spd={spd}"
                 if dispatch_ms is not None else
                 f"unmeasured (needs --breakdown on a backend); spd={spd} "
                 f"amortises host round-trips"),
    }, {
        "name": "lm_head+CE (vocab %d)" % cfg.vocab_size,
        "est_ms": ms["lm_head"] + ms["ce_loss"],
        "note": "unsharded head pass + f32 CE over the full vocab",
    }, {
        "name": "optimizer (bytes-bound)",
        "est_ms": ms["adam"],
        "note": "28 bytes/param HBM traffic",
    }]
    if comm["comm_total_ms"] > 0:
        cfg_note = comm["config"]
        suspects.append({
            "name": "exposed collective comm",
            "est_ms": comm["comm_exposed_ms"],
            "note": (f"{comm['comm_total_ms']:.2f} ms ICI total, "
                     f"{comm['comm_hidden_ms']:.2f} hidden under compute "
                     f"(tp_overlap={cfg_note['tp_overlap']}, "
                     f"dp_bucket={cfg_note['dp_bucket_mb']:g}MiB); fix: "
                     f"--tp_overlap ring / --dp_reduce_bucket_mb"),
        })
    if step_ms > analytic_step:
        # The most important row when a measurement exists: whatever the
        # itemised suspects do NOT cover. A large value here means the gap
        # is kernel efficiency / launch overhead / pipeline stalls — small
        # matmuls far off peak — not algorithmic waste; --breakdown's
        # fwd/bwd/adam splits localise which phase is off its roofline.
        gap = step_ms - analytic_step - (dispatch_ms or 0.0)
        if gap > 0:
            suspects.append({
                "name": "roofline gap (kernel efficiency)",
                "est_ms": gap,
                "note": ("measured minus analytic roofline: time the "
                         "itemised suspects cannot explain — small-matmul "
                         "MXU underutilisation and per-kernel overhead at "
                         f"d={cfg.attn_dim}"),
            })
    suspects.sort(key=lambda s: -s["est_ms"])
    for rank, s in enumerate(suspects, 1):
        s["rank"] = rank
        s["share"] = s["est_ms"] / step_ms if step_ms else 0.0

    return {"phases": [dataclasses.asdict(p) | {"ms_est": ms[p.name]}
                       for p in phases],
            "comm": comm,
            "buckets": buckets,
            "analytic_step_ms": analytic_step,
            "measured_step_ms": measured_step,
            "measured_amortised_ms": measured_amortised,
            "dispatch_ms": dispatch_ms,
            "step_ms_basis": step_ms,
            "tile_stats": stats,
            "suspects": suspects,
            "chip": chip, "world": world,
            "assumptions": (f"{chip} roofline ({peak_flops/1e12:.0f} "
                            f"TFLOP/s, {hbm_bw/1e9:.0f} GB/s) x {world} "
                            f"device(s); bf16 activations, f32 optimizer")}


def format_attribution(report: Dict,
                       measured: Optional[Dict[str, float]] = None) -> str:
    """Human table: ranked suspects + analytic-vs-measured bucket shares."""
    lines = ["step-time attribution (" + report["assumptions"] + ")"]
    basis = report["step_ms_basis"]
    src = ("measured" if report.get("measured_amortised_ms")
           or report.get("measured_step_ms") else "analytic")
    lines.append(f"  step basis: {basis:.1f} ms ({src})")

    measured = measured or {}
    mfwd = measured.get("fwd_ms")
    mbwd = (measured["fwdbwd_ms"] - measured["fwd_ms"]
            if "fwdbwd_ms" in measured and "fwd_ms" in measured else None)
    madam = (measured["step_ms"] - measured["fwdbwd_ms"]
             if "step_ms" in measured and "fwdbwd_ms" in measured else None)
    b = report["buckets"]
    lines.append("  bucket       analytic_ms   measured_ms")
    for name, analytic, meas in [("fwd", b["fwd_ms"], mfwd),
                                 ("bwd(+remat)", b["bwd_ms"], mbwd),
                                 ("adam", b["adam_ms"], madam)]:
        m = f"{meas:11.2f}" if meas is not None else "          —"
        lines.append(f"  {name:<12} {analytic:11.2f}   {m}")

    comm = report.get("comm") or {}
    if comm.get("comm_total_ms"):
        ici = comm["ici"]
        src = "calibrated" if ici["calibrated"] else "nominal"
        lines.append(
            f"  comm hidden / exposed: {comm['comm_hidden_ms']:.2f} / "
            f"{comm['comm_exposed_ms']:.2f} ms "
            f"(of {comm['comm_total_ms']:.2f} ms ICI, "
            f"{src} {ici['bw_bytes_per_s']/1e9:.0f} GB/s + "
            f"{ici['latency_s']*1e6:.1f}us/hop; "
            f"tp_overlap={comm['config']['tp_overlap']}, "
            f"dp_bucket={comm['config']['dp_bucket_mb']:g}MiB)")
        for r in comm["records"]:
            lines.append(
                f"    {r['name']:<38} x{r['count']:<3} "
                f"{r['serialized_ms']:6.2f} ms  hidden {r['hidden_ms']:6.2f}"
                f"  exposed {r['exposed_ms']:6.2f}  {r['note']}")

    lines.append("  rank  suspect                        est_ms  share  note")
    for s in report["suspects"]:
        lines.append(f"  {s['rank']:>4}  {s['name']:<29} {s['est_ms']:7.2f}"
                     f"  {s['share']*100:4.1f}%  {s['note']}")
    return "\n".join(lines)


# -- paged-decode roofline (ISSUE 14) ------------------------------------

def paged_decode_hbm_bytes(cfg, slots: int, max_pages: int, page_size: int,
                           kv_dtype=None, paged_attn: str = "gather",
                           decode_weight_dtype=None,
                           live_tokens: Optional[int] = None,
                           cp: int = 1) -> Dict:
    """Analytic HBM bytes ONE paged decode dispatch moves, itemised so the
    gather-vs-pallas A/B can assert the win instead of claiming it.

    The decode step at serving scale is bytes-bound; per dispatch it must
    move (a) the weights (int8 when `decode_weight_dtype='int8'` — the PR
    8 floor) and (b) the K/V context. How (b) is priced depends on the
    attend impl:

    * `'gather'` — `_gather_page_view` materializes the dense logical
      view per layer: the pool pages are READ (at their storage dtype),
      the dequantized compute-dtype view is WRITTEN to HBM, and the
      attend READS it back. The write+read of that view is
      `gather_copy_bytes` — pure overhead the kernel exists to kill —
      and the view spans the FULL (slots, max_pages*page_size) dense
      shape whatever the cursors say (the gather cannot skip).
    * `'pallas'` — the kernel streams pages pool->VMEM once;
      `gather_copy_bytes` is exactly 0, and the cursor-mask block skip
      bounds the pool read by the LIVE context (`live_tokens`, page-
      rounded) instead of the dense span.

    Returns {weight_bytes, kv_pool_read_bytes, gather_copy_bytes,
    total_bytes, paged_attn, cp}: `total = weight + pool_read +
    gather_copy`, so `total(gather) - total(pallas)` at equal live
    context is the gather-copy elimination plus the dead-page skip.

    `cp` > 1 (ISSUE 18) reports PER-CHIP bytes: each cp rank's page-table
    view spans only its max_pages/cp slab columns, so the dense span (and
    the live context a pallas read walks) divides by cp — the ~1/cp
    per-chip KV traffic the cp shard exists to buy. Weights replicate
    over cp, so `weight_bytes` does not divide."""
    if paged_attn not in ("gather", "pallas"):
        raise ValueError(f"paged_attn must be 'gather'/'pallas', got "
                         f"{paged_attn!r}")
    cp = max(1, cp)
    L, kvh, hd = cfg.num_layers, cfg.kv_heads, cfg.head_dim
    compute_itemsize = 2 if "bf16" in str(cfg.compute_dtype) or (
        "bfloat16" in str(cfg.compute_dtype)) else 4
    # stored bytes per token position (K+V, all layers): int8 pages carry
    # codes + one f32 scale per head-vector (kv_manager.kv_token_bytes)
    if kv_dtype in ("int8", "s8"):
        stored_per_tok = 2 * L * kvh * (hd + 4)
    else:
        stored_per_tok = 2 * L * kvh * hd * compute_itemsize
    view_per_tok = 2 * L * kvh * hd * compute_itemsize  # dequantized view
    dense_span = slots * (max_pages // cp) * page_size
    if paged_attn == "gather" or live_tokens is None:
        read_span = dense_span
    else:
        # block-granular skip: each rank's ~1/cp share of the live
        # context rounds up to whole local pages
        live_local = -(-int(live_tokens) // cp)
        read_span = min(dense_span,
                        -(-live_local // page_size) * page_size)
    weight_itemsize = 1 if decode_weight_dtype in ("int8", "s8") else (
        compute_itemsize)
    weight_bytes = cfg.num_params() * weight_itemsize
    pool_read = read_span * stored_per_tok
    gather_copy = 2 * dense_span * view_per_tok if paged_attn == "gather" \
        else 0
    return {
        "paged_attn": paged_attn,
        "cp": cp,
        "weight_bytes": int(weight_bytes),
        "kv_pool_read_bytes": int(pool_read),
        "gather_copy_bytes": int(gather_copy),
        "total_bytes": int(weight_bytes + pool_read + gather_copy),
    }


# -- checkable collective schedule (ISSUE 11) ----------------------------

def expected_collectives(tp: int = 1, sp: bool = False,
                         tp_overlap: str = "off", dp: int = 1,
                         dp_bucket_mb: float = 0.0,
                         dp_reduce_dtype: str = "f32",
                         zero_stage: int = 0,
                         serving: bool = False,
                         kind: Optional[str] = None,
                         cp: int = 1) -> Dict:
    """The schedule `comm_attribution` prices, as a CHECKABLE contract
    over a compiled program's collective inventory: (mesh axis, HLO op)
    pairs that must be present (`require`), may be present (`allow`), and
    must NOT be present (`forbid`), each with the wire dtypes the priced
    schedule carries. `analysis/contracts.check_collective_inventory`
    asserts a lowered program against this — so when a refactor changes
    the wire (a new collective, a dtype fallback, a gather that stopped
    ringing), the contract fails INSTEAD of the attribution silently
    mispricing it.

    The mapping from priced records to physical ops: monolithic psums are
    `all-reduce`; SP's boundary collectives are `all-gather` /
    `reduce-scatter`; every hand-rolled ring (ring/ring_q tp overlap, the
    quantized DP wire, ZeRO-3's per-layer gathers and their transposes)
    is `collective-permute`. Axes: 'dp'/'tp' are the mesh axes; 'all' is
    a reduction spanning the whole mesh (SP-replicated leaf grads, the
    loss mean); XLA-derived entries (the ZeRO-1/2 param all-gather, the
    all-to-all it may rewrite SP gathers into) are included and marked —
    they are part of the stage's schedule even though the pricing
    attributes them to other records.

    `dp_bucket_mb` is accepted for symmetry with `comm_attribution`'s
    config surface (program configs pass through verbatim): bucketing
    changes collective COUNTS and overlap, never the (axis, op)
    inventory, so it does not alter the sets today.
    """
    require: Dict[tuple, dict] = {}
    allow: Dict[tuple, str] = {}
    forbid: Dict[tuple, str] = {}
    wide = {"f32", "bf16", "f16"}

    if tp > 1:
        if sp:
            require[("tp", "all-gather")] = {
                "dtypes": wide,
                "note": "SP boundary gathers (qkv/ffn/lm_head records)"}
            require[("tp", "reduce-scatter")] = {
                "dtypes": wide,
                "note": "SP boundary scatters (wo/ffn/embed records)"}
            require[("tp", "all-reduce")] = {
                "dtypes": wide,
                "note": "CE scalar-field psums (+ small SP residuals)"}
            allow[("tp", "all-to-all")] = (
                "XLA rewrites some SP gather+slice patterns into "
                "all-to-all; same bytes, priced under the gather records")
        else:
            require[("tp", "all-reduce")] = {
                "dtypes": wide,
                "note": "monolithic per-sublayer psums (no-SP schedule)"}
            allow[("tp", "all-gather")] = "XLA-derived activation gathers"
            allow[("tp", "reduce-scatter")] = "XLA-derived scatters"
            allow[("tp", "all-to-all")] = "XLA-derived rewrites"
        if tp_overlap in ("ring", "ring_q"):
            require[("tp", "collective-permute")] = {
                "dtypes": ({"s8"} | wide if tp_overlap == "ring_q"
                           else wide),
                "note": f"the {tp_overlap} collective-matmul rings"}
        allow[("all", "all-reduce")] = (
            "whole-mesh sums: the loss mean and SP-replicated leaf grads "
            "(dp x tp groups)")

    if dp > 1 and not serving:
        int8 = dp_reduce_dtype in ("int8", "s8")
        if zero_stage >= 3:
            require[("dp", "collective-permute")] = {
                "dtypes": {"f32"},
                "note": "ZeRO-3 per-layer gather rings + their "
                        "reduce-scatter transposes (f32 by contract)"}
            forbid[("dp", "all-gather")] = (
                "a dp all-gather in a ZeRO-3 program is the whole-tree "
                "param materialisation the stage exists to eliminate")
            allow[("dp", "all-reduce")] = (
                "residual psums for leaves too small to shard")
        elif zero_stage == 2:
            if int8:
                require[("dp", "collective-permute")] = {
                    "dtypes": {"s8"},
                    "note": "quantized reduce-scatter ring (int8 codes; "
                            "f32 group scales ride below the sidecar "
                            "threshold)"}
            else:
                require[("dp", "reduce-scatter")] = {
                    "dtypes": wide,
                    "note": "stage-2 bucketed grad reduce-scatter (half "
                            "the all-reduce bytes)"}
            require[("dp", "all-gather")] = {
                "dtypes": {"f32"},
                "note": "the end-of-step param all-gather XLA inserts "
                        "for the replicated out_sharding (priced as "
                        "'ZeRO-2 param all-gather')"}
            allow[("dp", "all-reduce")] = (
                "residual psums for unscatterable leaves")
        else:
            if int8:
                require[("dp", "collective-permute")] = {
                    "dtypes": {"s8"},
                    "note": "quantized DP all-reduce ring (EQuARX "
                            "schedule: int8 codes, f32 sidecar scales)"}
                allow[("all", "collective-permute")] = (
                    "the quantized ring over combined (dp x tp) groups "
                    "for SP-replicated leaves")
                allow[("tp", "collective-permute")] = (
                    "the quantized ring's tp leg for SP-replicated "
                    "leaves (their grads reduce over dp AND tp)")
                allow[("dp", "all-reduce")] = (
                    "small-leaf / scalar residuals")
            else:
                require[("dp", "all-reduce")] = {
                    "dtypes": wide,
                    "note": "the DP grad reduce (bucketed or whole-tree)"}
            if zero_stage == 1:
                require[("dp", "all-gather")] = {
                    "dtypes": {"f32"},
                    "note": "stage-1 param gather from the dp-sharded "
                            "moment update (XLA-derived schedule)"}
        allow[("all", "all-reduce")] = (
            "whole-mesh sums (loss mean, SP-replicated leaf grads)")

    if serving and tp > 1:
        # inference programs: row-parallel psums on tp; gathers allowed
        # (vocab-parallel logits, page views); nothing on dp. All
        # serving kinds (decode / prefill_chunk / spec_verify) share one
        # schedule for BOTH paged-attention impls: the Pallas kernel
        # (ISSUE 14) changes only local HBM traffic, never the wire —
        # graftcheck's collective-inventory contract asserts the pallas
        # programs against this same schedule, so a kernel revision that
        # grew a collective would fail there. When the wires genuinely
        # diverge some day, differentiate on `kind` HERE so the contract
        # tightens with the implementation.
        require[("tp", "all-reduce")] = {
            "dtypes": wide | {"s32", "u32"},
            "note": f"row-parallel output psums + fused-sampler argmax "
                    f"reductions ({kind or 'serving'} dispatch)"}
        allow[("tp", "all-gather")] = "vocab/head gathers"
        allow[("tp", "reduce-scatter")] = "XLA-derived scatters"
        allow[("tp", "all-to-all")] = "XLA-derived rewrites"
        allow[("tp", "collective-permute")] = "XLA-derived rotations"

    if serving and cp > 1:
        # cp-sharded paged serving (ISSUE 18): decode combines the
        # per-rank partial (out, lse) with small cp psums; chunked
        # prefill (and its speculative-verify twin) ADDITIONALLY rings
        # the query carry around cp before one reassembling psum. Page
        # DATA never crosses the wire — the byte-threshold canary
        # (analysis/contracts.check_cp_no_page_gather) forbids
        # pool-sized cp gathers the way the ZeRO-3 rule forbids
        # whole-tree dp gathers; this inventory only admits small
        # XLA-derived gathers (psum rewrites, sampler plumbing).
        require[("cp", "all-reduce")] = {
            "dtypes": wide,
            "note": "the (out, lse) combine psums (decode) / the chunk "
                    "reassembly psum (prefill ring)"}
        if kind in ("prefill_chunk", "spec_verify"):
            require[("cp", "collective-permute")] = {
                "dtypes": wide | {"s32", "u32"},
                "note": "the prefill query ring: per-hop rotation of the "
                        "(qh, qph, o, lse, off) carry around cp"}
        else:
            allow[("cp", "collective-permute")] = (
                "XLA-derived rotations (decode itself combines with "
                "psums only)")
        allow[("cp", "all-gather")] = (
            "small XLA-derived gathers (psum rewrites / sampler "
            "plumbing); pool-sized page gathers are the byte-threshold "
            "canary's job, not this inventory's")
        allow[("cp", "reduce-scatter")] = "XLA-derived scatters"
        allow[("cp", "all-to-all")] = "XLA-derived rewrites"

    return {"require": require, "allow": allow, "forbid": forbid}


# -- cross-rank skew attribution (ISSUE 10) ------------------------------

DCN_BANDWIDTH = 25e9   # bytes/s per host NIC, the cross-host default


def kv_transfer_attribution(pages: int, page_bytes_each: int,
                            chip: str = "v5e", link: str = "ici",
                            measured_ms: Optional[float] = None) -> Dict:
    """Price one disaggregated prefill->decode KV page handoff (ISSUE
    19) in the comm-attribution record shape: bytes on the wire are
    EXACTLY pages x page_bytes (the transfer ships whole pages —
    bench.py --fleet asserts its measured per-request bytes against
    this), serialized over the chosen link's alpha-beta terms. `link`:
    'ici' (same-pod reshard, ICI_SPECS bandwidth) or 'dcn' (cross-host,
    the DCN_BANDWIDTH NIC default). `measured_ms` (the transfer span
    from obs.reqtrace's handoff gap, or serving.transfer's in-process
    clock) rides along so reports show expected vs observed; the wire
    is never overlapped with compute — a handoff serializes the
    request's path — so exposed == serialized."""
    if pages < 0 or page_bytes_each < 0:
        raise ValueError(f"pages/page_bytes must be >= 0, got "
                         f"{pages}/{page_bytes_each}")
    if link not in ("ici", "dcn"):
        raise ValueError(f"link must be 'ici' or 'dcn', got {link!r}")
    ici_bw, lat = ICI_SPECS.get(chip, ICI_SPECS["v5e"])
    bw = ici_bw if link == "ici" else DCN_BANDWIDTH
    nbytes = pages * page_bytes_each
    ms = (nbytes / bw + lat) * 1e3
    rec = {
        "name": "kv_page_transfer", "kind": "handoff", "count": 1,
        "bytes_each": nbytes, "serialized_ms": round(ms, 6),
        "hidden_ms": 0.0, "exposed_ms": round(ms, 6),
        "note": f"{pages} pages x {page_bytes_each} B over {link} "
                f"({chip}): the prefill->decode page stream",
        "pages": pages, "page_bytes": page_bytes_each, "link": link,
    }
    if measured_ms is not None:
        rec["measured_ms"] = round(float(measured_ms), 3)
    return rec


def rank_skew(records: List[Dict], tol: float = 0.20) -> Optional[Dict]:
    """Rank cross-rank straggler suspects from per-process phase timings.

    `records` are `rank_phase_stats` events (one per process per run:
    obs/observer.py emits them at close from the goodput buckets, and the
    proc-tagged metrics*.jsonl filenames keep them separable). The failure
    mode this catches is the one ZeRO-3's per-layer gathers and the ring
    overlap are most sensitive to: every collective runs at the pace of
    the SLOWEST rank, so one rank stuck in `data_wait` (a slow host input
    pipeline) or `h2d` (a sick PCIe link) taxes the whole mesh — and an
    aggregate goodput number cannot say WHICH rank.

    Returns None with < 2 records (nothing to compare). Otherwise:
      * per-phase mean/max across ranks and `skew` = max/mean - 1,
      * `suspects`: (process, phase) pairs whose time exceeds the phase
        mean by more than `tol`, ranked by absolute excess seconds (the
        wall-clock the mesh pays for that rank), and
      * `persistent`: processes that are the worst rank in >= 2 phases
        with skew past `tol` — a rank slow across phases is a sick HOST,
        not a noisy measurement.
    """
    by_proc = {}
    for r in records:
        by_proc[int(r["process"])] = {k: float(v)
                                      for k, v in r["phases_s"].items()}
    if len(by_proc) < 2:
        # DISTINCT ranks, not records: two single-process runs in one
        # dir (a re-run staged script) must not render a fake one-rank
        # "cross-rank" table with every skew at 0%
        return None
    phases = sorted({p for ph in by_proc.values() for p in ph})
    out_phases, suspects, worst_count = {}, [], {}
    for phase in phases:
        vals = {proc: ph.get(phase, 0.0) for proc, ph in by_proc.items()}
        mean = sum(vals.values()) / len(vals)
        max_proc = max(vals, key=lambda p: vals[p])
        mx = vals[max_proc]
        skew = (mx / mean - 1.0) if mean > 0 else 0.0
        out_phases[phase] = {"mean_s": round(mean, 6),
                             "max_s": round(mx, 6),
                             "max_process": max_proc,
                             "skew": round(skew, 4)}
        if mean <= 0:
            continue
        if skew > tol:
            worst_count[max_proc] = worst_count.get(max_proc, 0) + 1
        for proc, v in vals.items():
            if v > mean * (1.0 + tol):
                suspects.append({"process": proc, "phase": phase,
                                 "excess_s": round(v - mean, 6),
                                 "ratio": round(v / mean, 4)})
    suspects.sort(key=lambda s: -s["excess_s"])
    return {
        "ranks": len(by_proc),
        "tol": tol,
        "phases": out_phases,
        "suspects": suspects,
        "persistent": sorted(p for p, c in worst_count.items() if c >= 2),
    }
