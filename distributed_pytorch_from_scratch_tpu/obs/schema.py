"""MetricsWriter event-schema versioning + jsonl validation (ISSUE 10).

Every structured `MetricsWriter.event(...)` record now carries a
`schema_version` field, and this module is the one place that says what a
consumer may rely on: `EVENT_REQUIRED` maps each event tag to the fields
`scripts/summarize_run.py` and `scripts/check_bench_regression.py` key on.
Consumers call `validate_jsonl` BEFORE rendering, so a drifted producer
(a renamed field, a tag emitted without its contract) fails LOUDLY in the
summary instead of silently dropping a section — the exact rot mode the
r4/r5 post-mortems hit with regexes over free-form logs.

Deliberately dependency-free (no jax, no package imports): the validators
must be importable from standalone scripts and from `training/metrics.py`
without creating an import cycle.
"""

from __future__ import annotations

import json
from typing import Dict, List, Tuple

# Bump when an event's field contract changes incompatibly. Version 2 =
# the ISSUE-10 schema: versioned events + the request-trace/flight/skew
# event family. Version 3 = the ISSUE-12 live-telemetry family
# (telemetry_snapshot / fleet_rollup / rotated continuations) plus the
# cross-process request_trace fields (process, t0_wall, clock_offset_ms).
# Version 4 = the ISSUE-15 measured-attribution family
# (profile_attribution / hbm_watermark).
# Version 5 = the ISSUE-16 control-plane family: the decision ledger
# (tuning_decision / controller_decision) every --control advise/act
# actuation lands in.
# Version 6 = the ISSUE-17 run-forensics family: run_card (the archive
# index's normalized per-run summary) and run_diff (the pairwise
# forensic report obs_diff / check_bench_regression --explain emit).
# Version 7 = the ISSUE-20 elastic-reshard family: reshard_event (one
# any-layout->any-layout redistribution — elastic resume, fleet replica
# restart at a new width, or the offline CLI — with its plan summary).
# (Version 1 is retroactively "any pre-versioned event".)
EVENT_SCHEMA_VERSION = 7

# tag -> fields a consumer may key on (presence contract, not types).
# Only EVENT tags appear here — scalar ({"tag", "value", "step"}) and text
# records are TensorBoard-shaped and stay unversioned.
EVENT_REQUIRED: Dict[str, Tuple[str, ...]] = {
    "goodput_summary": ("wall_s", "buckets_s", "goodput", "steps"),
    "cost_analysis": ("flops",),
    "serving_summary": ("requests", "completed", "tokens_per_sec"),
    "paged_kv_stats": ("page_size", "num_pages", "kv_util_mean"),
    "spec_decode_stats": ("speculate_k", "spec_rounds"),
    "serve_request": ("rid", "generated"),
    # -- ISSUE 10: the request-scoped / rank-scoped family ---------------
    "request_trace": ("rid", "trace_id", "spans", "total_ms"),
    "request_exemplars": ("k", "worst_ttft", "worst_tpot"),
    "rank_phase_stats": ("process", "phases_s", "steps"),
    "sentinel/nonfinite": ("reason",),
    "watchdog/stall": ("process", "stalled_for"),
    # -- ISSUE 12: the live-telemetry family -----------------------------
    # periodic exporter registry mirror (obs/telemetry.py); the fleet
    # collector keys on both maps and the producing process index
    "telemetry_snapshot": ("gauges", "counters", "process"),
    # fleet-level aggregation (obs/collector.py); consumers key on the
    # proc count and the cross-proc attainment map
    "fleet_rollup": ("procs", "slo_attainment"),
    # size-based MetricsWriter rotation: the LAST record of a rotated-out
    # file names its continuation; tailers follow `next`
    "rotated": ("next",),
    # -- ISSUE 15: the measured-attribution family -----------------------
    # one parsed jax.profiler capture (training/metrics.py sampler paths
    # via obs/profparse): consumers key on the capture dir, what armed it
    # (duty / anomaly:<tag> / breakdown), and the measured phase-ms map
    # (empty + `error` when the capture failed to parse — still an event,
    # never a silent drop)
    "profile_attribution": ("capture", "trigger", "phases"),
    # live HBM watermark snapshot: `devices` is the per-device
    # memory_stats list, EMPTY with available=false on a statless
    # backend — the silent-zero fix exports 'unavailable' loudly instead
    # of a fake 0-byte watermark
    "hbm_watermark": ("devices", "available"),
    # -- ISSUE 16: the control-plane / decision-ledger family ------------
    # one RetuneAdvisor proposal (obs/control.py): which knob, old->new,
    # the evidence that justified it (per-phase drift ms, HBM headroom,
    # capture id), whether the run was allowed to act on it, and whether
    # it actually did (applied=false under --control advise)
    "tuning_decision": ("knob", "old", "new", "evidence", "mode",
                        "applied"),
    # one online SLO/admission adaptation (serving/controller.py):
    # cross-linked to the telemetry snapshot that triggered it via
    # `snapshot_seq`, so the ledger can replay trigger -> action
    "controller_decision": ("knob", "old", "new", "trigger", "mode",
                            "applied", "snapshot_seq"),
    # -- ISSUE 17: the run-forensics family ------------------------------
    # one normalized run from the archive index (obs/runindex.py):
    # consumers key on which run it is, what shape it came from
    # (bench / multichip / session), and the outage classification —
    # `outage` true means the card can NEVER be a baseline, and
    # baseline_eligible makes that machine-checkable
    "run_card": ("run", "kind", "outage", "baseline_eligible"),
    # one pairwise forensic report (obs/rundiff.py): the config delta
    # joined to its measured consequences, with the ranked suspects list
    "run_diff": ("run_a", "run_b", "config_delta", "suspects"),
    # -- ISSUE 20: the elastic-reshard family ----------------------------
    # one layout redistribution (reshard/): the source and target layout
    # signatures, the bytes the plan actually moved, the per-op schedule
    # counts, and the wall time — forensics joins this into run lineage
    # ("this run's params came from THAT layout")
    "reshard_event": ("src_layout", "dst_layout", "bytes_moved",
                      "plan_ops", "wall_ms"),
}


def is_event_record(rec: dict) -> bool:
    """Structured event vs a scalar/text record: events have a tag but
    neither a scalar `value` nor a `text` payload."""
    return ("tag" in rec and "value" not in rec and "text" not in rec)


def validate_record(rec: dict) -> List[str]:
    """Problems with one parsed record (empty list = fine). Scalar/text
    records always pass; unknown event tags only need a sane version."""
    if not isinstance(rec, dict):
        return ["record is not a JSON object"]
    if "tag" not in rec:
        return ["record has no 'tag'"]
    if not is_event_record(rec):
        return []
    tag = rec["tag"]
    problems = []
    v = rec.get("schema_version")
    if v is None:
        problems.append(f"{tag}: missing schema_version (pre-v"
                        f"{EVENT_SCHEMA_VERSION} writer? regenerate, or "
                        f"treat fields as best-effort)")
    elif not isinstance(v, int) or v < 1:
        problems.append(f"{tag}: schema_version {v!r} is not a positive int")
    elif v > EVENT_SCHEMA_VERSION:
        problems.append(f"{tag}: schema_version {v} is NEWER than this "
                        f"reader ({EVENT_SCHEMA_VERSION}) — update the "
                        f"consumer before trusting its rendering")
    for field in EVENT_REQUIRED.get(tag, ()):
        if field not in rec:
            problems.append(f"{tag}: missing required field {field!r}")
    return problems


def validate_jsonl(path: str, max_problems: int = 20) -> List[str]:
    """Validate every line of a metrics*.jsonl file; returns problem
    strings prefixed with the line number (capped at `max_problems` so a
    wholly drifted file does not flood the summary)."""
    problems: List[str] = []
    with open(path, errors="replace") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                problems.append(f"line {lineno}: unparseable JSON")
                continue
            problems.extend(f"line {lineno}: {p}"
                            for p in validate_record(rec))
            if len(problems) >= max_problems:
                problems.append(f"... (stopped at {max_problems} problems)")
                return problems
    return problems
