"""Cross-run diff engine + trajectory changepoint triage (ISSUE 17,
obs v6).

`runindex` says what each run IS; this module says what CHANGED between
two of them and — the part a bare config diff can't do — which measured
phase paid for it:

* `config_delta` joins the two RunCards' provenance stamps. A legacy
  side (no fingerprint) is reported loudly as unavailable, never as a
  silent None == None match.
* `phase_deltas` compares per-phase measured ms (the PR 14
  measured/analytic reconciles, falling back to duty-cycle capture
  phases), against a per-phase **noise floor** derived from the variance
  across each card's duty-cycle captures — a delta inside the floor is
  noise, not a finding.
* `suspects` ranks "this knob changed and this phase paid for it":
  every changed knob is joined to its affine phases (KNOB_PHASES);
  significant phase deltas no changed knob claims are reported as
  code/environment suspects (the git_rev delta owns them); changed
  knobs with no measured consequence rank last.
* `collective_diff` / `ledger_diff` / `hbm_delta` cover the graftcheck
  contract inventory, the PR 16 decision ledger, and the HBM watermark.
* the trajectory layer (`changepoint`, `trajectory_report`) generalizes
  the pairwise gate to the full outage-aware trajectory with a stdlib
  CUSUM-style step test that NAMES the run that moved each metric.

Stdlib-only, importable standalone next to runindex/schema.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

try:  # package import (obs consumers) vs obs-dir-on-sys.path (scripts)
    from . import runindex
    from .schema import EVENT_SCHEMA_VERSION
except ImportError:  # pragma: no cover - exercised via scripts
    import runindex
    from schema import EVENT_SCHEMA_VERSION

# knob -> the measured phases it plausibly moves. The join is advisory
# (a suspect, not a verdict): pages_per_block changes the page-copy
# granularity, bucket sizing changes the DP collective schedule, etc.
# Phases use the profparse MEASURED_PHASES taxonomy.
KNOB_PHASES: Dict[str, Tuple[str, ...]] = {
    "pages_per_block": ("copy", "compute"),
    "page_size": ("copy",),
    "paged_attn": ("copy", "compute"),
    "kv_dtype": ("copy", "convert"),
    "decode_weight_dtype": ("convert", "compute"),
    "prefill_chunk": ("host_gap", "compute"),
    "speculate_k": ("compute", "host_gap"),
    "steps_per_dispatch": ("host_gap",),
    "slots": ("host_gap",),
    "max_queue": ("host_gap",),
    "batch": ("compute",),
    "seqlen": ("compute",),
    "remat": ("compute",),
    "dp_reduce_bucket_mb": ("all-reduce", "reduce-scatter",
                            "collective-permute"),
    "dp_reduce_dtype": ("all-reduce", "reduce-scatter",
                        "collective-permute"),
    "zero": ("all-gather", "reduce-scatter"),
    "zero_stage": ("all-gather", "reduce-scatter"),
    "tp_overlap": ("collective-permute", "all-reduce", "all-gather"),
    "sequence_parallel": ("all-gather", "reduce-scatter", "all-reduce"),
}

# a phase delta below this many ms can never be significant, whatever
# the capture variance claims (two captures that happen to agree to a
# microsecond must not produce a zero floor)
MIN_FLOOR_MS = 0.05
# with fewer than 2 captures there is no variance estimate: fall back
# to this fraction of the baseline phase ms
DEFAULT_REL_FLOOR = 0.10


# -------------------------------------------------------------- config delta --

def config_delta(card_a: dict, card_b: dict) -> Dict[str, Any]:
    """Joined config view of two cards. When either side is legacy the
    delta is explicitly unavailable with a note naming the run — the
    diff must never pretend two unknown configs are identical."""
    fp_a = card_a.get("config_fingerprint")
    fp_b = card_b.get("config_fingerprint")
    out: Dict[str, Any] = {"fingerprint_a": fp_a, "fingerprint_b": fp_b,
                           "available": True, "changed": {},
                           "only_a": [], "only_b": [], "notes": []}
    legacy = [c["run"] for c in (card_a, card_b)
              if c.get("config_fingerprint") is None]
    if legacy:
        out["available"] = False
        out["notes"].append(
            f"config delta unavailable: {runindex.LEGACY_NOTE} on "
            f"{', '.join(legacy)}")
        return out
    if fp_a == fp_b:
        out["notes"].append("fingerprints match — same knobs")
        return out
    cfg_a = card_a.get("config") or {}
    cfg_b = card_b.get("config") or {}
    if not cfg_a or not cfg_b:
        out["notes"].append("fingerprints differ but a full config is "
                            "missing — knob-level delta unavailable")
        return out
    for k in sorted(set(cfg_a) | set(cfg_b)):
        if k not in cfg_a:
            out["only_b"].append(k)
        elif k not in cfg_b:
            out["only_a"].append(k)
        elif cfg_a[k] != cfg_b[k]:
            out["changed"][k] = [cfg_a[k], cfg_b[k]]
    return out


# -------------------------------------------------------------- phase deltas --

def _per_step_phases(entry: dict) -> Optional[Dict[str, float]]:
    phases = entry.get("phases")
    if not isinstance(phases, dict) or not phases:
        return None
    steps = entry.get("steps")
    div = float(steps) if isinstance(steps, (int, float)) and steps else 1.0
    return {p: v / div for p, v in phases.items()
            if isinstance(v, (int, float))}


def card_phases(card: dict) -> Optional[Dict[str, float]]:
    """Per-step phase ms for a card: the record's measured/analytic
    reconcile wins (already per-step); else the mean across duty-cycle
    capture events."""
    mva = card.get("measured_vs_analytic")
    if isinstance(mva, dict) and isinstance(mva.get("phases"), dict):
        return {p: v for p, v in mva["phases"].items()
                if isinstance(v, (int, float))}
    samples = [s for s in (_per_step_phases(e)
                           for e in card.get("profile_phases") or [])
               if s]
    if not samples:
        return None
    acc: Dict[str, List[float]] = {}
    for s in samples:
        for p, v in s.items():
            acc.setdefault(p, []).append(v)
    return {p: sum(vs) / len(vs) for p, vs in acc.items()}


def noise_floor(card: dict) -> Dict[str, float]:
    """Per-phase noise floor (ms) = population std across the card's
    duty-cycle captures. Needs >= 2 captures; phases with fewer samples
    get no entry (callers fall back to DEFAULT_REL_FLOOR)."""
    acc: Dict[str, List[float]] = {}
    for entry in card.get("profile_phases") or []:
        s = _per_step_phases(entry)
        if s:
            for p, v in s.items():
                acc.setdefault(p, []).append(v)
    floors = {}
    for p, vs in acc.items():
        if len(vs) >= 2:
            mean = sum(vs) / len(vs)
            floors[p] = max(
                math.sqrt(sum((v - mean) ** 2 for v in vs) / len(vs)),
                MIN_FLOOR_MS)
    return floors


def phase_deltas(card_a: dict, card_b: dict) -> List[Dict[str, Any]]:
    """Per-phase measured deltas b - a with per-phase noise floors.
    Each row: {phase, a_ms, b_ms, delta_ms, delta_pct, floor_ms,
    significant}. Phases only one side measured are listed with
    significant=None — visible, never silently dropped."""
    pa, pb = card_phases(card_a) or {}, card_phases(card_b) or {}
    floors_a, floors_b = noise_floor(card_a), noise_floor(card_b)
    rows = []
    for phase in sorted(set(pa) | set(pb)):
        a, b = pa.get(phase), pb.get(phase)
        if a is None or b is None:
            rows.append({"phase": phase, "a_ms": a, "b_ms": b,
                         "delta_ms": None, "delta_pct": None,
                         "floor_ms": None, "significant": None})
            continue
        floor = max(floors_a.get(phase, 0.0), floors_b.get(phase, 0.0))
        if floor == 0.0:
            floor = max(abs(a) * DEFAULT_REL_FLOOR, MIN_FLOOR_MS)
        delta = b - a
        rows.append({
            "phase": phase,
            "a_ms": round(a, 4), "b_ms": round(b, 4),
            "delta_ms": round(delta, 4),
            "delta_pct": round(100.0 * delta / a, 2) if a else None,
            "floor_ms": round(floor, 4),
            "significant": abs(delta) > floor,
        })
    return rows


# --------------------------------------- collectives / ledger / hbm deltas --

def collective_diff(card_a: dict, card_b: dict) -> Dict[str, Any]:
    """Graftcheck contract inventory diff: which expected_collectives /
    trace contracts flipped, appeared, or vanished between the runs."""
    ca = (card_a.get("collectives") or {}).get("contracts") or {}
    cb = (card_b.get("collectives") or {}).get("contracts") or {}
    if not ca and not cb:
        return {"available": False, "newly_failing": [],
                "newly_passing": [], "added": [], "removed": []}
    return {
        "available": True,
        "newly_failing": sorted(n for n in ca.keys() & cb.keys()
                                if ca[n] and not cb[n]),
        "newly_passing": sorted(n for n in ca.keys() & cb.keys()
                                if not ca[n] and cb[n]),
        "added": sorted(cb.keys() - ca.keys()),
        "removed": sorted(ca.keys() - cb.keys()),
    }


def ledger_diff(card_a: dict, card_b: dict) -> Dict[str, Any]:
    """Decision-ledger delta (PR 16): per-knob decision/applied counts
    on each side — a run whose controller suddenly started actuating a
    knob is itself a forensic lead."""
    ka = (card_a.get("ledger") or {}).get("knobs") or {}
    kb = (card_b.get("ledger") or {}).get("knobs") or {}
    rows = []
    for knob in sorted(set(ka) | set(kb)):
        a, b = ka.get(knob) or {}, kb.get(knob) or {}
        rows.append({"knob": knob,
                     "a": {"count": a.get("count", 0),
                           "applied": a.get("applied", 0),
                           "last": a.get("last")},
                     "b": {"count": b.get("count", 0),
                           "applied": b.get("applied", 0),
                           "last": b.get("last")}})
    return {"decisions_a": (card_a.get("ledger") or {}).get("decisions", 0),
            "decisions_b": (card_b.get("ledger") or {}).get("decisions", 0),
            "knobs": rows}


def hbm_delta(card_a: dict, card_b: dict) -> Optional[Dict[str, Any]]:
    ha, hb = card_a.get("hbm"), card_b.get("hbm")
    if not isinstance(ha, dict) and not isinstance(hb, dict):
        return None
    pa = (ha or {}).get("peak_bytes")
    pb = (hb or {}).get("peak_bytes")
    out = {"a_peak_bytes": pa, "b_peak_bytes": pb, "delta_bytes": None}
    if isinstance(pa, (int, float)) and isinstance(pb, (int, float)):
        out["delta_bytes"] = pb - pa
    return out


# ------------------------------------------------------------------ suspects --

def suspects(cfg_delta: dict, phases: List[Dict[str, Any]],
             card_a: dict, card_b: dict) -> List[Dict[str, Any]]:
    """Ranked "this knob changed and this phase paid for it" list.

    Ranking: knob-claimed significant deltas by |delta| / floor desc,
    then significant deltas no changed knob claims (attributed to the
    code/env delta), then changed knobs with no measured consequence."""
    sig = {r["phase"]: r for r in phases if r.get("significant")}
    changed = cfg_delta.get("changed") or {}
    claimed_phases = set()
    claimed, unclaimed, silent = [], [], []
    for knob, (old, new) in sorted(changed.items()):
        hit = False
        for phase in KNOB_PHASES.get(knob, ()):
            row = sig.get(phase)
            if row is None:
                continue
            hit = True
            claimed_phases.add(phase)
            claimed.append({
                "knob": knob, "old": old, "new": new, "phase": phase,
                "delta_ms": row["delta_ms"],
                "delta_pct": row["delta_pct"],
                "floor_ms": row["floor_ms"],
                "score": round(abs(row["delta_ms"]) /
                               max(row["floor_ms"], MIN_FLOOR_MS), 2),
                "verdict": f"{knob} changed {old!r} -> {new!r} and "
                           f"{phase} paid {row['delta_ms']:+.3f} ms/step",
            })
        if not hit:
            silent.append({
                "knob": knob, "old": old, "new": new, "phase": None,
                "delta_ms": None, "delta_pct": None, "floor_ms": None,
                "score": 0.0,
                "verdict": f"{knob} changed {old!r} -> {new!r} with no "
                           f"measured phase consequence above the noise "
                           f"floor",
            })
    for phase, row in sorted(sig.items()):
        if phase in claimed_phases:
            continue
        rev_a = card_a.get("git_rev") or "?"
        rev_b = card_b.get("git_rev") or "?"
        unclaimed.append({
            "knob": None, "old": None, "new": None, "phase": phase,
            "delta_ms": row["delta_ms"], "delta_pct": row["delta_pct"],
            "floor_ms": row["floor_ms"],
            "score": round(abs(row["delta_ms"]) /
                           max(row["floor_ms"], MIN_FLOOR_MS), 2),
            "verdict": f"{phase} moved {row['delta_ms']:+.3f} ms/step "
                       f"with no changed knob claiming it — code or "
                       f"environment delta (git {rev_a} -> {rev_b})",
        })
    claimed.sort(key=lambda s: -s["score"])
    unclaimed.sort(key=lambda s: -s["score"])
    return claimed + unclaimed + silent


# ------------------------------------------------------------------ diff doc --

def diff_runs(card_a: dict, card_b: dict) -> Dict[str, Any]:
    """The pairwise forensic report: one versioned run_diff document
    joining the config delta to its measured consequences."""
    cfg = config_delta(card_a, card_b)
    phases = phase_deltas(card_a, card_b)
    doc: Dict[str, Any] = {
        "tag": "run_diff",
        "schema_version": EVENT_SCHEMA_VERSION,
        "run_a": card_a.get("run"),
        "run_b": card_b.get("run"),
        "git_rev_a": card_a.get("git_rev"),
        "git_rev_b": card_b.get("git_rev"),
        "outage_a": card_a.get("outage_reason"),
        "outage_b": card_b.get("outage_reason"),
        "config_delta": cfg,
        "metric_deltas": [],
        "phase_deltas": phases,
        "collectives": collective_diff(card_a, card_b),
        "ledger": ledger_diff(card_a, card_b),
        "hbm": hbm_delta(card_a, card_b),
        "suspects": suspects(cfg, phases, card_a, card_b),
        "notes": list(cfg.get("notes") or []),
    }
    ma, mb = card_a.get("metrics") or {}, card_b.get("metrics") or {}
    for f in runindex.HEADLINE_FIELDS:
        if f in ("metric", "unit"):
            continue
        a, b = ma.get(f), mb.get(f)
        if not isinstance(a, (int, float)) or not isinstance(b,
                                                             (int, float)):
            continue
        doc["metric_deltas"].append({
            "field": f, "a": a, "b": b, "delta": round(b - a, 6),
            "delta_pct": round(100.0 * (b - a) / a, 2) if a else None,
        })
    for c in (card_a, card_b):
        if c.get("outage"):
            doc["notes"].append(
                f"{c['run']} is an OUTAGE ({c['outage_reason']}) — its "
                f"side of the diff is whatever the record carried, not a "
                f"trustworthy measurement")
    return doc


def format_diff(doc: dict) -> List[str]:
    """Human rendering of a run_diff doc (obs_diff / --explain stderr)."""
    lines = [f"run diff: {doc['run_a']} -> {doc['run_b']} "
             f"(git {doc.get('git_rev_a') or '?'} -> "
             f"{doc.get('git_rev_b') or '?'})"]
    cfg = doc.get("config_delta") or {}
    if not cfg.get("available"):
        lines.append("  config: (delta unavailable)")
    elif cfg.get("changed"):
        for k, (old, new) in sorted(cfg["changed"].items()):
            lines.append(f"  config: {k}: {old!r} -> {new!r}")
        for side, keys in (("a", cfg.get("only_a")),
                           ("b", cfg.get("only_b"))):
            if keys:
                lines.append(f"  config: only on {side}: "
                             f"{', '.join(keys)}")
    else:
        lines.append("  config: no knob changed")
    for row in doc.get("metric_deltas") or []:
        pct = (f" ({row['delta_pct']:+.1f}%)"
               if row.get("delta_pct") is not None else "")
        lines.append(f"  metric {row['field']}: {row['a']} -> "
                     f"{row['b']}{pct}")
    for row in doc.get("phase_deltas") or []:
        if row.get("significant") is None:
            lines.append(f"  phase {row['phase']}: only one side "
                         f"measured it (a={row['a_ms']}, b={row['b_ms']})")
        elif row["significant"]:
            lines.append(f"  phase {row['phase']}: {row['a_ms']} -> "
                         f"{row['b_ms']} ms/step "
                         f"({row['delta_ms']:+.3f}, floor "
                         f"{row['floor_ms']:.3f})")
    col = doc.get("collectives") or {}
    for key in ("newly_failing", "newly_passing", "added", "removed"):
        if col.get(key):
            lines.append(f"  collectives {key.replace('_', ' ')}: "
                         f"{', '.join(col[key])}")
    hbm = doc.get("hbm")
    if hbm and hbm.get("delta_bytes") is not None:
        lines.append(f"  hbm peak: {hbm['a_peak_bytes']:,} -> "
                     f"{hbm['b_peak_bytes']:,} "
                     f"({hbm['delta_bytes']:+,} bytes)")
    sus = doc.get("suspects") or []
    if sus:
        lines.append("  suspects (ranked):")
        for i, s in enumerate(sus, 1):
            lines.append(f"    {i}. {s['verdict']}")
    else:
        lines.append("  suspects: none — no knob change joined to a "
                     "significant phase delta")
    for note in doc.get("notes") or []:
        lines.append(f"  note: {note}")
    return lines


# ---------------------------------------------------------------- trajectory --

def changepoint(values: Sequence[float], min_seg: int = 2,
                threshold: float = 4.0) -> Optional[Dict[str, Any]]:
    """Single-changepoint step test (stdlib CUSUM flavor): for every
    split k the statistic is |mean_after - mean_before| over the pooled
    std error, with a scale floor so a perfectly flat series can't
    manufacture an infinite score. Returns the best split when it clears
    `threshold`, else None (no detectable step)."""
    vals = [float(v) for v in values]
    n = len(vals)
    if n < 2 * min_seg:
        return None
    best = None
    for k in range(min_seg, n - min_seg + 1):
        a, b = vals[:k], vals[k:]
        ma = sum(a) / len(a)
        mb = sum(b) / len(b)
        pooled = (sum((x - ma) ** 2 for x in a)
                  + sum((x - mb) ** 2 for x in b)) / max(n - 2, 1)
        scale = max(math.sqrt(pooled),
                    0.01 * (abs(ma) + abs(mb)) / 2.0, 1e-9)
        se = scale * math.sqrt(1.0 / len(a) + 1.0 / len(b))
        score = abs(mb - ma) / se
        if best is None or score > best["score"]:
            best = {"index": k, "score": round(score, 2),
                    "before_mean": round(ma, 4),
                    "after_mean": round(mb, 4)}
    if best is None or best["score"] < threshold:
        return None
    best["direction"] = ("up" if best["after_mean"] > best["before_mean"]
                         else "down")
    return best


def trajectory_report(cards: Sequence[dict], threshold: float = 4.0
                      ) -> List[Dict[str, Any]]:
    """Outage-aware trajectory over a card sequence (committed round
    order): outage cards are LISTED but never points — the BENCH_r02–r05
    tunnel outages must not read as a throughput collapse. One report
    per metric unit, with the changepoint (if any) naming the run whose
    arrival moved the metric."""
    groups: Dict[str, Dict[str, Any]] = {}
    for card in cards:
        m = card.get("metrics") or {}
        if card.get("outage"):
            unit = m.get("unit") or "(unknown)"
            g = groups.setdefault(unit, {"unit": unit, "metric": None,
                                         "series": [], "outages": []})
            g["outages"].append({"run": card.get("run"),
                                 "reason": card.get("outage_reason")})
            continue
        if not isinstance(m.get("value"), (int, float)):
            continue
        unit = m.get("unit") or "(unknown)"
        g = groups.setdefault(unit, {"unit": unit, "metric": None,
                                     "series": [], "outages": []})
        g["metric"] = g["metric"] or m.get("metric")
        g["series"].append({"run": card.get("run"),
                            "value": m["value"]})
    reports = []
    for unit in sorted(groups):
        g = groups[unit]
        cp = changepoint([pt["value"] for pt in g["series"]],
                         threshold=threshold)
        if cp is not None:
            cp = dict(cp, run=g["series"][cp["index"]]["run"])
        g["changepoint"] = cp
        reports.append(g)
    return reports


def format_trajectory(reports: Sequence[dict]) -> List[str]:
    lines = []
    for g in reports:
        lines.append(f"trajectory [{g['unit']}] "
                     f"{g.get('metric') or ''}".rstrip())
        for pt in g["series"]:
            lines.append(f"  {pt['run']}: {pt['value']:,}")
        for o in g["outages"]:
            lines.append(f"  {o['run']}: outage ({o['reason']}) — "
                         f"excluded from the series")
        cp = g.get("changepoint")
        if cp:
            lines.append(f"  CHANGEPOINT at {cp['run']}: mean "
                         f"{cp['before_mean']:,} -> {cp['after_mean']:,} "
                         f"({cp['direction']}, score {cp['score']})")
        elif len(g["series"]) >= 4:
            lines.append("  no detectable step")
        else:
            lines.append(f"  too few healthy points "
                         f"({len(g['series'])}) for a step test")
    return lines
