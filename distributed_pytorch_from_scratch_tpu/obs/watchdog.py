"""Heartbeat/hang watchdog: scream when the training loop stops progressing.

Multi-host hangs are the nastiest failure mode of collective-based training:
one process misses a collective and every other process blocks inside XLA
forever, producing no output and no error (the reference has exactly this
failure surface via NCCL and no watchdog either). The watchdog is a daemon
thread per process that watches a heartbeat the train loop taps on every
dispatch and on every span start/end. A phase that legitimately runs longer
than the timeout (a big model's first compile) still trips the report —
deliberately: the report names the in-flight phase (`last activity
'compile'`), and the matching `watchdog/recovered` line when it completes
distinguishes "slow but alive" from a true hang, which never recovers. It
keeps shouting at every further timeout window.

Deliberately NO collectives on the watchdog thread: a stalled process
gathering liveness over the same fabric that is hung would deadlock too.
Each process reports locally; the per-process `metrics*.jsonl` /
stdout streams are the cross-host view.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class HangWatchdog:
    def __init__(self, timeout_s: float, process_index: int = 0,
                 writer=None, tracer=None, flight=None,
                 on_stall: Optional[Callable[[dict], None]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 poll_s: Optional[float] = None):
        self.timeout_s = timeout_s
        self.process_index = process_index
        self.writer = writer
        self.tracer = tracer
        self.flight = flight  # obs.flight.FlightRecorder — flushed on stall
        self.on_stall = on_stall
        self._clock = clock
        self._lock = threading.Lock()
        self._last_beat = clock()
        self._last_step: Optional[int] = None
        self._last_phase = "startup"
        self._stalled = False
        self._stall_started: Optional[float] = None
        self.stall_count = 0
        self._stop = threading.Event()
        self._poll = poll_s if poll_s is not None else max(
            min(timeout_s / 4.0, 10.0), 0.05)
        self._thread = threading.Thread(target=self._watch, daemon=True,
                                        name="hang-watchdog")
        self._thread.start()

    def beat(self, step: Optional[int] = None, phase: str = "step") -> None:
        """Tap the heartbeat. `step` = last COMPLETED step when known;
        span starts beat with their phase and no step."""
        recovered = None
        with self._lock:
            self._last_beat = self._clock()
            self._last_phase = phase
            if step is not None:
                self._last_step = int(step)
            if self._stalled:
                self._stalled = False
                dur = (self._clock() - self._stall_started
                       if self._stall_started is not None else None)
                self._stall_started = None
                recovered = (dur, self._last_step)
        # emit/print OUTSIDE the lock: writer/tracer I/O (and any on_stall
        # callback) must never run while holding it — a callback touching
        # the watchdog would deadlock the beat path and hang the loop
        if recovered is not None:
            dur, last_step = recovered
            self._emit("watchdog/recovered",
                       stalled_for=None if dur is None else round(dur, 3))
            print(f"watchdog[p{self.process_index}]: progress resumed"
                  + (f" after {dur:.1f}s" if dur is not None else "")
                  + (f" (step {last_step})"
                     if last_step is not None else ""))

    def _emit(self, tag: str, **fields) -> None:
        rec = {"process": self.process_index, "last_step": self._last_step,
               "last_phase": self._last_phase, **fields}
        if self.writer is not None:
            self.writer.event(tag, **rec)
        if self.tracer is not None:
            self.tracer.instant(tag, **rec)
        if self.on_stall is not None and tag == "watchdog/stall":
            self.on_stall(rec)

    def _watch(self) -> None:
        while not self._stop.wait(self._poll):
            with self._lock:
                stalled_for = self._clock() - self._last_beat
                if stalled_for < self.timeout_s:
                    continue
                # re-arm so the next shout comes one full window later;
                # remember when the stall BEGAN so recovery can report the
                # true duration across multiple shout windows
                if not self._stalled:
                    self._stall_started = self._last_beat
                self._last_beat = self._clock()
                self._stalled = True
                self.stall_count += 1
                last_step, last_phase = self._last_step, self._last_phase
            # I/O and the on_stall callback run lock-free (see beat());
            # the flight ring freezes FIRST so the dump shows the system
            # state that preceded the stall, cross-linked from the event
            flight_path = None
            if self.flight is not None:
                flight_path = self.flight.dump(
                    {"kind": "watchdog_stall",
                     "process": self.process_index,
                     "last_step": last_step, "last_phase": last_phase,
                     "stalled_for": round(stalled_for, 3)},
                    tag="watchdog")
            self._emit("watchdog/stall", stalled_for=round(stalled_for, 3),
                       flight_dump=flight_path)
            print(f"WATCHDOG[p{self.process_index}]: no progress for "
                  f"{stalled_for:.1f}s — last completed step "
                  f"{last_step}, last activity "
                  f"'{last_phase}' (may still be executing — a "
                  f"'recovered' line follows if it finishes). If every "
                  f"process reports the same step, suspect the input "
                  f"pipeline; if they differ, a collective is hung."
                  + (f" Flight dump: {flight_path}" if flight_path else ""),
                  flush=True)

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
