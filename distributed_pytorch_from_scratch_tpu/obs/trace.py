"""Span-based step-timeline tracer emitting Chrome trace-event JSON.

Every span becomes one complete ("ph": "X") trace event streamed to
`trace.jsonl` (one JSON object per line — crash-safe, grep-able) and, at
`close()`, collected into a Perfetto/chrome://tracing-loadable `trace.json`
(`{"traceEvents": [...]}`, events sorted by timestamp).

This is the HOST timeline — what the training loop's wall clock was spent on
(compile, data wait, H2D, dispatch, checkpoint, eval) — complementary to
`jax.profiler` (`training/metrics.py:ProfilerTrace`), which captures the
DEVICE timeline for a short window. The host view is cheap enough to leave on
for a whole run; the device view is not.

Threads map to separate `tid` tracks (the prefetch thread and the async
checkpoint writer show up alongside the main loop); multi-host processes map
to `pid`, so traces from several hosts can be concatenated into one viewer.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Callable, Optional


class SpanTracer:
    """Thread-safe span recorder. `enabled=False` turns every method into a
    cheap no-op so call sites need no guards."""

    def __init__(self, log_dir: str, enabled: bool = True, pid: int = 0,
                 process_name: Optional[str] = None,
                 clock: Callable[[], float] = time.perf_counter):
        self.enabled = enabled
        self.pid = pid
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        self._jsonl = None
        self._closed = False
        self.log_dir = log_dir
        self._jsonl_path = os.path.join(log_dir, "trace.jsonl")
        self._process_name = process_name
        # File creation is LAZY (first emitted event): an invocation that
        # dies in argument/data validation emits nothing and therefore
        # must not touch — let alone rotate away — the previous run's
        # post-mortem timeline.

    def _open_locked(self) -> None:
        """First event: rotate the previous run's files one generation
        back (a --resume or relaunch into the same dir must not truncate
        the preempted run's timeline; ts epochs restart per run, so the
        generations stay separate files) and start the stream. Events go
        straight to disk; close() re-reads the file to build trace.json,
        so host memory stays O(1) over arbitrarily long runs."""
        os.makedirs(self.log_dir, exist_ok=True)
        for name in ("trace.jsonl", "trace.json"):
            old = os.path.join(self.log_dir, name)
            if os.path.exists(old):
                os.replace(old, os.path.join(self.log_dir, name + ".prev"))
        self._jsonl = open(self._jsonl_path, "w")
        if self._process_name:
            self._jsonl.write(json.dumps(
                {"name": "process_name", "ph": "M", "pid": self.pid,
                 "tid": 0, "args": {"name": self._process_name}}) + "\n")

    def now(self) -> float:
        """Clock sample for `complete()` (perf_counter seconds)."""
        return self._clock()

    def _ts_us(self, t: float) -> float:
        return (t - self._t0) * 1e6

    def _emit(self, ev: dict) -> None:
        with self._lock:
            if self._closed:
                return
            if self._jsonl is None:
                self._open_locked()
            self._jsonl.write(json.dumps(ev) + "\n")
            self._jsonl.flush()

    @contextmanager
    def span(self, name: str, cat: Optional[str] = None, **args):
        """Record a complete event covering the with-block."""
        if not self.enabled:
            yield
            return
        t0 = self._clock()
        try:
            yield
        finally:
            self.complete(name, t0, cat=cat, **args)

    def complete(self, name: str, start: float, cat: Optional[str] = None,
                 **args) -> None:
        """Record a complete event from an explicit `now()` start sample —
        for call sites where a with-block does not fit (producer loops)."""
        if not self.enabled:
            return
        end = self._clock()
        ev = {"name": name, "ph": "X", "ts": self._ts_us(start),
              "dur": (end - start) * 1e6, "pid": self.pid,
              "tid": threading.get_ident()}
        if cat:
            ev["cat"] = cat
        if args:
            ev["args"] = args
        self._emit(ev)

    def complete_span(self, name: str, start: float, end: float,
                      cat: Optional[str] = None, tid: Optional[int] = None,
                      **args) -> None:
        """Complete event from two explicit clock samples (same clock as
        `now()`). `tid` overrides the thread id — synthetic per-request
        tracks (obs/reqtrace.py) use it so a request's whole timeline
        renders as one row instead of scattering over host threads."""
        if not self.enabled:
            return
        ev = {"name": name, "ph": "X", "ts": self._ts_us(start),
              "dur": max(end - start, 0.0) * 1e6, "pid": self.pid,
              "tid": threading.get_ident() if tid is None else tid}
        if cat:
            ev["cat"] = cat
        if args:
            ev["args"] = args
        self._emit(ev)

    def flow(self, name: str, phase: str, flow_id: int, t: float,
             tid: Optional[int] = None) -> None:
        """Flow event (`ph` in {"s","t","f"}): draws an arrow between
        tracks in the viewer. The request tracer binds a request's
        enqueue to its retire so a cross-track timeline is followable."""
        if not self.enabled:
            return
        assert phase in ("s", "t", "f"), phase
        ev = {"name": name, "ph": phase, "id": int(flow_id),
              "cat": "request", "ts": self._ts_us(t), "pid": self.pid,
              "tid": threading.get_ident() if tid is None else tid}
        if phase == "f":
            ev["bp"] = "e"  # bind to the enclosing slice
        self._emit(ev)

    def instant(self, name: str, **args) -> None:
        if not self.enabled:
            return
        ev = {"name": name, "ph": "i", "s": "p",
              "ts": self._ts_us(self._clock()), "pid": self.pid,
              "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._emit(ev)

    def counter(self, name: str, value: float) -> None:
        if not self.enabled:
            return
        self._emit({"name": name, "ph": "C",
                    "ts": self._ts_us(self._clock()), "pid": self.pid,
                    "tid": 0, "args": {"value": float(value)}})

    def close(self) -> Optional[str]:
        """Finalise: close the jsonl stream, re-read it, and write the
        events as `trace.json` (sorted by ts). Returns the trace.json
        path, or None when disabled or no event was ever emitted (nothing
        was written OR rotated in that case). Idempotent."""
        if not self.enabled:
            return None
        with self._lock:
            if self._closed:
                return (os.path.join(self.log_dir, "trace.json")
                        if self._jsonl is not None else None)
            self._closed = True
            if self._jsonl is None:  # no events: leave prior runs alone
                return None
            self._jsonl.close()
        # Sort by ts (spans are recorded at END time, so raw order is not
        # monotonic) while keeping memory lean: hold (ts, raw_line) pairs,
        # not parsed event dicts — close() peaks at ~2x the jsonl size
        # instead of the ~10x that a list of dicts would cost.
        events = []
        with open(self._jsonl_path) as f:
            for line in f:
                line = line.strip()
                try:
                    ev = json.loads(line)
                except ValueError:  # torn final line from a hard kill
                    continue
                events.append((ev.get("ts", -1.0), line))
        events.sort(key=lambda p: p[0])
        path = os.path.join(self.log_dir, "trace.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write('{"traceEvents": [')
            f.write(",".join(line for _, line in events))
            f.write('], "displayTimeUnit": "ms"}')
        os.replace(tmp, path)
        return path
