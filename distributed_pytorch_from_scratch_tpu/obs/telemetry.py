"""Live telemetry exporter: in-process gauges/counters over local HTTP
(ISSUE 12).

Obs v2 is post-hoc — goodput summaries, request timelines and rank-skew
tables are read from jsonl AFTER the run ends. The multi-replica fleet
(ROADMAP item 1) needs the LIVE view: is replica 3's queue growing, did
the interactive class's attainment collapse two minutes ago, how many
pages does the fleet have left. This module is the per-process half of
that plane: producers (train loop, serving engines) publish gauges and
counters into a lock-protected registry, and one exporter thread serves
them at `http://127.0.0.1:<port>/metrics.json` (machine JSON) and
`/metrics` (Prometheus text exposition), plus mirrors a periodic
`telemetry_snapshot` event into the MetricsWriter jsonl so the fleet
collector (obs/collector.py) can follow a run live OR post-hoc through
one stream.

Overhead discipline (the "live never costs the hot path" budget):
* a producer update is one lock acquire + one dict store — no I/O, no
  string formatting, no collectives (the watchdog rule: a stalled
  process must never be asked to gather liveness over the fabric that
  stalled it);
* rendering (JSON/Prometheus text) happens on the EXPORTER thread per
  scrape, against a snapshot taken under the lock;
* `rate()` turns a monotone counter into a smoothed per-second gauge
  with two floats of state — producers never compute rates themselves.

Lock discipline (graftcheck `lock-discipline`): every mutation of the
registry dicts and the closed flag holds `_lock`; server/thread handles
are touched only by the owning start()/close() caller thread.
"""

from __future__ import annotations

import json
import re
import threading
import time
from typing import Callable, Dict, Optional

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def prometheus_name(name: str) -> str:
    """`serve/tokens_per_sec` -> `serve_tokens_per_sec` (the exposition
    format forbids '/' and friends); a leading digit gets a '_' prefix."""
    n = _PROM_BAD.sub("_", name)
    return ("_" + n) if n[:1].isdigit() else n


class TelemetryExporter:
    """Thread-safe gauge/counter registry + local HTTP endpoint.

    `writer`/`rollup_interval`: when both are set, a snapshot thread
    mirrors the registry into a `telemetry_snapshot` MetricsWriter event
    every `rollup_interval` seconds (the collector's jsonl food). The
    HTTP server starts only on `start(port)` — the registry alone works
    headless (bench arms that only want the jsonl mirror)."""

    def __init__(self, writer=None, process_index: int = 0,
                 rollup_interval: float = 0.0,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time):
        self.writer = writer
        self.process_index = process_index
        self.rollup_interval = rollup_interval
        self._clock = clock
        self._wall = wall
        self._lock = threading.Lock()
        self._gauges: Dict[str, float] = {}
        self._counters: Dict[str, float] = {}
        self._rate_state: Dict[str, tuple] = {}  # name -> (value, t, ewma)
        self._closed = False
        self._stop = threading.Event()
        self._server = None
        self._server_thread = None
        self._snap_thread = None
        self.port: Optional[int] = None
        self.scrapes = 0
        self.snapshots = 0

    # -- producer API (hot path: one lock + one store) --------------------
    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            if not self._closed:
                self._gauges[name] = float(value)

    def counter(self, name: str, value: float) -> None:
        """Set a monotone cumulative counter to its CURRENT total (the
        engines already keep the totals; re-deriving increments would add
        state for nothing)."""
        with self._lock:
            if not self._closed:
                self._counters[name] = float(value)

    def count(self, name: str, inc: float = 1.0) -> None:
        with self._lock:
            if not self._closed:
                self._counters[name] = self._counters.get(name, 0.0) + inc

    def rate(self, name: str, cumulative: float,
             decay: float = 0.7) -> None:
        """Publish `name` as a smoothed per-second rate of a monotone
        cumulative total (EWMA over successive calls; the first call just
        seeds the state). Gauge + counter in one: `<name>` is the rate,
        the raw total rides as `<name>_total`."""
        now = self._clock()
        with self._lock:
            if self._closed:
                return
            prev = self._rate_state.get(name)
            if prev is not None:
                last_v, last_t, ewma = prev
                dt = now - last_t
                if dt > 1e-6:
                    inst = max(cumulative - last_v, 0.0) / dt
                    ewma = (inst if ewma is None
                            else decay * ewma + (1 - decay) * inst)
                    self._gauges[name] = ewma
                    self._rate_state[name] = (cumulative, now, ewma)
            else:
                self._rate_state[name] = (cumulative, now, None)
            self._counters[name + "_total"] = float(cumulative)

    # -- consumer API -----------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {"ts_wall": self._wall(),
                    "process": self.process_index,
                    "gauges": dict(self._gauges),
                    "counters": dict(self._counters)}

    def prometheus(self) -> str:
        """Prometheus text exposition v0.0.4 of the current registry."""
        snap = self.snapshot()
        lines = []
        for name, v in sorted(snap["gauges"].items()):
            n = prometheus_name(name)
            lines.append(f"# TYPE {n} gauge")
            lines.append(f'{n}{{process="{snap["process"]}"}} {v:g}')
        for name, v in sorted(snap["counters"].items()):
            n = prometheus_name(name)
            lines.append(f"# TYPE {n} counter")
            lines.append(f'{n}{{process="{snap["process"]}"}} {v:g}')
        return "\n".join(lines) + "\n"

    # -- the exporter thread ----------------------------------------------
    def start(self, port: int) -> int:
        """Bind 127.0.0.1:`port` (0 = ephemeral; the bound port is
        returned and kept in `self.port`) and serve /metrics.json +
        /metrics from a daemon thread. A busy/forbidden port refuses
        LOUDLY up front — a run whose scrapes silently 404 is worse than
        no run (the require_writable_dir convention)."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib casing)
                with exporter._lock:   # handler threads are concurrent
                    exporter.scrapes += 1
                if self.path.startswith("/metrics.json"):
                    body = json.dumps(exporter.snapshot()).encode()
                    ctype = "application/json"
                elif self.path.startswith("/metrics"):
                    body = exporter.prometheus().encode()
                    ctype = "text/plain; version=0.0.4"
                else:
                    self.send_error(404, "try /metrics or /metrics.json")
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes must not spam stdout
                pass

        try:
            self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        except OSError as e:
            raise SystemExit(
                f"--metrics_port {port}: cannot bind 127.0.0.1:{port} "
                f"({type(e).__name__}: {e}) — the port is busy or "
                f"forbidden; pick a free port (0 = ephemeral) or drop "
                f"the flag")
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._server_thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="telemetry-exporter")
        self._server_thread.start()
        if self.writer is not None and self.rollup_interval > 0:
            self._snap_thread = threading.Thread(
                target=self._snapshot_loop, daemon=True,
                name="telemetry-snapshots")
            self._snap_thread.start()
        return self.port

    def _snapshot_loop(self) -> None:
        while not self._stop.wait(self.rollup_interval):
            self._emit_snapshot()

    def _emit_snapshot(self) -> None:
        snap = self.snapshot()
        with self._lock:
            self.snapshots += 1
        self.writer.event("telemetry_snapshot", gauges=snap["gauges"],
                          counters=snap["counters"],
                          process=snap["process"])

    def emit_snapshot(self) -> int:
        """Land ONE snapshot event now and return its 1-based sequence
        number (how many this process has emitted, in stream order) —
        the control plane's cross-link: a `controller_decision` stores
        this as `snapshot_seq`, so the post-hoc ledger joins the
        decision to the exact registry state that triggered it (ISSUE
        16). 0 when there is no writer to land the event in."""
        if self.writer is None:
            return 0
        self._emit_snapshot()
        return self.snapshots

    def close(self) -> None:
        """Stop the threads, then land ONE final snapshot event (a run's
        last registry state is the one the post-hoc reader wants — the
        snapshot thread is joined first so it cannot race a duplicate),
        then the registry refuses further writes. Idempotent."""
        with self._lock:
            if self._closed:
                return
        self._stop.set()
        if self._snap_thread is not None:
            self._snap_thread.join(timeout=5.0)
        if self.writer is not None:
            self._emit_snapshot()
        with self._lock:
            self._closed = True
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
        if self._server_thread is not None:
            self._server_thread.join(timeout=5.0)

    def __enter__(self) -> "TelemetryExporter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def fleet_slo_attainment(per_proc_counts) -> dict:
    """Fold per-process SLO counters into FLEET attainment: given an
    iterable of `{class: (completed, hit)}` dicts (one per process), the
    completion-weighted attainment per class — 100% of 2 requests on one
    replica must not mask 40% of 2000 on another. Pure math, shared by
    the collector rollup and the tests' hand-computed check."""
    agg: Dict[str, list] = {}
    for proc in per_proc_counts:
        for cls, (completed, hit) in proc.items():
            a = agg.setdefault(cls, [0, 0])
            a[0] += int(completed)
            a[1] += int(hit)
    return {cls: {"completed": c, "attained": round(h / c, 4) if c else 0.0}
            for cls, (c, h) in sorted(agg.items())}
