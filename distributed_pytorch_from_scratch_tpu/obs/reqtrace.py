"""Per-request span timelines for the serving stack (ISSUE 10).

`loadgen` has always reported TTFT/TPOT percentiles — COUNTS of SLO
misses. This module makes each miss EXPLAINABLE: every request carries a
trace id from submit to retire, and the engines mark phase transitions
(`queued`, `prefill_chunk`, `decode`, `spec_round`, `preempted`, ...) on
its timeline as they happen. The timeline is CONTIGUOUS by construction —
each mark closes the span that started at the previous mark — so the span
sum always equals the request's wall time (submit -> finish = TTFT +
decode wall), and a gap can never hide: whatever the engine was doing from
this request's point of view has a named span.

Memory stays bounded two ways: adjacent same-phase marks COALESCE (a
64-token decode is one span with count=64, its numeric args summed — the
waterfall needs phase totals, not per-token rows), and the retired-record
store is a ring (`max_completed`).

On retire the timeline is emitted three ways:
* a `request_trace` MetricsWriter event (jsonl — the machine-readable
  record `summarize_run.py`'s waterfall and the k-worst exemplars read),
* Chrome-trace spans on a synthetic per-request track in the existing
  `SpanTracer` file, with a flow arrow binding enqueue to retire, so a
  request's life renders alongside the engine's dispatch spans,
* `completed[rid]` for in-process consumers (loadgen's k-worst picker).

Cross-process propagation (ISSUE 12): a request that is prefilled in one
process and decoded in another (the router -> prefill replica -> decode
replica shape the fleet PR needs) carries a serializable `TraceContext`
across the boundary. The context holds the trace id plus a CLOCK-OFFSET
HANDSHAKE: the exporter stamps its wall clock at export, the adopter
stamps its own at adoption, and the difference translates the adopter's
timestamps into the ROOT process's wall timebase. (A one-way handshake
cannot separate transfer latency from clock skew; the merge therefore
keeps every measured span duration intact and renders any root-timebase
gap as an explicit `handoff` span.) Each process still retires its own
`request_trace`
record; `merge_traces` joins records sharing a trace id into ONE
contiguous waterfall whose span sum equals the cross-process wall — the
contract `scripts/summarize_run.py` renders and tests/test_telemetry.py
pins with a deliberately skewed clock.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import asdict, dataclass, field
from typing import Callable, Dict, List, Optional

# synthetic Chrome-trace track ids for request timelines: far above any
# real thread id is impossible (they are huge), so instead requests map
# onto a small band of dedicated tracks by rid
REQ_TRACK_BASE = 1_000_000
REQ_TRACKS = 64


@dataclass
class TraceContext:
    """The wire form of an in-flight request's trace: everything the
    next process needs to CONTINUE the timeline rather than start a new
    one. `handoff_wall` is the exporter's wall clock at export,
    expressed in the ROOT process's timebase (offsets compose across
    multi-hop chains: router -> prefill -> decode)."""

    trace_id: str
    rid: int
    parent_span: str          # the phase the origin closed at export
    origin_process: int
    handoff_wall: float       # root-timebase wall seconds at export

    def to_wire(self) -> dict:
        return asdict(self)

    @classmethod
    def from_wire(cls, d: dict) -> "TraceContext":
        return cls(trace_id=str(d["trace_id"]), rid=int(d["rid"]),
                   parent_span=str(d.get("parent_span", "handoff")),
                   origin_process=int(d.get("origin_process", 0)),
                   handoff_wall=float(d["handoff_wall"]))


@dataclass
class _Timeline:
    rid: int
    trace_id: str
    t0: float
    last: float
    t0_wall: float = 0.0      # local wall clock at begin
    offset_s: float = 0.0     # local wall + offset_s = ROOT wall
    origin: Optional[dict] = None  # adopted-from link (None for root)
    spans: List[dict] = field(default_factory=list)
    counters: Dict[str, float] = field(default_factory=dict)


class RequestTracer:
    """Thread-unsafe by design: all marks come from the engine's host
    loop (one thread). `clock` must be the ENGINE's clock (the Request
    timestamps' clock), so span sums agree with `ttft_s`/`tpot_s`."""

    def __init__(self, writer=None, tracer=None, flight=None,
                 clock: Callable[[], float] = time.monotonic,
                 max_completed: int = 8192, process_index: int = 0,
                 wall: Callable[[], float] = time.time):
        self.writer = writer
        self.tracer = tracer
        self.flight = flight
        self._clock = clock
        # wall clock for the cross-process handshake ONLY: span durations
        # stay on the monotonic engine clock; `wall` anchors this proc's
        # timeline to a timebase another proc can translate into
        self.process_index = process_index
        self._wall = wall
        # engine-clock -> tracer-clock translation, sampled once so the
        # request tracks land at the right offsets among the host spans
        self._off = (tracer.now() - clock()) if tracer is not None else 0.0
        self._live: Dict[int, _Timeline] = {}
        self.completed: "OrderedDict[int, dict]" = OrderedDict()
        self.max_completed = max_completed
        self._seq = 0

    # -- lifecycle --------------------------------------------------------
    def begin(self, req, t: Optional[float] = None,
              ctx: Optional[TraceContext] = None) -> str:
        """Open a timeline at submit time (use the request's `submit_t` —
        loadgen backdates it to the planned arrival, and TTFT is measured
        from there). Assigns `req.trace_id`. Re-begin of a live rid is a
        no-op returning the existing id (a preempted request re-enters
        through `requeue`, never through a second submit).

        `ctx`: a TraceContext exported by ANOTHER process — the timeline
        CONTINUES that trace (same id) and the adoption-time wall sample
        completes the clock-offset handshake: this proc's wall clock plus
        `offset_s` is the root proc's wall clock, so the two processes'
        retired records merge into one waterfall (merge_traces)."""
        tl = self._live.get(req.rid)
        if tl is not None:
            return tl.trace_id
        self._seq += 1
        if ctx is not None:
            trace_id = ctx.trace_id
        else:
            trace_id = f"r{req.rid}.{self._seq}"
        req.trace_id = trace_id
        # a CONTINUED trace starts its local segment at adoption time —
        # the origin's record already covers [submit_t, export], and an
        # in-process chain reuses the same Request object, so defaulting
        # to its (stale) submit_t would overlap the two hops' records
        # and break the merged span-sum == wall contract (merge_traces)
        t = (req.submit_t if ctx is None else None) if t is None else t
        if t is None:
            t = self._clock()
        # anchor the wall timebase at t0 even when submit_t was backdated
        # (loadgen stamps the PLANNED arrival): wall-now minus the mono
        # elapsed since t0 is the wall clock AT t0
        tl = _Timeline(rid=req.rid, trace_id=trace_id, t0=t, last=t,
                       t0_wall=self._wall() - (self._clock() - t))
        if ctx is not None:
            # handshake close: the export stamp (root timebase) minus the
            # adoption stamp (local wall) — transfer latency lands in the
            # merged waterfall's handoff gap, not inside any phase
            tl.offset_s = ctx.handoff_wall - tl.t0_wall
            tl.origin = {"parent_span": ctx.parent_span,
                         "origin_process": ctx.origin_process}
        self._live[req.rid] = tl
        return trace_id

    def export_context(self, req,
                       parent_span: str = "handoff") -> Optional[TraceContext]:
        """The wire context for handing `req` to another process. Closes
        the running span as `parent_span` first, so the origin-side
        timeline ends exactly where the receiving side's begins (modulo
        transfer time, which the merge renders as the handoff gap). The
        caller retires the request on this side after the send; the
        receiving engine passes the context to `submit`/`begin`."""
        tl = self._live.get(req.rid)
        if tl is None:
            return None
        self.mark(req, parent_span)
        return TraceContext(trace_id=tl.trace_id, rid=req.rid,
                            parent_span=parent_span,
                            origin_process=self.process_index,
                            handoff_wall=self._wall() + tl.offset_s)

    def mark(self, req, phase: str, t: Optional[float] = None,
             **num_args) -> None:
        """Close the span running since the last mark and label it
        `phase`. Numeric kwargs accumulate across coalesced marks
        (`positions`, `cow`, `accepted`, ...)."""
        tl = self._live.get(req.rid)
        if tl is None:
            return
        t = self._clock() if t is None else t
        if t < tl.last:          # monotonic clocks only; clamp regardless
            t = tl.last
        last = tl.spans[-1] if tl.spans else None
        if last is not None and last["name"] == phase:
            last["end"] = t
            last["count"] += 1
            for k, v in num_args.items():
                last[k] = last.get(k, 0) + v
        else:
            tl.spans.append({"name": phase, "start": tl.last, "end": t,
                             "count": 1, **num_args})
        tl.last = t

    def note(self, req, **counters) -> None:
        """Accumulate request-scoped counters (page leases/frees, COW
        copies) reported once in the retire record, not per span."""
        tl = self._live.get(req.rid)
        if tl is None:
            return
        for k, v in counters.items():
            tl.counters[k] = tl.counters.get(k, 0) + v

    def retire(self, req, t: Optional[float] = None) -> Optional[dict]:
        """Finalize + emit. Residual time between the last mark and the
        finish stamp becomes a closing `retire` span, so the span sum
        equals finish - submit EXACTLY."""
        tl = self._live.pop(req.rid, None)
        if tl is None:
            return None
        t = (req.finish_t if req.finish_t is not None else self._clock()) \
            if t is None else t
        if t > tl.last + 1e-9:
            tl.spans.append({"name": "retire", "start": tl.last, "end": t,
                             "count": 1})
            tl.last = t
        ms = lambda s: round(s * 1e3, 3)
        spans = [{"name": s["name"],
                  "start_ms": ms(s["start"] - tl.t0),
                  "dur_ms": ms(s["end"] - s["start"]),
                  **{k: v for k, v in s.items()
                     if k not in ("name", "start", "end")}}
                 for s in tl.spans]
        rec = {
            "rid": req.rid,
            "trace_id": tl.trace_id,
            "spans": spans,
            "total_ms": ms(tl.last - tl.t0),
            # -- cross-process merge anchors (ISSUE 12): this record's t0
            # in the ROOT process's wall timebase, the handshake offset
            # that produced it, and the adopted-from link (None = root)
            "process": self.process_index,
            "t0_wall": round(tl.t0_wall + tl.offset_s, 6),
            "clock_offset_ms": ms(tl.offset_s),
            "origin": tl.origin,
            "ttft_ms": None if req.ttft_s is None else ms(req.ttft_s),
            "tpot_ms": None if req.tpot_s is None else ms(req.tpot_s),
            "prompt_len": req.prompt_len or len(req.prompt),
            "generated": len(req.tokens),
            "preemptions": req.preemptions,
            "tenant": req.tenant,
            "slo_class": req.slo_class,
            **tl.counters,
        }
        if self.writer is not None:
            self.writer.event("request_trace", **rec)
        if self.tracer is not None:
            tid = REQ_TRACK_BASE + (req.rid % REQ_TRACKS)
            off = self._off
            for s in tl.spans:
                args = {k: v for k, v in s.items()
                        if k not in ("name", "start", "end")}
                self.tracer.complete_span(
                    f"req{req.rid}:{s['name']}", s["start"] + off,
                    s["end"] + off, cat="request", tid=tid,
                    trace_id=tl.trace_id, **args)
            # flow arrow: submit -> retire, id'd by the tracer sequence so
            # rid reuse across runs cannot cross-link
            self.tracer.flow(f"req{req.rid}", "s", self._seq_of(tl),
                             tl.t0 + off, tid=tid)
            self.tracer.flow(f"req{req.rid}", "f", self._seq_of(tl),
                             tl.last + off, tid=tid)
        if self.flight is not None:
            self.flight.record("request_retired", rid=req.rid,
                               total_ms=rec["total_ms"],
                               ttft_ms=rec["ttft_ms"],
                               preemptions=req.preemptions)
        self.completed[req.rid] = rec
        while len(self.completed) > self.max_completed:
            self.completed.popitem(last=False)
        return rec

    @staticmethod
    def _seq_of(tl: _Timeline) -> int:
        return int(tl.trace_id.rsplit(".", 1)[1])

    # -- accessors --------------------------------------------------------
    @property
    def live(self) -> int:
        return len(self._live)

    def timeline(self, rid: int) -> Optional[dict]:
        """The retired record for `rid` (None while live / evicted)."""
        return self.completed.get(rid)


def merge_traces(records: List[dict], gap_name: str = "handoff") -> dict:
    """Join `request_trace` records sharing one trace id (each retired in
    a different process) into ONE contiguous waterfall in the root
    process's wall timebase.

    Every record's spans are placed at `t0_wall + start_ms` (t0_wall is
    already root-timebase: the adopter folded its handshake offset in at
    retire). Gaps between consecutive spans become explicit `gap_name`
    spans; overlaps — the one-way handshake cannot separate transfer
    latency from clock skew, so an origin's post-export residual can
    land on top of the adopter's first activity — SHIFT the later span
    forward with its duration intact (a measured phase duration is
    ground truth; the placement is only as good as the handshake). The
    merged span sum therefore equals the merged `total_ms` EXACTLY (the
    single-process contiguity contract, now across processes), with
    every process's measured activity accounted contiguously. Consumers:
    scripts/summarize_run.py's cross-process waterfall section."""
    if not records:
        raise ValueError("merge_traces needs at least one record")
    segs = []
    for r in records:
        base_ms = float(r.get("t0_wall", 0.0)) * 1e3
        for s in r["spans"]:
            segs.append((base_ms + s["start_ms"], r.get("process", 0), s))
    segs.sort(key=lambda e: e[0])
    t0 = segs[0][0]
    cursor = t0
    spans: List[dict] = []
    for abs_ms, proc, s in segs:
        if abs_ms > cursor + 1e-6:
            spans.append({"name": gap_name,
                          "start_ms": round(cursor - t0, 3),
                          "dur_ms": round(abs_ms - cursor, 3),
                          "count": 1, "process": proc})
            cursor = abs_ms
        # abs_ms <= cursor: overlap — the span starts at the cursor with
        # its full measured duration
        spans.append({**{k: v for k, v in s.items()
                         if k not in ("start_ms", "dur_ms")},
                      "start_ms": round(cursor - t0, 3),
                      "dur_ms": round(s["dur_ms"], 3), "process": proc})
        cursor += s["dur_ms"]
    by_t0 = sorted(records, key=lambda r: float(r.get("t0_wall", 0.0)))
    return {
        "trace_id": records[0].get("trace_id"),
        "rid": by_t0[0].get("rid"),
        "spans": spans,
        "total_ms": round(cursor - t0, 3),
        "processes": sorted({r.get("process", 0) for r in records}),
        "records": len(records),
        # generated tokens accumulate across the hops
        "generated": sum(int(r.get("generated") or 0) for r in records),
    }
