"""Per-request span timelines for the serving stack (ISSUE 10).

`loadgen` has always reported TTFT/TPOT percentiles — COUNTS of SLO
misses. This module makes each miss EXPLAINABLE: every request carries a
trace id from submit to retire, and the engines mark phase transitions
(`queued`, `prefill_chunk`, `decode`, `spec_round`, `preempted`, ...) on
its timeline as they happen. The timeline is CONTIGUOUS by construction —
each mark closes the span that started at the previous mark — so the span
sum always equals the request's wall time (submit -> finish = TTFT +
decode wall), and a gap can never hide: whatever the engine was doing from
this request's point of view has a named span.

Memory stays bounded two ways: adjacent same-phase marks COALESCE (a
64-token decode is one span with count=64, its numeric args summed — the
waterfall needs phase totals, not per-token rows), and the retired-record
store is a ring (`max_completed`).

On retire the timeline is emitted three ways:
* a `request_trace` MetricsWriter event (jsonl — the machine-readable
  record `summarize_run.py`'s waterfall and the k-worst exemplars read),
* Chrome-trace spans on a synthetic per-request track in the existing
  `SpanTracer` file, with a flow arrow binding enqueue to retire, so a
  request's life renders alongside the engine's dispatch spans,
* `completed[rid]` for in-process consumers (loadgen's k-worst picker).
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

# synthetic Chrome-trace track ids for request timelines: far above any
# real thread id is impossible (they are huge), so instead requests map
# onto a small band of dedicated tracks by rid
REQ_TRACK_BASE = 1_000_000
REQ_TRACKS = 64


@dataclass
class _Timeline:
    rid: int
    trace_id: str
    t0: float
    last: float
    spans: List[dict] = field(default_factory=list)
    counters: Dict[str, float] = field(default_factory=dict)


class RequestTracer:
    """Thread-unsafe by design: all marks come from the engine's host
    loop (one thread). `clock` must be the ENGINE's clock (the Request
    timestamps' clock), so span sums agree with `ttft_s`/`tpot_s`."""

    def __init__(self, writer=None, tracer=None, flight=None,
                 clock: Callable[[], float] = time.monotonic,
                 max_completed: int = 8192):
        self.writer = writer
        self.tracer = tracer
        self.flight = flight
        self._clock = clock
        # engine-clock -> tracer-clock translation, sampled once so the
        # request tracks land at the right offsets among the host spans
        self._off = (tracer.now() - clock()) if tracer is not None else 0.0
        self._live: Dict[int, _Timeline] = {}
        self.completed: "OrderedDict[int, dict]" = OrderedDict()
        self.max_completed = max_completed
        self._seq = 0

    # -- lifecycle --------------------------------------------------------
    def begin(self, req, t: Optional[float] = None) -> str:
        """Open a timeline at submit time (use the request's `submit_t` —
        loadgen backdates it to the planned arrival, and TTFT is measured
        from there). Assigns `req.trace_id`. Re-begin of a live rid is a
        no-op returning the existing id (a preempted request re-enters
        through `requeue`, never through a second submit)."""
        tl = self._live.get(req.rid)
        if tl is not None:
            return tl.trace_id
        self._seq += 1
        trace_id = f"r{req.rid}.{self._seq}"
        req.trace_id = trace_id
        t = req.submit_t if t is None else t
        if t is None:
            t = self._clock()
        self._live[req.rid] = _Timeline(rid=req.rid, trace_id=trace_id,
                                        t0=t, last=t)
        return trace_id

    def mark(self, req, phase: str, t: Optional[float] = None,
             **num_args) -> None:
        """Close the span running since the last mark and label it
        `phase`. Numeric kwargs accumulate across coalesced marks
        (`positions`, `cow`, `accepted`, ...)."""
        tl = self._live.get(req.rid)
        if tl is None:
            return
        t = self._clock() if t is None else t
        if t < tl.last:          # monotonic clocks only; clamp regardless
            t = tl.last
        last = tl.spans[-1] if tl.spans else None
        if last is not None and last["name"] == phase:
            last["end"] = t
            last["count"] += 1
            for k, v in num_args.items():
                last[k] = last.get(k, 0) + v
        else:
            tl.spans.append({"name": phase, "start": tl.last, "end": t,
                             "count": 1, **num_args})
        tl.last = t

    def note(self, req, **counters) -> None:
        """Accumulate request-scoped counters (page leases/frees, COW
        copies) reported once in the retire record, not per span."""
        tl = self._live.get(req.rid)
        if tl is None:
            return
        for k, v in counters.items():
            tl.counters[k] = tl.counters.get(k, 0) + v

    def retire(self, req, t: Optional[float] = None) -> Optional[dict]:
        """Finalize + emit. Residual time between the last mark and the
        finish stamp becomes a closing `retire` span, so the span sum
        equals finish - submit EXACTLY."""
        tl = self._live.pop(req.rid, None)
        if tl is None:
            return None
        t = (req.finish_t if req.finish_t is not None else self._clock()) \
            if t is None else t
        if t > tl.last + 1e-9:
            tl.spans.append({"name": "retire", "start": tl.last, "end": t,
                             "count": 1})
            tl.last = t
        ms = lambda s: round(s * 1e3, 3)
        spans = [{"name": s["name"],
                  "start_ms": ms(s["start"] - tl.t0),
                  "dur_ms": ms(s["end"] - s["start"]),
                  **{k: v for k, v in s.items()
                     if k not in ("name", "start", "end")}}
                 for s in tl.spans]
        rec = {
            "rid": req.rid,
            "trace_id": tl.trace_id,
            "spans": spans,
            "total_ms": ms(tl.last - tl.t0),
            "ttft_ms": None if req.ttft_s is None else ms(req.ttft_s),
            "tpot_ms": None if req.tpot_s is None else ms(req.tpot_s),
            "prompt_len": req.prompt_len or len(req.prompt),
            "generated": len(req.tokens),
            "preemptions": req.preemptions,
            "tenant": req.tenant,
            "slo_class": req.slo_class,
            **tl.counters,
        }
        if self.writer is not None:
            self.writer.event("request_trace", **rec)
        if self.tracer is not None:
            tid = REQ_TRACK_BASE + (req.rid % REQ_TRACKS)
            off = self._off
            for s in tl.spans:
                args = {k: v for k, v in s.items()
                        if k not in ("name", "start", "end")}
                self.tracer.complete_span(
                    f"req{req.rid}:{s['name']}", s["start"] + off,
                    s["end"] + off, cat="request", tid=tid,
                    trace_id=tl.trace_id, **args)
            # flow arrow: submit -> retire, id'd by the tracer sequence so
            # rid reuse across runs cannot cross-link
            self.tracer.flow(f"req{req.rid}", "s", self._seq_of(tl),
                             tl.t0 + off, tid=tid)
            self.tracer.flow(f"req{req.rid}", "f", self._seq_of(tl),
                             tl.last + off, tid=tid)
        if self.flight is not None:
            self.flight.record("request_retired", rid=req.rid,
                               total_ms=rec["total_ms"],
                               ttft_ms=rec["ttft_ms"],
                               preemptions=req.preemptions)
        self.completed[req.rid] = rec
        while len(self.completed) > self.max_completed:
            self.completed.popitem(last=False)
        return rec

    @staticmethod
    def _seq_of(tl: _Timeline) -> int:
        return int(tl.trace_id.rsplit(".", 1)[1])

    # -- accessors --------------------------------------------------------
    @property
    def live(self) -> int:
        return len(self._live)

    def timeline(self, rid: int) -> Optional[dict]:
        """The retired record for `rid` (None while live / evicted)."""
        return self.completed.get(rid)
