"""Training-health sentinel: catch divergence the moment it is visible.

The sentinel is fed host floats at the existing logging-interval D2H sync
(`train.py` already pulls the accumulated loss there), so it adds ZERO device
syncs to the hot path — the steps between checks dispatch fully async, and a
blow-up is detected at most one log interval after it happens.

Two behaviours:
  * non-finite loss or grad norm  -> write a JSON state dump (history, EMA,
    config) and raise `TrainingHealthError`, halting the run. Training on
    NaN params silently corrupts every later checkpoint; dying loudly with
    forensics is strictly better.
  * loss spike (> spike_factor x EMA) -> log a `sentinel/loss_spike` event
    and keep going (spikes self-heal often enough that halting is wrong,
    but they are the leading indicator worth a timeline mark).
"""

from __future__ import annotations

import json
import math
import os
import time
from collections import deque
from typing import Optional


class TrainingHealthError(RuntimeError):
    """Raised by HealthSentinel on a non-finite loss/grad-norm; carries the
    path of the state dump written just before the halt."""

    def __init__(self, message: str, dump_path: Optional[str] = None):
        super().__init__(message)
        self.dump_path = dump_path


class HealthSentinel:
    def __init__(self, dump_dir: str, spike_factor: float = 3.0,
                 ema_decay: float = 0.9, halt_on_nonfinite: bool = True,
                 history: int = 64, writer=None, tracer=None, flight=None):
        self.dump_dir = dump_dir
        self.spike_factor = spike_factor
        self.ema_decay = ema_decay
        self.halt_on_nonfinite = halt_on_nonfinite
        self.writer = writer
        self.tracer = tracer
        self.flight = flight  # obs.flight.FlightRecorder — flushed on halt
        self.ema: Optional[float] = None
        self.spikes = 0
        self._history = deque(maxlen=history)

    def check(self, step: int, loss: float, grad_norm: Optional[float] = None
              ) -> None:
        """One health check on host floats. Raises TrainingHealthError on a
        non-finite value (after dumping state); records spikes otherwise."""
        loss = float(loss)
        gn = None if grad_norm is None else float(grad_norm)
        self._history.append({"step": int(step), "loss": loss,
                              "grad_norm": gn, "ts": time.time()})
        bad = []
        if not math.isfinite(loss):
            bad.append(f"loss={loss}")
        if gn is not None and not math.isfinite(gn):
            bad.append(f"grad_norm={gn}")
        if bad:
            reason = f"non-finite at step {step}: {', '.join(bad)}"
            path = self.dump(step, reason)
            self._event("sentinel/nonfinite", step, reason=reason, dump=path)
            if self.halt_on_nonfinite:
                raise TrainingHealthError(
                    f"training halted — {reason} (state dump: {path}); "
                    f"rerun with --debug_nans to trap the originating op",
                    dump_path=path)
            return
        if (self.ema is not None and self.spike_factor > 0
                and loss > self.spike_factor * self.ema):
            self.spikes += 1
            self._event("sentinel/loss_spike", step, loss=loss, ema=self.ema,
                        factor=loss / max(self.ema, 1e-12))
            print(f"sentinel: loss spike at step {step} — {loss:.4f} vs "
                  f"EMA {self.ema:.4f} (x{loss / max(self.ema, 1e-12):.1f})")
        self.ema = (loss if self.ema is None
                    else self.ema_decay * self.ema
                    + (1 - self.ema_decay) * loss)

    def dump(self, step: int, reason: str) -> str:
        """Write the sentinel's view of the run to a JSON file for
        post-mortem. Deliberately NO checkpoint of the at-halt params: a
        `tprank-*` file full of NaNs would become `latest_step` and poison
        the next `--resume`. The post-mortem pair is this file (the WHY)
        plus the last regular checkpoint (healthy params from at most
        save_interval steps earlier). When a flight recorder is attached,
        its ring is flushed FIRST and the two files cross-link — one
        anomaly, one pair of artifacts, no disjoint partial context."""
        os.makedirs(self.dump_dir, exist_ok=True)
        path = os.path.join(self.dump_dir, f"sentinel_dump_step{step}.json")
        flight_path = None
        if self.flight is not None:
            flight_path = self.flight.dump(
                {"kind": "sentinel_nonfinite", "step": int(step),
                 "reason": reason, "sentinel_dump": path},
                tag="sentinel")
        with open(path, "w") as f:
            json.dump({"reason": reason, "step": int(step), "ema": self.ema,
                       "spikes": self.spikes, "ts": time.time(),
                       "flight_dump": flight_path,
                       "history": list(self._history)}, f, indent=1)
        print(f"sentinel: state dump written to {path}"
              + (f" (flight recorder: {flight_path})" if flight_path else ""))
        return path

    def _event(self, tag: str, step: int, **fields) -> None:
        if self.writer is not None:
            self.writer.event(tag, step=step, **fields)
        if self.tracer is not None:
            self.tracer.instant(tag, step=step, **fields)
