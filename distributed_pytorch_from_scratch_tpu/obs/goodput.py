"""Goodput/badput accounting: bucket total wall time by what the host
was doing, in the sense of the goodput literature (e.g. Google's ML
Goodput): goodput = time the accelerators were training on tokens / total
wall time; everything else — compile, input-pipeline stalls, H2D, checkpoint
I/O, eval — is badput with a named cause.

The meter is driven by the same spans the tracer records (TrainObserver
feeds both from one `with observer.span(bucket)`), so the timeline view and
the aggregate view can never disagree. Time in no bucket (python loop
overhead, logging, model init) lands in `other`, so the buckets always sum
to wall time exactly.
"""

from __future__ import annotations

import time
from typing import Callable, Dict

# Every interval of wall time is attributed to exactly one of these.
# "step" = dispatching the train step + blocked waiting on device results:
# the tokens-on-device bucket that defines goodput. The rest is badput.
BUCKETS = ("compile", "data_wait", "h2d", "step", "checkpoint", "eval")


class GoodputMeter:
    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._t0 = clock()
        self._buckets: Dict[str, float] = {b: 0.0 for b in BUCKETS}
        self.tokens = 0
        self.steps = 0

    def account(self, bucket: str, seconds: float) -> None:
        """Attribute `seconds` of wall time to `bucket`. Unknown buckets are
        created on the fly (they show up in the summary like any other)."""
        self._buckets[bucket] = self._buckets.get(bucket, 0.0) + seconds

    def add_progress(self, tokens: int, steps: int = 1) -> None:
        self.tokens += tokens
        self.steps += steps

    def wall(self) -> float:
        return self._clock() - self._t0

    def summary(self) -> dict:
        """Buckets + derived numbers. `other` is the unattributed remainder,
        clamped at 0 (nested spans could in principle double-account; the
        train loop's spans do not nest across buckets)."""
        wall = max(self.wall(), 1e-9)
        buckets = dict(self._buckets)
        buckets["other"] = max(0.0, wall - sum(buckets.values()))
        return {
            "wall_s": wall,
            "buckets_s": {k: round(v, 6) for k, v in buckets.items()},
            "goodput": buckets.get("step", 0.0) / wall,
            "tokens": self.tokens,
            "steps": self.steps,
            "tokens_per_sec_wall": self.tokens / wall,
        }

    @staticmethod
    def format_summary(s: dict) -> str:
        wall = s["wall_s"]
        parts = ", ".join(
            f"{k} {v:.2f}s ({100 * v / wall:.1f}%)"
            for k, v in sorted(s["buckets_s"].items(),
                               key=lambda kv: -kv[1]) if v > 0)
        return (f"goodput {100 * s['goodput']:.1f}% over {wall:.2f}s wall "
                f"({s['tokens']} tokens, {s['steps']} steps): {parts}")
