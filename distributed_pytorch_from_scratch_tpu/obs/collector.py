"""Fleet collector: tail every process's metrics jsonl chain, fold the
streams into per-process state, and emit versioned `fleet_rollup` events
(ISSUE 12).

The per-process exporters (obs/telemetry.py) answer "how is THIS proc
doing"; the fleet questions — cross-replica SLO attainment, which rank
is the straggler, how many KV pages the fleet has left — need one reader
over every proc's stream. This module is that reader, built for the two
ways a stream can be consumed:

* **live tail**: `JsonlTailer.poll()` reads whatever bytes the producer
  has flushed so far. A torn trailing line (the producer mid-flush, or
  a hard kill) is HELD as the pending tail and resynced on the next
  poll — never dropped, never double-counted (the satellite's exact
  contract, pinned in tests/test_telemetry.py). Records that parse but
  fail `obs/schema.validate_record` are counted invalid and excluded
  from rollups instead of poisoning them.
* **rotation chain**: a `rotated` continuation event (MetricsWriter
  size-based rotation) switches the tailer to the named next file, so a
  bounded-growth serving run reads as one stream.

`FleetCollector` folds the records by tag (telemetry_snapshot /
serving_summary / paged_kv_stats / rank_phase_stats / goodput_summary)
and computes the rollup: fleet tokens/s, aggregate pool utilization,
completion-weighted cross-proc SLO attainment
(telemetry.fleet_slo_attainment), and ONLINE rank skew through the same
`obs/attribution.rank_skew` the post-hoc summary uses. Rollups append to
`fleet_rollup.jsonl` (its own file — the collector must never write into
a producer's metrics.jsonl) and render live in `scripts/obs_top.py`.

Deliberately jax-free: importable from a standalone script on a box
where jax is broken (the graftcheck layer-1 precedent); `rank_skew` is
a lazy import because obs/attribution is pure host math but lives in
the package namespace.
"""

from __future__ import annotations

import glob
import json
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional

from .schema import EVENT_SCHEMA_VERSION, validate_record
from .telemetry import fleet_slo_attainment

# rotated generations (metrics.001.jsonl, metrics.proc2.003.jsonl) are
# reached by FOLLOWING the chain from the base file, never discovered
# directly — double-tailing a generation would double-count its records
_ROTATED_GEN = re.compile(r"\.\d{3}\.jsonl$")


class JsonlTailer:
    """Incremental reader of one metrics jsonl chain (base file plus any
    `rotated` continuations). Not thread-safe; one collector thread owns
    each tailer."""

    def __init__(self, path: str):
        self.path = path
        self._f = None
        self._buf = ""      # the held partial tail (torn-line resync)
        self._visited = {os.path.realpath(path)}  # rotation-cycle guard
        self.records = 0    # complete, schema-valid records yielded
        self.invalid = 0    # parse failures / schema-invalid records
        self.torn_holds = 0  # polls that ended holding a partial tail
        self.rotations = 0  # `rotated` continuations followed

    def poll(self) -> List[dict]:
        """Every complete record flushed since the last poll, following
        rotation hops in the same call. A trailing partial line stays in
        the hold buffer until a later flush completes it."""
        out: List[dict] = []
        while True:
            if self._f is None:
                if not os.path.exists(self.path):
                    return out
                self._f = open(self.path, errors="replace")
            chunk = self._f.read()
            if chunk:
                self._buf += chunk
            rotated_to = None
            while "\n" in self._buf:
                line, self._buf = self._buf.split("\n", 1)
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    self.invalid += 1
                    continue
                if validate_record(rec):
                    self.invalid += 1
                    continue
                self.records += 1
                if rec.get("tag") == "rotated":
                    rotated_to = rec["next"]
                    break   # the rotated event is the file's last line
                out.append(rec)
            if rotated_to is None:
                if self._buf:
                    self.torn_holds += 1
                return out
            nxt = os.path.join(os.path.dirname(self.path), rotated_to)
            if os.path.realpath(nxt) in self._visited:
                # a corrupt/hand-edited chain that cycles back to a file
                # already read must not spin this poll (and re-yield its
                # records) forever — treat the cycle as drift and stop
                self.invalid += 1
                return out
            self._visited.add(os.path.realpath(nxt))
            self._f.close()
            self.path = nxt
            self._f = None
            self._buf = ""
            self.rotations += 1


# sentinel: a scrape that missed the liveness deadline (vs None, a fast
# failure) — poll() counts the two differently
_HUNG = object()


class FleetCollector:
    """Fold every proc's stream under `log_dirs` into fleet rollups.

    `endpoints`: optional `http://host:port` exporter URLs to scrape in
    addition to (or instead of) the jsonl tails — the live path for
    procs on other hosts whose filesystems this process cannot read."""

    def __init__(self, log_dirs, endpoints=None, out_path: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time,
                 scrape_timeout: float = 0.5):
        if scrape_timeout <= 0:
            raise ValueError(f"scrape_timeout must be > 0, got "
                             f"{scrape_timeout}")
        self.log_dirs = [log_dirs] if isinstance(log_dirs, str) \
            else list(log_dirs)
        self.endpoints = list(endpoints or [])
        self.out_path = out_path
        self._clock = clock
        self._wall = wall
        self._t0 = clock()
        self._tailers: Dict[str, JsonlTailer] = {}
        # (source key) -> latest per-tag state this proc reported
        self.procs: Dict[str, Dict[str, dict]] = {}
        self._lock = threading.Lock()
        self.rollups = 0
        self.scrape_errors = 0
        # liveness bound per endpoint scrape (ISSUE 19): a HUNG replica —
        # accepts the connection, never answers — must not block the
        # whole collector tick. The scrape runs in a worker joined with
        # this deadline; endpoints that miss it count here (the scrape
        # -side mirror of the hbm block's `procs_unavailable`).
        self.scrape_timeout = float(scrape_timeout)
        self.procs_unresponsive = 0     # endpoints past deadline, last poll
        self.unresponsive_scrapes = 0   # cumulative across polls

    # -- discovery --------------------------------------------------------
    def discover(self) -> List[str]:
        """Base metrics files under the log dirs (recursive — train's
        per-proc `logs/procN/` layout included), excluding rotated
        generations (the chain reaches them)."""
        found = []
        for d in self.log_dirs:
            for p in sorted(glob.glob(os.path.join(d, "**",
                                                   "metrics*.jsonl"),
                                      recursive=True)):
                if _ROTATED_GEN.search(p):
                    continue
                found.append(p)
                if p not in self._tailers:
                    self._tailers[p] = JsonlTailer(p)
        return found

    # -- folding ----------------------------------------------------------
    _KEEP_TAGS = ("telemetry_snapshot", "serving_summary", "paged_kv_stats",
                  "rank_phase_stats", "goodput_summary", "hbm_watermark",
                  "tuning_decision", "controller_decision")

    def poll(self) -> int:
        """One collection pass: tail every discovered file and scrape
        every endpoint; returns the number of new records folded."""
        self.discover()
        n = 0
        for key, tailer in self._tailers.items():
            for rec in tailer.poll():
                self._fold(key, rec)
                n += 1
        unresponsive = 0
        for url in self.endpoints:
            snap = self._scrape(url)
            if snap is _HUNG:
                unresponsive += 1
            elif snap is not None:
                self._fold(url, {"tag": "telemetry_snapshot",
                                 "schema_version": EVENT_SCHEMA_VERSION,
                                 "gauges": snap.get("gauges", {}),
                                 "counters": snap.get("counters", {}),
                                 "process": snap.get("process", 0)})
                n += 1
        self.procs_unresponsive = unresponsive
        self.unresponsive_scrapes += unresponsive
        return n

    def _scrape(self, url: str):
        """One endpoint fetch under a HARD liveness deadline: the HTTP
        round trip runs in a worker thread joined with `scrape_timeout`.
        The socket-level timeout alone is not a liveness bound — a
        replica that accepts the connection and then drips (or just
        hangs inside accept/headers) can hold a blocking urlopen for the
        full socket timeout per endpoint, serially stalling every tick.
        Returns the parsed snapshot, None on a FAST failure (connection
        refused: a dead replica is a fleet fact, counted in
        scrape_errors), or _HUNG past the deadline (counted by poll as
        procs_unresponsive; the abandoned worker dies on its own socket
        timeout)."""
        import urllib.request

        box: list = []

        def fetch():
            try:
                with urllib.request.urlopen(
                        url.rstrip("/") + "/metrics.json",
                        timeout=self.scrape_timeout) as r:
                    box.append(json.loads(r.read()))
            except Exception:
                box.append(None)

        w = threading.Thread(target=fetch, daemon=True)
        w.start()
        w.join(self.scrape_timeout)
        if w.is_alive():
            return _HUNG
        if box and box[0] is not None:
            return box[0]
        self.scrape_errors += 1
        return None

    def _fold(self, key: str, rec: dict) -> None:
        tag = rec.get("tag")
        if tag not in self._KEEP_TAGS:
            return
        with self._lock:
            self.procs.setdefault(key, {})[tag] = rec

    # -- the rollup -------------------------------------------------------
    @staticmethod
    def _slo_counts(state: dict) -> Optional[dict]:
        """{class: (completed, hit)} from a proc's freshest source: live
        exporter counters (`slo/<class>/completed|hit`) win over the
        post-run serving_summary attainment."""
        snap = state.get("telemetry_snapshot")
        if snap is not None:
            counts = {}
            for name, v in snap.get("counters", {}).items():
                m = re.fullmatch(r"slo/(.+)/(completed|hit)", name)
                if m:
                    c = counts.setdefault(m.group(1), [0, 0])
                    c[0 if m.group(2) == "completed" else 1] = int(v)
            if counts:
                return {cls: (c, h) for cls, (c, h) in counts.items()}
        summary = state.get("serving_summary")
        if summary is not None and summary.get("slo_attainment"):
            return {cls: (d["completed"],
                          round(d["attained"] * d["completed"]))
                    for cls, d in summary["slo_attainment"].items()}
        return None

    _HBM_DEV_GAUGE = re.compile(r"hbm/d\d+/bytes_in_use")

    @classmethod
    def _hbm_counts(cls, state: dict):
        """(available, bytes_in_use, peak_bytes) from a proc's freshest
        HBM source: live exporter gauges (`hbm/...`, ISSUE 15) win over
        the last `hbm_watermark` event. None when the proc never
        published either. bytes_in_use SUMS the per-device gauges when
        present (the aggregate `hbm/bytes_in_use` gauge is the
        worst-device watermark — summing semantics must match the event
        path, or a multi-device proc undercounts in the fleet total)."""
        snap = state.get("telemetry_snapshot")
        if snap is not None:
            g = snap.get("gauges", {})
            if "hbm/available" in g:
                if not g["hbm/available"]:
                    return (False, 0, 0)
                per_dev = [int(v) for k, v in g.items()
                           if cls._HBM_DEV_GAUGE.fullmatch(k)]
                in_use = (sum(per_dev) if per_dev
                          else int(g.get("hbm/bytes_in_use", 0)))
                return (True, in_use, int(g.get("hbm/peak_bytes", 0)))
        ev = state.get("hbm_watermark")
        if ev is not None:
            if not ev.get("available"):
                return (False, 0, 0)
            devs = ev.get("devices") or []
            return (True,
                    sum(int(d.get("bytes_in_use", 0)) for d in devs),
                    max((int(d.get("peak_bytes", 0)) for d in devs),
                        default=0))
        return None

    def rollup(self) -> dict:
        """The fleet view from the latest folded state (pure read)."""
        with self._lock:
            procs = {k: dict(v) for k, v in self.procs.items()}
        tokens_per_sec = 0.0
        pages_total = pages_used = 0
        kv_utils = []
        slo_inputs = []
        skew_recs = []
        # fleet HBM (ISSUE 15): per-proc watermark -> fleet peak gauge.
        # A proc that REPORTS unavailability still counts (loudly) — the
        # silent-zero fix must survive aggregation, so 'unavailable' is a
        # fleet fact, never a 0-byte proc folded into the sum.
        hbm_in_use = hbm_peak = 0
        hbm_procs = hbm_unavailable = 0
        # control plane (ISSUE 16): per-proc mode/decision gauges + the
        # freshest folded ledger event -> fleet controller state. A proc
        # that never published ctl/mode counts as off — pre-v5 streams
        # produce no block at all (the rollup shape is unchanged)
        ctl_modes = {"advise": 0, "act": 0}
        ctl_decisions = 0
        ctl_last = None
        for state in procs.values():
            snap = state.get("telemetry_snapshot")
            if snap is not None:
                g = snap.get("gauges", {})
                m = g.get("ctl/mode")
                if m is not None and 0 <= int(m) < 3:
                    mode = ("off", "advise", "act")[int(m)]
                    if mode in ctl_modes:
                        ctl_modes[mode] += 1
                    ctl_decisions += int(g.get("ctl/decisions", 0))
            d = (state.get("controller_decision")
                 or state.get("tuning_decision"))
            if d is not None and (ctl_last is None
                                  or d.get("t", 0) >= ctl_last.get("t", 0)):
                ctl_last = d
        for state in procs.values():
            snap = state.get("telemetry_snapshot")
            if snap is not None:
                g = snap.get("gauges", {})
                tokens_per_sec += g.get("serve/tokens_per_sec",
                                        g.get("train/tokens_per_sec", 0.0))
                if "serve/num_pages" in g:
                    pages_total += int(g["serve/num_pages"])
                    pages_used += int(g.get("serve/pages_in_use", 0))
                if "serve/kv_util" in g:
                    kv_utils.append(g["serve/kv_util"])
            hbm = self._hbm_counts(state)
            if hbm is not None:
                avail, in_use, peak = hbm
                if avail:
                    hbm_procs += 1
                    hbm_in_use += in_use
                    hbm_peak = max(hbm_peak, peak)
                else:
                    hbm_unavailable += 1
            kv = state.get("paged_kv_stats")
            if kv is not None and snap is None:
                pages_total += int(kv.get("num_pages", 0))
                pages_used += int(round(kv.get("pages_in_use_mean", 0.0)))
                kv_utils.append(kv.get("kv_util_mean", 0.0))
            counts = self._slo_counts(state)
            if counts is not None:
                slo_inputs.append(counts)
            rps = state.get("rank_phase_stats")
            if rps is not None:
                skew_recs.append(rps)
        out = {
            "procs": len(procs),
            "window_s": round(self._clock() - self._t0, 3),
            "tokens_per_sec": round(tokens_per_sec, 2),
            "slo_attainment": fleet_slo_attainment(slo_inputs),
        }
        if self.endpoints:
            # scrape liveness (ISSUE 19): endpoints that missed the last
            # poll's deadline — the procs_unavailable convention, applied
            # to the scrape path
            out["procs_unresponsive"] = self.procs_unresponsive
        if pages_total:
            out["pool"] = {
                "pages_in_use": pages_used,
                "num_pages": pages_total,
                "util": round(pages_used / pages_total, 4),
                "kv_util_mean": round(sum(kv_utils) / len(kv_utils), 4)
                if kv_utils else None,
            }
        if hbm_procs or hbm_unavailable:
            out["hbm"] = {
                "bytes_in_use_total": hbm_in_use,
                "peak_bytes_max": hbm_peak,
                "procs_reporting": hbm_procs,
                "procs_unavailable": hbm_unavailable,
            }
        if any(ctl_modes.values()) or ctl_last is not None:
            out["control"] = {
                "procs": {**ctl_modes,
                          "off": len(procs) - sum(ctl_modes.values())},
                "decisions": ctl_decisions,
            }
            if ctl_last is not None:
                out["control"]["last"] = {
                    "tag": ctl_last.get("tag"),
                    "knob": ctl_last.get("knob"),
                    "old": ctl_last.get("old"),
                    "new": ctl_last.get("new"),
                    "mode": ctl_last.get("mode"),
                    "applied": ctl_last.get("applied"),
                }
        if len(skew_recs) >= 2:
            try:
                from .attribution import rank_skew
                skew = rank_skew(skew_recs)
            except ImportError:
                skew = None
            if skew is not None:
                out["rank_skew"] = {
                    "suspects": skew["suspects"][:5],
                    "persistent": skew["persistent"],
                }
        return out

    def emit(self) -> dict:
        """Roll up and append one versioned `fleet_rollup` event to
        `out_path` (no-op write when out_path is None). The collector
        owns this file alone — producer metrics files are read-only to
        it by construction."""
        rec = {"tag": "fleet_rollup", "ts": self._wall(),
               "schema_version": EVENT_SCHEMA_VERSION, **self.rollup()}
        self.rollups += 1
        if self.out_path:
            os.makedirs(os.path.dirname(self.out_path) or ".",
                        exist_ok=True)
            with open(self.out_path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        return rec
