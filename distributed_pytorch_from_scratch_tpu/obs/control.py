"""The control plane (obs v5, ISSUE 16): drift-driven retuning with an
auditable decision ledger.

Obs v4 can say *where the analytic model is wrong* (the duty-cycled
measured-vs-analytic reconcile) and *how much HBM is left* (live
watermarks); every knob those signals implicate — `pages_per_block`,
prefill chunk, dp bucket MiB, speculative K — was still set by hand from
offline sweeps. This module closes the loop, under one discipline:
**every actuation is itself a first-class observable.** A knob never
moves without a versioned `tuning_decision` event recording what moved,
from what to what, and the evidence (per-phase drift ms, HBM headroom,
the capture id) that justified it.

The `--control {off,advise,act}` ladder:

* `off`    — the plane does not exist: no advisor, no events, no record
  fields (the zero-cost off-state the test suite pins byte-for-byte);
* `advise` — decisions are computed and landed in the ledger with
  `applied: false`; nothing mutates;
* `act`    — decisions queue at proposal time and mutate ONLY inside
  `apply_decisions()`, which callers invoke from a registered safe
  point: a function decorated with `@control_safe_point` (engine init
  boundaries, between capture windows, the engine's host-side decode
  tick — never mid-window, never inside a traced function). graftcheck's
  `controller-discipline` rule enforces the decoration statically.

Deliberately jax-free (the schema.py convention): the advisor consumes
already-parsed event fields and actuates through caller-supplied
knob setters, so it imports from standalone scripts and tests without
touching a backend.
"""

from __future__ import annotations

import sys
from typing import Callable, Dict, List, Optional

from .profparse import COLLECTIVE_KINDS

CONTROL_MODES = ("off", "advise", "act")
MODE_INDEX = {m: i for i, m in enumerate(CONTROL_MODES)}


def control_safe_point(fn):
    """Mark `fn` as a registered control-plane safe point: a call site
    allowed to invoke `apply_decisions()`/`actuate()`. The decoration is
    the registration — graftcheck's `controller-discipline` rule flags
    actuation calls from any undecorated function. Identity at runtime
    (no wrapper: safe points sit on host hot paths)."""
    fn.__control_safe_point__ = True
    return fn


class Knob:
    """One tunable the control plane may move: a getter, an optional
    setter (None = an init-boundary knob — its decisions are recorded
    but land only at the next engine init, e.g. dp bucket MiB baked
    into the compiled step), and clamp bounds."""

    def __init__(self, name: str, getter: Callable[[], float],
                 setter: Optional[Callable[[float], None]] = None,
                 lo: Optional[float] = None, hi: Optional[float] = None,
                 integer: bool = True):
        self.name = name
        self.getter = getter
        self.setter = setter
        self.lo = lo
        self.hi = hi
        self.integer = integer

    def clamp(self, v: float) -> float:
        if self.lo is not None:
            v = max(self.lo, v)
        if self.hi is not None:
            v = min(self.hi, v)
        return int(round(v)) if self.integer else float(v)


class RetuneAdvisor:
    """Drift-driven retuning: consume duty-cycled `profile_attribution`
    reconciles and `hbm_watermark` events, emit `tuning_decision` ledger
    events, and (mode=act) move registered knobs at safe points.

    The rules are deliberately small, directional, and evidenced — the
    advisor is a closed measurement loop, not an optimizer:

    * collective drift >= `drift_pct` -> grow `dp_bucket_mb` (x2, seeded
      at 4.0 from 0 — unbucketed): the wire is costing more than priced,
      bucketing amortizes latency per launch;
    * measured `copy` phase >= `copy_frac` of the step -> grow
      `pages_per_block` (x2): gather/scatter traffic the paged kernel's
      block fetch amortizes;
    * measured `host_gap` >= `host_gap_frac` of the step -> grow
      `prefill_chunk` (x2): fewer, larger host dispatches;
    * `compute` drift >= `drift_pct` -> shrink `speculate_k` (-1): the
      draft work costs more than the roofline priced it at;
    * HBM headroom < `hbm_headroom_frac` -> halve `pages_per_block` and
      `prefill_chunk`: working-set pressure beats throughput tuning.

    A knob re-proposes only when the target value changes (no event spam
    from a persistent signal), and an act-mode proposal queues until
    `apply_decisions()` runs from a `@control_safe_point` call site.
    """

    def __init__(self, mode: str, writer=None, telemetry=None,
                 drift_pct: float = 25.0, copy_frac: float = 0.10,
                 host_gap_frac: float = 0.20,
                 hbm_headroom_frac: float = 0.10):
        if mode not in CONTROL_MODES:
            raise ValueError(f"control mode must be one of "
                             f"{CONTROL_MODES}, got {mode!r}")
        self.mode = mode
        self.writer = writer
        self.telemetry = telemetry
        self.drift_pct = drift_pct
        self.copy_frac = copy_frac
        self.host_gap_frac = host_gap_frac
        self.hbm_headroom_frac = hbm_headroom_frac
        self.knobs: Dict[str, Knob] = {}
        self.decisions: List[dict] = []      # the emitted ledger, in order
        self.last_headroom: Optional[float] = None
        self._pending: List[tuple] = []      # (knob, decision) awaiting act
        self._last_proposed: Dict[str, float] = {}
        self._seq = 0
        if telemetry is not None and mode != "off":
            telemetry.gauge("ctl/mode", MODE_INDEX[mode])

    def register_knob(self, name: str, getter, setter=None, lo=None,
                      hi=None, integer: bool = True) -> None:
        self.knobs[name] = Knob(name, getter, setter, lo, hi, integer)

    # -- observation (proposal) rules ---------------------------------
    def observe_attribution(self, fields: Optional[dict]) -> List[dict]:
        """Consume one parsed capture's `profile_attribution` fields
        (the DutyCycleProfiler `on_attribution` hook — i.e. between
        capture windows). Returns the decisions proposed."""
        if self.mode == "off" or not fields:
            return []
        rec = fields.get("reconcile")
        if not rec:
            return []
        capture = fields.get("capture")
        rows = {r["phase"]: r for r in rec.get("rows", [])}
        step_ms = float(rec.get("measured_step_ms") or 0.0)
        out = []
        comm = [r for r in rows.values()
                if r["phase"] in COLLECTIVE_KINDS
                and r.get("drift_pct") is not None
                and r["drift_pct"] >= self.drift_pct]
        if comm:
            ev = {"capture": capture, "trigger": "comm_drift",
                  "phases": {r["phase"]: {
                      "measured_ms": r["measured_ms"],
                      "analytic_ms": r["analytic_ms"],
                      "drift_pct": r["drift_pct"]} for r in comm}}
            out += self._propose("dp_bucket_mb",
                                 lambda old: old * 2 if old else 4.0, ev)
        copy = rows.get("copy")
        if copy and step_ms > 0 \
                and copy["measured_ms"] >= self.copy_frac * step_ms:
            ev = {"capture": capture, "trigger": "copy_traffic",
                  "copy_ms": copy["measured_ms"], "step_ms": step_ms}
            out += self._propose("pages_per_block", lambda old: old * 2,
                                 ev)
        gap = rows.get("host_gap")
        if gap and step_ms > 0 \
                and gap["measured_ms"] >= self.host_gap_frac * step_ms:
            ev = {"capture": capture, "trigger": "host_gap",
                  "host_gap_ms": gap["measured_ms"], "step_ms": step_ms}
            out += self._propose("prefill_chunk", lambda old: old * 2, ev)
        comp = rows.get("compute")
        if comp and comp.get("drift_pct") is not None \
                and comp["drift_pct"] >= self.drift_pct:
            ev = {"capture": capture, "trigger": "compute_drift",
                  "drift_pct": comp["drift_pct"]}
            out += self._propose("speculate_k", lambda old: old - 1, ev)
        return out

    def observe_hbm(self, fields: Optional[dict]) -> List[dict]:
        """Consume one `hbm_watermark` event's fields. Low headroom
        shrinks the working-set knobs."""
        if self.mode == "off" or not fields or not fields.get("available"):
            return []
        rooms = [(d["limit_bytes"] - d["bytes_in_use"]) / d["limit_bytes"]
                 for d in fields.get("devices", ())
                 if d.get("limit_bytes")]
        if not rooms:
            return []
        self.last_headroom = min(rooms)
        if self.last_headroom >= self.hbm_headroom_frac:
            return []
        ev = {"trigger": "hbm_pressure",
              "hbm_headroom_frac": round(self.last_headroom, 4),
              "devices": len(fields.get("devices", ()))}
        out = []
        for name in ("pages_per_block", "prefill_chunk"):
            out += self._propose(name, lambda old: old // 2, dict(ev))
        return out

    # -- the ledger ----------------------------------------------------
    def _propose(self, name: str, fn, evidence: dict) -> List[dict]:
        knob = self.knobs.get(name)
        if knob is None:
            return []
        old = knob.getter()
        new = knob.clamp(fn(old))
        if new == old or self._last_proposed.get(name) == new:
            return []
        self._last_proposed[name] = new
        self._seq += 1
        d = {"knob": name, "old": old, "new": new,
             "evidence": evidence, "mode": self.mode, "seq": self._seq}
        if self.mode == "act":
            self._pending.append((knob, d))
        else:
            d["applied"] = False
            self._emit(d)
        return [d]

    def _emit(self, d: dict) -> None:
        self.decisions.append(d)
        if self.writer is not None:
            self.writer.event("tuning_decision", **d)
        if self.telemetry is not None:
            self.telemetry.gauge("ctl/decisions", len(self.decisions))
        print(f"control[{self.mode}]: {d['knob']} {d['old']} -> "
              f"{d['new']} ({d['evidence'].get('trigger')}"
              + ("" if d["applied"] else "; not applied") + ")",
              file=sys.stderr)

    def apply_decisions(self) -> int:
        """Actuate every queued act-mode decision. MUST be called from a
        `@control_safe_point` function (graftcheck-enforced); returns
        how many knobs actually moved. An init-boundary knob (no
        setter) and a refused cache write land in the ledger with
        `applied: false` plus the reason — a decision that could not
        act is still a decision."""
        applied = 0
        while self._pending:
            knob, d = self._pending.pop(0)
            if knob.setter is None:
                d["applied"] = False
                d["note"] = ("init-boundary knob: recorded; lands at "
                             "the next engine init")
            else:
                try:
                    knob.setter(d["new"])
                    d["applied"] = True
                    applied += 1
                except ValueError as e:   # e.g. a refused cache shadow
                    d["applied"] = False
                    d["error"] = str(e)
            self._emit(d)
        return applied

    def close(self) -> None:
        """Flush act-mode proposals that never reached a safe point —
        an unapplied decision must still reach the ledger."""
        while self._pending:
            _, d = self._pending.pop(0)
            d["applied"] = False
            d["note"] = "unapplied at run end (no safe point reached)"
            self._emit(d)

    def summary(self) -> dict:
        """Record-field summary (serve.py/train.py stdout records —
        added only when the mode is not off, the zero-cost-off rule)."""
        last = self.decisions[-1] if self.decisions else None
        return {"mode": self.mode, "decisions": len(self.decisions),
                "applied": sum(1 for d in self.decisions if d["applied"]),
                "last_knob": last["knob"] if last else None}
