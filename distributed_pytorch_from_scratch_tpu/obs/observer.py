"""TrainObserver: the one handle the training loop holds on the whole
observability stack — tracer + goodput meter + health sentinel + hang
watchdog — so instrumenting a call site is a single
`with observer.span("bucket"):` line.

One span call feeds three consumers at once: the Chrome-trace timeline
(where exactly did the wall clock go), the goodput buckets (aggregate
accounting, guaranteed consistent with the timeline because they share the
measurement), and the watchdog heartbeat (any activity is liveness). The
sentinel rides the loop's existing logging-interval D2H via
`check_health()`.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Optional

from .flight import FlightRecorder
from .goodput import GoodputMeter
from .sentinel import HealthSentinel
from .trace import SpanTracer
from .watchdog import HangWatchdog


class TrainObserver:
    def __init__(self, log_dir: str, writer=None, trace: bool = True,
                 watchdog_secs: float = 0.0, sentinel: bool = True,
                 spike_factor: float = 3.0, halt_on_nonfinite: bool = True,
                 process_index: int = 0, flight_ring: int = 256,
                 profile_on_anomaly: int = 0):
        self.writer = writer
        self.process_index = process_index
        self.tracer = SpanTracer(log_dir, enabled=trace, pid=process_index,
                                 process_name=f"train-p{process_index}")
        self.goodput = GoodputMeter()
        # anomaly-triggered device profiling (ISSUE 12): a flight dump
        # arms a bounded jax.profiler window that tick()s from heartbeat
        profiler = None
        if profile_on_anomaly > 0 and flight_ring > 0:
            from ..training.metrics import AnomalyProfiler
            # writer: the finished anomaly window parses into a
            # profile_attribution event (obs v4) — without it the train
            # path's captures would dodge the measured plane
            profiler = AnomalyProfiler(log_dir,
                                       window_steps=profile_on_anomaly,
                                       writer=writer)
        self.profiler = profiler
        # the anomaly flight recorder: every span/heartbeat lands in the
        # ring, and the sentinel/watchdog flush it on their halt/stall
        # paths so a post-mortem has the preceding seconds, not just the
        # triggering event (flight_ring 0 disables)
        self.flight = (FlightRecorder(log_dir, maxlen=flight_ring,
                                      profiler=profiler)
                       if flight_ring > 0 else None)
        self.sentinel = (HealthSentinel(
            log_dir, spike_factor=spike_factor,
            halt_on_nonfinite=halt_on_nonfinite,
            writer=writer, tracer=self.tracer,
            flight=self.flight) if sentinel else None)
        self.watchdog = (HangWatchdog(
            watchdog_secs, process_index=process_index, writer=writer,
            tracer=self.tracer,
            flight=self.flight) if watchdog_secs > 0 else None)
        self._closed = False
        self._local = threading.local()

    @contextmanager
    def span(self, bucket: str, name: Optional[str] = None, **args):
        """Trace a span AND attribute its wall time to a goodput bucket.
        `bucket` is one of obs.goodput.BUCKETS (or any new category);
        `name` defaults to the bucket for the timeline label. Nested spans
        all appear on the timeline, but only the OUTERMOST one accounts
        goodput time (else nesting would double-count the wall clock and
        the buckets would sum past 100%)."""
        if self.watchdog is not None:
            self.watchdog.beat(phase=name or bucket)
        depth = getattr(self._local, "depth", 0)
        self._local.depth = depth + 1
        t0 = time.perf_counter()
        try:
            with self.tracer.span(name or bucket, cat=bucket, **args):
                yield
        finally:
            self._local.depth = depth
            if depth == 0:
                dur = time.perf_counter() - t0
                self.goodput.account(bucket, dur)
                if self.flight is not None:
                    self.flight.record("span", bucket=bucket,
                                       name=name or bucket,
                                       dur_s=round(dur, 6), **args)
            if self.watchdog is not None:
                # beat on exit too: after a long compile/checkpoint the
                # stall clock restarts from completion, and the watchdog's
                # "recovered" line marks the moment it finished
                self.watchdog.beat(phase=f"{name or bucket}:done")

    def instant(self, name: str, **args) -> None:
        self.tracer.instant(name, **args)

    def heartbeat(self, step: int, tokens: int = 0, steps: int = 1,
                  sync=None) -> None:
        """Called once per completed dispatch: liveness + progress.
        `sync`: a device value from this dispatch — the anomaly
        profiler's stop barrier, so an armed window never truncates."""
        self.goodput.add_progress(tokens, steps)
        if self.flight is not None:
            self.flight.record("heartbeat", step=step, tokens=tokens)
            self.flight.tick(step, sync=sync)
        if self.watchdog is not None:
            self.watchdog.beat(step=step)

    def check_health(self, step: int, loss: float,
                     grad_norm: Optional[float] = None) -> None:
        """Raises TrainingHealthError on non-finite values (sentinel off ->
        no-op)."""
        if self.sentinel is not None:
            self.sentinel.check(step, loss, grad_norm=grad_norm)

    def report_compiled(self, analysis: dict, model_flops: float,
                        steps_in_program: int = 1,
                        expected_flops: Optional[float] = None,
                        step: int = 0) -> None:
        """Log the introspection record (obs.introspect.analyze_compiled)
        to metrics + trace; the caller prints the human line.
        `expected_flops` = the hand-rolled estimate scaled to THIS program
        (x steps per dispatch, / world size for SPMD per-device HLO)."""
        if self.writer is not None:
            self.writer.event(
                "cost_analysis", step=step,
                flops=analysis.get("flops"),
                bytes_accessed=analysis.get("bytes_accessed"),
                peak_hbm_bytes=analysis.get("peak_hbm_bytes"),
                collectives=analysis.get("collectives"),
                comm_bytes=analysis.get("comm_bytes"),
                model_flops_per_step=model_flops,
                steps_in_program=steps_in_program,
                expected_program_flops=expected_flops)
        self.tracer.instant("cost_analysis", flops=analysis.get("flops"))

    def close(self, print_summary: bool = True) -> Optional[dict]:
        """Stop the watchdog, write trace.json, log + return the goodput
        summary. Idempotent (later calls return None)."""
        if self._closed:
            return None
        self._closed = True
        if self.watchdog is not None:
            self.watchdog.close()
        if self.profiler is not None:
            self.profiler.close()
        summary = self.goodput.summary()
        if self.writer is not None:
            self.writer.event("goodput_summary", **summary)
            # proc-tagged per-rank phase timings: the cross-rank skew
            # attribution's input (obs/attribution.rank_skew) — each
            # process writes its own metrics*.jsonl, so the collection
            # across files IS the per-rank view
            self.writer.event(
                "rank_phase_stats", process=self.process_index,
                phases_s=summary["buckets_s"], steps=summary["steps"],
                tokens=summary["tokens"], wall_s=summary["wall_s"])
        if print_summary:
            print(GoodputMeter.format_summary(summary))
        path = self.tracer.close()
        if path is not None and print_summary:
            print(f"host timeline trace written to {path} "
                  f"(open in https://ui.perfetto.dev)")
        return summary
