"""Run-archive index: normalize every recorded run into a RunCard
(ISSUE 17, obs v6).

The repo accumulates runs in three shapes — committed `BENCH_rNN.json` /
`MULTICHIP_rNN.json` wrappers at the root, `runs/rN/` session dirs full
of bench arms + metrics jsonl + flight dumps, and raw `bench.py` stdout
lines — and until now nothing could answer "what runs do we have, which
are trustworthy, and what config produced each one". This module walks
all of them and emits one versioned **RunCard** per run: config
fingerprint, backend, headline metrics, event/anomaly counts,
controller-decision summary, profile-capture inventory, and an outage
classification.

Two contracts matter more than the rest:

* `outage_reason` is THE single outage classifier. The bench-regression
  gate's `pick_baseline` (scripts/check_bench_regression.py) and this
  index both call it — an rc != 0 / `backend_unavailable` / metric-less
  record is an *outage* and can never become a baseline, and there is
  exactly one piece of code that decides that (the r02/r05 records are
  the pinned fixtures).
* legacy records (the BENCH_r01–r05 era, before `config_fingerprint`
  stamping) flow through the same normalization with a loud
  "legacy record, fingerprint unavailable" note — never a crash, and
  never a silent `None == None` config match downstream.

Deliberately dependency-free (no jax, no package imports): scripts load
this file with the obs dir on sys.path, the same standalone contract as
`schema.py`.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import re
import subprocess
from typing import Any, Dict, List, Optional, Tuple

try:  # package import (obs consumers) vs obs-dir-on-sys.path (scripts)
    from .schema import EVENT_SCHEMA_VERSION
except ImportError:  # pragma: no cover - exercised via scripts
    from schema import EVENT_SCHEMA_VERSION

# Bump when a RunCard field a consumer keys on changes incompatibly.
# Version 1 = the ISSUE-17 card: run/kind/outage/baseline_eligible +
# fingerprint/metrics/ledger/captures inventory.
RUN_CARD_VERSION = 1

LEGACY_NOTE = "legacy record, fingerprint unavailable"

# headline fields lifted verbatim from a bench/serving record onto the
# card (the same fields the regression gate bands); everything else the
# diff engine needs (measured_vs_analytic, controller) is kept whole.
HEADLINE_FIELDS = (
    "metric", "unit", "value", "vs_baseline", "paged_vs_slot",
    "accepted_tokens_per_dispatch", "ttft_ms_p95", "tpot_ms_p95",
    "decode_hbm_bytes_per_step", "tokens_per_sec",
    # serving fleet (ISSUE 19): bench --fleet / serve_fleet records
    "fleet_tokens_per_sec", "fleet_slo_attainment_min",
    "disagg_vs_colocated", "transfer_ms_p95",
    "transfer_bytes_per_request",
)

_BACKEND_RE = re.compile(r"device\(s\)\s*\[([^\]]+)\]")


# ----------------------------------------------------------------- stamping --

def normalize_config(config: Dict[str, Any]) -> Dict[str, Any]:
    """JSON-stable view of an argparse namespace dict: scalars and
    scalar-lists pass through, anything exotic is stringified — the
    fingerprint must never depend on repr() ordering or object ids."""
    out: Dict[str, Any] = {}
    for key in sorted(config):
        v = config[key]
        if isinstance(v, (str, int, float, bool)) or v is None:
            out[key] = v
        elif isinstance(v, (list, tuple)):
            out[key] = [x if isinstance(x, (str, int, float, bool))
                        or x is None else str(x) for x in v]
        else:
            out[key] = str(v)
    return out


def config_fingerprint(config: Dict[str, Any]) -> str:
    """12-hex-char sha256 of the normalized config — the join key the
    diff engine uses to decide 'same knobs' without field-by-field
    comparison."""
    blob = json.dumps(normalize_config(config), sort_keys=True,
                      separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def git_rev(repo: Optional[str] = None) -> Optional[str]:
    """Short git rev of the producing tree, or None (never raises — a
    bench run inside a tarball export must still emit its record)."""
    try:
        proc = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=repo or os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
            capture_output=True, text=True, timeout=10)
    except (OSError, subprocess.SubprocessError):
        return None
    rev = proc.stdout.strip()
    return rev if proc.returncode == 0 and rev else None


def run_stamp(config: Dict[str, Any],
              repo: Optional[str] = None) -> Dict[str, Any]:
    """The provenance stamp every bench/serving/train summary record
    carries (ISSUE 17): the normalized config, its fingerprint, and the
    producing git rev. Merge into the record dict with `**run_stamp(...)`."""
    cfg = normalize_config(config)
    return {"config": cfg,
            "config_fingerprint": config_fingerprint(cfg),
            "git_rev": git_rev(repo)}


# ------------------------------------------------- outage classification --

def outage_reason(rec: Optional[dict],
                  rc: Optional[int] = None) -> Optional[str]:
    """THE outage classifier (ISSUE 17 satellite): one string naming why
    this record is an outage, or None for a healthy record. Shared by
    `pick_baseline` in scripts/check_bench_regression.py and by the
    index — the two must never diverge on what counts as a baseline.

    An outage: no parseable record at all, an `error` record
    (backend_unavailable and friends), a wrapper whose command exited
    rc != 0 (the BENCH_r02 lesson: a traceback tail parses to nothing),
    or a record that carries no `metric` to compare."""
    if rec is None:
        if rc not in (None, 0):
            return f"no parseable record (rc={rc})"
        return "no parseable record"
    if not isinstance(rec, dict):
        return "record is not a JSON object"
    if "error" in rec:
        detail = rec.get("detail")
        return f"{rec['error']}: {detail}" if detail else str(rec["error"])
    if rc not in (None, 0):
        return f"rc={rc}"
    if "metric" not in rec:
        return "record carries no metric"
    return None


def extract_record(text: str) -> Optional[dict]:
    """LAST parseable JSON-object line carrying `metric` or `error` —
    the same scan the regression gate's load_record does over bench.py
    stdout tails (diagnostics print before the record line)."""
    rec = None
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and ("metric" in obj or "error" in obj):
            rec = obj
    return rec


def classify_path(path: str) -> Dict[str, Any]:
    """Normalize ONE artifact file (BENCH/MULTICHIP wrapper, bare bench
    record, or stdout capture) into {record, rc, tail, outage} — outage
    is `outage_reason`'s verdict, never a re-implementation of it."""
    try:
        text = open(path, errors="replace").read()
    except OSError as e:
        return {"record": None, "rc": None, "tail": None,
                "outage": f"unreadable: {e}"}
    doc = None
    try:
        doc = json.loads(text)
    except ValueError:
        pass
    rc = None
    tail = None
    if isinstance(doc, dict) and ("rc" in doc or "tail" in doc) \
            and "metric" not in doc and "error" not in doc:
        # BENCH_rNN / MULTICHIP_rNN wrapper: {"n", "cmd", "rc", "tail",
        # "parsed"} — the parsed record wins, else scan the tail
        rc = doc.get("rc")
        tail = doc.get("tail")
        parsed = doc.get("parsed")
        rec = parsed if isinstance(parsed, dict) else \
            extract_record(tail or "")
    elif isinstance(doc, dict):
        rec = doc
    else:
        rec = extract_record(text)
    return {"record": rec, "rc": rc, "tail": tail,
            "outage": outage_reason(rec, rc=rc)}


def backend_from_tail(tail: Optional[str]) -> Optional[str]:
    """Backend name from a bench tail's "N device(s) [TPU v5 lite]"
    banner line, or None."""
    if not tail:
        return None
    m = _BACKEND_RE.search(tail)
    return m.group(1) if m else None


# ------------------------------------------------------------- card builders --

def _base_card(run: str, kind: str, source: str) -> Dict[str, Any]:
    return {
        "tag": "run_card",
        "schema_version": EVENT_SCHEMA_VERSION,
        "run_card_version": RUN_CARD_VERSION,
        "run": run,
        "kind": kind,
        "source": source,
        "outage": False,
        "outage_reason": None,
        "baseline_eligible": False,
        "legacy": False,
        "notes": [],
        "backend": None,
        "git_rev": None,
        "config_fingerprint": None,
        "config": None,
        "metrics": {},
        "measured_vs_analytic": None,
        "controller": None,
        "events": {},
        "anomalies": {},
        "ledger": {"decisions": 0, "applied": 0, "knobs": {}},
        "captures": {"count": 0, "errors": 0, "triggers": {}},
        "profile_phases": [],
        "hbm": None,
        "collectives": None,
    }


def _absorb_record(card: Dict[str, Any], rec: Optional[dict]) -> None:
    """Fold one bench/serving record into a card: headline metrics, the
    provenance stamp (or the loud legacy note), the measured reconcile,
    and the controller summary."""
    if not isinstance(rec, dict):
        return
    for f in HEADLINE_FIELDS:
        if f in rec:
            card["metrics"][f] = rec[f]
    if "error" in rec:
        card["metrics"].setdefault("error", rec["error"])
    if isinstance(rec.get("measured_vs_analytic"), dict):
        card["measured_vs_analytic"] = rec["measured_vs_analytic"]
    ctl = rec.get("controller") or rec.get("tuning")
    if isinstance(ctl, dict):
        card["controller"] = {
            "mode": ctl.get("mode"),
            "decisions": ctl.get("decisions"),
            "applied": ctl.get("applied"),
            "last_knob": ctl.get("last_knob"),
        }
    if "config_fingerprint" in rec:
        card["config_fingerprint"] = rec.get("config_fingerprint")
        card["git_rev"] = rec.get("git_rev")
        if isinstance(rec.get("config"), dict):
            card["config"] = rec["config"]
    else:
        card["legacy"] = True
        if LEGACY_NOTE not in card["notes"]:
            card["notes"].append(LEGACY_NOTE)


def card_from_record(rec: Optional[dict], run: str, source: str,
                     kind: str = "bench", rc: Optional[int] = None,
                     tail: Optional[str] = None) -> Dict[str, Any]:
    """RunCard for one loose record (a gate's --fresh file, a wrapper's
    parsed payload) — the shared path every other builder funnels into."""
    card = _base_card(run, kind, source)
    reason = outage_reason(rec, rc=rc)
    card["outage"] = reason is not None
    card["outage_reason"] = reason
    card["baseline_eligible"] = reason is None
    card["backend"] = backend_from_tail(tail)
    _absorb_record(card, rec)
    return card


def card_from_bench_path(path: str) -> Dict[str, Any]:
    """RunCard for a committed BENCH_rNN.json (or any single bench
    artifact/stdout capture)."""
    cls = classify_path(path)
    run = os.path.splitext(os.path.basename(path))[0]
    card = card_from_record(cls["record"], run=run, source=path,
                            kind="bench", rc=cls["rc"], tail=cls["tail"])
    if cls["rc"] is not None:
        card["rc"] = cls["rc"]
    return card


def card_from_multichip_path(path: str) -> Dict[str, Any]:
    """RunCard for a committed MULTICHIP_rNN.json wrapper ({"n_devices",
    "rc", "ok", "skipped", "tail"}): a multichip probe that was skipped
    or not-ok is an outage for baseline purposes like any rc != 0."""
    cls = classify_path(path)
    run = os.path.splitext(os.path.basename(path))[0]
    card = card_from_record(cls["record"], run=run, source=path,
                            kind="multichip", rc=cls["rc"],
                            tail=cls["tail"])
    try:
        doc = json.loads(open(path, errors="replace").read())
    except (OSError, ValueError):
        doc = {}
    if isinstance(doc, dict):
        card["n_devices"] = doc.get("n_devices")
        if doc.get("skipped") and not card["outage"]:
            card["outage"] = True
            card["outage_reason"] = "multichip probe skipped"
            card["baseline_eligible"] = False
    return card


def _tally_events(card: Dict[str, Any], path: str) -> None:
    """One metrics*.jsonl file into the card's event/anomaly/ledger/
    capture tallies. Unparseable lines count under events['<invalid>']
    — a corrupt writer shows up in the index, not as a crash."""
    try:
        lines = open(path, errors="replace").read().splitlines()
    except OSError:
        return
    ev = card["events"]
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            ev["<invalid>"] = ev.get("<invalid>", 0) + 1
            continue
        if not isinstance(rec, dict) or "tag" not in rec:
            ev["<invalid>"] = ev.get("<invalid>", 0) + 1
            continue
        tag = str(rec["tag"])
        ev[tag] = ev.get(tag, 0) + 1
        if tag.startswith(("sentinel/", "watchdog/")):
            an = card["anomalies"]
            an[tag] = an.get(tag, 0) + 1
        elif tag in ("tuning_decision", "controller_decision"):
            led = card["ledger"]
            led["decisions"] += 1
            if rec.get("applied"):
                led["applied"] += 1
            knob = rec.get("knob")
            if knob is not None:
                k = led["knobs"].setdefault(
                    str(knob), {"count": 0, "applied": 0, "last": None})
                k["count"] += 1
                if rec.get("applied"):
                    k["applied"] += 1
                k["last"] = [rec.get("old"), rec.get("new")]
        elif tag == "profile_attribution":
            cap = card["captures"]
            cap["count"] += 1
            if rec.get("error"):
                cap["errors"] += 1
            trig = str(rec.get("trigger"))
            cap["triggers"][trig] = cap["triggers"].get(trig, 0) + 1
            phases = rec.get("phases")
            if isinstance(phases, dict) and phases:
                card["profile_phases"].append(
                    {"phases": phases, "steps": rec.get("steps")})
        elif tag == "hbm_watermark":
            devices = rec.get("devices") or []
            peaks = [d.get("peak_bytes_in_use") for d in devices
                     if isinstance(d, dict)
                     and isinstance(d.get("peak_bytes_in_use"),
                                    (int, float))]
            card["hbm"] = {"available": bool(rec.get("available")),
                           "devices": len(devices),
                           "peak_bytes": max(peaks) if peaks else None}


def card_from_run_dir(rdir: str) -> Dict[str, Any]:
    """RunCard for a runs/rN/ session dir: every bench_*.json arm is
    classified (the card is an outage only if ALL arms are), metrics
    jsonl events are tallied, flight dumps counted as anomalies, and the
    graftcheck report becomes the collective inventory. A dir with no
    bench artifacts (the staged-but-unrun r6–r17 backlog) is healthy but
    not baseline-eligible — staged is not measured."""
    rdir = rdir.rstrip("/")
    card = _base_card(os.path.basename(rdir), "session", rdir)
    arms: List[Dict[str, Any]] = []
    for p in sorted(glob.glob(os.path.join(rdir, "bench_*.json"))):
        cls = classify_path(p)
        rec = cls["record"] or {}
        arms.append({
            "arm": os.path.splitext(os.path.basename(p))[0],
            "outage": cls["outage"] is not None,
            "outage_reason": cls["outage"],
            "metric": rec.get("metric"),
            "unit": rec.get("unit"),
            "value": rec.get("value"),
            "config_fingerprint": rec.get("config_fingerprint"),
        })
        if cls["outage"] is None:
            if not card["baseline_eligible"]:
                card["baseline_eligible"] = True
                _absorb_record(card, rec)
            card["backend"] = card["backend"] or \
                backend_from_tail(cls["tail"])
    card["arms"] = arms
    if arms and all(a["outage"] for a in arms):
        card["outage"] = True
        card["outage_reason"] = "all bench arms are outages"
    if not arms:
        card["notes"].append("no bench artifacts — staged or unmeasured")
    for p in sorted(glob.glob(os.path.join(rdir, "**", "metrics*.jsonl"),
                              recursive=True)):
        _tally_events(card, p)
    flights = glob.glob(os.path.join(rdir, "**", "flightdump_*.json"),
                        recursive=True)
    if flights:
        card["anomalies"]["flight_dumps"] = len(flights)
    reports = sorted(glob.glob(os.path.join(rdir, "graftcheck*.json")))
    if reports:
        try:
            rep = json.loads(open(reports[-1], errors="replace").read())
        except (OSError, ValueError):
            rep = None
        if isinstance(rep, dict):
            card["collectives"] = {
                "ok": rep.get("ok"),
                "violations": len(rep.get("violations") or []),
                "contracts": {c.get("name"): c.get("ok")
                              for c in rep.get("contracts") or []
                              if isinstance(c, dict)},
            }
    return card


def index_repo(repo: str) -> List[Dict[str, Any]]:
    """Every run the repo knows about, one RunCard each: the committed
    BENCH/MULTICHIP trajectory in round order, then runs/* session dirs."""
    cards: List[Dict[str, Any]] = []
    for p in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        cards.append(card_from_bench_path(p))
    for p in sorted(glob.glob(os.path.join(repo, "MULTICHIP_r*.json"))):
        cards.append(card_from_multichip_path(p))
    for d in sorted(glob.glob(os.path.join(repo, "runs", "*"))):
        if os.path.isdir(d):
            cards.append(card_from_run_dir(d))
    return cards


# --------------------------------------------------------------- rendering --

def _fmt_value(v: Any) -> str:
    if isinstance(v, float):
        return f"{v:,.3f}" if abs(v) < 1000 else f"{v:,.0f}"
    return str(v)


def format_card(card: Dict[str, Any]) -> List[str]:
    """Human lines for one card (summarize_run / obs_diff stderr)."""
    lines = []
    status = f"OUTAGE ({card['outage_reason']})" if card["outage"] else (
        "baseline-eligible" if card["baseline_eligible"] else "unmeasured")
    lines.append(f"{card['run']} [{card['kind']}] — {status}")
    fp = card.get("config_fingerprint")
    rev = card.get("git_rev")
    lines.append(f"  fingerprint {fp or '(unavailable)'}  "
                 f"git {rev or '(unknown)'}"
                 + (f"  backend {card['backend']}" if card.get("backend")
                    else ""))
    m = card.get("metrics") or {}
    if m.get("metric") is not None:
        lines.append(f"  {m.get('metric')}: "
                     f"{_fmt_value(m.get('value'))} {m.get('unit', '')}")
    for f in ("ttft_ms_p95", "tpot_ms_p95", "decode_hbm_bytes_per_step"):
        if f in m:
            lines.append(f"  {f}: {_fmt_value(m[f])}")
    for arm in card.get("arms") or []:
        tagline = (f"outage: {arm['outage_reason']}" if arm["outage"]
                   else f"{_fmt_value(arm.get('value'))} "
                        f"{arm.get('unit') or ''}")
        lines.append(f"  arm {arm['arm']}: {tagline}")
    led = card.get("ledger") or {}
    if led.get("decisions"):
        lines.append(f"  ledger: {led['decisions']} decision(s), "
                     f"{led['applied']} applied "
                     f"({', '.join(sorted(led['knobs']))})")
    cap = card.get("captures") or {}
    if cap.get("count"):
        lines.append(f"  captures: {cap['count']} "
                     f"({cap['errors']} errored)")
    an = card.get("anomalies") or {}
    if an:
        lines.append("  anomalies: " + ", ".join(
            f"{k}={v}" for k, v in sorted(an.items())))
    for note in card.get("notes") or []:
        lines.append(f"  note: {note}")
    return lines


def _fields_missing(card: dict, fields: Tuple[str, ...]) -> List[str]:
    return [f for f in fields if f not in card]


def validate_card(card: dict) -> List[str]:
    """Presence problems with one RunCard (mirrors schema.validate_record
    for the run_card tag; used by tests and by consumers before keying)."""
    if not isinstance(card, dict):
        return ["card is not a JSON object"]
    problems = [f"run_card: missing required field {f!r}" for f in
                _fields_missing(card, ("tag", "run", "kind", "outage",
                                       "baseline_eligible"))]
    if card.get("tag") != "run_card":
        problems.append(f"run_card: tag is {card.get('tag')!r}")
    if card.get("outage") and card.get("baseline_eligible"):
        problems.append("run_card: an outage can never be "
                        "baseline_eligible")
    return problems
