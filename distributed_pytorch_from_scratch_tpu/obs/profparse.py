"""Measured attribution: parse `jax.profiler` capture dirs and reconcile
them against the analytic roofline (ISSUE 15).

Everything priced in this repo — the roofline phases, the EQuARX int8
rings, the ZeRO comm ladder — is ANALYTIC (obs/attribution.py), and until
now nothing ever checked those prices against a real device timeline:
`AnomalyProfiler` (PR 12) wrote capture dirs no code read. This module is
the reader. A capture dir is the `jax.profiler.start_trace` layout:

    <log_dir>/plugins/profile/<timestamp>/<host>.trace.json.gz

where each `*.trace.json.gz` is a Chrome trace-event JSON: metadata
events name processes ("/device:TPU:0", "/host:CPU") and threads ("XLA
Ops", "tf_XLATfrtCpuClient/..."), and complete ('X') events carry the
executed HLO ops — on every backend the op events carry
`args: {hlo_module, hlo_op}`, which is the discriminator this parser
keys on (python host-callstack events never do).

The parser classifies device events into a fixed MEASURED taxonomy —
fusions/dots (compute), each collective kind (the wires the analytic
model prices), copies/transposes (traffic the model prices at ZERO, so
any measured ms here is a direct "model is wrong here" signal), and the
host gap (device idle inside the capture window) — and emits a
`measured_phases` report in the same phases/total schema the analytic
side folds into (`analytic_phase_report`), so `reconcile()` can compute
per-phase drift and name the worst suspects.

Deliberately dependency-free (stdlib only, no jax): importable from
standalone scripts and from `training/metrics.py` without cycles — the
schema.py convention.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
from typing import Dict, List

#: the measured taxonomy, in render order. "compute" is the fold of
#: fusion+dot+other device work when reconciling (the analytic model
#: prices compute as one roofline, not per-HLO-op).
MEASURED_PHASES = (
    "fusion", "dot", "all-reduce", "all-gather", "reduce-scatter",
    "collective-permute", "all-to-all", "copy", "transpose", "convert",
    "transfer", "other", "host_gap",
)

#: kinds that fold into the single analytic "compute" roofline row
COMPUTE_KINDS = ("fusion", "dot", "convert", "other")
#: the wires comm_attribution prices per collective record
COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")

# op-name prefix -> phase; longest match wins (all-reduce-start must not
# land in a hypothetical "all" bucket). HLO op names come as
# "fusion.123" / "all-reduce-start.1" / "%dot.2" — strip the sigil and
# match the leading identifier.
_PREFIX_TABLE = [
    ("all-reduce", "all-reduce"), ("all_reduce", "all-reduce"),
    ("all-gather", "all-gather"), ("all_gather", "all-gather"),
    ("reduce-scatter", "reduce-scatter"),
    ("reduce_scatter", "reduce-scatter"),
    ("collective-permute", "collective-permute"),
    ("collective_permute", "collective-permute"),
    ("all-to-all", "all-to-all"), ("all_to_all", "all-to-all"),
    ("fusion", "fusion"),
    ("dot", "dot"), ("gemm", "dot"), ("convolution", "dot"),
    ("cublas", "dot"), ("matmul", "dot"),
    ("copy", "copy"), ("dynamic-update-slice", "copy"),
    ("dynamic_update_slice", "copy"),
    ("transpose", "transpose"),
    ("bitcast-convert", "convert"), ("convert", "convert"),
    ("infeed", "transfer"), ("outfeed", "transfer"),
    ("send", "transfer"), ("recv", "transfer"),
]

_TRAILING_ID = re.compile(r"[._]\d+$")


def classify_op(name: str) -> str:
    """HLO op name -> measured phase. 'all-reduce-start.1' -> 'all-reduce',
    'fusion.2047' -> 'fusion', anything unrecognised -> 'other'."""
    n = name.strip().lstrip("%").lower()
    n = _TRAILING_ID.sub("", n)
    for prefix, phase in _PREFIX_TABLE:
        if n.startswith(prefix):
            return phase
    return "other"


def find_trace_files(path: str) -> List[str]:
    """Every `*.trace.json[.gz]` under a capture dir, whatever level the
    caller holds: the profiler log dir (contains plugins/profile/...),
    the plugins/profile dir, one timestamp dir, or a trace file itself."""
    if os.path.isfile(path):
        return [path] if path.endswith((".trace.json", ".trace.json.gz")) \
            else []
    out = []
    for pat in ("*.trace.json.gz", "*.trace.json"):
        out.extend(glob.glob(os.path.join(path, "**", pat), recursive=True))
    return sorted(out)


def _load_trace(path: str) -> dict:
    if path.endswith(".gz"):
        with gzip.open(path, "rt", encoding="utf-8", errors="replace") as f:
            return json.load(f)
    with open(path, encoding="utf-8", errors="replace") as f:
        return json.load(f)


def parse_capture(path: str) -> dict:
    """Parse a capture dir (or one trace file) into the measured report.

    Device events are the 'X' events carrying `args.hlo_op`/`hlo_module`
    (backend-proof: the CPU client thread and the TPU "XLA Ops" lanes
    both stamp them; python host-callstack events never do). Busy time
    sums per device lane (pid); `host_gap` is each lane's capture span
    minus its busy time — device idle the analytic model never prices,
    i.e. dispatch/input starvation made visible.

    Raises ValueError when the path holds no trace files — a capture
    that silently parses to zero phases would defeat the whole point.
    """
    files = find_trace_files(path)
    if not files:
        raise ValueError(f"no *.trace.json[.gz] under {path!r} — not a "
                         f"jax.profiler capture dir "
                         f"(expected plugins/profile/<ts>/)")
    phase_us: Dict[str, float] = {}
    phase_count: Dict[str, int] = {}
    lanes: Dict[tuple, dict] = {}   # (file, pid) -> {busy, t0, t1}
    pnames: Dict[tuple, str] = {}
    n_device = 0
    for fp in files:
        doc = _load_trace(fp)
        for ev in doc.get("traceEvents", []):
            ph = ev.get("ph")
            if ph == "M":
                if ev.get("name") == "process_name":
                    pnames[(fp, ev.get("pid"))] = \
                        ev.get("args", {}).get("name", "")
                continue
            if ph != "X":
                continue
            args = ev.get("args") or {}
            if "hlo_op" not in args and "hlo_module" not in args:
                continue
            dur = float(ev.get("dur", 0.0))
            ts = float(ev.get("ts", 0.0))
            op = args.get("hlo_op") or ev.get("name", "")
            phase = classify_op(str(op))
            phase_us[phase] = phase_us.get(phase, 0.0) + dur
            phase_count[phase] = phase_count.get(phase, 0) + 1
            n_device += 1
            lane = lanes.setdefault((fp, ev.get("pid")),
                                    {"busy": 0.0, "t0": ts, "t1": ts + dur})
            lane["busy"] += dur
            lane["t0"] = min(lane["t0"], ts)
            lane["t1"] = max(lane["t1"], ts + dur)
    if n_device == 0:
        raise ValueError(
            f"{path!r}: {len(files)} trace file(s) but no device op "
            f"events (hlo_op/hlo_module) — the window closed before any "
            f"profiled step executed, or the capture is host-only")
    busy_ms = sum(v for v in phase_us.values()) / 1e3
    gap_us = sum(max(0.0, ln["t1"] - ln["t0"] - ln["busy"])
                 for ln in lanes.values())
    phase_us["host_gap"] = gap_us
    phase_count["host_gap"] = len(lanes)
    phases = [{"name": name,
               "ms": round(phase_us[name] / 1e3, 4),
               "count": phase_count[name]}
              for name in MEASURED_PHASES if name in phase_us]
    total = busy_ms + gap_us / 1e3
    for p in phases:
        p["share"] = round(p["ms"] / total, 4) if total else 0.0
    devices = sorted({pnames.get(k, f"pid{k[1]}") for k in lanes})
    return {
        "source": path,
        "files": len(files),
        "events": n_device,
        "devices": devices,
        "device_busy_ms": round(busy_ms, 4),
        "host_gap_ms": round(gap_us / 1e3, 4),
        "phases": phases,
        "total_ms": round(total, 4),
    }


def phase_ms_map(report: dict) -> Dict[str, float]:
    """phases list -> {name: ms} (both measured and analytic reports)."""
    return {p["name"]: float(p["ms"]) for p in report.get("phases", [])}


def analytic_phase_report(attr_report: dict) -> dict:
    """Fold an `obs.attribution.attribution()` report into the measured
    schema, so the two sides join by phase name:

    * `compute` — the whole roofline step (the analytic model prices
      compute as max(flops, bytes) per phase, never as HLO-op kinds);
    * each collective kind — `serialized_ms` summed over the comm
      records of that kind (count x per-collective ms);
    * `copy`/`transpose`/`host_gap` — priced at 0 by construction: the
      model assumes XLA fuses them away and dispatch is amortised, so
      every measured ms here is drift by definition.
    """
    phases = [{"name": "compute",
               "ms": round(float(attr_report["analytic_step_ms"]), 4)}]
    comm = attr_report.get("comm") or {}
    by_kind: Dict[str, float] = {}
    for r in comm.get("records", []):
        by_kind[r["kind"]] = by_kind.get(r["kind"], 0.0) \
            + float(r["serialized_ms"])
    for kind in COLLECTIVE_KINDS:
        if kind in by_kind:
            phases.append({"name": kind, "ms": round(by_kind[kind], 4)})
    total = sum(p["ms"] for p in phases)
    return {
        "source": "analytic",
        "phases": phases,
        "comm_exposed_ms": round(float(comm.get("comm_exposed_ms", 0.0)), 4),
        "total_ms": round(total, 4),
    }


def reconcile(measured: dict, analytic: dict, steps: int = 1,
              drift_floor_ms: float = 0.05) -> dict:
    """Join a measured report against an analytic one and compute drift.

    `steps` normalises the measured capture (a W-step window) down to
    per-step ms before diffing — the analytic side always prices ONE
    step. Measured compute kinds (fusion/dot/convert/other) fold into
    the single `compute` row the analytic model prices; collective
    kinds join one-to-one; copy/transpose/host_gap join against an
    analytic 0.

    Each row: {phase, measured_ms, analytic_ms, drift_pct} where
    drift_pct = (measured - analytic) / analytic x 100 (None when the
    analytic side prices the phase at 0 — an unpriced phase has no
    denominator, its measured ms IS the finding). `suspects` ranks the
    "model is wrong here" rows by absolute ms gap, skipping rows under
    `drift_floor_ms` — sub-floor noise must not outrank real drift.
    """
    steps = max(int(steps), 1)
    m = phase_ms_map(measured)
    a = phase_ms_map(analytic)
    rows = []
    compute_m = sum(m.get(k, 0.0) for k in COMPUTE_KINDS) / steps
    order = ["compute"] + list(COLLECTIVE_KINDS) + ["copy", "transpose",
                                                   "transfer", "host_gap"]
    for name in order:
        mv = compute_m if name == "compute" else m.get(name, 0.0) / steps
        av = a.get(name, 0.0)
        if mv == 0.0 and av == 0.0:
            continue
        drift = round((mv - av) / av * 100.0, 1) if av > 0 else None
        rows.append({"phase": name, "measured_ms": round(mv, 4),
                     "analytic_ms": round(av, 4), "drift_pct": drift})
    suspects = []
    for r in rows:
        gap = abs(r["measured_ms"] - r["analytic_ms"])
        if gap < drift_floor_ms:
            continue
        note = ("unpriced by the analytic model — every measured ms is "
                "drift" if r["drift_pct"] is None else
                f"{r['drift_pct']:+.1f}% vs the analytic price")
        suspects.append({"phase": r["phase"], "gap_ms": round(gap, 4),
                         "note": note})
    suspects.sort(key=lambda s: -s["gap_ms"])
    measured_step = round(measured["total_ms"] / steps, 4)
    analytic_step = round(analytic.get("total_ms", 0.0), 4)
    comm_ms = round(sum(m.get(k, 0.0) for k in COLLECTIVE_KINDS) / steps, 4)
    return {
        "steps": steps,
        "phases": {r["phase"]: r["measured_ms"] for r in rows},
        "rows": rows,
        "suspects": suspects,
        "measured_step_ms": measured_step,
        "analytic_step_ms": analytic_step,
        "comm_ms": comm_ms,
        "total_drift_pct": (round((measured_step - analytic_step)
                                  / analytic_step * 100.0, 1)
                            if analytic_step > 0 else None),
    }


def format_reconcile(rec: dict) -> str:
    """Human table for summarize_run's 'Measured vs analytic' section."""
    lines = [f"  measured {rec['measured_step_ms']:.2f} ms/step vs "
             f"analytic {rec['analytic_step_ms']:.2f} ms/step"
             + (f" ({rec['total_drift_pct']:+.1f}%)"
                if rec.get("total_drift_pct") is not None else "")
             + f" over {rec['steps']} profiled step(s)"]
    lines.append("  phase                 measured_ms  analytic_ms   drift")
    for r in rec["rows"]:
        d = ("      —" if r["drift_pct"] is None
             else f"{r['drift_pct']:+6.1f}%")
        lines.append(f"  {r['phase']:<21} {r['measured_ms']:11.3f}  "
                     f"{r['analytic_ms']:11.3f}  {d}")
    for s in rec["suspects"][:3]:
        lines.append(f"  suspect: {s['phase']} — {s['gap_ms']:.3f} ms gap "
                     f"({s['note']})")
    return "\n".join(lines)
