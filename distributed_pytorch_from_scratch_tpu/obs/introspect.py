"""Compiled-program introspection: what did XLA actually build?

Run once per program right after compile (zero steady-state cost):
  * `compiled.cost_analysis()`  -> FLOPs + bytes accessed, the ground truth
    to cross-check the hand-rolled `model_flops_per_step` MFU estimate
    against (a 2x disagreement means the MFU number is fiction);
  * `compiled.memory_analysis()` -> peak HBM (arguments + outputs + temps),
    the number that says how close to OOM the config runs;
  * the optimized HLO text -> per-collective comm byte counts (all-reduce /
    all-gather / reduce-scatter / all-to-all / collective-permute), the
    visibility that motivates comm-optimization work (arXiv:2211.05322) and
    quantized-collective accounting (arXiv:2506.17615): you cannot shrink
    traffic you cannot see.

Every probe is best-effort — backends without an analysis return None for
that field rather than failing the run.
"""

from __future__ import annotations

import re
from typing import Dict, Optional

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e5m2": 1, "f8e4m3fn": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# `%x = f32[8,128]{1,0} all-reduce(...)` / tuple-shaped async starts.
# `-start` variants fold into the base op; `-done` carries no new bytes.
_COLL_RE = re.compile(
    r"=\s+(?P<shape>[^=\n]*?)\s*"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute|collective-broadcast)(?P<start>-start)?\(")

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([\d,]*)\]")


def _member_bytes(shape: str) -> "list[int]":
    """Bytes of each `dtype[dims]` member in an HLO shape string (unknown
    dtypes count 0)."""
    out = []
    for dtype, dims in _SHAPE_RE.findall(shape):
        size = _DTYPE_BYTES.get(dtype)
        if size is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        out.append(n * size)
    return out


def _shape_bytes(shape: str) -> int:
    """Total bytes of an HLO shape string (tuples sum their members)."""
    return sum(_member_bytes(shape))


def parse_collectives(hlo_text: str) -> Dict[str, dict]:
    """{op_kind: {"count": N, "bytes": output bytes summed}} from optimized
    HLO. Output-shape bytes are the standard per-hop accounting unit (a
    ring all-reduce moves ~2x this on the wire; the relative picture across
    collectives is what matters). Async `-start` forms carry a
    (operand..., result, context...) tuple shape — only the LARGEST member
    (the result) is counted, so the same logical op reports the same bytes
    whether XLA lowered it sync or async."""
    out: Dict[str, dict] = {}
    for m in _COLL_RE.finditer(hlo_text):
        op = m.group("op")
        rec = out.setdefault(op, {"count": 0, "bytes": 0})
        rec["count"] += 1
        members = _member_bytes(m.group("shape"))
        rec["bytes"] += (max(members, default=0) if m.group("start")
                         else sum(members))
    return out


def _cost_dict(compiled) -> Optional[dict]:
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return dict(ca) if ca else None


def analyze_compiled(compiled) -> dict:
    """Best-effort {flops, bytes_accessed, peak_hbm_bytes, collectives,
    comm_bytes} for one compiled executable."""
    out = {"flops": None, "bytes_accessed": None, "peak_hbm_bytes": None,
           "alias_bytes": None, "collectives": {}, "comm_bytes": 0}
    cost = _cost_dict(compiled)
    if cost:
        flops = cost.get("flops")
        out["flops"] = float(flops) if flops is not None else None
        ba = cost.get("bytes accessed")
        out["bytes_accessed"] = float(ba) if ba is not None else None
    try:
        ma = compiled.memory_analysis()
        out["peak_hbm_bytes"] = int(
            getattr(ma, "argument_size_in_bytes", 0)
            + getattr(ma, "output_size_in_bytes", 0)
            + getattr(ma, "temp_size_in_bytes", 0)
            + getattr(ma, "alias_size_in_bytes", 0))
        # donation hygiene: the bytes the donated params/opt state actually
        # aliased in-place. A train-step program reporting ~0 here means a
        # refactor broke the donation (e.g. a dtype change) and the
        # optimizer state silently doubled its footprint.
        out["alias_bytes"] = int(getattr(ma, "alias_size_in_bytes", 0))
    except Exception:
        pass
    try:
        colls = parse_collectives(compiled.as_text())
        out["collectives"] = colls
        out["comm_bytes"] = sum(c["bytes"] for c in colls.values())
    except Exception:
        pass
    return out


def format_analysis(a: dict, model_flops: Optional[float] = None,
                    steps_in_program: int = 1) -> str:
    """One human line; when `model_flops` (the hand-rolled per-step
    estimate) is given, append the cross-check ratio."""
    gib = 1024 ** 3
    parts = []
    if a.get("flops") is not None:
        parts.append(f"{a['flops'] / 1e9:.2f} GFLOPs/program")
        if model_flops:
            ratio = a["flops"] / max(model_flops * steps_in_program, 1e-9)
            parts.append(f"{ratio:.2f}x the model_flops_per_step estimate")
    if a.get("bytes_accessed") is not None:
        parts.append(f"{a['bytes_accessed'] / gib:.2f} GiB accessed")
    if a.get("peak_hbm_bytes"):
        parts.append(f"peak HBM {a['peak_hbm_bytes'] / gib:.2f} GiB")
    if a.get("alias_bytes") is not None:
        parts.append(f"donated/aliased {a['alias_bytes'] / gib:.2f} GiB "
                     f"in-place")
    if a.get("collectives"):
        comm = ", ".join(
            f"{op} x{c['count']} ({c['bytes'] / 2 ** 20:.1f} MiB)"
            for op, c in sorted(a["collectives"].items()))
        parts.append(f"comm: {comm}")
    return "compiled step: " + ("; ".join(parts) if parts
                                else "no analysis available on this backend")
