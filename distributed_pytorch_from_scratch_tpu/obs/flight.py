"""Anomaly flight recorder: a bounded ring of recent telemetry that every
anomaly path dumps as one self-contained JSON file (ISSUE 10).

The r4/r5 outages were post-mortemed from TensorBoard scrollback and
half-overwritten logs: the sentinel wrote its loss history, the watchdog
printed its last phase, the serving engine counted preemptions — three
disjoint partial contexts, none of which showed what the SYSTEM looked
like in the seconds before the event. The flight recorder fixes the shape
of the problem: producers `record()` cheap dict events (spans, heartbeats,
pool stats, scheduler decisions) into a lock-protected `deque(maxlen=N)` —
O(1) memory forever — and any anomaly path calls `dump(trigger)` to freeze
the ring plus the triggering event into `flightdump_<tag>_<seq>.json`.

One recorder is shared by every producer in a process (the train loop's
observer, or a serving engine + its scheduler + KV pool), so a dump is the
interleaved recent history of all of them, in arrival order. `max_dumps`
caps the files a preemption storm can write; the skipped count is
reported so a capped storm is still visible in the last dump's metadata.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, List, Optional

from .schema import EVENT_SCHEMA_VERSION


class FlightRecorder:
    """`profiler` (ISSUE 12): an optional `training.metrics.AnomalyProfiler`
    (duck-typed: `.arm(tag)` -> capture path | None, `.tick(step, sync)`).
    Every successful `dump()` ARMS it, and the capture path it will write
    to is stamped into the dump as `"profile"` — so an anomaly's flight
    dump cross-links the device profile of the steps around it. The
    owning host loop drives `tick()` once per dispatch; arming from the
    dump path (any thread) only flips a flag."""

    def __init__(self, dump_dir: str, maxlen: int = 512, max_dumps: int = 8,
                 clock: Callable[[], float] = time.monotonic,
                 profiler=None):
        if maxlen < 1:
            raise ValueError(f"flight ring maxlen must be >= 1, got {maxlen}")
        self.dump_dir = dump_dir
        self.maxlen = maxlen
        self.max_dumps = max_dumps
        self.profiler = profiler
        self._clock = clock
        self._ring: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self.recorded = 0          # total record() calls (ring may be full)
        self.dumps: List[str] = []  # paths actually WRITTEN, trigger order
        self.dumps_skipped = 0     # triggers past the max_dumps cap
        self.dump_failures = 0     # writes that failed (disk full, ...)
        self._dump_seq = 0         # filename sequence (failed writes too)
        self._dumps_inflight = 0   # reserved slots with writes pending

    def record(self, kind: str, **fields) -> None:
        """Append one event to the ring. Cheap enough for per-decode-step
        pool stats and per-page scheduler decisions — the deque evicts the
        oldest entry at capacity, so memory is bounded whatever the rate."""
        ev = {"ts": round(self._clock(), 6), "kind": kind, **fields}
        with self._lock:
            self._ring.append(ev)
            self.recorded += 1

    def tick(self, step: int, sync=None) -> None:
        """Host-loop heartbeat for the anomaly profiler: starts an armed
        `jax.profiler` window at the next step boundary and stops it when
        the window elapses. Call once per dispatch from the thread that
        owns the device (never from the watchdog thread — jax profiling
        is driven from the host loop; arming is the cross-thread part)."""
        if self.profiler is not None:
            self.profiler.tick(step, sync=sync)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def dump(self, trigger: dict, tag: str = "anomaly") -> Optional[str]:
        """Freeze the ring + `trigger` into a self-contained JSON file and
        return its path. Returns None once `max_dumps` files exist (a
        preemption storm must not fill the disk); the cap-skip is counted
        and stamped into every written dump's metadata. A FAILED write
        (disk full, dump dir removed) also returns None — a diagnostic
        artifact must never kill the run it is diagnosing — and does NOT
        occupy a max_dumps slot or appear in `dumps`."""
        with self._lock:
            if len(self.dumps) + self._dumps_inflight >= self.max_dumps:
                self.dumps_skipped += 1
                return None
            ring = list(self._ring)
            # reserve a cap slot + a distinct FILENAME under the lock
            # (concurrent triggers: watchdog thread + main loop); the
            # dumps list only gains the path once the bytes are on disk
            self._dumps_inflight += 1
            seq = self._dump_seq
            self._dump_seq += 1
            path = os.path.join(
                self.dump_dir, f"flightdump_{tag}_{seq:03d}.json")
        # arm the anomaly profiler BEFORE writing, so the dump can carry
        # the capture path it cross-links (None when profiling is off,
        # the capture budget is spent, or no host loop ever ticks again)
        profile_path = (self.profiler.arm(tag)
                        if self.profiler is not None else None)
        doc = {
            "schema_version": EVENT_SCHEMA_VERSION,
            "tag": tag,
            "trigger": {"ts": round(self._clock(), 6), **trigger},
            "profile": profile_path,
            "ring": ring,
            "ring_maxlen": self.maxlen,
            "recorded_total": self.recorded,
            "dumps_skipped": self.dumps_skipped,
            "wall_time": time.time(),
        }
        tmp = path + ".tmp"
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, path)
        except OSError:
            with self._lock:
                self._dumps_inflight -= 1
                self.dump_failures += 1
            return None
        with self._lock:
            self._dumps_inflight -= 1
            self.dumps.append(path)
        return path
