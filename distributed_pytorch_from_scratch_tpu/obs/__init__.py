"""Observability: step-timeline tracing, goodput accounting, compiled-
program introspection, a training-health sentinel, a hang watchdog,
(v2, ISSUE 10) per-request tracing, an anomaly flight recorder, and
cross-rank skew attribution, (v3, ISSUE 12) the live telemetry
plane: per-process exporters, the fleet collector, cross-process trace
propagation, and anomaly-triggered device profiling, (v5, ISSUE 16)
the control plane: drift-driven retuning with an auditable decision
ledger, and (v6, ISSUE 17) run forensics: the run-archive RunCard
index, the cross-run diff engine, and trajectory changepoint triage.

See docs/OBSERVABILITY.md for the operator's view (trace format, goodput
buckets, sentinel thresholds, flight-dump walkthrough, live endpoints).
"""

from .attribution import (attribution, flash_tile_stats, format_attribution,
                          kv_transfer_attribution, rank_skew)
from .collector import FleetCollector, JsonlTailer
from .control import (CONTROL_MODES, Knob, RetuneAdvisor,
                      control_safe_point)
from .profparse import (analytic_phase_report, format_reconcile,
                        parse_capture, reconcile)
from .flight import FlightRecorder
from .goodput import BUCKETS, GoodputMeter
from .introspect import analyze_compiled, format_analysis, parse_collectives
from .observer import TrainObserver
from .reqtrace import RequestTracer, TraceContext, merge_traces
from .rundiff import (changepoint, diff_runs, format_diff,
                      format_trajectory, trajectory_report)
from .runindex import (card_from_bench_path, card_from_run_dir,
                       config_fingerprint, format_card, index_repo,
                       outage_reason, run_stamp)
from .schema import (EVENT_REQUIRED, EVENT_SCHEMA_VERSION, validate_jsonl,
                     validate_record)
from .sentinel import HealthSentinel, TrainingHealthError
from .telemetry import TelemetryExporter, fleet_slo_attainment
from .trace import SpanTracer
from .watchdog import HangWatchdog

__all__ = [
    "BUCKETS", "CONTROL_MODES", "EVENT_REQUIRED", "EVENT_SCHEMA_VERSION",
    "FleetCollector", "FlightRecorder", "GoodputMeter", "HangWatchdog",
    "HealthSentinel", "JsonlTailer", "Knob", "RequestTracer",
    "RetuneAdvisor", "SpanTracer", "TelemetryExporter", "TraceContext",
    "TrainObserver", "TrainingHealthError", "analytic_phase_report",
    "analyze_compiled", "attribution", "card_from_bench_path",
    "card_from_run_dir", "changepoint", "config_fingerprint",
    "control_safe_point", "diff_runs", "flash_tile_stats",
    "fleet_slo_attainment", "format_analysis", "format_attribution",
    "format_card", "format_diff", "format_reconcile",
    "format_trajectory", "index_repo", "kv_transfer_attribution",
    "merge_traces", "outage_reason",
    "parse_capture", "parse_collectives", "rank_skew", "reconcile",
    "run_stamp", "trajectory_report", "validate_jsonl",
    "validate_record",
]
