"""Observability: step-timeline tracing, goodput accounting, compiled-
program introspection, a training-health sentinel, a hang watchdog, and
(v2, ISSUE 10) per-request tracing, an anomaly flight recorder, and
cross-rank skew attribution.

See docs/OBSERVABILITY.md for the operator's view (trace format, goodput
buckets, sentinel thresholds, flight-dump walkthrough).
"""

from .attribution import (attribution, flash_tile_stats, format_attribution,
                          rank_skew)
from .flight import FlightRecorder
from .goodput import BUCKETS, GoodputMeter
from .introspect import analyze_compiled, format_analysis, parse_collectives
from .observer import TrainObserver
from .reqtrace import RequestTracer
from .schema import (EVENT_REQUIRED, EVENT_SCHEMA_VERSION, validate_jsonl,
                     validate_record)
from .sentinel import HealthSentinel, TrainingHealthError
from .trace import SpanTracer
from .watchdog import HangWatchdog

__all__ = [
    "BUCKETS", "EVENT_REQUIRED", "EVENT_SCHEMA_VERSION", "FlightRecorder",
    "GoodputMeter", "HangWatchdog", "HealthSentinel", "RequestTracer",
    "SpanTracer", "TrainObserver", "TrainingHealthError",
    "analyze_compiled", "attribution", "flash_tile_stats",
    "format_analysis", "format_attribution", "parse_collectives",
    "rank_skew", "validate_jsonl", "validate_record",
]
