"""Observability: step-timeline tracing, goodput accounting, compiled-
program introspection, a training-health sentinel, and a hang watchdog.

See docs/OBSERVABILITY.md for the operator's view (trace format, goodput
buckets, sentinel thresholds).
"""

from .attribution import (attribution, flash_tile_stats, format_attribution)
from .goodput import BUCKETS, GoodputMeter
from .introspect import analyze_compiled, format_analysis, parse_collectives
from .observer import TrainObserver
from .sentinel import HealthSentinel, TrainingHealthError
from .trace import SpanTracer
from .watchdog import HangWatchdog

__all__ = [
    "BUCKETS", "GoodputMeter", "HangWatchdog", "HealthSentinel",
    "SpanTracer", "TrainObserver", "TrainingHealthError",
    "analyze_compiled", "attribution", "flash_tile_stats",
    "format_analysis", "format_attribution", "parse_collectives",
]
