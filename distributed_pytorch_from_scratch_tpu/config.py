"""Typed configuration for the TPU-native framework.

The reference scattered configuration across three channels: argparse flags
(`/root/reference/train.py:25-52`), a frozen dataclass (`ModelArgumments`,
`/root/reference/constants.py:9-17`) and ambient environment variables
(``DTYPE``/``DEVICE``, read at `/root/reference/models/model.py:39-40,153`).
Here everything is a typed dataclass; dtype is an explicit field, and the CLI
produces these dataclasses instead of an untyped `Namespace`.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Optional

import jax.numpy as jnp

# Special-token conventions, byte-compatible with the reference
# (`/root/reference/constants.py:3-6`) so its tokenizer.json and token-JSON
# files interoperate.
BOS_TOKEN = "<BOS>"
EOS_TOKEN = "<EOS>"
UNK_TOKEN = "<UNK>"
IGNORE_INDEX = -1

_DTYPES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
}


def resolve_dtype(name: str):
    if name not in _DTYPES:
        raise ValueError(f"Unknown dtype {name!r}; expected one of {sorted(_DTYPES)}")
    return _DTYPES[name]


@dataclass(frozen=True)
class ModelConfig:
    """LLaMA-style decoder-only transformer shape.

    Defaults mirror the reference's `ModelArgumments`
    (`/root/reference/constants.py:9-17`): a ~45M-parameter model.
    """

    attn_dim: int = 512
    ffn_dim: int = 2048
    num_heads: int = 8
    num_layers: int = 12
    vocab_size: int = 1024
    maxlen: int = 1000
    rope_theta: float = 10000.0
    # Grouped-query attention: number of K/V heads (each shared by
    # num_heads/num_kv_heads query heads). None = num_heads = the
    # reference's plain multi-head attention.
    num_kv_heads: "int | None" = None
    # Dtype used for matmuls/activations inside the forward pass. Parameters
    # and the loss always stay float32 (the reference's autocast semantics:
    # `/root/reference/train.py:99-104`).
    compute_dtype: str = "float32"
    # Mixture-of-Experts: 0 = dense SwiGLU FFN (the reference's only FFN,
    # `/root/reference/models/model.py:81-95`); > 0 swaps every layer's FFN
    # for a top-k routed MoE (parallel/moe.py) with experts sharded over the
    # mesh axis 'ep'. No reference counterpart (SURVEY §2.4 "EP ❌").
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    moe_aux_coef: float = 0.01   # load-balance loss weight (Switch: 0.01)
    moe_z_coef: float = 1e-3     # router z-loss weight (ST-MoE: 1e-3)

    @property
    def head_dim(self) -> int:
        assert self.attn_dim % self.num_heads == 0
        return self.attn_dim // self.num_heads

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads if self.num_kv_heads is not None else self.num_heads

    @property
    def kv_dim(self) -> int:
        """Output width of wk/wv: kv_heads * head_dim (== attn_dim for MHA)."""
        return self.kv_heads * self.head_dim

    def padded_vocab_size(self, tp_size: int) -> int:
        """Vocab size rounded up to a multiple of tp_size.

        The reference handles non-divisible vocabs by giving the LAST rank a
        ragged partition (`/root/reference/models/layers.py:126-131`). Ragged
        shards are hostile to SPMD/XLA, so we instead pad the vocab dimension
        and mask the padded logits to -inf (see models/transformer.py).
        """
        return ((self.vocab_size + tp_size - 1) // tp_size) * tp_size

    def num_params(self) -> int:
        d, f, v, L = self.attn_dim, self.ffn_dim, self.vocab_size, self.num_layers
        kd = self.kv_dim
        attn = 2 * d * d + 2 * d * kd + 2 * d + 2 * kd  # wq/wo + wk/wv (+ biases)
        if self.num_experts:
            ffn = self.num_experts * 3 * d * f + d * self.num_experts  # experts + router
        else:
            ffn = 3 * d * f + 2 * f + d          # gate/up/down weights + biases
        norms = 2 * d
        return v * d + L * (attn + ffn + norms) + d + v * d + v  # emb + layers + final norm + lm_head


# CLI flag-string -> Transformer.remat value (shared by train.py/bench.py)
REMAT_CHOICES = {"true": True, "dots": "dots", "false": False}

# Named model presets (BASELINE.md "configs to cover"). "45m" is the
# reference's exact shape (`/root/reference/constants.py:9-17`); "gpt2-124m"
# is BASELINE config 3 (GPT-2 small: d=768, 12 heads/layers, vocab 50257,
# ctx 1024 — untied lm_head like the reference, so ~190M actual params);
# "tiny" is BASELINE config 1 (2-layer d_model=128 GPT for CPU smoke runs).
MODEL_PRESETS = {
    "45m": ModelConfig(),
    "gpt2-124m": ModelConfig(attn_dim=768, ffn_dim=3072, num_heads=12,
                             num_layers=12, vocab_size=50257, maxlen=1024),
    "tiny": ModelConfig(attn_dim=128, ffn_dim=512, num_heads=4,
                        num_layers=2, vocab_size=1024, maxlen=256),
    # the 45m shape with its FFN swapped for 8 routed experts (top-2):
    # ~160M total params, 45m-class active compute per token
    "45m-moe8": ModelConfig(num_experts=8, moe_top_k=2),
    # GPT-2 Medium shape — 3x the reference's biggest config; params+Adam
    # state ~4.3 GiB f32, fits the 16 GiB chip with remat at b4xt1024
    "gpt2-355m": ModelConfig(attn_dim=1024, ffn_dim=4096, num_heads=16,
                             num_layers=24, vocab_size=50257, maxlen=1024),
}


def model_preset(name: str, **overrides) -> ModelConfig:
    if name not in MODEL_PRESETS:
        raise ValueError(
            f"unknown model preset {name!r}; expected one of "
            f"{sorted(MODEL_PRESETS)}")
    return dataclasses.replace(MODEL_PRESETS[name], **overrides)


@dataclass(frozen=True)
class MeshConfig:
    """5-D device mesh: ('dp', 'pp', 'cp', 'ep', 'tp').

    The reference supports exactly one axis (TP == world size, asserted at
    `/root/reference/process_manager.py:13`). We design for >=2 axes from day
    one per BASELINE.json config 5 (TPxDP 4x2), plus a context-parallel axis
    'cp' for long sequences (ring attention / Ulysses), a pipeline axis 'pp'
    (stage-sharded layer stack), and an expert axis 'ep' (MoE expert
    sharding; a pure extra data axis for dense compute) — all absent from
    the reference (SURVEY §2.4) and all defaulting to size 1, in which case
    the mesh degenerates to the reference-parity ('dp', 'tp') shape.
    """

    dp: int = 1
    tp: int = 1
    cp: int = 1
    ep: int = 1
    pp: int = 1

    @property
    def world_size(self) -> int:
        return self.dp * self.pp * self.cp * self.ep * self.tp


@dataclass(frozen=True)
class OptimizerConfig:
    """Adam + OneCycle, matching the reference's
    `optim.Adam` + `OneCycleLR` setup (`/root/reference/train.py:83-84`),
    including torch's OneCycle defaults (div_factor=25, final_div_factor=1e4,
    cosine annealing, and beta1 cycling between 0.85 and 0.95)."""

    lr: float = 3e-4
    warmup_steps: int = 2000
    max_steps: int = 20000
    betas: tuple = (0.9, 0.999)
    eps: float = 1e-8
    # OneCycle details (torch defaults)
    div_factor: float = 25.0
    final_div_factor: float = 1e4
    cycle_momentum: bool = True
    base_momentum: float = 0.85
    max_momentum: float = 0.95
    # Global-norm gradient clipping (torch clip_grad_norm_ semantics: one
    # norm over ALL grads, scale = max_norm / (norm + 1e-6) when exceeded).
    # None = off — the reference has no clipping (SURVEY non-goals), so off
    # stays the parity default.
    clip_grad_norm: "float | None" = None
    # Decoupled weight decay (torch.optim.AdamW semantics: params shrink by
    # lr*wd BEFORE the Adam step). 0.0 = plain Adam, the reference's setup.
    weight_decay: float = 0.0
    # 'onecycle' (reference parity) or 'cosine' (linear warmup over
    # warmup_steps -> cosine decay to cosine_min_ratio * lr; beta1 fixed —
    # the standard pretraining schedule the reference lacks).
    lr_schedule: str = "onecycle"
    cosine_min_ratio: float = 0.1


@dataclass(frozen=True)
class TrainConfig:
    data_path: str = ""
    save_dir: str = "./checkpoints"
    batch_size: int = 32
    max_steps: int = 20000
    log_interval: int = 100
    save_interval: int = 1000
    reserve_last_n_ckpts: int = -1
    bf16: bool = False
    seed: int = 0
    # Fixed-shape padding length for XLA (reference pads to per-batch max,
    # `/root/reference/dataset.py:41` — dynamic shapes would recompile under
    # jit, so we pad to model maxlen; CE ignore-index masking keeps the loss
    # identical).
    pad_to: Optional[int] = None
    # 'vocab_parallel' computes the CE loss on sharded logits (no all-gather
    # of the (b, t, vocab) tensor); 'gather' materialises full logits first,
    # matching the reference's lm_head gather_output=True data path
    # (`/root/reference/models/model.py:137`). Both are numerically equal.
    loss_mode: str = "vocab_parallel"
    # Resume from the latest checkpoint in save_dir (the reference cannot
    # resume training at all — save-only, `/root/reference/train.py:121-133`).
    resume: bool = False


@dataclass(frozen=True)
class EvalConfig:
    data_path: str = ""
    tokenizer_path: str = ""
    ckpt_dir: str = ""
    max_decode_len: int = 128
    batch_size: int = 1
    seed: int = 0
    bf16: bool = True


def replace(cfg, **kw):
    return dataclasses.replace(cfg, **kw)
