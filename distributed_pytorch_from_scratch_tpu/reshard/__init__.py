"""reshard/ — mesh-elastic checkpoints + any-layout→any-layout
redistribution (ISSUE 20).

Three layers:

* `layout` — the Layout record (mesh axes + per-leaf canonical
  PartitionSpec + ZeRO stage) that `save_checkpoint` stamps into every
  shard and the planner consumes; legacy unstamped checkpoints resolve
  through a loud filename-inference path, never a crash.
* `plan` — the redistribution pass: per-leaf fragment schedules (the
  interval intersections of the source and target shard grids) plus the
  device-op classification (copy / gather / slice / permute) whose
  inventory the graftcheck layer-2 contract pins.
* `apply` — the executors: a STREAMED host path (leaf-at-a-time, peak
  host bytes bounded by one leaf + one source shard, metered and
  asserted in tests — never the one-shot full-tree materialisation the
  "host-gather-in-reshard" lint forbids) for file→file and file→device,
  and a per-leaf `device_put` path for live params (fleet replica
  restarts at a new tp width).
"""

from .layout import (LAYOUT_KEY, Layout, layouts_equal, make_layout,
                     read_stamp, resolve_source_layout)
from .plan import LeafPlan, ReshardError, ReshardPlan, plan_reshard
from .apply import (HostMeter, plan_checkpoint, reshard_checkpoint,
                    reshard_params, stream_load)

__all__ = [
    "LAYOUT_KEY", "Layout", "layouts_equal", "make_layout", "read_stamp",
    "resolve_source_layout", "LeafPlan", "ReshardError", "ReshardPlan",
    "plan_reshard", "HostMeter", "plan_checkpoint", "reshard_checkpoint",
    "reshard_params", "stream_load",
]
