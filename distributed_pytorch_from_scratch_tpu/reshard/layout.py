"""Layout records + checkpoint layout stamping (reshard subsystem).

A `Layout` is everything the redistribution planner needs to know about
where a checkpoint's tree LIVED: the mesh axes that were larger than 1,
the per-leaf canonical PartitionSpec (flat-keyed exactly like the
checkpoint's npz members — ``param/embedding/weight``), and the ZeRO
stage. The stage is carried separately from the specs on purpose: on
disk every shard holds GLOBAL values sliced only along its tp dim, and
the dp extension ZeRO applies is a DEVICE-layout fact derived from the
same one rule everywhere (`training/zero._zero_dim`) — stamping the
derived specs too would let the two drift.

`save_checkpoint` serialises a Layout into each shard under
``__layout__`` (a JSON string; `assemble` ignores any ``__``-prefixed
member, so pre-ISSUE-20 readers skip it untouched). Legacy checkpoints
without the stamp resolve through `resolve_source_layout`'s loud
"layout inferred from filenames" note — the runindex legacy-record
convention — never a crash.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Optional, Tuple

from jax.sharding import PartitionSpec as P

LAYOUT_KEY = "__layout__"
LAYOUT_VERSION = 1


def _flatten_specs(specs: Any, prefix: str = "param") -> Dict[str, P]:
    """Canonical spec tree -> {checkpoint flat key: PartitionSpec}, the
    same key derivation as `training/checkpoint._flatten` (specs are
    pytrees of P leaves, so the flatten walks with is_leaf)."""
    import jax
    flat: Dict[str, P] = {}
    pairs = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, P))[0]
    for path, leaf in pairs:
        key = prefix + "".join(
            f"/{p.key}" if hasattr(p, "key") else f"/{p.idx}" for p in path)
        flat[key] = leaf
    return flat


def _spec_to_jsonable(spec: P) -> list:
    out = []
    for entry in tuple(spec):
        if entry is None or isinstance(entry, str):
            out.append(entry)
        else:                       # a tuple of axis names, e.g. ("dp","tp")
            out.append(list(entry))
    return out


def _spec_from_jsonable(entries: list) -> P:
    return P(*[tuple(e) if isinstance(e, list) else e for e in entries])


@dataclasses.dataclass(frozen=True)
class Layout:
    """One checkpoint-producing (or -consuming) arrangement: mesh axes of
    size > 1, flat canonical specs, and the ZeRO stage."""

    mesh_axes: Tuple[Tuple[str, int], ...]
    specs: Dict[str, P]             # "param/..." -> canonical PartitionSpec
    zero_stage: int = 0

    def axis_size(self, name: str) -> int:
        for axis, size in self.mesh_axes:
            if axis == name:
                return size
        return 1

    @property
    def tp(self) -> int:
        return self.axis_size("tp")

    @property
    def dp(self) -> int:
        return self.axis_size("dp")

    def spec_for(self, key: str) -> P:
        """Spec of any flat key — moments ride their param's spec (the
        `save_checkpoint` rule: mu/nu shard exactly like param)."""
        kind, _, rest = key.partition("/")
        pkey = "param/" + rest if kind in ("mu", "nu") else key
        try:
            return self.specs[pkey]
        except KeyError:
            raise KeyError(f"no spec for checkpoint key {key!r} "
                           f"(looked up {pkey!r})") from None

    def describe(self) -> str:
        axes = "x".join(f"{a}{s}" for a, s in self.mesh_axes) or "single"
        return f"{axes} zero{self.zero_stage}"

    def signature(self) -> tuple:
        """Order-independent comparable form (mesh axis order is a mesh
        construction detail, not a layout difference)."""
        return (tuple(sorted(self.mesh_axes)), int(self.zero_stage),
                tuple(sorted((k, tuple(_spec_to_jsonable(s)))
                             for k, s in self.specs.items())))

    def to_json(self) -> str:
        return json.dumps({
            "version": LAYOUT_VERSION,
            "mesh_axes": [[a, s] for a, s in self.mesh_axes],
            "zero_stage": int(self.zero_stage),
            "specs": {k: _spec_to_jsonable(s)
                      for k, s in sorted(self.specs.items())},
        })

    @classmethod
    def from_json(cls, text: str) -> "Layout":
        d = json.loads(text)
        if d.get("version", 0) > LAYOUT_VERSION:
            raise ValueError(
                f"checkpoint layout stamp is version {d['version']}; this "
                f"reader understands <= {LAYOUT_VERSION} — update before "
                f"resharding")
        return cls(
            mesh_axes=tuple((a, int(s)) for a, s in d["mesh_axes"]),
            specs={k: _spec_from_jsonable(v)
                   for k, v in d["specs"].items()},
            zero_stage=int(d["zero_stage"]))


def layouts_equal(a: Layout, b: Layout) -> bool:
    return a.signature() == b.signature()


def mesh_axes_of(mesh) -> Tuple[Tuple[str, int], ...]:
    """(axis, size) pairs of a live Mesh, size-1 axes dropped (an unused
    axis is a mesh-construction detail, not a layout fact)."""
    return tuple((str(name), int(size))
                 for name, size in zip(mesh.axis_names, mesh.devices.shape)
                 if int(size) > 1)


def make_layout(mesh_axes: Any, specs: Any, zero_stage: int = 0) -> Layout:
    """Build a Layout from a live Mesh (or explicit (axis, size) pairs)
    and a canonical spec TREE (`model.canonical_specs()`)."""
    if hasattr(mesh_axes, "axis_names"):
        mesh_axes = mesh_axes_of(mesh_axes)
    else:
        mesh_axes = tuple((a, int(s)) for a, s in mesh_axes if int(s) > 1)
    flat = specs if isinstance(specs, dict) and all(
        isinstance(k, str) and "/" in k for k in specs) else \
        _flatten_specs(specs)
    return Layout(mesh_axes=mesh_axes, specs=dict(flat),
                  zero_stage=int(zero_stage))


def stamp(shard: Dict[str, Any], layout: Layout) -> None:
    """Add the layout stamp to one shard dict about to be npz-written."""
    import numpy as np
    shard[LAYOUT_KEY] = np.asarray(layout.to_json())


def read_stamp(npz) -> Optional[Layout]:
    """The Layout stamped into an open NpzFile (or shard dict), None when
    the checkpoint predates the stamp."""
    try:
        member = npz[LAYOUT_KEY]
    except KeyError:
        return None
    return Layout.from_json(str(member.item() if hasattr(member, "item")
                                else member))


def resolve_source_layout(ckpt_dir: str, step: int, specs: Any = None,
                          ext: str = "npz",
                          echo=print) -> Tuple[Layout, bool]:
    """(source Layout, is_legacy) for a checkpoint on disk.

    Stamped npz shards return their stamp verbatim. Anything else — a
    pre-ISSUE-20 npz, or a torch ``.pth`` rank span (which has nowhere to
    carry the stamp) — is LEGACY: the tp width comes from
    `validate_checkpoint`'s filename/metadata logic, the zero stage from
    ``__zero_stage__`` when present, and the specs must be supplied by
    the caller (a model's `canonical_specs()`). Legacy resolution prints
    a loud note and never crashes; only a legacy source with NO spec
    source raises, naming the fix.
    """
    import numpy as np

    from ..training.checkpoint import validate_checkpoint

    tp_size, rank_files = validate_checkpoint(ckpt_dir, step, ext=ext)
    zero_stage = 0
    if ext == "npz":
        with np.load(rank_files[min(rank_files)]) as npz:
            stamped = read_stamp(npz)
            if stamped is not None:
                return stamped, False
            try:
                zero_stage = int(npz["__zero_stage__"])
            except KeyError:
                pass
    if specs is None:
        raise ValueError(
            f"legacy checkpoint (no {LAYOUT_KEY} stamp) at {ckpt_dir} "
            f"iter {step}: pass the model's canonical_specs() (CLI: "
            f"--model <preset>) so the layout can be inferred")
    echo(f"note: legacy checkpoint at {ckpt_dir} iter {step} — layout "
         f"inferred from filenames (tp{tp_size}, zero{zero_stage}); "
         f"re-save to stamp it")
    return make_layout((("tp", tp_size),), specs,
                       zero_stage=zero_stage), True
