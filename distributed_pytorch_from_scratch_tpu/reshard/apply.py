"""The reshard executors: streamed host paths + per-leaf device paths.

Every file-touching path here moves ONE leaf at a time through a single
staging buffer — never the `dict(np.load(...))` whole-tree
materialisation `load_checkpoint` uses (the "host-gather-in-reshard"
lint forbids it in this package). The npz container makes that cheap:
a `np.savez` archive is a ZIP of ``key.npy`` members, so member headers
(shape/dtype) read without payloads, and a member's C-order payload
STREAMS directly into its block of a preallocated global leaf
(`_stream_member_into` — the axis-block of a C-contiguous buffer is a
run of contiguous byte ranges, one per leading index). Peak host bytes
are therefore exactly ONE global leaf, metered by `HostMeter` and
asserted ≤ the largest leaf in tests — the ISSUE-20 acceptance bound.

Paths:

* `reshard_checkpoint` — file→file: a source shard set at layout A
  becomes a `validate_checkpoint`-clean shard set at layout B (the
  offline `scripts/reshard_ckpt.py` CLI, and the serve-side prestep).
* `stream_load` — file→device: leaves land on the target mesh via
  per-leaf `device_put` against the target sharding (elastic
  `train.py --resume`, serving loads); optimizer moments ride the same
  plan as their params.
* `reshard_params` — device→device: live trees re-lay per leaf (fleet
  replica restart at a new tp width); XLA lowers it to the plan's
  fragment-wise schedule, pinned by the graftcheck contract.

Legacy ``.pth`` rank spans (the reference's torch pickles) have no
streamable container; they bridge through `interop` per the loud
legacy note and are exempt from the one-leaf bound (documented, not
silent — the meter still records what they cost).
"""

from __future__ import annotations

import os
import re
import time
import zipfile
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..training.checkpoint import _flatten, _tp_dim, _unflatten_into
from .layout import LAYOUT_KEY, Layout, resolve_source_layout
from .plan import ReshardError, ReshardPlan, plan_reshard


class HostMeter:
    """Live/peak accounting of host staging bytes, so tests can ASSERT
    the streamed paths' bound (peak ≤ largest single leaf) instead of
    trusting the docstring."""

    def __init__(self):
        self.live = 0
        self.peak = 0

    def alloc(self, nbytes: int) -> int:
        self.live += int(nbytes)
        self.peak = max(self.peak, self.live)
        return int(nbytes)

    def free(self, nbytes: int) -> None:
        self.live -= int(nbytes)


# ----------------------------------------------------- npz member access --

def _read_header(f) -> Tuple[Tuple[int, ...], bool, np.dtype]:
    version = np.lib.format.read_magic(f)
    if version == (1, 0):
        return np.lib.format.read_array_header_1_0(f)
    return np.lib.format.read_array_header_2_0(f)


def member_headers(path: str) -> Dict[str, Tuple[Tuple[int, ...], np.dtype]]:
    """{key: (shape, dtype)} of every array in one npz shard, read from
    the ``.npy`` member headers — no payload bytes touch the host."""
    out: Dict[str, Tuple[Tuple[int, ...], np.dtype]] = {}
    with zipfile.ZipFile(path) as zf:
        for name in zf.namelist():
            if not name.endswith(".npy"):
                continue
            with zf.open(name) as f:
                shape, _, dtype = _read_header(f)
            out[name[:-4]] = (tuple(shape), np.dtype(dtype))
    return out


def _readinto_exact(f, view) -> None:
    got = 0
    while got < len(view):
        n = f.readinto(view[got:])
        if not n:
            raise ReshardError(
                f"npz member truncated: expected {len(view)} bytes, "
                f"got {got}")
        got += n


def _stream_member_into(zf: zipfile.ZipFile, key: str, out: np.ndarray,
                        dim: Optional[int], block: Tuple[int, int],
                        meter: Optional[HostMeter] = None) -> None:
    """Stream one member's payload into `out[block along dim]` without
    materialising the member: the member's C-order bytes map onto one
    contiguous destination run per leading index."""
    with zf.open(key + ".npy") as f:
        shape, fortran, dtype = _read_header(f)
        expect = list(out.shape)
        if dim is not None:
            expect[dim] = block[1] - block[0]
        if tuple(shape) != tuple(expect) or np.dtype(dtype) != out.dtype:
            raise ReshardError(
                f"shard member {key!r} is {shape}/{np.dtype(dtype)}; the "
                f"plan expects {tuple(expect)}/{out.dtype} — shard files "
                f"disagree with their stamped layout")
        if fortran:
            # np.savez never writes these; survive one anyway, at the
            # cost of materialising this single member
            with zf.open(key + ".npy") as f2:
                arr = np.lib.format.read_array(f2, allow_pickle=False)
            if meter is not None:
                meter.alloc(arr.nbytes)
            sl = [slice(None)] * out.ndim
            if dim is not None:
                sl[dim] = slice(*block)
            out[tuple(sl)] = arr
            if meter is not None:
                meter.free(arr.nbytes)
            return
        mv = memoryview(out).cast("B")
        item = out.dtype.itemsize
        trail = 1
        for d in out.shape[(0 if dim is None else dim) + 1:]:
            trail *= d
        if dim is None:
            _readinto_exact(f, mv)
            return
        lead = 1
        for d in out.shape[:dim]:
            lead *= d
        run = (block[1] - block[0]) * trail * item
        stride = out.shape[dim] * trail * item
        off0 = block[0] * trail * item
        for b in range(lead):
            _readinto_exact(f, mv[b * stride + off0:
                                  b * stride + off0 + run])


class _NpzStreamWriter:
    """One destination shard, written member-at-a-time (the np.savez zip
    layout: STORED ``key.npy`` members), atomically published."""

    def __init__(self, path: str):
        self.path = path
        self.tmp = path + ".tmp"
        self.zf = zipfile.ZipFile(self.tmp, "w", zipfile.ZIP_STORED)

    def write(self, key: str, arr: np.ndarray) -> None:
        with self.zf.open(key + ".npy", "w", force_zip64=True) as f:
            np.lib.format.write_array(f, np.asanyarray(arr),
                                      allow_pickle=False)

    def close(self) -> None:
        self.zf.close()
        os.replace(self.tmp, self.path)

    def abort(self) -> None:
        self.zf.close()
        if os.path.exists(self.tmp):
            os.remove(self.tmp)


# ------------------------------------------------------- source indexing --

class _NpzSource:
    """A stamped-or-legacy npz shard set, indexed for leaf streaming."""

    def __init__(self, rank_files: Dict[int, str], layout: Layout):
        self.layout = layout
        self.tp = layout.tp
        self.zfs = {r: zipfile.ZipFile(rank_files[r])
                    for r in sorted(rank_files)}
        hdrs = member_headers(rank_files[min(rank_files)])
        self.meta = {k: v for k, v in hdrs.items() if k.startswith("__")}
        self.keys = sorted(k for k in hdrs if not k.startswith("__"))
        self.shapes: Dict[str, Tuple[int, ...]] = {}
        self.dtypes: Dict[str, np.dtype] = {}
        for k in self.keys:
            shape, dtype = hdrs[k]
            sdim = self._sdim(k)
            g = list(shape)
            if sdim is not None:
                g[sdim] *= self.tp
            self.shapes[k] = tuple(g)
            self.dtypes[k] = dtype

    def _sdim(self, key: str) -> Optional[int]:
        return _tp_dim(self.layout.spec_for(key)) if self.tp > 1 else None

    def read_global(self, key: str,
                    meter: Optional[HostMeter] = None) -> np.ndarray:
        """ONE global leaf, streamed member-by-member into a single
        buffer — the whole-load peak is this buffer."""
        out = np.empty(self.shapes[key], self.dtypes[key])
        if meter is not None:
            meter.alloc(out.nbytes)
        sdim = self._sdim(key)
        if sdim is None:
            _stream_member_into(self.zfs[min(self.zfs)], key, out, None,
                                (0, 0), meter)
        else:
            n = out.shape[sdim] // self.tp
            for r, zf in self.zfs.items():
                _stream_member_into(zf, key, out, sdim,
                                    (r * n, (r + 1) * n), meter)
        return out

    def metadata(self) -> Dict[str, np.ndarray]:
        zf0 = self.zfs[min(self.zfs)]
        out = {}
        for k in self.meta:
            if k == LAYOUT_KEY:
                continue
            with zf0.open(k + ".npy") as f:
                out[k] = np.lib.format.read_array(f, allow_pickle=False)
        return out

    def close(self) -> None:
        for zf in self.zfs.values():
            zf.close()


class _TreeSource:
    """A flat in-memory global tree posing as a source — the legacy .pth
    bridge and the live-params path share it."""

    def __init__(self, flat: Dict[str, np.ndarray], layout: Layout,
                 meta: Optional[Dict[str, np.ndarray]] = None):
        self.layout = layout
        self.flat = flat
        self.keys = sorted(flat)
        self.shapes = {k: tuple(v.shape) for k, v in flat.items()}
        self.dtypes = {k: v.dtype for k, v in flat.items()}
        self.meta = dict(meta or {})

    def read_global(self, key: str,
                    meter: Optional[HostMeter] = None) -> np.ndarray:
        arr = self.flat[key]
        if meter is not None:
            meter.alloc(arr.nbytes)
        return arr

    def metadata(self) -> Dict[str, np.ndarray]:
        return dict(self.meta)

    def close(self) -> None:
        pass


def _parse_loss(rank_files: Dict[int, str]) -> str:
    m = re.search(r"_loss-(.+?)\.(npz|pth)$",
                  os.path.basename(rank_files[min(rank_files)]))
    return m.group(1) if m else "0.0000"


def _open_source(ckpt_dir: str, step: int, specs=None, ext: str = "npz",
                 cfg=None, echo=print):
    """(source, src_layout, is_legacy, loss_text) for any on-disk format."""
    src_layout, legacy = resolve_source_layout(ckpt_dir, step, specs=specs,
                                               ext=ext, echo=echo)
    from ..training.checkpoint import validate_checkpoint
    _, rank_files = validate_checkpoint(ckpt_dir, step, ext=ext)
    loss = _parse_loss(rank_files)
    if ext == "npz":
        return _NpzSource(rank_files, src_layout), src_layout, legacy, loss
    if ext == "pth":
        if cfg is None:
            raise ValueError("a legacy .pth span needs the model config "
                             "(CLI: the --attn_dim/--num_layers/... flags) "
                             "to rebuild the tree")
        echo(f"note: legacy .pth span at {ckpt_dir} iter {step} — torch "
             f"pickles are not streamable; bridging through interop "
             f"(host cost: the param tree, once)")
        from ..interop import load_reference_checkpoint
        tree = load_reference_checkpoint(ckpt_dir, step, cfg,
                                         pad_vocab_multiple=max(
                                             1, src_layout.tp))
        flat = {k: np.asarray(v) for k, v in
                _flatten(tree, "param").items()}
        return _TreeSource(flat, src_layout), src_layout, legacy, loss
    raise ValueError(f"unknown checkpoint extension {ext!r}")


# --------------------------------------------------------------- planning --

def plan_checkpoint(ckpt_dir: str, step: int, dst_layout: Layout,
                    specs=None, ext: str = "npz", cfg=None,
                    echo=print) -> Tuple[ReshardPlan, Layout, bool]:
    """Plan (only) a reshard of an on-disk checkpoint: (plan, source
    layout, is_legacy). Header reads for npz; the .pth bridge loads."""
    source, src_layout, legacy, _ = _open_source(ckpt_dir, step, specs=specs,
                                                 ext=ext, cfg=cfg, echo=echo)
    try:
        plan = plan_reshard(source.keys, source.shapes,
                            {k: d.itemsize for k, d in source.dtypes.items()},
                            src_layout, dst_layout)
    finally:
        source.close()
    return plan, src_layout, legacy


# ------------------------------------------------------------ file→file --

def reshard_checkpoint(src_dir: str, step: int, dst_dir: str,
                       dst_layout: Layout, specs=None, ext: str = "npz",
                       cfg=None, meter: Optional[HostMeter] = None,
                       echo=print) -> Tuple[List[str], ReshardPlan, dict]:
    """Source shard set at layout A → new shard set at layout B, leaf at
    a time. Returns (paths, plan, info) where `info` is the
    reshard_event payload (src/dst layouts, bytes moved, op counts,
    wall ms)."""
    t0 = time.perf_counter()
    meter = meter if meter is not None else HostMeter()
    source, src_layout, legacy, loss = _open_source(
        src_dir, step, specs=specs, ext=ext, cfg=cfg, echo=echo)
    try:
        plan = plan_reshard(source.keys, source.shapes,
                            {k: d.itemsize for k, d in source.dtypes.items()},
                            src_layout, dst_layout)
        os.makedirs(dst_dir, exist_ok=True)
        tp = dst_layout.tp
        writers = [_NpzStreamWriter(os.path.join(
            dst_dir, f"tprank-{q}_iter-{step}_loss-{loss}.npz"))
            for q in range(tp)]
        try:
            for key in source.keys:
                leaf = source.read_global(key, meter)
                spec = dst_layout.spec_for(key)
                ddim = _tp_dim(spec) if tp > 1 else None
                for q, w in enumerate(writers):
                    if ddim is None:
                        w.write(key, leaf)
                    else:
                        n = leaf.shape[ddim] // tp
                        sl = [slice(None)] * leaf.ndim
                        sl[ddim] = slice(q * n, (q + 1) * n)
                        w.write(key, leaf[tuple(sl)])
                meter.free(leaf.nbytes)
                del leaf
            meta = source.metadata()
            meta["__step__"] = np.asarray(step, np.int64)
            meta["__tp_size__"] = np.asarray(tp, np.int64)
            meta.setdefault("__has_opt__", np.asarray(
                any(k.startswith("mu/") for k in source.keys)))
            meta["__zero_stage__"] = np.asarray(dst_layout.zero_stage,
                                                np.int64)
            from .layout import stamp
            stamp(meta, dst_layout)
            for w in writers:
                for k, v in meta.items():
                    w.write(k, v)
                w.close()
        except BaseException:
            for w in writers:
                w.abort()
            raise
    finally:
        source.close()
    info = dict(plan.summary(), legacy=bool(legacy),
                wall_ms=round((time.perf_counter() - t0) * 1e3, 3),
                peak_host_bytes=meter.peak)
    return [w.path for w in writers], plan, info


# ---------------------------------------------------------- file→device --

def stream_load(ckpt_dir: str, step: int, template, specs,
                dst_layout: Layout, param_shardings,
                moment_shardings=None, with_opt: bool = False,
                ext: str = "npz", cfg=None,
                meter: Optional[HostMeter] = None,
                echo=print):
    """Load a checkpoint saved under ANY layout onto the target mesh,
    one leaf at a time: stream-assemble a global leaf, `device_put` it
    against the target sharding, free it. Returns
    (params, opt_state | None, step, info)."""
    import jax

    from ..training.optim import AdamState

    t0 = time.perf_counter()
    meter = meter if meter is not None else HostMeter()
    source, src_layout, legacy, _ = _open_source(
        ckpt_dir, step, specs=specs, ext=ext, cfg=cfg, echo=echo)
    try:
        plan = plan_reshard(source.keys, source.shapes,
                            {k: d.itemsize for k, d in source.dtypes.items()},
                            src_layout, dst_layout)
        flat_sh = _flatten(param_shardings, "param")
        if moment_shardings is not None:
            flat_sh.update(_flatten(moment_shardings, "mu"))
            flat_sh.update(_flatten(moment_shardings, "nu"))
        dev: Dict[str, Any] = {}
        for key in source.keys:
            kind = key.split("/", 1)[0]
            if kind != "param" and not with_opt:
                continue
            sh = flat_sh.get(key)
            if sh is None:
                raise ReshardError(
                    f"no target sharding for checkpoint key {key!r} — "
                    f"pass moment_shardings to load optimizer state")
            leaf = source.read_global(key, meter)
            dev[key] = jax.device_put(leaf, sh)
            dev[key].block_until_ready()
            meter.free(leaf.nbytes)
            del leaf
        meta = source.metadata()
        step_loaded = int(meta.get("__step__", np.asarray(step)))
        params = _unflatten_into(template, dev, "param")
        opt_state = None
        has_opt = bool(meta.get("__has_opt__", np.asarray(False)))
        if with_opt and has_opt:
            mu = _unflatten_into(template, dev, "mu")
            nu = _unflatten_into(template, dev, "nu")
            opt_state = AdamState(step=np.asarray(step_loaded, np.int32),
                                  mu=mu, nu=nu)
    finally:
        source.close()
    info = dict(plan.summary(), legacy=bool(legacy),
                wall_ms=round((time.perf_counter() - t0) * 1e3, 3),
                peak_host_bytes=meter.peak)
    return params, opt_state, step_loaded, info


# -------------------------------------------------------- device→device --

def reshard_params(tree, mesh, specs):
    """Re-lay a LIVE tree onto `mesh` per leaf (`device_put` against each
    leaf's NamedSharding) — both meshes' devices must be addressable.
    XLA lowers the layout change to the plan's fragment-wise schedule
    (pinned by the `reshard-fragmentwise` graftcheck contract)."""
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(
        lambda s, x: jax.device_put(x, NamedSharding(mesh, s)),
        specs, tree, is_leaf=lambda x: isinstance(x, P))
