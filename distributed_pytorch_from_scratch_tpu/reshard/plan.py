"""The redistribution pass: any-layout→any-layout reshard plans.

Per leaf, two complementary facts are planned from the (source, target)
`Layout` pair:

* **File fragments** — the interval intersections of the source and
  target shard grids, in each shard's LOCAL coordinates. The on-disk
  convention (training/checkpoint.py) slices only along a leaf's tp dim
  and stores global values otherwise, so the fragment grid is the
  cross-intersection of the source tp blocking and the target tp
  blocking (possibly on DIFFERENT dims). Every fragment reads at most
  one source shard member and writes one target slice — the unit the
  streamed host executor (`apply.py`) moves, which is what bounds peak
  host bytes to one leaf + one source member instead of the tree.

* **Device op** — what the live-mesh schedule does for this leaf, from
  the EFFECTIVE specs (canonical spec + the stage's dp extension, the
  `training/zero._zero_dim` rule — re-derived here so reshard ownership
  can never disagree with the optimizer's): ``copy`` (same partitioning),
  ``gather`` (target strictly coarser: dp dropped, or tp4→tp2 — the
  fragment-wise all-gather legs the graftcheck contract counts),
  ``slice`` (target strictly finer: local, no wire), ``permute``
  (mixed/moved dims: collective-permute class). The memory-efficient
  fragment schedule follows "Memory-efficient array redistribution
  through portable collective communication"; the cross-mesh spirit is
  "On Optimizing the Communication of Model Parallelism" (PAPERS.md).

`bytes_moved` counts fragment bytes that change file-residence (rank or
extent): a pure zero-stage change (zero2→zero0, same tp) moves 0 bytes —
the shard files are already byte-identical — while any tp change moves
every byte of every tp-sharded replica written.

Inexpressible targets refuse LOUDLY with `ReshardError` (an indivisible
shard dim, a spec axis the planner cannot block evenly, a key-set
mismatch between source and target template) — never a silent fallback.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from jax.sharding import PartitionSpec as P

from ..training.checkpoint import _tp_dim
from .layout import Layout

Interval = Tuple[int, int]                  # [start, stop)
SliceMap = Dict[int, Interval]              # dim -> interval (absent = full)


class ReshardError(ValueError):
    """A layout the planner cannot express — raised loudly, never a
    silent fallback to a wrong (or whole-tree) schedule."""


@dataclasses.dataclass(frozen=True)
class Fragment:
    src_rank: int
    src_slice: Tuple[Tuple[int, Interval], ...]   # local coords, sparse
    dst_slice: Tuple[Tuple[int, Interval], ...]
    nbytes: int


@dataclasses.dataclass
class LeafPlan:
    key: str
    shape: Tuple[int, ...]
    itemsize: int
    op: str                               # copy | gather | slice | permute
    moved_bytes: int
    fragments: Dict[int, List[Fragment]]  # dst_rank -> fragments

    @property
    def nbytes(self) -> int:
        n = self.itemsize
        for d in self.shape:
            n *= d
        return n


@dataclasses.dataclass
class ReshardPlan:
    src: Layout
    dst: Layout
    leaves: Dict[str, LeafPlan]

    def summary(self) -> dict:
        ops: Dict[str, int] = {}
        moved = 0
        max_leaf = 0
        for lp in self.leaves.values():
            ops[lp.op] = ops.get(lp.op, 0) + 1
            moved += lp.moved_bytes
            max_leaf = max(max_leaf, lp.nbytes)
        return {"src": self.src.describe(), "dst": self.dst.describe(),
                "ops": ops, "bytes_moved": moved,
                "n_leaves": len(self.leaves),
                "max_leaf_bytes": max_leaf}


# ------------------------------------------------------- effective specs --

def _subtree_start(key: str) -> int:
    """The `zero3_dims` stacked-layer rule on flat keys: leaves under the
    layers subtree skip dim 0 (the scan's num_layers axis)."""
    parts = key.split("/")
    return 1 if len(parts) > 1 and parts[1] == "layers" else 0


def effective_spec(layout: Layout, key: str,
                   shape: Tuple[int, ...]) -> P:
    """Canonical spec + the ZeRO stage's dp extension for one flat key —
    params extend at stage 3 (layers skipping the stacked axis), moments
    extend from stage 1 (stage 1/2 by the `zero1_specs` rule, stage 3 on
    the param layout). Exactly `training/zero`'s `_zero_dim` selection,
    reused, so shard ownership re-derives identically on any mesh."""
    from ..training.zero import _extend_spec, _zero_dim

    spec = layout.spec_for(key)
    dp = layout.dp
    stage = layout.zero_stage
    kind = key.split("/", 1)[0]
    if dp == 1:
        return spec
    if kind == "param":
        if stage < 3:
            return spec
        start = _subtree_start(key)
    else:                                   # mu / nu
        if stage < 1:
            return spec
        start = _subtree_start(key) if stage >= 3 else 0
    shaped = _Shaped(shape)
    return _extend_spec(spec, shaped, _zero_dim(spec, shaped, dp,
                                                start=start), "dp")


class _Shaped:
    __slots__ = ("shape", "ndim")

    def __init__(self, shape):
        self.shape = tuple(shape)
        self.ndim = len(self.shape)


def _partitions(layout: Layout, spec: P,
                shape: Tuple[int, ...], key: str) -> Dict[int, int]:
    """dim -> number of shards a spec blocks it into on this layout's
    mesh (absent axes count 1; size-1 results dropped)."""
    out: Dict[int, int] = {}
    for dim, entry in enumerate(tuple(spec)):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        n = 1
        for a in axes:
            n *= layout.axis_size(a)
        if n <= 1:
            continue
        if dim >= len(shape) or shape[dim] % n != 0:
            raise ReshardError(
                f"layout {layout.describe()} shards {key} dim {dim} "
                f"{n}-way but its size is "
                f"{shape[dim] if dim < len(shape) else '<missing>'} — "
                f"not evenly divisible; this layout is inexpressible "
                f"for the leaf")
        out[dim] = n
    return out


def _leaf_op(src_parts: Dict[int, int], dst_parts: Dict[int, int]) -> str:
    if src_parts == dst_parts:
        return "copy"
    coarser = finer = moved = False
    for d in set(src_parts) | set(dst_parts):
        s, t = src_parts.get(d, 1), dst_parts.get(d, 1)
        if s == t:
            continue
        if s > t and s % t == 0:
            coarser = True
        elif t > s and t % s == 0:
            finer = True
        else:
            moved = True
    if moved or (coarser and finer):
        return "permute"
    return "gather" if coarser else "slice"


# --------------------------------------------------------- file fragments --

def _overlap(a: Interval, b: Interval) -> Optional[Interval]:
    lo, hi = max(a[0], b[0]), min(a[1], b[1])
    return (lo, hi) if lo < hi else None


def _block(n: int, parts: int, rank: int) -> Interval:
    w = n // parts
    return (rank * w, (rank + 1) * w)


def _frag_bytes(shape, itemsize: int, region: SliceMap) -> int:
    n = itemsize
    for d, size in enumerate(shape):
        lo, hi = region.get(d, (0, size))
        n *= hi - lo
    return n


def file_fragments(shape: Tuple[int, ...], itemsize: int,
                   src_spec: P, src_tp: int, dst_spec: P,
                   dst_tp: int) -> Dict[int, List[Fragment]]:
    """dst_rank -> fragments, per the on-disk rule: shard files slice one
    leaf only along its tp dim (`checkpoint._shard_slice`); dp/zero never
    slice files. Local coordinates on both ends."""
    sdim = _tp_dim(src_spec) if src_tp > 1 else None
    ddim = _tp_dim(dst_spec) if dst_tp > 1 else None
    out: Dict[int, List[Fragment]] = {}
    for q in range(dst_tp):
        frags: List[Fragment] = []
        dblk = _block(shape[ddim], dst_tp, q) if ddim is not None else None
        if sdim is None:
            # replicated (or tp1) source: rank 0 holds the global leaf
            region: SliceMap = {} if ddim is None else {ddim: dblk}
            src_local = dict(region)
            dst_local = {} if ddim is None else \
                {ddim: (0, dblk[1] - dblk[0])}
            frags.append(Fragment(
                0, tuple(sorted(src_local.items())),
                tuple(sorted(dst_local.items())),
                _frag_bytes(shape, itemsize, region)))
        else:
            for r in range(src_tp):
                sblk = _block(shape[sdim], src_tp, r)
                if ddim is None:
                    region = {sdim: sblk}
                    src_local = {sdim: (0, sblk[1] - sblk[0])}
                    dst_local = {sdim: sblk}
                elif ddim == sdim:
                    ov = _overlap(sblk, dblk)
                    if ov is None:
                        continue
                    region = {sdim: ov}
                    src_local = {sdim: (ov[0] - sblk[0], ov[1] - sblk[0])}
                    dst_local = {ddim: (ov[0] - dblk[0], ov[1] - dblk[0])}
                else:
                    region = {sdim: sblk, ddim: dblk}
                    src_local = {sdim: (0, sblk[1] - sblk[0]), ddim: dblk}
                    dst_local = {sdim: sblk,
                                 ddim: (0, dblk[1] - dblk[0])}
                frags.append(Fragment(
                    r, tuple(sorted(src_local.items())),
                    tuple(sorted(dst_local.items())),
                    _frag_bytes(shape, itemsize, region)))
        out[q] = frags
    return out


def slices_of(slice_items: Tuple[Tuple[int, Interval], ...],
              ndim: int) -> Tuple[slice, ...]:
    """A Fragment's sparse slice map -> a full indexing tuple."""
    sl = [slice(None)] * ndim
    for d, (lo, hi) in slice_items:
        sl[d] = slice(lo, hi)
    return tuple(sl)


# ---------------------------------------------------------------- planner --

def plan_reshard(keys: List[str], shapes: Dict[str, Tuple[int, ...]],
                 itemsizes: Dict[str, int], src: Layout,
                 dst: Layout) -> ReshardPlan:
    """Plan every leaf's fragments + device op for a src→dst reshard.

    `keys` are checkpoint flat keys (param/mu/nu); `shapes` are GLOBAL
    shapes. Refuses loudly (ReshardError) on an inexpressible target or
    a key the source layout has no spec for.
    """
    missing = [k for k in keys
               if "param/" + k.partition("/")[2] not in src.specs
               and k not in src.specs]
    if missing:
        raise ReshardError(
            f"source layout has no spec for {len(missing)} checkpoint "
            f"key(s), e.g. {missing[:3]} — the checkpoint and the spec "
            f"tree disagree (wrong --model preset for a legacy source?)")
    leaves: Dict[str, LeafPlan] = {}
    for key in keys:
        shape = tuple(shapes[key])
        item = int(itemsizes[key])
        s_eff = effective_spec(src, key, shape)
        d_eff = effective_spec(dst, key, shape)
        s_parts = _partitions(src, s_eff, shape, key)
        d_parts = _partitions(dst, d_eff, shape, key)
        op = _leaf_op(s_parts, d_parts)
        frags = file_fragments(shape, item, src.spec_for(key), src.tp,
                               dst.spec_for(key), dst.tp)
        same_files = (src.tp == dst.tp)
        moved = 0 if same_files else sum(
            f.nbytes for fl in frags.values() for f in fl)
        leaves[key] = LeafPlan(key=key, shape=shape, itemsize=item,
                               op=op, moved_bytes=moved, fragments=frags)
    return ReshardPlan(src=src, dst=dst, leaves=leaves)
