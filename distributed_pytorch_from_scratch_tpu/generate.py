"""Interactive generation entry point: prompt in, text out.

`python -m distributed_pytorch_from_scratch_tpu.generate --ckpt_dir ... --tokenizer_path ... \
     --prompt "Once upon a time" [--temperature 0.8 --decode_top_p 0.9]`

The reference has no generation CLI at all — its only decode surface is
the eight prompts hard-coded inside `test.py` (`/root/reference/test.py:126-135`).
This wraps the same KV-cache decoder `evaluate.py` uses (models/decode.py:
prefill + fused on-device loop, one dispatch per prompt set) behind a
user-facing command. Multiple --prompt flags batch into ONE dispatch.
"""

from __future__ import annotations

import argparse

import jax

from .cli import add_model_shape_args, build_model_config
from .config import BOS_TOKEN, EOS_TOKEN, MeshConfig
from .models.transformer import Transformer
from .runtime.mesh import make_mesh
from .training.checkpoint import latest_step, load_checkpoint


def get_generate_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--ckpt_dir", required=True)
    p.add_argument("--tokenizer_path", "-t", required=True)
    p.add_argument("--prompt", action="append", required=True,
                   help="repeatable; all prompts decode in one dispatch")
    p.add_argument("--iter", type=int, default=None,
                   help="checkpoint iteration (default: latest)")
    p.add_argument("--max_new_tokens", type=int, default=128)
    p.add_argument("--tp_size", type=int, default=1)
    p.add_argument("--cp_size", type=int, default=1,
                   help="context-parallel ranks: decoding routes through "
                        "the PAGED serving engine with a cp-sharded page "
                        "pool (ring chunked prefill + cp-local decode, "
                        "serving/engine.PagedEngine — prompts far beyond "
                        "one chip's KV budget); greedy output is token-"
                        "identical to cp_size=1 (ISSUE 18)")
    p.add_argument("--cp_impl", choices=["ring", "ulysses"], default="ring",
                   help="attention schedule the model was trained with. "
                        "Decode runs the ring schedule only: with "
                        "--cp_size > 1 a ulysses-trained config must "
                        "decode via 'ring' (identical weights — cp_impl "
                        "only changes the attention schedule) or "
                        "--cp_size 1; 'ulysses' here errors out with that "
                        "pointer instead of silently switching")
    p.add_argument("--family", choices=["llama", "gpt2"], default="llama")
    add_model_shape_args(p.add_argument_group("model shape"))
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy; > 0 samples softmax(logits/T)")
    p.add_argument("--decode_top_k", type=int, default=0)
    p.add_argument("--decode_top_p", type=float, default=0.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--prefill_bucket", type=int, default=64,
                   help="serving-engine prefill width bucket: each prompt "
                        "prefills over a buffer padded to a multiple of "
                        "this instead of the whole decode buffer (identical "
                        "tokens — causal attention makes the width a pure "
                        "cost knob); 0 pads to the full buffer. cp decode "
                        "(--cp_size > 1) runs the paged engine, which "
                        "chunks prefill by pages instead")
    p.add_argument("--slots", type=int, default=8,
                   help="serving-engine KV slots (concurrent decodes); "
                        "prompts beyond this queue FIFO")
    args = p.parse_args(argv)
    if (args.decode_top_k or args.decode_top_p) and not args.temperature:
        p.error("--decode_top_k/--decode_top_p need --temperature > 0")
    if not 0.0 <= args.decode_top_p <= 1.0:
        p.error(f"--decode_top_p must be in [0, 1], got {args.decode_top_p}")
    return args


def generate(args: argparse.Namespace) -> list:
    if args.cp_size > 1 and args.cp_impl == "ulysses":
        # VERDICT r5 #5: refuse loudly instead of silently requiring the
        # ring path — cp decoding (the paged engine's query ring) runs the
        # ring schedule only.
        raise SystemExit(
            f"--cp_impl ulysses has no decode path (cp decoding is "
            f"ring-only: cp serving rings the prefill queries over "
            f"cp-local pages). A "
            f"ulysses-trained checkpoint is layout-identical to a ring one "
            f"— cp_impl only changes the attention schedule, not the "
            f"weights — so rerun with --cp_impl ring or --cp_size 1 (got "
            f"--cp_size {args.cp_size})")
    from tokenizers import Tokenizer as HFTokenizer

    tokenizer = HFTokenizer.from_file(args.tokenizer_path)
    vocab_size = tokenizer.get_vocab_size()
    bos_id = tokenizer.token_to_id(BOS_TOKEN)
    eos_id = tokenizer.token_to_id(EOS_TOKEN)
    if bos_id is None or eos_id is None:
        raise SystemExit(f"tokenizer {args.tokenizer_path} lacks the "
                         f"{BOS_TOKEN}/{EOS_TOKEN} specials")

    cfg = build_model_config(args, vocab_size)
    mesh = make_mesh(MeshConfig(tp=args.tp_size, cp=args.cp_size))
    if args.family == "gpt2":
        from .models.gpt2 import GPT2Transformer
        model = GPT2Transformer(cfg, tp_size=args.tp_size,
                                cp_size=args.cp_size)
    else:
        model = Transformer(cfg, tp_size=args.tp_size,
                            cp_size=args.cp_size)

    step = args.iter if args.iter is not None else latest_step(args.ckpt_dir)
    if step is None:
        raise SystemExit(f"no checkpoints found in {args.ckpt_dir}")
    template = jax.eval_shape(lambda: model.init(jax.random.key(0)))
    params, _, _ = load_checkpoint(args.ckpt_dir, step, template,
                                   model.specs())
    params = jax.device_put(params, model.shardings(mesh))
    print(f"loaded checkpoint iter {step} from {args.ckpt_dir}")

    encoded = [tokenizer.encode(t).ids for t in args.prompt]
    longest = max(len(e) for e in encoded)
    buf_len = longest + args.max_new_tokens + 2
    cap = getattr(model, "max_decode_positions", None)
    if cap is not None:
        buf_len = min(buf_len, cap)
        if buf_len < longest + 2:
            raise SystemExit(f"prompt needs {longest + 2} positions but the "
                             f"model's position table has {cap}")
    prompts = [[bos_id] + e for e in encoded]
    if args.cp_size > 1:
        # long-context path: the paged engine's cp-sharded page pool
        # (ring chunked prefill + cp-local decode) — each cp rank holds
        # 1/cp of the KV pages; greedy output token-identical to
        # cp_size=1 (tests/test_serving_cp.py pins it). The engine
        # rounds its page budget to cp multiples internally.
        from .serving.engine import PagedEngine, decode_prompts

        engine = PagedEngine(
            model, mesh, params, num_slots=min(len(prompts), args.slots),
            buf_len=buf_len, eos_id=eos_id, temperature=args.temperature,
            top_k=args.decode_top_k, top_p=args.decode_top_p)
        gens = decode_prompts(engine, prompts, args.max_new_tokens,
                              base_seed=args.seed)
    else:
        # continuous-batching engine: mixed-length prompts prefill in
        # length buckets instead of all padding to the longest+budget
        # buffer (token-identical to GreedyDecoder for greedy decode —
        # tests/test_serving.py pins it; sampled decode draws per-request)
        from .serving.engine import ContinuousBatchingEngine, decode_prompts

        engine = ContinuousBatchingEngine(
            model, mesh, params, num_slots=min(len(prompts), args.slots),
            buf_len=buf_len, eos_id=eos_id, temperature=args.temperature,
            top_k=args.decode_top_k, top_p=args.decode_top_p,
            prefill_bucket=args.prefill_bucket)
        gens = decode_prompts(engine, prompts, args.max_new_tokens,
                              base_seed=args.seed)
        waste = engine.stats()["prefill_pad_waste_eliminated"]
        if waste > 0:
            print(f"prefill pad waste eliminated by length bucketing: "
                  f"{100 * waste:.0f}% ({engine.prefill_positions} "
                  f"bucketed positions vs "
                  f"{engine.prefill_positions_monolithic} at the "
                  f"full-buffer padding)")
    outs = []
    for text, ids, gen in zip(args.prompt, encoded, gens):
        full = tokenizer.decode(ids + gen).strip()
        outs.append(full)
        print(f"{text!r} -> {full!r}")
    return outs


def main(argv=None):
    return generate(get_generate_args(argv))


if __name__ == "__main__":
    main()
