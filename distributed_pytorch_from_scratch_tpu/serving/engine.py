"""Continuous-batching inference engine over the slot-granular KV pool.

The one-shot decoder (`models/decode.GreedyDecoder`) fuses prefill + the
whole generation loop into a single dispatch: perfect for a fixed prompt
set, useless for serving — the batch pads to the slowest prompt and no new
request can enter until every row retires. This engine inverts the control
flow: the HOST drives a loop of small compiled programs, so between any two
decode steps it can retire finished slots and prefill queued prompts into
the freed cache rows. The device programs are built from the SAME lowering
functions the fused decoder uses (`models/decode._prefill`, `_decode_one`,
`make_token_sampler`), which is why continuous-batched greedy output is
token-identical to per-prompt `GreedyDecoder` decode (pinned in
tests/test_serving.py).

Two compiled programs, both donating the pool so slot writes are in place:

* **prefill** (one variant per (batch, width) bucket): runs the causal
  full-buffer forward over a bucket-padded prompt buffer, scatters the
  per-layer K/V into the target slots' cache rows, and samples each row's
  first token. Under causal attention the buffer width changes cost only,
  never values, so length-bucketing (scheduler.py) is free correctness-wise.
* **step** (one variant total): advances ALL slots one token — each row
  writes its pending token's K/V at its OWN cursor (`_decode_one`'s per-row
  scatter), attends over its prefix, and samples its next token. Free/dead
  slots compute garbage that flows only into garbage: their rows are
  overwritten by the next prefill before anything can attend to them (the
  same argument as the pipeline bubble steps, models/transformer.py).

Step loop (host): retire -> admit (scheduler FIFO groups -> prefill) ->
one decode dispatch. TTFT/TPOT/queue-wait are measured per request and
emitted through obs/ (SpanTracer spans + MetricsWriter events) so a serving
run renders in the same Chrome trace / summary pipeline as training.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..models.decode import (_decode_one, _paged_decode_one,
                             _paged_prefill_chunk, _prefill,
                             host_sample_tokens, make_token_sampler,
                             rope_tables)
from ..config import resolve_dtype
from ..obs.control import control_safe_point
from ..ops.quant import dequantize_decode_params, quantize_decode_params
from .kv_manager import (KVCachePool, POOL_SPEC, PagedKVPool, PoolExhausted)
from .scheduler import FIFOScheduler, SLOScheduler


# ISSUE 15: HBM watermark cadences — gauges refresh every N decode steps
# (a handful of host memory_stats() calls: cheap, but not per-step free;
# the exporter overhead pin covers the gauge path), events land every M so
# a metrics chain carries a bounded watermark series, plus the first step
# so short runs still record one.
_HBM_GAUGE_EVERY = 10
_HBM_EVENT_EVERY = 100


def _publish_hbm_plane(engine, pool_bytes=None) -> None:
    """Shared per-engine HBM watermark publication (ISSUE 15): live
    gauges into the exporter, `hbm_watermark` events into the metrics
    chain, both on their cadence. `pool_bytes` is the paged pool's
    ACCOUNTED page bytes — the pool-vs-device cross-check gauge."""
    step = engine.decode_steps
    gauge = engine.telemetry is not None and (
        step == 1 or step % _HBM_GAUGE_EVERY == 0)
    event = engine.writer is not None and (
        step == 1 or step % _HBM_EVENT_EVERY == 0)
    if not (gauge or event):
        return
    from ..training.metrics import publish_hbm
    publish_hbm(telemetry=engine.telemetry if gauge else None,
                writer=engine.writer if event else None, step=step,
                pool_accounted_bytes=pool_bytes, event=event)


def _setup_decode_weights(engine, model, mesh, params, decode_weight_dtype):
    """Shared weight-dtype plumbing for every engine: `engine._params_in`
    is what the compiled programs take (int8 codes + per-output-channel
    scales when decode_weight_dtype='int8'), `engine._pspec` its matching
    spec tree, and `engine._deq(params)` the inside-program prologue that
    hands the decode/prefill lowerings ordinary dense weights (dequant-on-
    use: XLA fuses the int8->f32 convert into the consuming matmul, so
    the weights' HBM traffic — the decode latency floor at small models —
    is int8). Sampling, caches, and every token produced stay governed by
    the engines' usual contracts; weight rounding shifts logits by a
    bounded amount (pinned in tests/test_quant.py)."""
    if decode_weight_dtype in (None, "native"):
        engine._params_in = params
        engine._pspec = model.specs()
        engine._deq = lambda p: p
    elif decode_weight_dtype in ("int8", jnp.int8):
        engine._params_in, engine._pspec = quantize_decode_params(
            params, model.specs(), mesh)
        engine._deq = dequantize_decode_params
    else:
        raise ValueError(f"decode_weight_dtype must be None/'native'/"
                         f"'int8', got {decode_weight_dtype!r}")


@dataclass
class Request:
    """One generation request. `tokens` fills with the generated ids (EOS
    excluded, like GreedyDecoder.decode); the *_t fields are engine-clock
    samples for the serving metrics. `tenant`/`slo_class` drive the paged
    engine's SLO scheduler (the FIFO scheduler ignores them)."""

    rid: int
    prompt: List[int]
    max_new: int
    seed: int = 0
    arrival: float = 0.0                 # loadgen's planned arrival offset
    tenant: str = "default"              # fair-queuing bucket (SLOScheduler)
    slo_class: Optional[str] = None      # TTFT deadline class (None=default)
    trace_id: Optional[str] = None       # per-request trace (obs/reqtrace)
    trace_ctx: Optional[dict] = None     # wire TraceContext from another
    #                                      process (obs/reqtrace, ISSUE 12):
    #                                      submit CONTINUES that trace
    tokens: List[int] = field(default_factory=list)
    submit_t: Optional[float] = None     # entered the admission queue
    admit_t: Optional[float] = None      # left the queue (prefill dispatch)
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    prompt_len: int = 0
    limit: int = 0
    deadline_t: Optional[float] = None   # submit_t + class TTFT budget
    preemptions: int = 0                 # times evicted and re-queued

    # -- derived metrics (seconds; None until the request finishes) ------
    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.submit_t is None or self.admit_t is None:
            return None
        return self.admit_t - self.submit_t

    @property
    def ttft_s(self) -> Optional[float]:
        if self.submit_t is None or self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    @property
    def tpot_s(self) -> Optional[float]:
        """Time per output token AFTER the first (the decode-loop rate);
        None with < 2 tokens."""
        if (self.first_token_t is None or self.finish_t is None
                or len(self.tokens) < 2):
            return None
        return (self.finish_t - self.first_token_t) / (len(self.tokens) - 1)


def _wire_ctx(req: Request):
    """Deserialize a request's cross-process trace handoff, if any."""
    if req.trace_ctx is None:
        return None
    from ..obs.reqtrace import TraceContext
    return TraceContext.from_wire(req.trace_ctx)


def decode_prompts(engine: "ContinuousBatchingEngine", prompts,
                   max_new, base_seed: int = 0) -> List[List[int]]:
    """Batch-CLI convenience shared by generate.py and evaluate.py: submit
    `prompts` FIFO with per-request seeds base_seed+i, drain the engine,
    and return the generated ids in PROMPT order. `max_new` is an int
    (shared budget) or a per-prompt sequence."""
    budgets = ([max_new] * len(prompts) if isinstance(max_new, int)
               else list(max_new))
    for i, pr in enumerate(prompts):
        engine.submit(Request(rid=i, prompt=pr, max_new=budgets[i],
                              seed=base_seed + i))
    engine.run_to_completion()
    return [r.tokens for r in sorted(engine.completed, key=lambda r: r.rid)]


def _pow2_at_most(n: int, cap: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return min(p, cap) if cap else p


def _chunk_maps(ids, s: int, n: int, cw: int, ps: int, eos_id: int,
                scratch_page: int, tbl_row):
    """Host-side destination maps for one prefill chunk: the (1, cw) token
    buffer eos-padded past n, and per-position destination page/offset.
    Real positions land in `tbl_row`'s pages at (s+i)//ps, (s+i)%ps; pad
    positions write the scratch page at distinct offsets so the scatter
    never collides with live rows. Shared by the target engine's
    `_dispatch_chunk` and the drafter's `_drafter_prefill` — the pad-offset
    convention must stay identical on both sides."""
    buf = np.full((1, cw), eos_id, np.int32)
    buf[0, :n] = ids[s:s + n]
    dstp = np.full((1, cw), scratch_page, np.int32)
    dsto = np.zeros((1, cw), np.int32)
    for i in range(cw):
        if i < n:
            dstp[0, i] = tbl_row[(s + i) // ps]
            dsto[0, i] = (s + i) % ps
        else:
            dsto[0, i] = i % ps
    return buf, dstp, dsto


class ContinuousBatchingEngine:
    """Slot-based continuous batching over a TP-sharded KV pool.

    Sampling knobs are build-time constants (one compiled step serves every
    request, like GreedyDecoder); randomness is PER REQUEST via its seed
    (`make_token_sampler`'s fold-in schedule), so a request's sampled tokens
    reproduce regardless of arrival order, slot placement, or batch mix.
    """

    def __init__(self, model, mesh: Mesh, params, num_slots: int,
                 buf_len: int, eos_id: int, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 0.0,
                 prefill_bucket: int = 64, max_prefill_batch: int = 4,
                 max_queue: int = 0, debug_host_sampler: bool = False,
                 decode_weight_dtype=None,
                 tracer=None, writer=None, request_tracer=None,
                 flight=None, telemetry=None, duty_profiler=None,
                 clock=time.monotonic):
        if getattr(model, "cp_size", 1) > 1:
            raise ValueError(
                "the slot engine's per-slot caches are replicated over cp; "
                "long-context cp serving is the PAGED engine's job "
                f"(--paged with --cp {model.cp_size}, ISSUE 18) — use "
                "PagedEngine, or rebuild the model at cp=1")
        cap = getattr(model, "max_decode_positions", None)
        if cap is not None and buf_len > cap:
            raise ValueError(
                f"buf_len {buf_len} exceeds the model's learned position "
                f"table ({cap}); clamp the buffer or retrain with a larger "
                f"maxlen")
        if max_prefill_batch < 1:
            raise ValueError(f"max_prefill_batch must be >= 1, got "
                             f"{max_prefill_batch}")
        self.model = model
        self.mesh = mesh
        self.params = params
        self.buf_len = buf_len
        self.eos_id = int(eos_id)
        self.max_prefill_batch = max_prefill_batch
        self._clock = clock
        self.tracer = tracer
        self.writer = writer
        self.rt = request_tracer        # obs.reqtrace.RequestTracer | None
        self.flight = flight            # obs.flight.FlightRecorder | None
        self.telemetry = telemetry      # obs.telemetry.TelemetryExporter
        # ISSUE 15: optional training.metrics.DutyCycleProfiler — ticked
        # once per decode step from the host loop (the thread owning the
        # device queue), exactly like the flight recorder's anomaly tick
        self.duty_profiler = duty_profiler
        self._dtype = resolve_dtype(model.cfg.compute_dtype)
        self._table_len = max(model.cfg.maxlen, buf_len)
        # sampling knobs kept on the engine: the fused in-program sampler
        # stays the only production path; debug_host_sampler switches to
        # host-side full-vocab sampling for the equivalence tests and the
        # r10 cost ablation
        self._temperature, self._top_k, self._top_p = temperature, top_k, top_p
        self._debug_host_sampler = debug_host_sampler
        self._sample = make_token_sampler(model, temperature=temperature,
                                          top_k=top_k, top_p=top_p)
        _setup_decode_weights(self, model, mesh, params, decode_weight_dtype)
        self.pool = KVCachePool(model, mesh, num_slots, buf_len)
        self.scheduler = FIFOScheduler(buf_len, prefill_bucket=prefill_bucket,
                                       max_queue=max_queue, clock=clock,
                                       flight=flight)
        n = num_slots + 1  # + the scratch row (kv_manager.py)
        self._tokens = np.zeros(n, np.int32)
        self._pos = np.zeros(n, np.int32)
        self._seeds = np.zeros(n, np.uint32)
        self._slot_req: Dict[int, Request] = {}
        self._step_fn = self._build_step(n)
        self._prefill_fns: Dict[tuple, object] = {}
        self.completed: List[Request] = []
        # -- aggregate stats ---------------------------------------------
        self.decode_steps = 0
        self.generated_tokens = 0
        self._occupancy_sum = 0.0
        self.prefill_positions = 0            # Σ nb * width dispatched
        self.prefill_positions_monolithic = 0  # Σ rows * buf_len (no bucket)
        self.prompt_tokens = 0

    # -- compiled programs ----------------------------------------------
    def _tables(self):
        if not self.model.uses_rope:
            return None, None
        return rope_tables(self._table_len, self.model.cfg.head_dim,
                           self.model.cfg.rope_theta)

    def _build_step(self, n: int):
        model, buf_len, dtype = self.model, self.buf_len, self._dtype
        debug = self._debug_host_sampler

        def shard_fn(params, pool_k, pool_v, tokens, pos, seeds):
            params = self._deq(params)   # int8 decode weights dequant here
            cos_t, sin_t = self._tables()
            pool_k, pool_v, logits = _decode_one(
                model, params, pool_k, pool_v, tokens, pos, buf_len,
                cos_t, sin_t, dtype)
            if debug:
                # ablation: hand the LOCAL vocab shards back (the
                # out_specs concatenation materialises full-vocab logits
                # for the host) instead of sampling in-program
                return pool_k, pool_v, logits.astype(jnp.float32)
            tok = self._sample(logits, seeds, pos + 1)
            return pool_k, pool_v, tok

        fn = jax.shard_map(
            shard_fn, mesh=self.mesh,
            in_specs=(self._pspec, POOL_SPEC, POOL_SPEC, P(None), P(None),
                      P(None)),
            out_specs=(POOL_SPEC, POOL_SPEC,
                       P(None, "tp") if debug else P(None)))
        return jax.jit(fn, donate_argnums=(1, 2))

    def _build_prefill(self, nb: int, width: int):
        model, dtype = self.model, self._dtype

        def shard_fn(params, pool_k, pool_v, buf, prompt_len, slots, seeds):
            params = self._deq(params)
            cos_t, sin_t = self._tables()
            ks, vs, logits = _prefill(model, params, buf, prompt_len,
                                      cos_t, sin_t, dtype)
            # scatter the (L, nb, kvh, width, hd) prefill caches into the
            # target slots' first `width` rows; rows past the prompt are
            # re-written by decode steps before any query attends to them
            pool_k = pool_k.at[:, slots, :, :width, :].set(
                ks.astype(pool_k.dtype))
            pool_v = pool_v.at[:, slots, :, :width, :].set(
                vs.astype(pool_v.dtype))
            tok = self._sample(logits, seeds, prompt_len)
            return pool_k, pool_v, tok

        fn = jax.shard_map(
            shard_fn, mesh=self.mesh,
            in_specs=(self._pspec, POOL_SPEC, POOL_SPEC, P(None, None),
                      P(None), P(None), P(None)),
            out_specs=(POOL_SPEC, POOL_SPEC, P(None)))
        return jax.jit(fn, donate_argnums=(1, 2))

    # -- request intake --------------------------------------------------
    def submit(self, req: Request) -> None:
        """FIFO enqueue (raises scheduler.QueueFull past the backpressure
        bound). An accepted request opens its trace timeline at submit_t
        (rejected ones never get one — they have no life to explain); a
        `trace_ctx` handed over from another process CONTINUES that
        trace instead (obs/reqtrace.TraceContext)."""
        self.scheduler.submit(req)
        if self.rt is not None:
            self.rt.begin(req, ctx=_wire_ctx(req))

    def has_work(self) -> bool:
        return bool(self.scheduler.pending or self._slot_req)

    @property
    def live_requests(self) -> int:
        return len(self._slot_req)

    # -- the continuous-batching loop ------------------------------------
    def step(self) -> List[Request]:
        """One engine iteration: admit queued prompts into free slots
        (bucket-grouped prefills), then advance every live slot one token.
        Returns the requests that finished during this iteration."""
        done: List[Request] = []
        self._admit(done)
        if self._slot_req:
            self._decode(done)
        return done

    def run_to_completion(self) -> List[Request]:
        """Drain the queue and all live slots; returns all completions in
        finish order."""
        out: List[Request] = []
        while self.has_work():
            out.extend(self.step())
        return out

    # -- internals --------------------------------------------------------
    def _span(self, name, **args):
        if self.tracer is not None:
            return self.tracer.span(name, cat="serve", **args)
        import contextlib
        return contextlib.nullcontext()

    def _admit(self, done: List[Request]) -> None:
        while self.scheduler.pending and self.pool.free_slots:
            group = self.scheduler.take_batch(
                min(self.pool.free_slots, self.max_prefill_batch))
            if not group:
                break
            now = self._clock()
            ready = []
            for req in group:
                req.admit_t = now
                req.prompt_len = len(req.prompt)
                req.limit = min(req.prompt_len + req.max_new, self.buf_len)
                self.prompt_tokens += req.prompt_len
                if self.rt is not None:
                    self.rt.mark(req, "queued", now)
                if req.limit <= req.prompt_len:   # max_new == 0
                    req.finish_t = now
                    self._complete(req, done)
                else:
                    ready.append(req)
            if not ready:
                continue
            self._prefill_group(ready, done)

    def _prefill_group(self, ready: List[Request], done: List[Request]):
        width = self.scheduler.group_width(ready)
        nb = _pow2_at_most(len(ready), self.max_prefill_batch)
        slots = self.pool.alloc_many(len(ready))
        buf = np.full((nb, width), self.eos_id, np.int32)
        plens = np.ones(nb, np.int32)          # pad rows: 1-token dummy
        slot_idx = np.full(nb, self.pool.scratch_slot, np.int32)
        seeds = np.zeros(nb, np.uint32)
        for i, req in enumerate(ready):
            buf[i, : req.prompt_len] = req.prompt
            plens[i] = req.prompt_len
            slot_idx[i] = slots[i]
            seeds[i] = np.uint32(req.seed)
        key = (nb, width)
        if key not in self._prefill_fns:
            self._prefill_fns[key] = self._build_prefill(nb, width)
        with self._span("prefill", rows=len(ready), nb=nb, width=width):
            ks, vs, tok = self._prefill_fns[key](
                self._params_in, self.pool.ks, self.pool.vs, jnp.asarray(buf),
                jnp.asarray(plens), jnp.asarray(slot_idx),
                jnp.asarray(seeds))
            self.pool.adopt(ks, vs)
            tok = np.asarray(tok)
        self.prefill_positions += nb * width
        self.prefill_positions_monolithic += len(ready) * self.buf_len
        now = self._clock()
        for i, req in enumerate(ready):
            req.first_token_t = now
            if self.rt is not None:
                self.rt.mark(req, "prefill", now, positions=req.prompt_len)
            first = int(tok[i])
            if first == self.eos_id:              # 0 generated tokens
                req.finish_t = now
                self.pool.free(slots[i])
                self._complete(req, done)
                continue
            slot = slots[i]
            self._slot_req[slot] = req
            self._tokens[slot] = first
            self._pos[slot] = req.prompt_len
            self._seeds[slot] = np.uint32(req.seed)

    def _decode(self, done: List[Request]) -> None:
        with self._span("decode_step", live=len(self._slot_req)):
            ks, vs, tok = self._step_fn(
                self._params_in, self.pool.ks, self.pool.vs,
                jnp.asarray(self._tokens), jnp.asarray(self._pos),
                jnp.asarray(self._seeds))
            self.pool.adopt(ks, vs)
            if self._debug_host_sampler:
                # `tok` is the (b, vocab_padded) full-vocab logits — the
                # per-step host transfer the fused path avoids by design
                tok = host_sample_tokens(
                    self.model, np.asarray(tok), self._seeds, self._pos + 1,
                    self._temperature, self._top_k, self._top_p)
            else:
                tok = np.asarray(tok)
        now = self._clock()
        self.decode_steps += 1
        self._occupancy_sum += self.pool.occupancy
        if self.tracer is not None:
            self.tracer.counter("slots_live", len(self._slot_req))
        if self.flight is not None:
            self.flight.record("pool_stats", live=len(self._slot_req),
                               free_slots=self.pool.free_slots,
                               queued=self.scheduler.pending)
            # `tok` is host-side already (the np.asarray above), so this
            # step's device work is done — safe profiler stop barrier
            self.flight.tick(self.decode_steps)
        if self.duty_profiler is not None:
            # same safe point: device work for this step is host-side
            self.duty_profiler.tick(self.decode_steps)
        if self.telemetry is not None:
            tel = self.telemetry
            tel.gauge("serve/live", len(self._slot_req))
            tel.gauge("serve/queue_depth", self.scheduler.pending)
            tel.rate("serve/tokens_per_sec", self.generated_tokens)
            tel.counter("serve/decode_steps", self.decode_steps)
        _publish_hbm_plane(self)
        for slot, req in list(self._slot_req.items()):
            # the pending token was written at `pos` by this dispatch: it
            # is now part of the output (mirrors make_generate's buf write)
            if self.rt is not None:
                self.rt.mark(req, "decode", now)
            req.tokens.append(int(self._tokens[slot]))
            self.generated_tokens += 1
            cand = int(tok[slot])
            self._pos[slot] += 1
            gen = len(req.tokens)
            if cand == self.eos_id or req.prompt_len + gen >= req.limit:
                req.finish_t = now
                del self._slot_req[slot]
                self.pool.free(slot)
                self._complete(req, done)
            else:
                self._tokens[slot] = cand

    def _complete(self, req: Request, done: List[Request]) -> None:
        self.completed.append(req)
        done.append(req)
        if self.rt is not None:
            self.rt.retire(req)
        if self.writer is not None:
            ms = lambda s: None if s is None else round(s * 1e3, 3)
            self.writer.event(
                "serve_request", rid=req.rid, prompt_len=req.prompt_len,
                generated=len(req.tokens), trace_id=req.trace_id,
                queue_wait_ms=ms(req.queue_wait_s), ttft_ms=ms(req.ttft_s),
                tpot_ms=ms(req.tpot_s))

    # -- aggregate view ---------------------------------------------------
    def stats(self) -> dict:
        occ = (self._occupancy_sum / self.decode_steps
               if self.decode_steps else 0.0)
        mono = max(self.prefill_positions_monolithic, 1)
        return {
            "decode_steps": self.decode_steps,
            "generated_tokens": self.generated_tokens,
            "prompt_tokens": self.prompt_tokens,
            "completed": len(self.completed),
            "rejected": self.scheduler.rejected,
            "slot_occupancy_mean": round(occ, 4),
            "prefill_positions": self.prefill_positions,
            # share of the monolithic full-buffer prefill cost that
            # length-bucketing removed (generate.py logs this). Can go
            # NEGATIVE when bucketing is off but pow2 batch-padding added
            # rows — callers gate their print on > 0
            "prefill_pad_waste_eliminated": round(
                1.0 - self.prefill_positions / mono, 4)
            if self.prefill_positions_monolithic else 0.0,
        }


@dataclass
class _PrefillState:
    """Host-side cursor of an in-flight (chunked) prefill: `ids` is the
    full token prefix to materialise (prompt, plus any tokens a preempted
    request had already generated — the resume-through-prefill path),
    `s` the next position to process, `keys` the page-aligned prefix-index
    chain keys for registration."""

    req: Request
    ids: List[int]
    s: int
    keys: List[object] = field(default_factory=list)


class PagedEngine:
    """Continuous batching over a PAGED KV cache (serving v2, ISSUE 6).

    Same host-driven loop as `ContinuousBatchingEngine` — retire, admit,
    one decode dispatch — but the cache is a pool of fixed-size PAGES
    (`kv_manager.PagedKVPool`) indexed through a shape-stable
    `(slots, max_pages)` page table, which buys three things the slot
    engine cannot do:

    * **capacity = live tokens, not worst-case rows**: a slot leases pages
      as its cursor grows, so a mixed-length burst fits in the same HBM
      budget that the slot engine spends on `slots x buf_len` whatever the
      prompts actually are (`num_pages` is the budget; oversubscribing
      slots past it is the point).
    * **copy-on-write prefix reuse**: identical prompt prefixes (system
      prompts, few-shot headers) prefill ONCE — later arrivals reference
      the donor's pages through the pool's prefix index and only
      materialise a private copy when they WRITE into a shared page.
    * **chunked prefill**: a long prompt prefills `prefill_chunk` tokens
      at a time, interleaved into the decode loop, so a live stream's
      TPOT never stalls by more than one chunk
      (`max_interleaved_prefill_positions` in stats() is the measured
      bound).

    Admission is `scheduler.SLOScheduler` (TTFT deadline classes,
    per-tenant fairness, overdue-EDF rescue); when an overdue request
    cannot be admitted — or a live slot cannot grow a page — a victim from
    a looser deadline class (most generated tokens first: the most
    over-budget work) is PREEMPTED: its pages are freed, and it re-enters
    the queue with its generated prefix re-admitted through the COW path
    (greedy decode restarted from prompt+generated is token-identical to
    the uninterrupted run — per-position math depends only on the prefix).

    Token-identity contract: greedy paged output equals the slot engine's
    (and per-prompt GreedyDecoder's) for every request, across page
    sizes, arrival orders, COW sharing, chunking, and preemption — the
    decode/chunk lowerings reuse `_decode_one`'s attend math over a
    gathered page view (`models/decode._paged_decode_one`,
    `_paged_prefill_chunk`), pinned in tests/test_serving_paged.py."""

    def __init__(self, model, mesh: Mesh, params, num_slots: int,
                 buf_len: int, eos_id: int, page_size: int = 64,
                 num_pages: int = 0, prefill_chunk: int = 128,
                 temperature: float = 0.0, top_k: int = 0, top_p: float = 0.0,
                 slo_classes=None, default_class: str = "standard",
                 max_queue: int = 0, debug_host_sampler: bool = False,
                 kv_dtype=None, decode_weight_dtype=None,
                 paged_attn_impl: str = "gather",
                 paged_attn_interpret: bool = False,
                 tracer=None, writer=None, request_tracer=None,
                 flight=None, telemetry=None, duty_profiler=None,
                 controller=None, clock=time.monotonic,
                 prefill_only: bool = False):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if prefill_chunk < 1:
            raise ValueError(f"prefill_chunk must be >= 1, got "
                             f"{prefill_chunk}")
        # cp-sharded serving (ISSUE 18): the pool's page dim shards over
        # the 'cp' mesh axis; the host keeps ONE global page table and
        # rank-global accounting, and the compiled programs translate to
        # local slabs per rank. Page-table column j belongs to cp rank
        # j // (max_pages/cp), so max_pages rounds up to a cp multiple.
        self.cp = max(1, int(getattr(model, "cp_size", 1)))
        # the logical per-request buffer rounds UP to whole pages; the
        # dense gathered view is max_pages * page_size wide
        self.page_size = page_size
        pages = -(-buf_len // page_size)
        self.max_pages = self.cp * -(-pages // self.cp)
        self.buf_len = self.max_pages * page_size
        self._mpp = self.max_pages // self.cp   # page-table cols per cp rank
        cap = getattr(model, "max_decode_positions", None)
        if cap is not None and self.buf_len > cap:
            raise ValueError(
                f"buf_len {self.buf_len} ({self.max_pages} pages of "
                f"{page_size}) exceeds the model's learned position table "
                f"({cap}); clamp the buffer or retrain with a larger maxlen")
        if not num_pages:
            num_pages = num_slots * self.max_pages  # no oversubscription
        # the pool splits its pages into equal per-rank slabs (cp=1: one)
        num_pages = self.cp * -(-num_pages // self.cp)
        self.model = model
        self.mesh = mesh
        self.params = params
        self.num_slots = num_slots
        self.eos_id = int(eos_id)
        self.prefill_chunk = prefill_chunk
        self._clock = clock
        self.tracer = tracer
        self.writer = writer
        self.rt = request_tracer        # obs.reqtrace.RequestTracer | None
        self.flight = flight            # obs.flight.FlightRecorder | None
        self.telemetry = telemetry      # obs.telemetry.TelemetryExporter
        # ISSUE 15: optional training.metrics.DutyCycleProfiler — ticked
        # once per decode step on the host loop (the flight recorder's
        # anomaly-tick contract)
        self.duty_profiler = duty_profiler
        # ISSUE 16: optional serving.controller.SLOController — observed
        # and actuated only from _control_tick (the registered safe point)
        self.controller = controller
        # online per-class SLO accounting (ISSUE 12): {class: [completed,
        # hit]}, updated at every _complete — feeds the live exporter
        # gauges AND the in-run attainment-collapse flight trigger (the
        # post-run loadgen check can only dump after the damage is done)
        self._slo_counts: Dict[str, list] = {}
        self.slo_collapsed: set = set()
        self._dtype = resolve_dtype(model.cfg.compute_dtype)
        self._table_len = max(model.cfg.maxlen, self.buf_len)
        # fused in-program sampling is the only production path; the knobs
        # stay on the engine for the host-debug sampler and the speculative
        # subclass (serving/speculative.py reuses them for draft + verify)
        self._temperature, self._top_k, self._top_p = temperature, top_k, top_p
        self._debug_host_sampler = debug_host_sampler
        self._sample = make_token_sampler(model, temperature=temperature,
                                          top_k=top_k, top_p=top_p)
        _setup_decode_weights(self, model, mesh, params, decode_weight_dtype)
        # paged-attention impl (ISSUE 14): 'gather' materializes the dense
        # page view (the oracle); 'pallas' walks the page table in place.
        # Resolved ONCE here — a non-TPU backend without the interpreter
        # opt-in falls back to gather with a one-time warning, so every
        # compiled program below agrees on one impl.
        from ..ops.pallas.paged_attention import resolve_paged_attn_impl
        self.paged_attn_impl = resolve_paged_attn_impl(
            paged_attn_impl, interpret=paged_attn_interpret)
        self._paged_attn_interpret = bool(paged_attn_interpret)
        # int8 pages: codes + per-head-vector scales through the SAME
        # lease/COW/free accounting (kv_manager.PagedKVPool docstring)
        self.kv_dtype = kv_dtype
        self.pool = PagedKVPool(model, mesh, num_pages, page_size,
                                kv_dtype=kv_dtype, flight=flight)
        # ISSUE 15: bytes one leased page costs, for the pool-vs-device
        # HBM cross-check gauge (accounted pool bytes / measured
        # bytes_in_use)
        from .kv_manager import page_bytes
        self._page_bytes_each = page_bytes(model.cfg, page_size, kv_dtype)
        self.scheduler = SLOScheduler(self.buf_len, classes=slo_classes,
                                      default_class=default_class,
                                      max_queue=max_queue, clock=clock,
                                      flight=flight)
        self._free_slots = deque(range(num_slots))
        # (slots, max_pages) page table; free rows aim at the scratch page
        self._tbl = np.full((num_slots, self.max_pages),
                            self.pool.scratch_page, np.int32)
        self._tokens = np.zeros(num_slots, np.int32)
        self._pos = np.zeros(num_slots, np.int32)
        self._seeds = np.zeros(num_slots, np.uint32)
        self._slot_req: Dict[int, Request] = {}
        self._prefilling: Dict[int, _PrefillState] = {}
        self._step_fn = self._build_step()
        self._chunk_fns: Dict[int, object] = {}
        self.completed: List[Request] = []
        # disaggregated serving (ISSUE 19): a prefill_only engine never
        # decodes — finished prefills park in `handoffs` (page refs held
        # by the ledger) until the caller streams them out and calls
        # finish_handoff; a decode engine adopts them via admit_prefilled
        self.prefill_only = bool(prefill_only)
        self.handoffs: deque = deque()
        self.handoffs_staged = 0
        self.pages_exported = 0
        self.pages_imported = 0
        # -- aggregate stats ---------------------------------------------
        self.decode_steps = 0
        self.generated_tokens = 0
        self.prompt_tokens = 0
        self.prefill_positions = 0          # positions actually dispatched
        self.prefill_token_demand = 0       # Σ len(ids) at admissions
        self.prefix_hit_tokens = 0          # positions served from shared pages
        self.preemptions = 0
        self.max_live = 0
        self.max_interleaved_prefill = 0    # the chunk stall bound, measured
        self._occupancy_sum = 0.0
        self._kv_util_sum = 0.0
        self._pages_used_sum = 0

    # -- compiled programs ------------------------------------------------
    def _tables(self):
        if not self.model.uses_rope:
            return None, None
        return rope_tables(self._table_len, self.model.cfg.head_dim,
                           self.model.cfg.rope_theta)

    def _build_step(self):
        model, ps, dtype = self.model, self.page_size, self._dtype
        debug = self._debug_host_sampler
        impl, interp = self.paged_attn_impl, self._paged_attn_interpret
        cp = self.cp
        pspec = self.pool.pspec   # POOL_SPEC / CP_POOL_SPEC, or (codes, sc)

        def shard_fn(params, pool_k, pool_v, tokens, pos, seeds, tbl):
            params = self._deq(params)   # int8 decode weights dequant here
            cos_t, sin_t = self._tables()
            pool_k, pool_v, logits = _paged_decode_one(
                model, params, pool_k, pool_v, tokens, pos, tbl, ps,
                cos_t, sin_t, dtype, attn_impl=impl,
                attn_interpret=interp, cp=cp)
            if debug:
                return pool_k, pool_v, logits.astype(jnp.float32)
            tok = self._sample(logits, seeds, pos + 1)
            return pool_k, pool_v, tok

        fn = jax.shard_map(
            shard_fn, mesh=self.mesh,
            in_specs=(self._pspec, pspec, pspec, P(None), P(None),
                      P(None), P(None, None)),
            out_specs=(pspec, pspec,
                       P(None, "tp") if debug else P(None)))
        return jax.jit(fn, donate_argnums=(1, 2))

    def _build_chunk(self, cw: int):
        model, ps, dtype = self.model, self.page_size, self._dtype
        impl, interp = self.paged_attn_impl, self._paged_attn_interpret
        cp = self.cp
        pspec = self.pool.pspec

        def shard_fn(params, pool_k, pool_v, chunk, start, qlen, tbl,
                     dstp, dsto, seeds):
            params = self._deq(params)
            cos_t, sin_t = self._tables()
            pool_k, pool_v, logits = _paged_prefill_chunk(
                model, params, pool_k, pool_v, chunk, start, qlen, tbl,
                dstp, dsto, ps, cos_t, sin_t, dtype, attn_impl=impl,
                attn_interpret=interp, cp=cp)
            tok = self._sample(logits, seeds, start + qlen)
            return pool_k, pool_v, tok

        fn = jax.shard_map(
            shard_fn, mesh=self.mesh,
            in_specs=(self._pspec, pspec, pspec, P(None, None),
                      P(None), P(None), P(None, None), P(None, None),
                      P(None, None), P(None)),
            out_specs=(pspec, pspec, P(None)))
        return jax.jit(fn, donate_argnums=(1, 2))

    # -- request intake ---------------------------------------------------
    def submit(self, req: Request) -> None:
        """Enqueue through the SLO scheduler (QueueFull past the
        backpressure bound). Refuses up front a request whose WORST-CASE
        private footprint cannot fit the page pool — admitted, it would
        deadlock preemption once it became the only live request."""
        need = -(-min(len(req.prompt) + req.max_new, self.buf_len)
                 // self.page_size)
        if need > self.pool.num_pages:
            raise ValueError(
                f"request {req.rid}: needs up to {need} pages "
                f"({len(req.prompt)}+{req.max_new} tokens / page_size "
                f"{self.page_size}) but the pool has {self.pool.num_pages} "
                f"— raise --num_pages or lower the budget")
        # cp>1: ownership is positional (column j -> rank j//mpp), so the
        # worst case drawn from ONE rank's slab is min(need, mpp) pages
        if min(need, self._mpp) > self.pool.pages_per_rank:
            raise ValueError(
                f"request {req.rid}: needs up to {min(need, self._mpp)} "
                f"pages from one cp rank's slab ({need} total over cp="
                f"{self.cp}) but each slab holds "
                f"{self.pool.pages_per_rank} — raise --num_pages or lower "
                f"the budget")
        self.scheduler.submit(req)
        if self.rt is not None:
            self.rt.begin(req, ctx=_wire_ctx(req))

    def has_work(self) -> bool:
        return bool(self.scheduler.pending or self._slot_req
                    or self._prefilling)

    @property
    def live_requests(self) -> int:
        return len(self._slot_req) + len(self._prefilling)

    # -- the engine loop --------------------------------------------------
    def step(self) -> List[Request]:
        """One iteration: admit (slots + shared-prefix match), pump AT MOST
        one chunk of prefill while streams are live (the TPOT stall
        bound), then advance every live slot one token."""
        done: List[Request] = []
        self._admit(done)
        self._pump_prefill(done)
        if self._slot_req:
            self._decode(done)
        self.max_live = max(self.max_live, self.live_requests)
        return done

    def run_to_completion(self) -> List[Request]:
        out: List[Request] = []
        while self.has_work():
            out.extend(self.step())
        return out

    # -- internals --------------------------------------------------------
    def _span(self, name, **args):
        if self.tracer is not None:
            return self.tracer.span(name, cat="serve", **args)
        import contextlib
        return contextlib.nullcontext()

    def _chain_keys(self, ids: List[int]) -> List[object]:
        """Prefix-index chain keys for every page-aligned run of `ids`
        (the last may be partial)."""
        ps, keys, parent = self.page_size, [], None
        for j in range(-(-len(ids) // ps)):
            parent = self.pool.chain_key(parent, ids[j * ps:(j + 1) * ps])
            keys.append(parent)
        return keys

    def _try_share(self, slot: int, st: _PrefillState) -> None:
        """At a page boundary, extend the slot's prefix through the pool's
        index instead of recomputing it: a donor page whose valid tokens
        lead-match the remaining ids is referenced in place (refcount++),
        and the cursor jumps past the shared run. A partial match (shorter
        donor tail, or a divergence inside the page) still shares the
        matched positions — visibility masks the rest — but ends the walk.
        Capped at len(ids)-1 so at least one position is always recomputed
        (its logits seed the first sampled token). Runs before every chunk
        dispatch, so a donor admitted in the SAME step is found as soon as
        its pages register."""
        ps = self.page_size
        while st.s % ps == 0:
            cap = len(st.ids) - 1 - st.s
            if cap <= 0:
                break
            j = st.s // ps
            parent = st.keys[j - 1] if j else None
            window = st.ids[st.s:st.s + min(ps, cap)]
            best_page, best_len = None, 0
            for page, toks in self.pool.children(parent):
                n = 0
                for a, b in zip(toks, window):
                    if a != b:
                        break
                    n += 1
                if n > best_len:
                    best_page, best_len = page, n
            if best_len == 0:
                break
            self.pool.ref(best_page)
            self._tbl[slot, j] = best_page
            st.s += best_len
            self.prefix_hit_tokens += best_len
            if self.rt is not None:
                self.rt.note(st.req, prefix_hit_tokens=best_len)
            if best_len < ps:
                break                      # partial match ends the walk

    def _admit(self, done: List[Request]) -> None:
        while self._free_slots or self.scheduler.pending:
            req = self.scheduler.peek()
            if req is None:
                break
            now = self._clock()
            overdue = req.deadline_t is not None and now >= req.deadline_t
            if not self._free_slots:
                # an overdue head may evict a looser-class victim
                if not (overdue and self._preempt_for(req)):
                    break
                continue
            ids = req.prompt + req.tokens
            # gate on the pages the FIRST chunk needs (conservative: prefix
            # sharing, resolved at chunk time, can only reduce it), so a
            # freshly admitted request never instantly deadlocks the pump
            need = -(-min(len(ids), self.prefill_chunk) // self.page_size)
            if not self._fits_free(need):
                if not (overdue and self._preempt_for(req)):
                    break
                continue
            self.scheduler.take()
            if req.admit_t is None:
                req.admit_t = now
                req.prompt_len = len(req.prompt)
                req.limit = min(req.prompt_len + req.max_new, self.buf_len)
                self.prompt_tokens += req.prompt_len
            if self.rt is not None:
                # covers the first admission AND every preempt-resume
                # re-admission (the span since `preempted` was queue time)
                self.rt.mark(req, "queued", now)
            if req.limit <= len(ids):      # max_new == 0
                req.finish_t = now
                self._complete(req, done)
                continue
            slot = self._free_slots.popleft()
            self.prefill_token_demand += len(ids)
            st = _PrefillState(req, ids, 0)
            st.keys = self._chain_keys(ids)
            self._prefilling[slot] = st

    def _candidates(self, exclude_slot=None):
        """Live + prefilling requests preemption may evict, worst first:
        loosest deadline class, then most generated tokens (the most
        over-budget work), then latest admission."""
        cands = []
        for slot, req in self._slot_req.items():
            if slot != exclude_slot:
                cands.append((slot, req))
        for slot, st in self._prefilling.items():
            if slot != exclude_slot:
                cands.append((slot, st.req))
        classes = self.scheduler.classes
        cands.sort(key=lambda sr: (-classes.get(sr[1].slo_class, 0.0),
                                   -len(sr[1].tokens),
                                   -(sr[1].admit_t or 0.0)))
        return cands

    def _preempt_for(self, req) -> bool:
        """Evict one victim from a STRICTLY looser deadline class than
        `req` (same-class work is never displaced — that would ping-pong).
        Returns True when something was freed."""
        classes = self.scheduler.classes
        bound = classes[req.slo_class or self.scheduler.default_class]
        for slot, victim in self._candidates():
            if classes.get(victim.slo_class, 0.0) > bound:
                self._preempt(slot)
                return True
        return False

    def _preempt(self, slot: int) -> None:
        """Evict a slot: pages unref'd (shared ones survive for their
        sharers), the request re-queued with prompt+generated as its new
        prefill prefix (COW re-admission); its pending sampled token is
        dropped — the resume prefill re-derives it (same prefix, same
        greedy argmax / same fold_in(seed, position) draw)."""
        if slot in self._slot_req:
            req = self._slot_req.pop(slot)
        else:
            req = self._prefilling.pop(slot).req
        freed = self._release_slot(slot)
        req.preemptions += 1
        self.preemptions += 1
        if self.rt is not None:
            self.rt.mark(req, "preempted", self._clock())
            self.rt.note(req, pages_freed=freed)
        if self.flight is not None:
            self.flight.record("preempt", rid=req.rid, slot=slot,
                               generated=len(req.tokens),
                               pages_freed=freed,
                               slo_class=req.slo_class)
        self.scheduler.requeue(req)

    def _release_slot(self, slot: int) -> int:
        """Returns the number of page references dropped (the request-
        trace pages_freed counter)."""
        scratch = self.pool.scratch_page
        freed = 0
        for j in range(self.max_pages):
            if self._tbl[slot, j] != scratch:
                self.pool.unref(int(self._tbl[slot, j]))
                self._tbl[slot, j] = scratch
                freed += 1
        self._pos[slot] = 0
        self._free_slots.append(slot)
        return freed

    def _fits_free(self, need: int) -> bool:
        """Can `need` pages for page-table columns [0, need) be leased
        right now? cp=1: one free list. cp>1: the columns split into
        per-rank spans of `mpp`, and every rank's share must fit its own
        slab — a pool half-free in aggregate still refuses when rank 0's
        slab is dry (ownership is positional, pages cannot migrate)."""
        if self.cp == 1:
            return need <= self.pool.free_pages
        for o in range(self.cp):
            cols = max(0, min(need, (o + 1) * self._mpp) - o * self._mpp)
            if cols > self.pool.free_pages_of(o):
                return False
        return True

    def _alloc_page(self, needy_slot: int, owner: int = 0) -> int:
        """A free page from cp rank `owner`'s slab (cp=1: the whole pool),
        evicting victims if the slab is dry (never the needy slot itself).
        Submit-time validation guarantees a sole live request fits, so
        exhaustion with no victim cannot happen. A PoolExhausted-forced
        preemption freezes the flight ring: the dump shows the
        pool/scheduler state that led to the eviction."""
        while True:
            try:
                return self.pool.alloc(owner)
            except PoolExhausted:
                cands = self._candidates(exclude_slot=needy_slot)
                if not cands:
                    raise RuntimeError(
                        "page pool exhausted with no preemption candidate "
                        "— a single request outgrew num_pages (submit-time "
                        "validation should have refused it)")
                victim_slot, victim = cands[0]
                self._preempt(victim_slot)
                if self.flight is not None:
                    self.flight.dump(
                        {"kind": "pool_exhausted_preempt",
                         "needy_slot": needy_slot,
                         "victim_rid": victim.rid,
                         "victim_slot": victim_slot,
                         "victim_generated": len(victim.tokens),
                         "num_pages": self.pool.num_pages},
                        tag="pool_exhausted")

    def _ensure_writable(self, slot: int, lo: int, hi: int):
        """Positions [lo, hi) of `slot` must land in PRIVATE pages before
        a write dispatch: unmapped entries allocate, shared entries
        copy-on-write (one bucketed copy dispatch). Returns
        (pages_allocated, cow_copies) so callers can attribute the page
        churn to the owning request's timeline."""
        ps, scratch = self.page_size, self.pool.scratch_page
        pairs = []
        allocated = 0
        for j in range(lo // ps, -(-hi // ps)):
            owner = j // self._mpp     # cp rank whose slab backs column j
            pid = int(self._tbl[slot, j])
            if pid == scratch:
                self._tbl[slot, j] = self._alloc_page(slot, owner)
                allocated += 1
            elif self.pool.refcount[pid] > 1:
                # same-column COW: src and dst share the owner, so the
                # device copy never crosses cp slabs
                new = self._alloc_page(slot, owner)
                pairs.append((pid, new))
                self.pool.unref(pid)
                self._tbl[slot, j] = new
        self.pool.copy_pages(pairs)
        return allocated, len(pairs)

    def _pump_prefill(self, done: List[Request]) -> None:
        """Advance prefills chunk by chunk. While ANY stream is live
        decoding, at most `prefill_chunk` positions are dispatched per
        engine step — the bound on how long a decode dispatch can be
        delayed by prefill work (`max_interleaved_prefill` tracks the
        realised max; tests assert it)."""
        interleaved = 0
        while self._prefilling:
            live_before = bool(self._slot_req)
            if live_before and interleaved >= self.prefill_chunk:
                break
            slot, st = next(iter(self._prefilling.items()))
            self._try_share(slot, st)      # COW prefix reuse, page-aligned
            budget = (self.prefill_chunk - interleaved if live_before
                      else self.prefill_chunk)
            n = min(len(st.ids) - st.s, budget)
            self._dispatch_chunk(slot, st, n, done)
            if live_before:
                interleaved += n
        self.max_interleaved_prefill = max(self.max_interleaved_prefill,
                                           interleaved)

    def _dispatch_chunk(self, slot: int, st: _PrefillState, n: int,
                        done: List[Request]) -> None:
        ps = self.page_size
        s, ids, req = st.s, st.ids, st.req
        leased, cowed = self._ensure_writable(slot, s, s + n)
        cw = _pow2_at_most(n, self.prefill_chunk)
        # the cp query ring splits the chunk into cp sub-blocks, so the
        # dispatch width rounds up to a cp multiple (pads are scratch-aimed)
        cw = self.cp * -(-cw // self.cp)
        buf, dstp, dsto = _chunk_maps(ids, s, n, cw, ps, self.eos_id,
                                      self.pool.scratch_page,
                                      self._tbl[slot])
        if cw not in self._chunk_fns:
            self._chunk_fns[cw] = self._build_chunk(cw)
        with self._span("prefill_chunk", slot=slot, pos0=s, n=n, cw=cw):
            ks, vs, tok = self._chunk_fns[cw](
                self._params_in, self.pool.ks, self.pool.vs, jnp.asarray(buf),
                jnp.asarray([s], np.int32), jnp.asarray([n], np.int32),
                jnp.asarray(self._tbl[slot:slot + 1]), jnp.asarray(dstp),
                jnp.asarray(dsto),
                jnp.asarray([req.seed], np.uint32))
            self.pool.adopt(ks, vs)
            tok = np.asarray(tok)
        self.prefill_positions += n
        # register freshly completed prompt pages in the prefix index:
        # full pages whose last position this chunk wrote, and the partial
        # tail once the whole prefix is in (shared donors dedupe inside
        # register_prefix)
        for j in range(s // ps, -(-(s + n) // ps)):
            end = min((j + 1) * ps, len(ids))
            if s + n >= end:
                parent = st.keys[j - 1] if j else None
                self.pool.register_prefix(parent, int(self._tbl[slot, j]),
                                          ids[j * ps:end])
        st.s += n
        if self.rt is not None:
            self.rt.mark(req, "prefill_chunk", self._clock(),
                         positions=n, cow=cowed)
            self.rt.note(req, pages_leased=leased, cow_copies=cowed)
        if st.s >= len(ids):
            self._finish_prefill(slot, st, int(tok[0]), done)

    def _finish_prefill(self, slot: int, st: _PrefillState, first: int,
                        done: List[Request]) -> None:
        req = st.req
        del self._prefilling[slot]
        now = self._clock()
        if req.first_token_t is None:
            req.first_token_t = now
        if self.prefill_only:
            self._stage_handoff(slot, st, int(first), now)
            return
        if first == self.eos_id:              # 0 (more) generated tokens
            req.finish_t = now
            freed = self._release_slot(slot)
            if self.rt is not None:
                self.rt.note(req, pages_freed=freed)
            self._complete(req, done)
            return
        self._slot_req[slot] = req
        self._tokens[slot] = first
        self._pos[slot] = len(st.ids)
        self._seeds[slot] = np.uint32(req.seed)

    # -- disaggregated prefill/decode handoff (ISSUE 19) ------------------
    def _stage_handoff(self, slot: int, st: _PrefillState, first: int,
                       now: float) -> None:
        """Park a finished prefill for stream-out instead of decoding:
        the page-table row detaches into the handoff ledger WITH its
        references — the pages (and their prefix-index registrations)
        stay live for export_pages and for sharing with later prefills —
        until finish_handoff drops them after the transfer. The slot
        frees immediately, so a prefill_only engine's slot count bounds
        concurrent prefills, not in-flight handoffs."""
        n_pages = -(-len(st.ids) // self.page_size)
        pages = [int(self._tbl[slot, j]) for j in range(n_pages)]
        self._tbl[slot, :] = self.pool.scratch_page
        self._pos[slot] = 0
        self._free_slots.append(slot)
        self.handoffs.append({"req": st.req, "pages": pages,
                              "first": first, "n_tokens": len(st.ids)})
        self.handoffs_staged += 1
        if self.rt is not None:
            self.rt.mark(st.req, "prefill_done", now, pages=n_pages)

    def export_handoff(self, h) -> tuple:
        """Host payload for one staged handoff: (k, v) from
        PagedKVPool.export_pages over the request's page list (global
        head layout — the importer reshards under its own tp width)."""
        k, v = self.pool.export_pages(h["pages"])
        self.pages_exported += len(h["pages"])
        return k, v

    def finish_handoff(self, h) -> None:
        """Drop the ledger's page references once the receiving pool
        holds its own copies (shared prefix pages survive for their
        other referents), and retire the local trace record — the decode
        side continues the trace from the exported context."""
        for p in h["pages"]:
            self.pool.unref(p)
        if self.rt is not None:
            self.rt.retire(h["req"])

    def admit_prefilled(self, req: Request, k, v, first: int) -> int:
        """Disaggregated decode intake: lease + import pages for an
        ALREADY-PREFILLED request (payload from export_pages on the
        prefill side — any tp/cp width) and install the slot state
        exactly as _finish_prefill would, so the decode loop continues
        token-identically to colocated serving (position math depends
        only on the prefix, and the prefix bytes just arrived). Returns
        the slot used, or -1 when the request completed immediately
        (first == eos, or max_new exhausted). Raises RuntimeError when
        no slot is free and PoolExhausted when the pool is — both are
        the caller's backpressure signals; nothing is partially
        admitted."""
        ids = req.prompt + req.tokens
        n_pages = -(-len(ids) // self.page_size)
        if n_pages > self.max_pages:
            raise ValueError(
                f"handoff {req.rid}: {len(ids)} prefilled tokens need "
                f"{n_pages} page-table columns but the row has "
                f"{self.max_pages} (buf_len {self.buf_len})")
        need = -(-min(len(ids) + req.max_new, self.buf_len)
                 // self.page_size)
        if need > self.pool.num_pages:
            raise ValueError(
                f"handoff {req.rid}: worst case {need} pages exceeds the "
                f"pool's {self.pool.num_pages} — raise --num_pages")
        if not self._free_slots:
            raise RuntimeError(
                f"no free slot for handoff {req.rid} "
                f"({self.num_slots} slots busy)")
        now = self._clock()
        if req.submit_t is None:
            req.submit_t = now
        req.admit_t = now
        req.prompt_len = len(req.prompt)
        req.limit = min(req.prompt_len + req.max_new, self.buf_len)
        self.prompt_tokens += req.prompt_len
        if self.rt is not None:
            self.rt.begin(req, ctx=_wire_ctx(req))
        pages = self.pool.import_pages(
            k, v, owners=[j // self._mpp for j in range(n_pages)])
        self.pages_imported += len(pages)
        slot = self._free_slots.popleft()
        for j, p in enumerate(pages):
            self._tbl[slot, j] = p
        # register the imported prompt pages so later LOCAL arrivals
        # share them exactly as a locally prefilled donor's
        keys = self._chain_keys(ids)
        ps = self.page_size
        for j in range(n_pages):
            self.pool.register_prefix(keys[j - 1] if j else None, pages[j],
                                      ids[j * ps:min((j + 1) * ps,
                                                     len(ids))])
        if self.rt is not None:
            self.rt.mark(req, "kv_import", self._clock(), pages=n_pages)
        if req.first_token_t is None:
            req.first_token_t = self._clock()
        if int(first) == self.eos_id or req.limit <= len(ids):
            req.finish_t = self._clock()
            freed = self._release_slot(slot)
            if self.rt is not None:
                self.rt.note(req, pages_freed=freed)
            self._complete(req, [])
            return -1
        self._slot_req[slot] = req
        self._tokens[slot] = int(first)
        self._pos[slot] = len(ids)
        self._seeds[slot] = np.uint32(req.seed)
        self.max_live = max(self.max_live, self.live_requests)
        return slot

    def _decode(self, done: List[Request]) -> None:
        # grow/privatise the write page of every live slot FIRST — this
        # may itself preempt victims (page exhaustion), so iterate a
        # snapshot and re-check liveness
        for slot in list(self._slot_req):
            if slot not in self._slot_req:
                continue
            pos = int(self._pos[slot])
            leased, cowed = self._ensure_writable(slot, pos, pos + 1)
            if self.rt is not None and (leased or cowed):
                req = self._slot_req.get(slot)
                if req is not None:
                    self.rt.note(req, pages_leased=leased, cow_copies=cowed)
        if not self._slot_req:
            return
        # the dispatch is dense over ALL slot rows, and a non-live row
        # (a slot mid-prefill, or freed this step) still flows through it
        # with cursor 0 and a stale pending token — so its spurious
        # position-0 K/V write must land on the scratch page, NOT the real
        # (possibly shared) page its table maps. Freed slots' tables are
        # already all-scratch; mid-prefill slots' are not, so mask them
        # here rather than hand the program a live page to scribble on.
        tbl = self._tbl
        if self._prefilling:
            tbl = self._tbl.copy()
            for slot in self._prefilling:
                tbl[slot, :] = self.pool.scratch_page
        with self._span("decode_step", live=len(self._slot_req)):
            ks, vs, tok = self._step_fn(
                self._params_in, self.pool.ks, self.pool.vs,
                jnp.asarray(self._tokens), jnp.asarray(self._pos),
                jnp.asarray(self._seeds), jnp.asarray(tbl))
            self.pool.adopt(ks, vs)
            if self._debug_host_sampler:
                tok = host_sample_tokens(
                    self.model, np.asarray(tok), self._seeds, self._pos + 1,
                    self._temperature, self._top_k, self._top_p)
            else:
                tok = np.asarray(tok)
        now = self._clock()
        self.decode_steps += 1
        live_tokens = sum(int(self._pos[s]) + 1 for s in self._slot_req)
        live_tokens += sum(st.s for st in self._prefilling.values())
        used = self.pool.pages_in_use
        self._occupancy_sum += self.live_requests / self.num_slots
        self._pages_used_sum += used
        if used:
            self._kv_util_sum += live_tokens / (used * self.page_size)
        if self.tracer is not None:
            self.tracer.counter("slots_live", len(self._slot_req))
            self.tracer.counter("pages_in_use", used)
        if self.flight is not None:
            self.flight.record("pool_stats", live=len(self._slot_req),
                               prefilling=len(self._prefilling),
                               pages_in_use=used,
                               free_pages=self.pool.free_pages,
                               queued=self.scheduler.pending)
            # device work for this step is already host-side (`tok`);
            # safe point to drive an armed anomaly-profiler window
            self.flight.tick(self.decode_steps)
        if self.duty_profiler is not None:
            self.duty_profiler.tick(self.decode_steps)
        if self.telemetry is not None:
            self._publish_telemetry(used, live_tokens)
        _publish_hbm_plane(self, pool_bytes=used * self._page_bytes_each)
        if self.controller is not None:
            self._control_tick()
        for slot, req in list(self._slot_req.items()):
            if self.rt is not None:
                self.rt.mark(req, "decode", now)
            req.tokens.append(int(self._tokens[slot]))
            self.generated_tokens += 1
            cand = int(tok[slot])
            self._pos[slot] += 1
            if cand == self.eos_id or req.prompt_len + len(req.tokens) >= req.limit:
                req.finish_t = now
                del self._slot_req[slot]
                freed = self._release_slot(slot)
                if self.rt is not None:
                    self.rt.note(req, pages_freed=freed)
                self._complete(req, done)
            else:
                self._tokens[slot] = cand

    @control_safe_point
    def _control_tick(self) -> None:
        """The control plane's registered safe point (ISSUE 16): device
        work for this decode step is already host-side (the same
        contract as flight.tick above), nothing is traced, and no
        capture window is mid-flight on this thread — so the SLO
        controller may observe AND (mode=act) actuate here. graftcheck's
        `controller-discipline` rule pins that `apply_decisions` is only
        ever called from a `@control_safe_point` function."""
        self.controller.tick(self.decode_steps)
        self.controller.apply_decisions()

    def _publish_telemetry(self, pages_used: int, live_tokens: int) -> None:
        """Per-decode-step exporter update (ISSUE 12): a handful of lock-
        guarded dict stores — the pinned hot-path budget is why nothing
        here formats strings or touches I/O."""
        tel = self.telemetry
        tel.gauge("serve/live", len(self._slot_req))
        tel.gauge("serve/prefilling", len(self._prefilling))
        tel.gauge("serve/queue_depth", self.scheduler.pending)
        tel.gauge("serve/pages_in_use", pages_used)
        tel.gauge("serve/free_pages", self.pool.free_pages)
        tel.gauge("serve/num_pages", self.pool.num_pages)
        if pages_used:
            tel.gauge("serve/kv_util",
                      live_tokens / (pages_used * self.page_size))
        tel.rate("serve/tokens_per_sec", self.generated_tokens)
        tel.counter("serve/decode_steps", self.decode_steps)
        tel.counter("serve/preemptions", self.preemptions)

    def _account_slo(self, req: Request) -> None:
        """Fold one completion into the live per-class attainment; an
        in-run collapse (< 50% attained over >= 4 completions) freezes
        the flight ring ONCE per class, while the pool/scheduler history
        that produced it is still in the ring — and, when an anomaly
        profiler is armed, cross-links a device capture of the very next
        steps."""
        cls = req.slo_class or self.scheduler.default_class
        deadline = self.scheduler.classes.get(cls)
        if deadline is None:
            return
        c = self._slo_counts.setdefault(cls, [0, 0])
        c[0] += 1
        if req.ttft_s is not None and req.ttft_s <= deadline:
            c[1] += 1
        attained = c[1] / c[0]
        if self.telemetry is not None:
            tel = self.telemetry
            tel.counter(f"slo/{cls}/completed", c[0])
            tel.counter(f"slo/{cls}/hit", c[1])
            tel.gauge(f"slo/{cls}/attained", attained)
        if (self.flight is not None and c[0] >= 4 and attained < 0.5
                and cls not in self.slo_collapsed):
            self.slo_collapsed.add(cls)
            self.flight.dump(
                {"kind": "slo_attainment_collapse", "slo_class": cls,
                 "completed": c[0], "attained": round(attained, 4),
                 "deadline_s": deadline},
                tag="slo_collapse")

    def _complete(self, req: Request, done: List[Request]) -> None:
        self.completed.append(req)
        done.append(req)
        if self.scheduler.classes:
            self._account_slo(req)
        if self.rt is not None:
            self.rt.retire(req)
        if self.writer is not None:
            ms = lambda s: None if s is None else round(s * 1e3, 3)
            self.writer.event(
                "serve_request", rid=req.rid, prompt_len=req.prompt_len,
                generated=len(req.tokens), tenant=req.tenant,
                slo_class=req.slo_class, preemptions=req.preemptions,
                trace_id=req.trace_id,
                queue_wait_ms=ms(req.queue_wait_s), ttft_ms=ms(req.ttft_s),
                tpot_ms=ms(req.tpot_s))

    # -- aggregate view ---------------------------------------------------
    def stats(self) -> dict:
        steps = max(self.decode_steps, 1)
        demand = max(self.prefill_token_demand, 1)
        return {
            "decode_steps": self.decode_steps,
            "generated_tokens": self.generated_tokens,
            "prompt_tokens": self.prompt_tokens,
            "completed": len(self.completed),
            "rejected": self.scheduler.rejected,
            "slot_occupancy_mean": round(
                self._occupancy_sum / steps if self.decode_steps else 0.0, 4),
            "prefill_positions": self.prefill_positions,
            # -- token-granular occupancy (the paged win, measured) ------
            "page_size": self.page_size,
            "kv_dtype": self.kv_dtype or "native",
            "paged_attn": self.paged_attn_impl,
            # -- cp page sharding (ISSUE 18) -----------------------------
            "cp": self.cp,
            "pages_per_rank": self.pool.pages_per_rank,
            "num_pages": self.pool.num_pages,
            "pages_in_use": self.pool.pages_in_use,
            "pages_in_use_mean": round(self._pages_used_sum / steps
                                       if self.decode_steps else 0.0, 2),
            # live tokens / allocated page bytes: 1.0 = no dead space
            "kv_util_mean": round(
                self._kv_util_sum / steps if self.decode_steps else 0.0, 4),
            "kv_fragmentation_mean": round(
                1.0 - self._kv_util_sum / steps
                if self.decode_steps else 0.0, 4),
            # -- COW prefix cache ----------------------------------------
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "prefix_hit_rate": round(self.prefix_hit_tokens / demand, 4)
            if self.prefill_token_demand else 0.0,
            "cow_copies": self.pool.cow_copies,
            # -- scheduler/preemption ------------------------------------
            "preemptions": self.preemptions,
            "max_live": self.max_live,
            "max_interleaved_prefill_positions": self.max_interleaved_prefill,
            # -- disaggregated handoff (ISSUE 19) ------------------------
            "prefill_only": self.prefill_only,
            "handoffs_staged": self.handoffs_staged,
            "pages_exported": self.pages_exported,
            "pages_imported": self.pages_imported,
        }
