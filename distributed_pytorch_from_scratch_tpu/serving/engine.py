"""Continuous-batching inference engine over the slot-granular KV pool.

The one-shot decoder (`models/decode.GreedyDecoder`) fuses prefill + the
whole generation loop into a single dispatch: perfect for a fixed prompt
set, useless for serving — the batch pads to the slowest prompt and no new
request can enter until every row retires. This engine inverts the control
flow: the HOST drives a loop of small compiled programs, so between any two
decode steps it can retire finished slots and prefill queued prompts into
the freed cache rows. The device programs are built from the SAME lowering
functions the fused decoder uses (`models/decode._prefill`, `_decode_one`,
`make_token_sampler`), which is why continuous-batched greedy output is
token-identical to per-prompt `GreedyDecoder` decode (pinned in
tests/test_serving.py).

Two compiled programs, both donating the pool so slot writes are in place:

* **prefill** (one variant per (batch, width) bucket): runs the causal
  full-buffer forward over a bucket-padded prompt buffer, scatters the
  per-layer K/V into the target slots' cache rows, and samples each row's
  first token. Under causal attention the buffer width changes cost only,
  never values, so length-bucketing (scheduler.py) is free correctness-wise.
* **step** (one variant total): advances ALL slots one token — each row
  writes its pending token's K/V at its OWN cursor (`_decode_one`'s per-row
  scatter), attends over its prefix, and samples its next token. Free/dead
  slots compute garbage that flows only into garbage: their rows are
  overwritten by the next prefill before anything can attend to them (the
  same argument as the pipeline bubble steps, models/transformer.py).

Step loop (host): retire -> admit (scheduler FIFO groups -> prefill) ->
one decode dispatch. TTFT/TPOT/queue-wait are measured per request and
emitted through obs/ (SpanTracer spans + MetricsWriter events) so a serving
run renders in the same Chrome trace / summary pipeline as training.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..models.decode import (_decode_one, _prefill, make_token_sampler,
                             rope_tables)
from ..config import resolve_dtype
from .kv_manager import KVCachePool, POOL_SPEC
from .scheduler import FIFOScheduler


@dataclass
class Request:
    """One generation request. `tokens` fills with the generated ids (EOS
    excluded, like GreedyDecoder.decode); the *_t fields are engine-clock
    samples for the serving metrics."""

    rid: int
    prompt: List[int]
    max_new: int
    seed: int = 0
    arrival: float = 0.0                 # loadgen's planned arrival offset
    tokens: List[int] = field(default_factory=list)
    submit_t: Optional[float] = None     # entered the admission queue
    admit_t: Optional[float] = None      # left the queue (prefill dispatch)
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    prompt_len: int = 0
    limit: int = 0

    # -- derived metrics (seconds; None until the request finishes) ------
    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.submit_t is None or self.admit_t is None:
            return None
        return self.admit_t - self.submit_t

    @property
    def ttft_s(self) -> Optional[float]:
        if self.submit_t is None or self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    @property
    def tpot_s(self) -> Optional[float]:
        """Time per output token AFTER the first (the decode-loop rate);
        None with < 2 tokens."""
        if (self.first_token_t is None or self.finish_t is None
                or len(self.tokens) < 2):
            return None
        return (self.finish_t - self.first_token_t) / (len(self.tokens) - 1)


def decode_prompts(engine: "ContinuousBatchingEngine", prompts,
                   max_new, base_seed: int = 0) -> List[List[int]]:
    """Batch-CLI convenience shared by generate.py and evaluate.py: submit
    `prompts` FIFO with per-request seeds base_seed+i, drain the engine,
    and return the generated ids in PROMPT order. `max_new` is an int
    (shared budget) or a per-prompt sequence."""
    budgets = ([max_new] * len(prompts) if isinstance(max_new, int)
               else list(max_new))
    for i, pr in enumerate(prompts):
        engine.submit(Request(rid=i, prompt=pr, max_new=budgets[i],
                              seed=base_seed + i))
    engine.run_to_completion()
    return [r.tokens for r in sorted(engine.completed, key=lambda r: r.rid)]


def _pow2_at_most(n: int, cap: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return min(p, cap) if cap else p


class ContinuousBatchingEngine:
    """Slot-based continuous batching over a TP-sharded KV pool.

    Sampling knobs are build-time constants (one compiled step serves every
    request, like GreedyDecoder); randomness is PER REQUEST via its seed
    (`make_token_sampler`'s fold-in schedule), so a request's sampled tokens
    reproduce regardless of arrival order, slot placement, or batch mix.
    """

    def __init__(self, model, mesh: Mesh, params, num_slots: int,
                 buf_len: int, eos_id: int, temperature: float = 0.0,
                 top_k: int = 0, top_p: float = 0.0,
                 prefill_bucket: int = 64, max_prefill_batch: int = 4,
                 max_queue: int = 0, tracer=None, writer=None,
                 clock=time.monotonic):
        if getattr(model, "cp_size", 1) > 1:
            raise ValueError(
                "the serving engine decodes on the cp=1 path (per-slot "
                "caches are replicated over cp); long-context cp prefill "
                "stays with models/decode.GreedyDecoder — rebuild the "
                f"model with cp_size=1 (got {model.cp_size})")
        cap = getattr(model, "max_decode_positions", None)
        if cap is not None and buf_len > cap:
            raise ValueError(
                f"buf_len {buf_len} exceeds the model's learned position "
                f"table ({cap}); clamp the buffer or retrain with a larger "
                f"maxlen")
        if max_prefill_batch < 1:
            raise ValueError(f"max_prefill_batch must be >= 1, got "
                             f"{max_prefill_batch}")
        self.model = model
        self.mesh = mesh
        self.params = params
        self.buf_len = buf_len
        self.eos_id = int(eos_id)
        self.max_prefill_batch = max_prefill_batch
        self._clock = clock
        self.tracer = tracer
        self.writer = writer
        self._dtype = resolve_dtype(model.cfg.compute_dtype)
        self._table_len = max(model.cfg.maxlen, buf_len)
        self._sample = make_token_sampler(model, temperature=temperature,
                                          top_k=top_k, top_p=top_p)
        self.pool = KVCachePool(model, mesh, num_slots, buf_len)
        self.scheduler = FIFOScheduler(buf_len, prefill_bucket=prefill_bucket,
                                       max_queue=max_queue, clock=clock)
        n = num_slots + 1  # + the scratch row (kv_manager.py)
        self._tokens = np.zeros(n, np.int32)
        self._pos = np.zeros(n, np.int32)
        self._seeds = np.zeros(n, np.uint32)
        self._slot_req: Dict[int, Request] = {}
        self._step_fn = self._build_step(n)
        self._prefill_fns: Dict[tuple, object] = {}
        self.completed: List[Request] = []
        # -- aggregate stats ---------------------------------------------
        self.decode_steps = 0
        self.generated_tokens = 0
        self._occupancy_sum = 0.0
        self.prefill_positions = 0            # Σ nb * width dispatched
        self.prefill_positions_monolithic = 0  # Σ rows * buf_len (no bucket)
        self.prompt_tokens = 0

    # -- compiled programs ----------------------------------------------
    def _tables(self):
        if not self.model.uses_rope:
            return None, None
        return rope_tables(self._table_len, self.model.cfg.head_dim,
                           self.model.cfg.rope_theta)

    def _build_step(self, n: int):
        model, buf_len, dtype = self.model, self.buf_len, self._dtype

        def shard_fn(params, pool_k, pool_v, tokens, pos, seeds):
            cos_t, sin_t = self._tables()
            pool_k, pool_v, logits = _decode_one(
                model, params, pool_k, pool_v, tokens, pos, buf_len,
                cos_t, sin_t, dtype)
            tok = self._sample(logits, seeds, pos + 1)
            return pool_k, pool_v, tok

        fn = jax.shard_map(
            shard_fn, mesh=self.mesh,
            in_specs=(model.specs(), POOL_SPEC, POOL_SPEC, P(None), P(None),
                      P(None)),
            out_specs=(POOL_SPEC, POOL_SPEC, P(None)))
        return jax.jit(fn, donate_argnums=(1, 2))

    def _build_prefill(self, nb: int, width: int):
        model, dtype = self.model, self._dtype

        def shard_fn(params, pool_k, pool_v, buf, prompt_len, slots, seeds):
            cos_t, sin_t = self._tables()
            ks, vs, logits = _prefill(model, params, buf, prompt_len,
                                      cos_t, sin_t, dtype)
            # scatter the (L, nb, kvh, width, hd) prefill caches into the
            # target slots' first `width` rows; rows past the prompt are
            # re-written by decode steps before any query attends to them
            pool_k = pool_k.at[:, slots, :, :width, :].set(
                ks.astype(pool_k.dtype))
            pool_v = pool_v.at[:, slots, :, :width, :].set(
                vs.astype(pool_v.dtype))
            tok = self._sample(logits, seeds, prompt_len)
            return pool_k, pool_v, tok

        fn = jax.shard_map(
            shard_fn, mesh=self.mesh,
            in_specs=(model.specs(), POOL_SPEC, POOL_SPEC, P(None, None),
                      P(None), P(None), P(None)),
            out_specs=(POOL_SPEC, POOL_SPEC, P(None)))
        return jax.jit(fn, donate_argnums=(1, 2))

    # -- request intake --------------------------------------------------
    def submit(self, req: Request) -> None:
        """FIFO enqueue (raises scheduler.QueueFull past the backpressure
        bound)."""
        self.scheduler.submit(req)

    def has_work(self) -> bool:
        return bool(self.scheduler.pending or self._slot_req)

    @property
    def live_requests(self) -> int:
        return len(self._slot_req)

    # -- the continuous-batching loop ------------------------------------
    def step(self) -> List[Request]:
        """One engine iteration: admit queued prompts into free slots
        (bucket-grouped prefills), then advance every live slot one token.
        Returns the requests that finished during this iteration."""
        done: List[Request] = []
        self._admit(done)
        if self._slot_req:
            self._decode(done)
        return done

    def run_to_completion(self) -> List[Request]:
        """Drain the queue and all live slots; returns all completions in
        finish order."""
        out: List[Request] = []
        while self.has_work():
            out.extend(self.step())
        return out

    # -- internals --------------------------------------------------------
    def _span(self, name, **args):
        if self.tracer is not None:
            return self.tracer.span(name, cat="serve", **args)
        import contextlib
        return contextlib.nullcontext()

    def _admit(self, done: List[Request]) -> None:
        while self.scheduler.pending and self.pool.free_slots:
            group = self.scheduler.take_batch(
                min(self.pool.free_slots, self.max_prefill_batch))
            if not group:
                break
            now = self._clock()
            ready = []
            for req in group:
                req.admit_t = now
                req.prompt_len = len(req.prompt)
                req.limit = min(req.prompt_len + req.max_new, self.buf_len)
                self.prompt_tokens += req.prompt_len
                if req.limit <= req.prompt_len:   # max_new == 0
                    req.finish_t = now
                    self._complete(req, done)
                else:
                    ready.append(req)
            if not ready:
                continue
            self._prefill_group(ready, done)

    def _prefill_group(self, ready: List[Request], done: List[Request]):
        width = self.scheduler.group_width(ready)
        nb = _pow2_at_most(len(ready), self.max_prefill_batch)
        slots = self.pool.alloc_many(len(ready))
        buf = np.full((nb, width), self.eos_id, np.int32)
        plens = np.ones(nb, np.int32)          # pad rows: 1-token dummy
        slot_idx = np.full(nb, self.pool.scratch_slot, np.int32)
        seeds = np.zeros(nb, np.uint32)
        for i, req in enumerate(ready):
            buf[i, : req.prompt_len] = req.prompt
            plens[i] = req.prompt_len
            slot_idx[i] = slots[i]
            seeds[i] = np.uint32(req.seed)
        key = (nb, width)
        if key not in self._prefill_fns:
            self._prefill_fns[key] = self._build_prefill(nb, width)
        with self._span("prefill", rows=len(ready), nb=nb, width=width):
            ks, vs, tok = self._prefill_fns[key](
                self.params, self.pool.ks, self.pool.vs, jnp.asarray(buf),
                jnp.asarray(plens), jnp.asarray(slot_idx),
                jnp.asarray(seeds))
            self.pool.adopt(ks, vs)
            tok = np.asarray(tok)
        self.prefill_positions += nb * width
        self.prefill_positions_monolithic += len(ready) * self.buf_len
        now = self._clock()
        for i, req in enumerate(ready):
            req.first_token_t = now
            first = int(tok[i])
            if first == self.eos_id:              # 0 generated tokens
                req.finish_t = now
                self.pool.free(slots[i])
                self._complete(req, done)
                continue
            slot = slots[i]
            self._slot_req[slot] = req
            self._tokens[slot] = first
            self._pos[slot] = req.prompt_len
            self._seeds[slot] = np.uint32(req.seed)

    def _decode(self, done: List[Request]) -> None:
        with self._span("decode_step", live=len(self._slot_req)):
            ks, vs, tok = self._step_fn(
                self.params, self.pool.ks, self.pool.vs,
                jnp.asarray(self._tokens), jnp.asarray(self._pos),
                jnp.asarray(self._seeds))
            self.pool.adopt(ks, vs)
            tok = np.asarray(tok)
        now = self._clock()
        self.decode_steps += 1
        self._occupancy_sum += self.pool.occupancy
        if self.tracer is not None:
            self.tracer.counter("slots_live", len(self._slot_req))
        for slot, req in list(self._slot_req.items()):
            # the pending token was written at `pos` by this dispatch: it
            # is now part of the output (mirrors make_generate's buf write)
            req.tokens.append(int(self._tokens[slot]))
            self.generated_tokens += 1
            cand = int(tok[slot])
            self._pos[slot] += 1
            gen = len(req.tokens)
            if cand == self.eos_id or req.prompt_len + gen >= req.limit:
                req.finish_t = now
                del self._slot_req[slot]
                self.pool.free(slot)
                self._complete(req, done)
            else:
                self._tokens[slot] = cand

    def _complete(self, req: Request, done: List[Request]) -> None:
        self.completed.append(req)
        done.append(req)
        if self.writer is not None:
            ms = lambda s: None if s is None else round(s * 1e3, 3)
            self.writer.event(
                "serve_request", rid=req.rid, prompt_len=req.prompt_len,
                generated=len(req.tokens),
                queue_wait_ms=ms(req.queue_wait_s), ttft_ms=ms(req.ttft_s),
                tpot_ms=ms(req.tpot_s))

    # -- aggregate view ---------------------------------------------------
    def stats(self) -> dict:
        occ = (self._occupancy_sum / self.decode_steps
               if self.decode_steps else 0.0)
        mono = max(self.prefill_positions_monolithic, 1)
        return {
            "decode_steps": self.decode_steps,
            "generated_tokens": self.generated_tokens,
            "prompt_tokens": self.prompt_tokens,
            "completed": len(self.completed),
            "rejected": self.scheduler.rejected,
            "slot_occupancy_mean": round(occ, 4),
            "prefill_positions": self.prefill_positions,
            # share of the monolithic full-buffer prefill cost that
            # length-bucketing removed (generate.py logs this). Can go
            # NEGATIVE when bucketing is off but pow2 batch-padding added
            # rows — callers gate their print on > 0
            "prefill_pad_waste_eliminated": round(
                1.0 - self.prefill_positions / mono, 4)
            if self.prefill_positions_monolithic else 0.0,
        }
