"""FIFO admission queue with length-bucketed prefill batching.

Admission policy: strictly first-come-first-served — `take_batch` peels
requests off the HEAD of the queue and stops at the first one whose
bucket-padded prefill width differs from the head's (or when the slot /
batch budget runs out). Nothing ever jumps the queue, so a stream of
mixed-length prompts admits in arrival order; the bucketing only decides
how many neighbours ride the same prefill dispatch.

Width bucketing (the PR-3 `--seq_bucket` idea applied to prefill): a
prompt of length p prefills over a buffer of width
`ceil(p / prefill_bucket) * prefill_bucket` (clamped to the pool's
buf_len) instead of the full decode buffer. Under causal attention the
K/V rows and the last-position logits for positions < p are bit-identical
whatever the buffer width, so bucketing changes COST ONLY — the engine's
token-identity contract (tests/test_serving.py) is width-independent.

Backpressure: `max_queue` bounds the number of waiting requests;
`submit()` past the bound raises `QueueFull` — the caller (loadgen, a
future RPC front-end) decides whether that is a drop, a retry, or a
client-visible 429. Unbounded (0) is the bring-up default.
"""

from __future__ import annotations

import time
from collections import deque
from typing import TYPE_CHECKING, List, Optional

if TYPE_CHECKING:  # engine imports the scheduler; keep the cycle type-only
    from .engine import Request


class QueueFull(RuntimeError):
    """Raised by submit() when the admission queue is at max_queue."""


def bucket_width(prompt_len: int, prefill_bucket: int, buf_len: int) -> int:
    """Bucket-padded prefill width for a prompt: smallest multiple of
    `prefill_bucket` >= prompt_len, clamped to buf_len (prefill never needs
    more than the decode buffer). `prefill_bucket` 0 disables bucketing
    (every prefill uses the full buffer, the one-shot decoder's padding
    behaviour)."""
    if prefill_bucket <= 0:
        return buf_len
    w = -(-prompt_len // prefill_bucket) * prefill_bucket
    return min(w, buf_len)


class FIFOScheduler:
    def __init__(self, buf_len: int, prefill_bucket: int = 64,
                 max_queue: int = 0,
                 clock=time.monotonic):
        self.buf_len = buf_len
        self.prefill_bucket = prefill_bucket
        self.max_queue = max_queue
        self._clock = clock
        self._queue: "deque[Request]" = deque()  # noqa: F821 — type-only
        self.rejected = 0

    def __len__(self) -> int:
        return len(self._queue)

    @property
    def pending(self) -> int:
        return len(self._queue)

    def submit(self, req: Request) -> None:
        """Enqueue a request (FIFO). Raises QueueFull past `max_queue`;
        validates the prompt fits the decode buffer NOW, not at admission
        time (a doomed request must not wait in line to fail)."""
        if not req.prompt:
            raise ValueError(f"request {req.rid}: prompt must be non-empty "
                             f"(a width-0 prefill has no position to sample "
                             f"the first token from)")
        if len(req.prompt) >= self.buf_len:
            raise ValueError(
                f"request {req.rid}: prompt length {len(req.prompt)} must "
                f"leave room in buf_len {self.buf_len}")
        if req.max_new < 0:
            raise ValueError(f"request {req.rid}: max_new must be >= 0, "
                             f"got {req.max_new}")
        if self.max_queue and len(self._queue) >= self.max_queue:
            self.rejected += 1
            raise QueueFull(
                f"admission queue full ({self.max_queue} waiting); request "
                f"{req.rid} refused — retry later or raise --queue_limit")
        if req.submit_t is None:
            req.submit_t = self._clock()
        self._queue.append(req)

    def take_batch(self, max_requests: int) -> List[Request]:
        """Pop the next prefill group: up to `max_requests` requests from
        the queue HEAD that share the head's bucket-padded width. Returns
        [] when the queue is empty or max_requests == 0. Strict FIFO: the
        group is always a PREFIX of the queue."""
        if not self._queue or max_requests <= 0:
            return []
        head_w = bucket_width(len(self._queue[0].prompt),
                              self.prefill_bucket, self.buf_len)
        group: List[Request] = []
        while (self._queue and len(group) < max_requests
               and bucket_width(len(self._queue[0].prompt),
                                self.prefill_bucket,
                                self.buf_len) == head_w):
            group.append(self._queue.popleft())
        return group

    def group_width(self, group: List[Request]) -> int:
        return bucket_width(max(len(r.prompt) for r in group),
                            self.prefill_bucket, self.buf_len)

    def peek_submit_t(self) -> Optional[float]:
        return self._queue[0].submit_t if self._queue else None
