"""FIFO admission queue with length-bucketed prefill batching.

Admission policy: strictly first-come-first-served — `take_batch` peels
requests off the HEAD of the queue and stops at the first one whose
bucket-padded prefill width differs from the head's (or when the slot /
batch budget runs out). Nothing ever jumps the queue, so a stream of
mixed-length prompts admits in arrival order; the bucketing only decides
how many neighbours ride the same prefill dispatch.

Width bucketing (the PR-3 `--seq_bucket` idea applied to prefill): a
prompt of length p prefills over a buffer of width
`ceil(p / prefill_bucket) * prefill_bucket` (clamped to the pool's
buf_len) instead of the full decode buffer. Under causal attention the
K/V rows and the last-position logits for positions < p are bit-identical
whatever the buffer width, so bucketing changes COST ONLY — the engine's
token-identity contract (tests/test_serving.py) is width-independent.

Backpressure: `max_queue` bounds the number of waiting requests;
`submit()` past the bound raises `QueueFull` — the caller (loadgen, a
future RPC front-end) decides whether that is a drop, a retry, or a
client-visible 429. Unbounded (0) is the bring-up default.
"""

from __future__ import annotations

import time
from collections import deque
from typing import TYPE_CHECKING, List, Optional

if TYPE_CHECKING:  # engine imports the scheduler; keep the cycle type-only
    from .engine import Request


class QueueFull(RuntimeError):
    """Raised by submit() when the admission queue is at max_queue."""


def bucket_width(prompt_len: int, prefill_bucket: int, buf_len: int) -> int:
    """Bucket-padded prefill width for a prompt: smallest multiple of
    `prefill_bucket` >= prompt_len, clamped to buf_len (prefill never needs
    more than the decode buffer). `prefill_bucket` 0 disables bucketing
    (every prefill uses the full buffer, the one-shot decoder's padding
    behaviour)."""
    if prefill_bucket <= 0:
        return buf_len
    w = -(-prompt_len // prefill_bucket) * prefill_bucket
    return min(w, buf_len)


class FIFOScheduler:
    def __init__(self, buf_len: int, prefill_bucket: int = 64,
                 max_queue: int = 0,
                 clock=time.monotonic, flight=None):
        self.buf_len = buf_len
        self.prefill_bucket = prefill_bucket
        self.max_queue = max_queue
        self._clock = clock
        self.flight = flight  # obs.flight.FlightRecorder: decision ring
        self._queue: "deque[Request]" = deque()  # noqa: F821 — type-only
        self.rejected = 0

    def __len__(self) -> int:
        return len(self._queue)

    @property
    def pending(self) -> int:
        return len(self._queue)

    def submit(self, req: Request) -> None:
        """Enqueue a request (FIFO). Raises QueueFull past `max_queue`;
        validates the prompt fits the decode buffer NOW, not at admission
        time (a doomed request must not wait in line to fail)."""
        if not req.prompt:
            raise ValueError(f"request {req.rid}: prompt must be non-empty "
                             f"(a width-0 prefill has no position to sample "
                             f"the first token from)")
        if len(req.prompt) >= self.buf_len:
            raise ValueError(
                f"request {req.rid}: prompt length {len(req.prompt)} must "
                f"leave room in buf_len {self.buf_len}")
        if req.max_new < 0:
            raise ValueError(f"request {req.rid}: max_new must be >= 0, "
                             f"got {req.max_new}")
        if self.max_queue and len(self._queue) >= self.max_queue:
            self.rejected += 1
            if self.flight is not None:
                self.flight.record("sched_reject", rid=req.rid,
                                   pending=len(self._queue))
            raise QueueFull(
                f"admission queue full ({self.max_queue} waiting); request "
                f"{req.rid} refused — retry later or raise --queue_limit")
        if req.submit_t is None:
            req.submit_t = self._clock()
        if self.flight is not None:
            self.flight.record("sched_submit", rid=req.rid,
                               prompt_len=len(req.prompt),
                               pending=len(self._queue))
        self._queue.append(req)

    def take_batch(self, max_requests: int) -> List[Request]:
        """Pop the next prefill group: up to `max_requests` requests from
        the queue HEAD that share the head's bucket-padded width. Returns
        [] when the queue is empty or max_requests == 0. Strict FIFO: the
        group is always a PREFIX of the queue."""
        if not self._queue or max_requests <= 0:
            return []
        head_w = bucket_width(len(self._queue[0].prompt),
                              self.prefill_bucket, self.buf_len)
        group: List[Request] = []
        while (self._queue and len(group) < max_requests
               and bucket_width(len(self._queue[0].prompt),
                                self.prefill_bucket,
                                self.buf_len) == head_w):
            group.append(self._queue.popleft())
        return group

    def group_width(self, group: List[Request]) -> int:
        return bucket_width(max(len(r.prompt) for r in group),
                            self.prefill_bucket, self.buf_len)

    def peek_submit_t(self) -> Optional[float]:
        return self._queue[0].submit_t if self._queue else None


# -- SLO-aware admission (serving v2 / the paged engine) -----------------

# TTFT deadline classes: name -> seconds from submit to the first token.
# The names are wire-stable (requests carry them, metrics aggregate by
# them); the budgets are per-deployment knobs (serve.py --slo_classes).
DEFAULT_SLO_CLASSES = {"interactive": 0.25, "standard": 1.0, "batch": 8.0}


def parse_slo_classes(spec: str) -> dict:
    """'interactive=0.25,standard=1,batch=8' -> {name: deadline_s}."""
    out = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"SLO class {part!r} must be name=deadline_s")
        name, val = part.split("=", 1)
        d = float(val)
        if d <= 0:
            raise ValueError(f"SLO class {name!r}: deadline must be > 0, "
                             f"got {d}")
        out[name.strip()] = d
    if not out:
        raise ValueError("empty SLO class spec")
    return out


class SLOScheduler:
    """Deadline-class + per-tenant-fair admission for the paged engine.

    Three rules, applied in order each time the engine asks for the next
    request (`take`):

    1. **Overdue rescue (EDF)**: if any queue head has blown past its TTFT
       deadline, admit the earliest deadline first — damage control beats
       fairness, and it is also the anti-starvation bound: a `batch`
       request waits at most its (loose) deadline before it outranks any
       fresh `interactive` arrival.
    2. **Deadline class**: otherwise tighter-deadline classes admit first
       (`interactive` before `standard` before `batch`) — TTFT SLOs are
       the point of the classes.
    3. **Per-tenant fairness**: within a class, tenants are served by
       LEAST ACCUMULATED SERVICE (admitted prompt + budget tokens — a
       deficit-round-robin ledger), so one tenant's flood interleaves
       with another's trickle instead of starving it. Ties break FIFO.

    Preemption victims re-enter through `requeue`: they go to the FRONT
    of their own (tenant, class) lane (they are the oldest work of that
    class) with a fresh deadline budget, and their service is NOT
    re-charged — a victim does not pay twice.

    Queues are keyed (tenant, class), not tenant alone, so every class a
    tenant has pending is VISIBLE as a head: with one tenant, a batch
    arrival cannot hide the interactive request behind it (rule 2 would
    be inert), and a requeued fresh-deadline victim cannot hide an
    overdue request of a tighter class — which would livelock the
    engine's admit loop: preempt victim -> victim re-peeks as head ->
    re-admit -> overdue head preempts it again, forever.

    The same submit-time validation and `QueueFull` backpressure contract
    as FIFOScheduler; `rejected` counts refusals."""

    def __init__(self, buf_len: int, classes: Optional[dict] = None,
                 default_class: str = "standard", max_queue: int = 0,
                 clock=time.monotonic, flight=None):
        self.buf_len = buf_len
        self.classes = dict(classes or DEFAULT_SLO_CLASSES)
        if default_class not in self.classes:
            raise ValueError(f"default SLO class {default_class!r} not in "
                             f"{sorted(self.classes)}")
        self.default_class = default_class
        self.max_queue = max_queue
        self._clock = clock
        self.flight = flight  # obs.flight.FlightRecorder: decision ring
        self._queues: dict = {}          # (tenant, class) -> deque[Request]
        self.service: dict = {}          # tenant -> tokens admitted
        self.rejected = 0
        self._seq = 0                    # global FIFO tie-break

    def __len__(self) -> int:
        return sum(len(q) for q in self._queues.values())

    @property
    def pending(self) -> int:
        return len(self)

    def _validate(self, req) -> None:
        if not req.prompt:
            raise ValueError(f"request {req.rid}: prompt must be non-empty "
                             f"(a width-0 prefill has no position to sample "
                             f"the first token from)")
        if len(req.prompt) >= self.buf_len:
            raise ValueError(
                f"request {req.rid}: prompt length {len(req.prompt)} must "
                f"leave room in buf_len {self.buf_len}")
        if req.max_new < 0:
            raise ValueError(f"request {req.rid}: max_new must be >= 0, "
                             f"got {req.max_new}")
        if req.slo_class is not None and req.slo_class not in self.classes:
            raise ValueError(f"request {req.rid}: unknown SLO class "
                             f"{req.slo_class!r} (have "
                             f"{sorted(self.classes)})")

    def submit(self, req) -> None:
        self._validate(req)
        if self.max_queue and len(self) >= self.max_queue:
            self.rejected += 1
            if self.flight is not None:
                self.flight.record("sched_reject", rid=req.rid,
                                   pending=len(self))
            raise QueueFull(
                f"admission queue full ({self.max_queue} waiting); request "
                f"{req.rid} refused — retry later or raise --queue_limit")
        if req.slo_class is None:
            req.slo_class = self.default_class
        if req.submit_t is None:
            req.submit_t = self._clock()
        req.deadline_t = req.submit_t + self.classes[req.slo_class]
        req._sched_seq = self._seq
        self._seq += 1
        if self.flight is not None:
            self.flight.record("sched_submit", rid=req.rid,
                               tenant=req.tenant, slo_class=req.slo_class,
                               prompt_len=len(req.prompt),
                               pending=len(self))
        self._queues.setdefault((req.tenant, req.slo_class),
                                deque()).append(req)

    def requeue(self, req) -> None:
        """Re-admit a preemption victim: front of its (tenant, class)
        lane, fresh deadline budget, no second service charge, never a
        QueueFull (the engine already owns this work)."""
        req.deadline_t = self._clock() + self.classes[req.slo_class]
        if self.flight is not None:
            self.flight.record("sched_requeue", rid=req.rid,
                               slo_class=req.slo_class,
                               generated=len(req.tokens))
        self._queues.setdefault((req.tenant, req.slo_class),
                                deque()).appendleft(req)

    def _heads(self):
        return [(t, q[0]) for (t, _c), q in self._queues.items() if q]

    def peek(self):
        """The request `take` would hand out next (None when empty)."""
        heads = self._heads()
        if not heads:
            return None
        now = self._clock()
        overdue = [(t, r) for t, r in heads if now >= r.deadline_t]
        if overdue:
            t, r = min(overdue,
                       key=lambda tr: (tr[1].deadline_t, tr[1]._sched_seq))
            return r
        t, r = min(heads, key=lambda tr: (
            self.classes[tr[1].slo_class],
            self.service.get(tr[0], 0),
            tr[1]._sched_seq))
        return r

    def take(self):
        """Pop the next admission (None when empty) and charge its tenant's
        service ledger."""
        req = self.peek()
        if req is None:
            return None
        q = self._queues[(req.tenant, req.slo_class)]
        assert q[0] is req
        q.popleft()
        if not getattr(req, "_service_charged", False):
            self.service[req.tenant] = (self.service.get(req.tenant, 0)
                                        + len(req.prompt) + req.max_new)
            req._service_charged = True
        if self.flight is not None:
            self.flight.record(
                "sched_admit", rid=req.rid, tenant=req.tenant,
                slo_class=req.slo_class,
                overdue=bool(req.deadline_t is not None
                             and self._clock() >= req.deadline_t))
        return req
