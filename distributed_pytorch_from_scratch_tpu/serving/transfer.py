"""KV page streaming for disaggregated prefill/decode (ISSUE 19).

A prefill replica finishes a request's chunked prefill into its own
paged pool, then ships the request — metadata plus the fixed-size KV
*pages* backing its prompt — to a decode replica over a length-prefixed
socket stream. The decode side leases pages out of its own pool
(`PagedEngine.admit_prefilled` -> `PagedKVPool.import_pages`) and the
request continues through the unmodified decode loop, token-identically
to colocated serving.

Wire format (docs/SERVING.md "Serving fleet v1"): one frame per
handoff —

    magic  b"KVPG"
    u32    header length (big-endian)
    bytes  header: UTF-8 JSON — request fields, first sampled token,
           kv kind ('native' | 'int8'), page_size, n_tokens, the
           TraceContext wire dict, and per-blob {dtype, shape} metadata
    per blob: u64 length (big-endian) + raw C-order bytes

Blobs are the export_pages payload flattened in tree order: native
pools send [k, v]; int8 pools send [k_codes, k_scales, v_codes,
v_scales]. export_pages materializes the GLOBAL head layout, so the
receiving pool's tp width need not match the sender's — the reshard is
implicit in the import scatter ("Memory-efficient array redistribution
through portable collective communication", PAPERS.md, done host-side
at page granularity).

`run_disaggregated` is the in-process reference driver (socketpair,
prefill thread + receiver thread + decode loop) used by tests and
`bench.py --fleet`; a real deployment runs the same frame protocol over
TCP between hosts.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from collections import deque
from typing import List, Optional, Tuple

import numpy as np

from .engine import PagedEngine, Request
from .kv_manager import PoolExhausted

MAGIC = b"KVPG"


def _np_dtype(name: str) -> np.dtype:
    """Resolve a dtype by `dtype.name`, reaching into ml_dtypes for the
    jax-only names (bfloat16, ...) numpy itself cannot parse."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly n bytes; None on clean EOF at a frame boundary
    (0 bytes read so far). A mid-frame EOF is a protocol error."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            if not buf:
                return None
            raise ConnectionError(
                f"page stream truncated mid-frame: wanted {n} bytes, "
                f"got {len(buf)}")
        buf.extend(chunk)
    return bytes(buf)


def send_frame(sock: socket.socket, header: dict,
               blobs: List[np.ndarray]) -> int:
    """One length-prefixed frame; returns total bytes put on the wire."""
    hdr = json.dumps(header).encode("utf-8")
    parts = [MAGIC, struct.pack(">I", len(hdr)), hdr]
    for b in blobs:
        raw = np.ascontiguousarray(b).tobytes()
        parts.append(struct.pack(">Q", len(raw)))
        parts.append(raw)
    payload = b"".join(parts)
    sock.sendall(payload)
    return len(payload)


def recv_frame(sock: socket.socket) -> Optional[Tuple[dict,
                                                      List[np.ndarray]]]:
    """Inverse of send_frame; None on clean EOF (sender shut down)."""
    magic = _recv_exact(sock, 4)
    if magic is None:
        return None
    if magic != MAGIC:
        raise ConnectionError(f"bad page-stream magic {magic!r} "
                              f"(framing desync)")
    (hlen,) = struct.unpack(">I", _recv_exact(sock, 4))
    header = json.loads(_recv_exact(sock, hlen).decode("utf-8"))
    blobs = []
    for meta in header.get("blobs", []):
        (blen,) = struct.unpack(">Q", _recv_exact(sock, 8))
        raw = _recv_exact(sock, blen)
        blobs.append(np.frombuffer(raw, dtype=_np_dtype(meta["dtype"]))
                     .reshape(meta["shape"]))
    return header, blobs


def _flatten_kv(k, v) -> List[np.ndarray]:
    if isinstance(k, tuple):                      # int8 (codes, scales)
        return [k[0], k[1], v[0], v[1]]
    return [k, v]


def _unflatten_kv(kind: str, blobs: List[np.ndarray]):
    if kind == "int8":
        return (blobs[0], blobs[1]), (blobs[2], blobs[3])
    return blobs[0], blobs[1]


def send_handoff(sock: socket.socket, h: dict, k, v, kv_dtype,
                 page_size: int, ctx=None, clock=time.monotonic) -> int:
    """Ship one staged PagedEngine handoff (engine.export_handoff
    payload) as a frame; returns bytes sent. `ctx` is the prefill-side
    RequestTracer.export_context — the decode engine continues the
    trace from it. submit_t/first_token_t ride along for IN-PROCESS
    receivers (same clock domain: bench's TTFT spans the full disagg
    path); cross-host receivers must drop them."""
    req = h["req"]
    blobs = _flatten_kv(k, v)
    header = {
        "rid": req.rid, "prompt": list(req.prompt),
        "tokens": list(req.tokens), "max_new": req.max_new,
        "seed": req.seed, "tenant": req.tenant,
        "slo_class": req.slo_class, "arrival": req.arrival,
        "submit_t": req.submit_t, "first_token_t": req.first_token_t,
        "first": int(h["first"]), "n_tokens": int(h["n_tokens"]),
        "pages": len(h["pages"]), "page_size": int(page_size),
        "kv": kv_dtype or "native",
        "trace_ctx": ctx.to_wire() if ctx is not None else None,
        "t_send": clock(),
        "blobs": [{"dtype": b.dtype.name, "shape": list(b.shape)}
                  for b in blobs],
    }
    return send_frame(sock, header, blobs)


def recv_handoff(sock: socket.socket):
    """Receive one handoff; returns (req, first, k, v, header) with a
    freshly built Request carrying the wire trace context, or None on
    clean EOF."""
    got = recv_frame(sock)
    if got is None:
        return None
    header, blobs = got
    req = Request(rid=int(header["rid"]), prompt=list(header["prompt"]),
                  max_new=int(header["max_new"]),
                  seed=int(header["seed"]), arrival=header["arrival"],
                  tenant=header["tenant"], slo_class=header["slo_class"],
                  trace_ctx=header.get("trace_ctx"))
    req.tokens = list(header.get("tokens", ()))
    req.submit_t = header.get("submit_t")
    req.first_token_t = header.get("first_token_t")
    k, v = _unflatten_kv(header["kv"], blobs)
    return req, int(header["first"]), k, v, header


def run_disaggregated(prefill: PagedEngine, decode: PagedEngine,
                      requests: List[Request], clock=time.monotonic,
                      sleep=time.sleep, poll_s: float = 0.0005) -> dict:
    """Drive a prefill_only engine and a decode engine joined by a
    socketpair page stream until every request completes. Three strands:
    the prefill thread steps its engine and streams staged handoffs, a
    receiver thread drains frames into an inbox, and the caller's thread
    admits + decodes (admission backpressure — no free slot or dry pool
    — just parks the handoff until decode retires something).

    Returns {completed, transfers, wall_s, bytes_per_request,
    transfer_ms_p50/p95}: `transfers` has one {rid, pages, bytes,
    send_ms, transfer_ms} per handoff, transfer_ms measured export-side
    send start -> decode-side admit on the shared in-process clock."""
    if prefill.pool.kv_dtype != decode.pool.kv_dtype:
        raise ValueError(
            f"kv_dtype mismatch across the stream: prefill side "
            f"{prefill.pool.kv_dtype or 'native'}, decode side "
            f"{decode.pool.kv_dtype or 'native'}")
    if prefill.page_size != decode.page_size:
        raise ValueError(
            f"page_size mismatch across the stream: {prefill.page_size} "
            f"vs {decode.page_size} (pages are the transfer unit)")
    a, b = socket.socketpair()
    transfers: List[dict] = []
    inbox: deque = deque()
    eof = threading.Event()
    errors: List[BaseException] = []
    t0 = clock()

    def prefill_side():
        try:
            for req in sorted(requests, key=lambda r: r.arrival):
                prefill.submit(req)
            while prefill.has_work() or prefill.handoffs:
                prefill.step()
                while prefill.handoffs:
                    h = prefill.handoffs.popleft()
                    k, v = prefill.export_handoff(h)
                    ctx = (prefill.rt.export_context(h["req"], "handoff")
                           if prefill.rt is not None else None)
                    ts = clock()
                    nbytes = send_handoff(a, h, k, v,
                                          prefill.pool.kv_dtype,
                                          prefill.page_size, ctx=ctx,
                                          clock=clock)
                    transfers.append({"rid": h["req"].rid,
                                      "pages": len(h["pages"]),
                                      "bytes": nbytes,
                                      "send_ms": (clock() - ts) * 1e3})
                    prefill.finish_handoff(h)
        except BaseException as e:          # surfaced by the caller
            errors.append(e)
        finally:
            try:
                a.shutdown(socket.SHUT_WR)
            except OSError:
                pass

    def recv_side():
        try:
            while True:
                item = recv_handoff(b)
                if item is None:
                    break
                inbox.append((item, clock()))
        except BaseException as e:
            errors.append(e)
        finally:
            eof.set()

    tp = threading.Thread(target=prefill_side, daemon=True)
    tr = threading.Thread(target=recv_side, daemon=True)
    tp.start()
    tr.start()
    pending: deque = deque()
    completed: List[Request] = []
    while (not eof.is_set() or inbox or pending or decode.has_work()):
        if errors:
            break
        while inbox:
            pending.append(inbox.popleft())
        progressed = False
        while pending:
            (req, first, k, v, header), _ = pending[0]
            try:
                decode.admit_prefilled(req, k, v, first)
            except (RuntimeError, PoolExhausted):
                break                        # backpressure: decode first
            pending.popleft()
            progressed = True
            by_rid = {t["rid"]: t for t in transfers}
            rec = by_rid.get(req.rid)
            if rec is not None and header.get("t_send") is not None:
                rec["transfer_ms"] = (clock() - header["t_send"]) * 1e3
            if req.finish_t is not None:     # completed at admit (eos)
                completed.append(req)
        if decode.has_work():
            for req in decode.step():
                completed.append(req)
            progressed = True
        if not progressed:
            sleep(poll_s)
    tp.join(timeout=30)
    tr.join(timeout=30)
    a.close()
    b.close()
    if errors:
        raise errors[0]
    # max_new == 0 requests complete on the prefill side without a handoff
    completed.extend(prefill.completed)
    wall = clock() - t0
    byt = [t["bytes"] for t in transfers]
    tms = sorted(t.get("transfer_ms", t["send_ms"]) for t in transfers)
    pct = lambda q: (tms[min(len(tms) - 1,
                             int(q * (len(tms) - 1)))] if tms else 0.0)
    return {
        "completed": completed,
        "transfers": transfers,
        "wall_s": wall,
        "transferred_pages": sum(t["pages"] for t in transfers),
        "transferred_bytes": sum(byt),
        "bytes_per_request": (sum(byt) / len(byt)) if byt else 0.0,
        "transfer_ms_p50": round(pct(0.50), 3),
        "transfer_ms_p95": round(pct(0.95), 3),
    }
