"""Speculative decoding over the paged KV cache (ISSUE 7).

BASELINE.md's roofline finding is that decode at the 45M-355M scale is
DISPATCH-latency-bound, not FLOP-bound: one model dispatch per generated
token, plus a device->host round-trip per step to learn the token. This
engine attacks the dispatch count itself: a cheap DRAFTER model (default:
the `tiny` preset) autoregressively proposes k tokens per round against
its own small paged KV pool, and the target model scores all k+1
positions in ONE dispatch — `models/decode._paged_prefill_chunk` with
`all_logits=True`, i.e. `_paged_decode_one`'s per-row cursor generalised
to advance k positions through the same page table, with page growth and
COW resolved by the host before the dispatch exactly like a prefill
chunk. Per round the host sees only (accepted_count, tokens): one D2H of
a handful of int32s buys up to k+1 tokens.

Correctness contract (pinned in tests/test_speculative.py):

* **greedy (temperature 0)** — a draft token is accepted iff it equals
  the target argmax at its position, and the first rejection (or the
  bonus position) emits the target argmax itself, so the emitted stream
  is TOKEN-IDENTICAL to the non-speculative paged engine (and therefore
  to the slot engine and per-prompt `GreedyDecoder`) whatever the
  drafter proposes — across k, page sizes, arrival orders, COW sharing,
  and preempt-resume. A bad drafter costs speed, never tokens.
* **sampled (temperature > 0)** — exact rejection sampling: draft d ~ q
  is accepted with probability min(1, p(d)/q(d)); the first rejection
  resamples from the residual distribution norm(max(p - q, 0)); an
  all-accept round draws the free bonus token from p directly. The
  emitted tokens are DISTRIBUTION-identical to the plain sampler
  (Leviathan et al.'s guarantee), pinned by a chi-square test. Draft /
  accept / resample draws fold (request_seed, absolute_position,
  stream_tag), so a request's randomness stays independent of batch mix
  and round boundaries.

Drafter state threads through the SAME retire -> admit -> decode loop as
`PagedEngine`: the drafter leases pages from its own pool under the same
accounting (its bytes count against the serving HBM budget — bench.py's
equal-HBM A/B subtracts them from the target pool), a preempted victim
frees BOTH page lists, and a resumed (or freshly admitted) request
rebuilds the drafter cache through the same chunked-prefill path that
rebuilds the target cache. The drafter never COW-shares: at drafter
scale, recompute is cheaper than index bookkeeping.
"""

from __future__ import annotations

import time
from typing import List

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..config import resolve_dtype
from ..models.decode import (_filter_logits, _full_vocab_logits,
                             _paged_decode_one, _paged_prefill_chunk,
                             rope_tables)
from .engine import (PagedEngine, Request, _chunk_maps, _pow2_at_most,
                     _publish_hbm_plane)
from .kv_manager import PagedKVPool, PoolExhausted, page_bytes

# Randomness stream tags: every speculative draw folds
# (seed, absolute_position, TAG), so the drafter's proposal draw, the
# accept threshold, and the residual resample are mutually independent AND
# independent of the plain sampler's (seed, position) stream — the same
# reproducibility contract make_token_sampler gives continuous batching.
TAG_DRAFT, TAG_ACCEPT, TAG_RESAMPLE = 1, 2, 3


def _spec_key(seed, pos, tag):
    """fold_in chain for one speculative draw (called under vmap)."""
    key = jax.random.fold_in(jax.random.key(0), seed)
    key = jax.random.fold_in(key, pos)
    return jax.random.fold_in(key, tag)


class SpeculativeEngine(PagedEngine):
    """`PagedEngine` with a drafter: k tokens drafted per round by a small
    model over its own paged pool, verified by the target in one
    k+1-position dispatch with exact rejection sampling.

    Two extra compiled programs (both donate their pool):

    * **draft** — one jit variant: a `lax.scan` of k+1 drafter
      single-token steps fused into ONE dispatch (step j feeds the round's
      j-th token at cursor+j and samples the next proposal). The extra
      (k+1)-th step consumes the LAST draft token so the drafter cache
      stays complete through an all-accept round; its own proposal is
      discarded. Emits the k draft tokens (+ the drafter's full-vocab
      proposal distributions when sampling — the q the accept ratio
      needs), which stay ON DEVICE for the verify dispatch.
    * **verify** — one jit variant: the target scores all k+1 positions
      (`_paged_prefill_chunk(all_logits=True)` over the row's page view,
      per-row cursors), runs the accept/resample rule in-program, and
      returns only (accepted_count, tokens) — the whole round is one D2H
      of 2(k+2) ints per row.

    Rows whose buffer cannot fit k+1 more positions verify a shorter
    window (per-row `qlen`); rows at qlen=1 degenerate to the
    non-speculative step. Rejected positions' K/V writes are garbage
    beyond the new cursor — masked now, overwritten by the next round
    before anything attends to them (the standard quarantine argument).
    """

    def __init__(self, model, mesh, params, drafter_model, drafter_params,
                 num_slots: int, buf_len: int, eos_id: int,
                 speculate_k: int = 4, drafter_pages: int = 0, **kw):
        if speculate_k < 1:
            raise ValueError(f"speculate_k must be >= 1, got {speculate_k}")
        if kw.get("debug_host_sampler"):
            raise ValueError(
                "debug_host_sampler is the NON-speculative engines' "
                "ablation knob (the speculative round never materialises "
                "host logits); drop --speculate to measure it")
        super().__init__(model, mesh, params, num_slots, buf_len, eos_id,
                         **kw)
        if drafter_model.cfg.vocab_size != model.cfg.vocab_size:
            raise ValueError(
                f"drafter vocab {drafter_model.cfg.vocab_size} != target "
                f"vocab {model.cfg.vocab_size} — build the drafter preset "
                f"with the target's vocab_size (serve.py does)")
        if getattr(drafter_model, "cp_size", 1) > 1:
            raise ValueError(
                "speculative serving shards only the TARGET's pages over "
                "cp (supported shape: target cp>=1, drafter cp=1) — the "
                "drafter's pool is small enough to replicate, so build the "
                f"drafter preset with cp_size=1 (got "
                f"{drafter_model.cp_size})")
        self.k = int(speculate_k)
        self.drafter_model = drafter_model
        self._dparams = drafter_params
        self._ddtype = resolve_dtype(drafter_model.cfg.compute_dtype)
        ps = self.page_size
        # the drafter logically buffers buf_len + k + 1 positions: on an
        # all-accept round it has consumed one token PAST the last position
        # the target buffer holds
        self._d_max_pages = -(-(self.buf_len + self.k + 1) // ps)
        dbuf = self._d_max_pages * ps
        cap = getattr(drafter_model, "max_decode_positions", None)
        if cap is not None and dbuf > cap:
            raise ValueError(
                f"drafter buffer {dbuf} (buf_len {self.buf_len} + k "
                f"{self.k} + 1, page-rounded) exceeds the drafter's "
                f"learned position table ({cap}); pick a RoPE drafter or "
                f"shrink the buffer")
        self._dtable_len = max(drafter_model.cfg.maxlen, dbuf)
        if not drafter_pages:
            # default: every slot can hold its full drafter row — the
            # drafter pool is never the binding resource unless the caller
            # squeezes it (bench.py's equal-HBM arm does, via the budget)
            drafter_pages = num_slots * self._d_max_pages
        # the drafter pool inherits kv_dtype: int8 pages halve ITS budget
        # share too, so the equal-HBM split stays one knob
        self.dpool = PagedKVPool(drafter_model, mesh, drafter_pages, ps,
                                 kv_dtype=self.kv_dtype)
        # ISSUE 15: drafter pages count toward the accounted-HBM
        # cross-check too (the equal-byte budget charges both pools)
        self._drafter_page_bytes_each = page_bytes(drafter_model.cfg, ps,
                                                   self.kv_dtype)
        self._dtbl = np.full((num_slots, self._d_max_pages),
                             self.dpool.scratch_page, np.int32)
        # verify dispatch width: k+1 positions, padded up to a cp multiple
        # for the prefill-chunk query ring (pads aim at scratch, qlen<=k+1)
        self._vw = self.cp * -(-(self.k + 1) // self.cp)
        self._draft_fn = self._build_draft()
        self._verify_fn = self._build_verify()
        self._dchunk_fns = {}
        # -- speculative stats -------------------------------------------
        self.spec_rounds = 0                 # verify dispatches
        self.spec_row_rounds = 0             # Σ live rows over rounds
        self.spec_emitted = 0                # tokens emitted by rounds
        self.drafter_s = 0.0                 # draft + drafter-prefill wall
        self.target_s = 0.0                  # verify wall
        self._acc_attempt = np.zeros(self.k, np.int64)
        self._acc_accept = np.zeros(self.k, np.int64)

    # -- compiled programs ------------------------------------------------
    def _dtables(self):
        if not self.drafter_model.uses_rope:
            return None, None
        return rope_tables(self._dtable_len,
                           self.drafter_model.cfg.head_dim,
                           self.drafter_model.cfg.rope_theta)

    def _build_draft(self):
        model, ps, k = self.drafter_model, self.page_size, self.k
        dtype = self._ddtype
        impl, interp = self.paged_attn_impl, self._paged_attn_interpret
        temperature, top_k, top_p = (self._temperature, self._top_k,
                                     self._top_p)

        def shard_fn(params, pool_k, pool_v, tokens, pos, seeds, tbl):
            cos_t, sin_t = self._dtables()
            pos = jnp.asarray(pos, jnp.int32)

            def body(carry, j):
                pk, pv, tok = carry
                pk, pv, logits = _paged_decode_one(
                    model, params, pk, pv, tok, pos + j, tbl, ps,
                    cos_t, sin_t, dtype, attn_impl=impl,
                    attn_interpret=interp)
                full = _full_vocab_logits(model, logits)     # (b, V) f32
                if temperature == 0.0:
                    nxt = jnp.argmax(full, axis=-1).astype(jnp.int32)
                    q = full                   # dead on the greedy path
                else:
                    scaled = _filter_logits(full / temperature, top_k,
                                            top_p)
                    q = jax.nn.softmax(scaled, axis=-1)

                    def draw(seed, p, row):
                        return jax.random.categorical(
                            _spec_key(seed, p, TAG_DRAFT), row, axis=-1)

                    nxt = jax.vmap(draw)(
                        seeds.astype(jnp.uint32),
                        (pos + j + 1).astype(jnp.int32),
                        scaled).astype(jnp.int32)
                nxt = lax.pmax(nxt, "tp")
                return (pk, pv, nxt), (nxt, q)

            (pool_k, pool_v, _), (drafts, qs) = lax.scan(
                body, (pool_k, pool_v, jnp.asarray(tokens, jnp.int32)),
                jnp.arange(k + 1, dtype=jnp.int32))
            draft = drafts[:k].T                             # (b, k)
            if temperature == 0.0:
                return pool_k, pool_v, draft
            q = lax.pmax(qs[:k].transpose(1, 0, 2), "tp")    # (b, k, V)
            return pool_k, pool_v, draft, q

        dspec = self.dpool.pspec
        out = (dspec, dspec, P(None, None))
        if temperature != 0.0:
            out = out + (P(None, None, None),)
        fn = jax.shard_map(
            shard_fn, mesh=self.mesh,
            in_specs=(model.specs(), dspec, dspec, P(None),
                      P(None), P(None), P(None, None)),
            out_specs=out)
        return jax.jit(fn, donate_argnums=(1, 2))

    def _build_verify(self):
        model, ps, k = self.model, self.page_size, self.k
        dtype = self._dtype
        impl, interp = self.paged_attn_impl, self._paged_attn_interpret
        temperature, top_k, top_p = (self._temperature, self._top_k,
                                     self._top_p)
        cw = k + 1
        # cp>1: the ring splits the dispatch width into cp sub-blocks, so
        # the verify window pads up to a cp multiple with scratch-aimed
        # columns (per-row qlen stays <= k+1; pads are never scored)
        vw = self._vw
        eos, cp = self.eos_id, self.cp

        def leading(accept, qlen):
            """Per-row count of leading accepted drafts, capped by the
            row's valid verify window (draft i sits at window slot i+1)."""
            valid = ((jnp.arange(k, dtype=jnp.int32)[None, :] + 1)
                     < qlen[:, None])
            lead = jnp.cumprod((accept & valid).astype(jnp.int32), axis=1)
            return lead.sum(axis=1).astype(jnp.int32)

        def shard_fn(params, pool_k, pool_v, tokens, draft, pos, qlen, tbl,
                     dstp, dsto, seeds, *maybe_q):
            params = self._deq(params)   # int8 decode weights (target)
            cos_t, sin_t = self._tables()
            pos = jnp.asarray(pos, jnp.int32)
            qlen = jnp.asarray(qlen, jnp.int32)
            block = jnp.concatenate(
                [jnp.asarray(tokens, jnp.int32)[:, None],
                 jnp.asarray(draft, jnp.int32)], axis=1)      # (b, cw)
            b = block.shape[0]
            if vw > cw:
                block = jnp.concatenate(
                    [block, jnp.full((b, vw - cw), eos, jnp.int32)], axis=1)
            pool_k, pool_v, logits = _paged_prefill_chunk(
                model, params, pool_k, pool_v, block, pos, qlen, tbl,
                dstp, dsto, ps, cos_t, sin_t, dtype, all_logits=True,
                attn_impl=impl, attn_interpret=interp, cp=cp)
            full = _full_vocab_logits(model, logits)[:, :cw]  # (b, cw, V)
            block = block[:, :cw]
            if temperature == 0.0:
                tgt = jnp.argmax(full, axis=-1).astype(jnp.int32)
                n_acc = leading(block[:, 1:] == tgt[:, :k], qlen)
                nxt = jnp.take_along_axis(tgt, n_acc[:, None],
                                          axis=1)[:, 0]
            else:
                qprobs = maybe_q[0]                           # (b, k, V)
                scaled = _filter_logits(
                    full.reshape(b * cw, -1) / temperature, top_k, top_p)
                p = jax.nn.softmax(scaled, axis=-1).reshape(b, cw, -1)
                d = block[:, 1:]                              # (b, k)
                p_d = jnp.take_along_axis(p[:, :k], d[..., None],
                                          axis=-1)[..., 0]
                q_d = jnp.take_along_axis(qprobs, d[..., None],
                                          axis=-1)[..., 0]
                posm = (pos[:, None] + 1
                        + jnp.arange(k, dtype=jnp.int32)[None, :])

                def u_one(seed, pp):
                    return jax.random.uniform(
                        _spec_key(seed, pp, TAG_ACCEPT), ())

                u = jax.vmap(jax.vmap(u_one, in_axes=(None, 0)))(
                    seeds.astype(jnp.uint32), posm)
                # u < p/q  <=>  u*q < p (no div-by-zero; q(d) > 0 for a
                # token actually drawn from q)
                n_acc = leading(u * q_d < p_d, qlen)
                # residual at the first rejected position. q is ZEROED at
                # slot k (the all-accept bonus draw) AND at draft slots
                # outside the row's verify window: there the "rejection"
                # was forced by the window, not by an accept test, so the
                # exact draw is from p itself — max(p - 0, 0) = p. Only a
                # REAL rejection (draft tested and refused) subtracts q.
                valid = ((jnp.arange(k, dtype=jnp.int32)[None, :] + 1)
                         < qlen[:, None])                 # (b, k)
                qpad = jnp.concatenate(
                    [jnp.where(valid[..., None], qprobs, 0.0),
                     jnp.zeros_like(qprobs[:, :1])], axis=1)
                p_at = jnp.take_along_axis(
                    p, n_acc[:, None, None], axis=1)[:, 0]
                q_at = jnp.take_along_axis(
                    qpad, n_acc[:, None, None], axis=1)[:, 0]
                res = jnp.maximum(p_at - q_at, 0.0)
                # p == q exactly zeroes the residual (probability-0 event
                # under real draws — only garbage rows hit it); fall back
                # to p so categorical always sees a distribution
                res = jnp.where(res.sum(-1, keepdims=True) > 0.0, res,
                                p_at)

                def draw(seed, pp, row):
                    return jax.random.categorical(
                        _spec_key(seed, pp, TAG_RESAMPLE),
                        jnp.log(jnp.maximum(row, 1e-30)), axis=-1)

                nxt = jax.vmap(draw)(
                    seeds.astype(jnp.uint32),
                    (pos + 1 + n_acc).astype(jnp.int32),
                    res).astype(jnp.int32)
            out = jnp.concatenate(
                [block[:, 1:], jnp.zeros((b, 1), jnp.int32)], axis=1)
            out = out.at[jnp.arange(b), n_acc].set(nxt)
            # every tp shard computed the same verdict; pmax clears the
            # varying tags (the sampler convention)
            return (pool_k, pool_v, lax.pmax(n_acc, "tp"),
                    lax.pmax(out, "tp"))

        tspec = self.pool.pspec
        in_specs = [self._pspec, tspec, tspec, P(None),
                    P(None, None), P(None), P(None), P(None, None),
                    P(None, None), P(None, None), P(None)]
        if temperature != 0.0:
            in_specs.append(P(None, None, None))
        fn = jax.shard_map(
            shard_fn, mesh=self.mesh, in_specs=tuple(in_specs),
            out_specs=(tspec, tspec, P(None), P(None, None)))
        return jax.jit(fn, donate_argnums=(1, 2))

    def _build_drafter_chunk(self, cw: int):
        model, ps, dtype = self.drafter_model, self.page_size, self._ddtype
        impl, interp = self.paged_attn_impl, self._paged_attn_interpret

        def shard_fn(params, pool_k, pool_v, chunk, start, qlen, tbl,
                     dstp, dsto):
            cos_t, sin_t = self._dtables()
            pool_k, pool_v, _ = _paged_prefill_chunk(
                model, params, pool_k, pool_v, chunk, start, qlen, tbl,
                dstp, dsto, ps, cos_t, sin_t, dtype, attn_impl=impl,
                attn_interpret=interp)
            # only the K/V writes matter: the draft loop re-reads the cache
            # next round (the dead logits head DCEs out of the program)
            return pool_k, pool_v

        dspec = self.dpool.pspec
        fn = jax.shard_map(
            shard_fn, mesh=self.mesh,
            in_specs=(model.specs(), dspec, dspec, P(None, None),
                      P(None), P(None), P(None, None), P(None, None),
                      P(None, None)),
            out_specs=(dspec, dspec))
        return jax.jit(fn, donate_argnums=(1, 2))

    # -- request intake ---------------------------------------------------
    def submit(self, req: Request) -> None:
        """`PagedEngine.submit` plus the drafter-side worst-case check:
        admitted work must fit BOTH pools alone, else preemption could
        deadlock with the request as the sole survivor."""
        need_d = -(-min(len(req.prompt) + req.max_new + self.k + 1,
                        self._d_max_pages * self.page_size)
                   // self.page_size)
        if need_d > self.dpool.num_pages:
            raise ValueError(
                f"request {req.rid}: drafter needs up to {need_d} pages "
                f"but the drafter pool has {self.dpool.num_pages} — raise "
                f"--drafter_pages or lower the budget")
        super().submit(req)

    # -- drafter page plumbing -------------------------------------------
    def _dalloc_page(self, needy_slot: int) -> int:
        while True:
            try:
                return self.dpool.alloc()
            except PoolExhausted:
                cands = self._candidates(exclude_slot=needy_slot)
                if not cands:
                    raise RuntimeError(
                        "drafter page pool exhausted with no preemption "
                        "candidate — a single request outgrew "
                        "drafter_pages (submit-time validation should "
                        "have refused it)")
                self._preempt(cands[0][0])

    def _ensure_drafter_writable(self, slot: int, lo: int, hi: int) -> None:
        """Drafter pages for positions [lo, hi): always private (the
        drafter never COW-shares), so unmapped entries just allocate."""
        ps, scratch = self.page_size, self.dpool.scratch_page
        for j in range(lo // ps, -(-hi // ps)):
            if self._dtbl[slot, j] == scratch:
                self._dtbl[slot, j] = self._dalloc_page(slot)

    def _release_slot(self, slot: int) -> int:
        # retire/preempt frees BOTH page lists — the drafter's first, so a
        # preemption triggered from target-page pressure cannot leak the
        # drafter rows
        scratch = self.dpool.scratch_page
        freed = 0
        for j in range(self._d_max_pages):
            if self._dtbl[slot, j] != scratch:
                self.dpool.unref(int(self._dtbl[slot, j]))
                self._dtbl[slot, j] = scratch
                freed += 1
        return freed + super()._release_slot(slot)

    # -- drafter prefill (admission and preempt-resume) -------------------
    def _drafter_prefill(self, slot: int, ids: List[int]) -> None:
        """Materialise the drafter's K/V for the whole prefix `ids` in
        `prefill_chunk`-sized dispatches. No prefix index on the drafter
        side: the shared-prefix positions the target COW-skipped are
        recomputed here at drafter cost (~the ratio of the two models'
        per-token FLOPs — docs/SERVING.md prices it)."""
        ps = self.page_size
        s = 0
        while s < len(ids):
            n = min(len(ids) - s, self.prefill_chunk)
            self._ensure_drafter_writable(slot, s, s + n)
            cw = _pow2_at_most(n, self.prefill_chunk)
            buf, dstp, dsto = _chunk_maps(ids, s, n, cw, ps, self.eos_id,
                                          self.dpool.scratch_page,
                                          self._dtbl[slot])
            if cw not in self._dchunk_fns:
                self._dchunk_fns[cw] = self._build_drafter_chunk(cw)
            t0 = time.monotonic()
            with self._span("drafter_prefill_chunk", slot=slot, pos0=s,
                            n=n):
                dk, dv = self._dchunk_fns[cw](
                    self._dparams, self.dpool.ks, self.dpool.vs,
                    jnp.asarray(buf), jnp.asarray([s], np.int32),
                    jnp.asarray([n], np.int32),
                    jnp.asarray(self._dtbl[slot:slot + 1]),
                    jnp.asarray(dstp), jnp.asarray(dsto))
                self.dpool.adopt(dk, dv)
                jax.block_until_ready(self.dpool.ks)
            self.drafter_s += time.monotonic() - t0
            s += n

    def _finish_prefill(self, slot, st, first, done) -> None:
        # the target cache is complete; build the drafter's before the slot
        # goes live (a preempt-resumed request passes through here too, so
        # both caches rebuild from the same prompt+generated prefix)
        self._drafter_prefill(slot, st.ids)
        if self.rt is not None:
            self.rt.mark(st.req, "drafter_prefill", self._clock(),
                         positions=len(st.ids))
        super()._finish_prefill(slot, st, first, done)

    # -- the speculative decode round -------------------------------------
    def _decode(self, done: List[Request]) -> None:
        k, ps = self.k, self.page_size
        # page growth / COW for every live slot's verify window FIRST —
        # target pages for [pos, pos+qlen), private drafter pages for
        # [pos, pos+k+1). Either may preempt victims, so iterate snapshots
        # and re-check liveness (the parent step's pattern).
        for slot in list(self._slot_req):
            if slot not in self._slot_req:
                continue
            pos = int(self._pos[slot])
            self._ensure_writable(slot, pos,
                                  pos + min(k + 1, self.buf_len - pos))
        for slot in list(self._slot_req):
            if slot not in self._slot_req:
                continue
            pos = int(self._pos[slot])
            self._ensure_drafter_writable(slot, pos, pos + k + 1)
        if not self._slot_req:
            return
        b = self.num_slots
        dstp = np.full((b, self._vw), self.pool.scratch_page, np.int32)
        dsto = np.tile(np.arange(self._vw, dtype=np.int32)[None, :] % ps,
                       (b, 1))
        qlen = np.zeros(b, np.int32)          # free rows: nothing valid
        for slot in self._slot_req:
            pos = int(self._pos[slot])
            ql = min(k + 1, self.buf_len - pos)
            qlen[slot] = ql
            for i in range(ql):
                dstp[slot, i] = self._tbl[slot, (pos + i) // ps]
                dsto[slot, i] = (pos + i) % ps
        t0 = time.monotonic()
        with self._span("draft", live=len(self._slot_req), k=k):
            args = (self._dparams, self.dpool.ks, self.dpool.vs,
                    jnp.asarray(self._tokens), jnp.asarray(self._pos),
                    jnp.asarray(self._seeds), jnp.asarray(self._dtbl))
            if self._temperature == 0.0:
                dk, dv, draft = self._draft_fn(*args)
                qprobs = None
            else:
                dk, dv, draft, qprobs = self._draft_fn(*args)
            self.dpool.adopt(dk, dv)
            # sync so the drafter/target wall split is honest (draft and
            # qprobs stay ON DEVICE — the verify consumes them directly)
            jax.block_until_ready(draft)
        self.drafter_s += time.monotonic() - t0
        t0 = time.monotonic()
        with self._span("verify", live=len(self._slot_req), k=k):
            vargs = [self._params_in, self.pool.ks, self.pool.vs,
                     jnp.asarray(self._tokens), draft,
                     jnp.asarray(self._pos), jnp.asarray(qlen),
                     jnp.asarray(self._tbl), jnp.asarray(dstp),
                     jnp.asarray(dsto), jnp.asarray(self._seeds)]
            if qprobs is not None:
                vargs.append(qprobs)
            ks, vs, n_acc, out = self._verify_fn(*vargs)
            self.pool.adopt(ks, vs)
            # the round's ONLY device->host transfer: 2(k+2) ints per row
            n_acc, out = np.asarray(n_acc), np.asarray(out)
        self.target_s += time.monotonic() - t0
        now = self._clock()
        self.decode_steps += 1
        self.spec_rounds += 1
        self.spec_row_rounds += len(self._slot_req)
        live_tokens = sum(int(self._pos[s]) + 1 for s in self._slot_req)
        live_tokens += sum(st.s for st in self._prefilling.values())
        used = self.pool.pages_in_use
        self._occupancy_sum += self.live_requests / self.num_slots
        self._pages_used_sum += used
        if used:
            self._kv_util_sum += live_tokens / (used * self.page_size)
        if self.tracer is not None:
            self.tracer.counter("slots_live", len(self._slot_req))
            self.tracer.counter("pages_in_use", used)
        if self.flight is not None:
            self.flight.record("pool_stats", live=len(self._slot_req),
                               prefilling=len(self._prefilling),
                               pages_in_use=used,
                               free_pages=self.pool.free_pages,
                               drafter_pages_in_use=self.dpool.pages_in_use,
                               queued=self.scheduler.pending)
            # the verify round's D2H already synced this step's device
            # work — safe point for an armed anomaly-profiler window
            self.flight.tick(self.decode_steps)
        if self.duty_profiler is not None:
            # same safe point (ISSUE 15): duty windows tick per verify
            # round on the speculative engine
            self.duty_profiler.tick(self.decode_steps)
        if self.telemetry is not None:
            self._publish_telemetry(used, live_tokens)
            self.telemetry.gauge("serve/drafter_pages_in_use",
                                 self.dpool.pages_in_use)
        _publish_hbm_plane(
            self, pool_bytes=used * self._page_bytes_each
            + self.dpool.pages_in_use * self._drafter_page_bytes_each)
        if self.controller is not None:
            # same safe point as the plain paged decode tick (ISSUE 16)
            self._control_tick()
        for slot, req in list(self._slot_req.items()):
            na = int(n_acc[slot])
            n_att = min(k, int(qlen[slot]) - 1)
            for j in range(min(na, n_att)):
                self._acc_attempt[j] += 1
                self._acc_accept[j] += 1
            if na < n_att:
                self._acc_attempt[na] += 1    # the first rejected draft
            if self.rt is not None:
                # one contiguous `spec_round` span per verify dispatch;
                # `accepted` sums across coalesced rounds, so the retired
                # timeline shows tokens-per-round at a glance
                self.rt.mark(req, "spec_round", now, accepted=na)
            # the pending token was written at `pos` by the verify
            # dispatch: emitted (the non-speculative step's contract)
            req.tokens.append(int(self._tokens[slot]))
            self.generated_tokens += 1
            self.spec_emitted += 1
            adv, finished = 1, False
            for j in range(na + 1):
                cand = int(out[slot, j])
                if (cand == self.eos_id
                        or req.prompt_len + len(req.tokens) >= req.limit):
                    req.finish_t = now
                    del self._slot_req[slot]
                    freed = self._release_slot(slot)
                    if self.rt is not None:
                        self.rt.note(req, pages_freed=freed)
                    self._complete(req, done)
                    finished = True
                    break
                if j < na:                    # an accepted draft: emitted
                    req.tokens.append(cand)
                    self.generated_tokens += 1
                    self.spec_emitted += 1
                    adv += 1
                else:                         # the round's new pending
                    self._tokens[slot] = cand
            if not finished:
                self._pos[slot] += adv

    # -- aggregate view ---------------------------------------------------
    def stats(self) -> dict:
        st = super().stats()
        att = np.maximum(self._acc_attempt, 1)
        st.update({
            "speculate_k": self.k,
            "spec_rounds": self.spec_rounds,
            # emitted tokens per ROW per TARGET dispatch — the headline:
            # the non-speculative engine emits exactly 1.0 (one token per
            # live slot per decode dispatch), a perfect drafter k+1.
            # Normalised per row so batch width cannot masquerade as
            # acceptance.
            "accepted_tokens_per_dispatch": round(
                self.spec_emitted / max(self.spec_row_rounds, 1), 4),
            "acceptance_rate_by_position": [
                round(float(a) / float(t), 4)
                for a, t in zip(self._acc_accept, att)],
            "acceptance_rate": round(
                float(self._acc_accept.sum())
                / max(float(self._acc_attempt.sum()), 1.0), 4),
            "rounds_per_request": round(
                self.spec_rounds / max(len(self.completed), 1), 4),
            "drafter_ms_total": round(self.drafter_s * 1e3, 3),
            "target_ms_total": round(self.target_s * 1e3, 3),
            "drafter_num_pages": self.dpool.num_pages,
            "drafter_pages_in_use": self.dpool.pages_in_use,
            "drafter_page_bytes": page_bytes(self.drafter_model.cfg,
                                             self.page_size, self.kv_dtype),
            "target_page_bytes": page_bytes(self.model.cfg,
                                            self.page_size, self.kv_dtype),
        })
        return st
