"""Continuous-batching inference serving over the fused KV-cache decoder.

The training side of this repo got its scale-out in PRs 1-4; this package
opens the INFERENCE workload: a slot-granular KV-cache pool
(`kv_manager.py`), a continuous-batching engine whose device programs share
the one-shot decoder's lowerings (`engine.py` — greedy output is
token-identical to `models/decode.GreedyDecoder`), a FIFO scheduler with
length-bucketed prefill batching (`scheduler.py`), a Poisson/burst/replay
arrival driver (`loadgen.py`), and the `serve.py` benchmark CLI.

Serving v2 (ISSUE 6) adds the PAGED path: `PagedKVPool` (fixed-size KV
pages, refcounts, COW prefix index), `PagedEngine` (page-table decode,
chunked prefill interleaved into the decode loop, preemption with
resume-through-prefill), and `SLOScheduler` (TTFT deadline classes,
per-tenant fairness). Same token-identity bar as v1, pinned in
tests/test_serving_paged.py. See docs/SERVING.md.

Speculative decoding (ISSUE 7): `SpeculativeEngine` drafts k tokens per
round with a cheap drafter model over its own paged pool and verifies
them in ONE target dispatch with exact rejection sampling — greedy output
token-identical to the paged engine, sampled output distribution-
identical, pinned in tests/test_speculative.py.
"""

from .engine import (ContinuousBatchingEngine, PagedEngine, Request,
                     decode_prompts)
from .kv_manager import (KVCachePool, PagedKVPool, PoolExhausted,
                         kv_token_bytes, page_bytes)
from .loadgen import run_loadgen, slo_attainment, synthetic_requests
from .scheduler import (DEFAULT_SLO_CLASSES, FIFOScheduler, QueueFull,
                        SLOScheduler, bucket_width, parse_slo_classes)
from .speculative import SpeculativeEngine

__all__ = [
    "ContinuousBatchingEngine", "DEFAULT_SLO_CLASSES", "FIFOScheduler",
    "KVCachePool", "PagedEngine", "PagedKVPool", "PoolExhausted",
    "QueueFull", "Request", "SLOScheduler", "SpeculativeEngine",
    "bucket_width", "decode_prompts", "kv_token_bytes", "page_bytes",
    "parse_slo_classes", "run_loadgen", "slo_attainment",
    "synthetic_requests",
]
