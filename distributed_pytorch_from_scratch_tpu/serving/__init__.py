"""Continuous-batching inference serving over the fused KV-cache decoder.

The training side of this repo got its scale-out in PRs 1-4; this package
opens the INFERENCE workload: a slot-granular KV-cache pool
(`kv_manager.py`), a continuous-batching engine whose device programs share
the one-shot decoder's lowerings (`engine.py` — greedy output is
token-identical to `models/decode.GreedyDecoder`), a FIFO scheduler with
length-bucketed prefill batching (`scheduler.py`), a Poisson/burst/replay
arrival driver (`loadgen.py`), and the `serve.py` benchmark CLI. See
docs/SERVING.md.
"""

from .engine import ContinuousBatchingEngine, Request, decode_prompts
from .kv_manager import KVCachePool
from .loadgen import run_loadgen, synthetic_requests
from .scheduler import FIFOScheduler, QueueFull, bucket_width

__all__ = [
    "ContinuousBatchingEngine", "FIFOScheduler", "KVCachePool", "QueueFull",
    "Request", "bucket_width", "decode_prompts", "run_loadgen",
    "synthetic_requests",
]
