"""Continuous-batching inference serving over the fused KV-cache decoder.

The training side of this repo got its scale-out in PRs 1-4; this package
opens the INFERENCE workload: a slot-granular KV-cache pool
(`kv_manager.py`), a continuous-batching engine whose device programs share
the one-shot decoder's lowerings (`engine.py` — greedy output is
token-identical to `models/decode.GreedyDecoder`), a FIFO scheduler with
length-bucketed prefill batching (`scheduler.py`), a Poisson/burst/replay
arrival driver (`loadgen.py`), and the `serve.py` benchmark CLI.

Serving v2 (ISSUE 6) adds the PAGED path: `PagedKVPool` (fixed-size KV
pages, refcounts, COW prefix index), `PagedEngine` (page-table decode,
chunked prefill interleaved into the decode loop, preemption with
resume-through-prefill), and `SLOScheduler` (TTFT deadline classes,
per-tenant fairness). Same token-identity bar as v1, pinned in
tests/test_serving_paged.py. See docs/SERVING.md.

Speculative decoding (ISSUE 7): `SpeculativeEngine` drafts k tokens per
round with a cheap drafter model over its own paged pool and verifies
them in ONE target dispatch with exact rejection sampling — greedy output
token-identical to the paged engine, sampled output distribution-
identical, pinned in tests/test_speculative.py.

Serving fleet v1 (ISSUE 19): `FleetRouter` dispatches across N replicas
by predicted prefix-cache hit (a host-side shadow of the pool's chain
index) blended with least-loaded, with session affinity + loud spill;
`transfer.py` streams a prefilled request's KV pages to a decode-side
engine over a length-prefixed socket (disaggregated prefill/decode,
token-identical to colocated, any tp/cp widths). Pinned in
tests/test_fleet.py; `scripts/serve_fleet.py` is the CLI.
"""

from .engine import (ContinuousBatchingEngine, PagedEngine, Request,
                     decode_prompts)
from .kv_manager import (KVCachePool, PagedKVPool, PoolExhausted,
                         kv_token_bytes, page_bytes)
from .loadgen import (run_fleet_loadgen, run_loadgen, slo_attainment,
                      synthetic_requests)
from .router import FleetRouter
from .scheduler import (DEFAULT_SLO_CLASSES, FIFOScheduler, QueueFull,
                        SLOScheduler, bucket_width, parse_slo_classes)
from .speculative import SpeculativeEngine
from .transfer import (recv_handoff, run_disaggregated, send_handoff)

__all__ = [
    "ContinuousBatchingEngine", "DEFAULT_SLO_CLASSES", "FIFOScheduler",
    "FleetRouter", "KVCachePool", "PagedEngine", "PagedKVPool",
    "PoolExhausted", "QueueFull", "Request", "SLOScheduler",
    "SpeculativeEngine", "bucket_width", "decode_prompts",
    "kv_token_bytes", "page_bytes", "parse_slo_classes", "recv_handoff",
    "run_disaggregated", "run_fleet_loadgen", "run_loadgen",
    "send_handoff", "slo_attainment", "synthetic_requests",
]
