"""Slot-granular KV-cache pool for the continuous-batching engine.

The one-shot decoder (`models/decode.make_generate`) materialises fresh
per-layer K/V tensors inside every generation dispatch — fine when a whole
prompt batch lives and dies together, fatal for serving, where request i
retires while request j is mid-generation. Here the caches are a persistent
POOL: one device array per K/V with a `slots` axis,

    (num_layers, num_slots + 1, local_kv_heads, buf_len, head_dim)

sharded over 'tp' on the heads dim — the SAME head partitioning as training
and one-shot decode (models/decode.py layout), so the same checkpoint params
drive it unchanged and the per-slot row layout is byte-compatible with what
`_prefill`/`_decode_one` produce.

Slot lifecycle: `alloc()` leases a free slot to a request; prefill scatters
the prompt's K/V into that slot's rows; every decode step advances all slots
in place; `free()` returns the slot. The LAST slot (index `num_slots`) is a
scratch row that is never leased — prefill batches padded up to a bucket
size aim their pad rows at it, so pad work can scatter somewhere harmless
without ever colliding with a live lease.

The pool arrays are handed to jitted programs with `donate_argnums`, so on
TPU every prefill/step updates the pool IN PLACE (the engine adopts the
returned arrays via `adopt()`); a refill never reallocates the pool. On
backends without donation support (CPU tests) XLA falls back to a copy —
values identical, just not zero-copy.
"""

from __future__ import annotations

from collections import deque
from typing import List

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import resolve_dtype

# pool layout: (layers, slots, kv_heads, buf, head_dim); 'tp' shards the
# heads dim, everything else replicated — matches models/decode.py caches
POOL_SPEC = P(None, None, "tp", None, None)


class KVCachePool:
    """Device-resident K/V pool + host-side slot free-list."""

    def __init__(self, model, mesh: Mesh, num_slots: int, buf_len: int):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        cfg = model.cfg
        self.num_slots = num_slots
        self.buf_len = buf_len
        self.scratch_slot = num_slots          # never leased; pad-row target
        self.dtype = resolve_dtype(cfg.compute_dtype)
        shape = (cfg.num_layers, num_slots + 1, cfg.kv_heads, buf_len,
                 cfg.head_dim)
        sharding = NamedSharding(mesh, POOL_SPEC)
        alloc = jax.jit(lambda: jnp.zeros(shape, self.dtype),
                        out_shardings=sharding)
        self.ks = alloc()
        self.vs = alloc()
        self._free = deque(range(num_slots))

    # -- slot leasing ----------------------------------------------------
    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def live_slots(self) -> int:
        return self.num_slots - len(self._free)

    @property
    def occupancy(self) -> float:
        return self.live_slots / self.num_slots

    def alloc(self) -> int:
        if not self._free:
            raise RuntimeError("KV pool exhausted: no free slot (the "
                               "scheduler must gate admissions on "
                               "free_slots)")
        return self._free.popleft()

    def alloc_many(self, n: int) -> List[int]:
        if n > len(self._free):
            raise RuntimeError(f"KV pool exhausted: asked for {n} slots, "
                               f"{len(self._free)} free")
        return [self._free.popleft() for _ in range(n)]

    def free(self, slot: int) -> None:
        if not 0 <= slot < self.num_slots:
            raise ValueError(f"slot {slot} out of range [0, {self.num_slots})")
        if slot in self._free:
            raise ValueError(f"slot {slot} double-freed")
        self._free.append(slot)

    # -- device-array handoff -------------------------------------------
    def adopt(self, ks, vs) -> None:
        """Swap in the pool arrays a donating jitted program returned (the
        old handles were consumed by donation — holding on to them would
        raise on next use)."""
        self.ks, self.vs = ks, vs
