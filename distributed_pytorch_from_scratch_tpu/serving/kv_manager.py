"""Slot-granular KV-cache pool for the continuous-batching engine.

The one-shot decoder (`models/decode.make_generate`) materialises fresh
per-layer K/V tensors inside every generation dispatch — fine when a whole
prompt batch lives and dies together, fatal for serving, where request i
retires while request j is mid-generation. Here the caches are a persistent
POOL: one device array per K/V with a `slots` axis,

    (num_layers, num_slots + 1, local_kv_heads, buf_len, head_dim)

sharded over 'tp' on the heads dim — the SAME head partitioning as training
and one-shot decode (models/decode.py layout), so the same checkpoint params
drive it unchanged and the per-slot row layout is byte-compatible with what
`_prefill`/`_decode_one` produce.

Slot lifecycle: `alloc()` leases a free slot to a request; prefill scatters
the prompt's K/V into that slot's rows; every decode step advances all slots
in place; `free()` returns the slot. The LAST slot (index `num_slots`) is a
scratch row that is never leased — prefill batches padded up to a bucket
size aim their pad rows at it, so pad work can scatter somewhere harmless
without ever colliding with a live lease.

The pool arrays are handed to jitted programs with `donate_argnums`, so on
TPU every prefill/step updates the pool IN PLACE (the engine adopts the
returned arrays via `adopt()`); a refill never reallocates the pool. On
backends without donation support (CPU tests) XLA falls back to a copy —
values identical, just not zero-copy.
"""

from __future__ import annotations

from collections import deque
from typing import List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import resolve_dtype

# pool layout: (layers, slots, kv_heads, buf, head_dim); 'tp' shards the
# heads dim, everything else replicated — matches models/decode.py caches
POOL_SPEC = P(None, None, "tp", None, None)
# int8 pools carry a parallel scale array (layers, pages, kv_heads, page)
# — one f32 per stored head-vector; same 'tp'-on-heads partitioning
KV_SCALE_SPEC = P(None, None, "tp", None)
# cp-sharded PAGED pool (ISSUE 18): the page dim ALSO shards, over 'cp' —
# each cp rank owns a contiguous slab of physical pages (plus its own local
# scratch page), so per-chip KV bytes shrink ~1/cp at equal context
CP_POOL_SPEC = P(None, "cp", "tp", None, None)
CP_KV_SCALE_SPEC = P(None, "cp", "tp", None)


def local_page_ids(tbl, ppr: int, axis: str = "cp"):
    """GLOBAL page ids -> this cp rank's LOCAL pool indices (call inside
    shard_map over a mesh with a (possibly size-1) `axis`).

    Layout contract (PagedKVPool, cp > 1): rank r's local slab is
    [pages_per_rank + 1] entries — global pages [r*ppr, (r+1)*ppr) at local
    [0, ppr), then ONE rank-local scratch page at local index ppr. Any id
    this rank does not own (another rank's page, or the host's global
    scratch sentinel `num_pages`) maps to the LOCAL scratch: reads see
    quarantined garbage that visibility masks to zero weight, writes are
    quarantined like the cp=1 scratch page. With cp == 1 (ppr == num_pages)
    the formula is the identity on every valid id — one rule, no branch."""
    r = jax.lax.axis_index(axis)
    lo = r * ppr
    owned = (tbl >= lo) & (tbl < lo + ppr)
    return jnp.where(owned, tbl - lo, ppr)


def kv_token_bytes(cfg, kv_dtype=None) -> int:
    """K+V cache bytes per TOKEN POSITION at a model shape (all layers,
    all kv heads, both K and V, global across tp). The equal-HBM accounting
    unit: bench.py's serving A/B spends `slots x buf_len` of these on the
    slot engine and must hand the paged/speculative arms the same number —
    including the speculative drafter's pages, which buy acceptance, not
    capacity, and therefore count against the budget.

    `kv_dtype='int8'` prices the quantized pool HONESTLY: one int8 code
    per element PLUS the f32 scale per stored head-vector — so the int8
    capacity win the budget math grants is (itemsize x hd) / (hd + 4),
    ~2x under bf16 at hd 64, never the naive 2x that ignores scales."""
    if kv_dtype in ("int8", jnp.int8):
        per_head = cfg.head_dim + 4            # int8 codes + f32 scale
    else:
        itemsize = jnp.dtype(resolve_dtype(cfg.compute_dtype)).itemsize
        per_head = cfg.head_dim * itemsize
    return 2 * cfg.num_layers * cfg.kv_heads * per_head


def page_bytes(cfg, page_size: int, kv_dtype=None) -> int:
    """K+V bytes of ONE page at a model shape (scratch page excluded)."""
    return kv_token_bytes(cfg, kv_dtype) * page_size


class KVCachePool:
    """Device-resident K/V pool + host-side slot free-list."""

    def __init__(self, model, mesh: Mesh, num_slots: int, buf_len: int):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        cfg = model.cfg
        self.num_slots = num_slots
        self.buf_len = buf_len
        self.scratch_slot = num_slots          # never leased; pad-row target
        self.dtype = resolve_dtype(cfg.compute_dtype)
        shape = (cfg.num_layers, num_slots + 1, cfg.kv_heads, buf_len,
                 cfg.head_dim)
        self.pspec = POOL_SPEC    # uniform engine-facing spec handle
        sharding = NamedSharding(mesh, POOL_SPEC)
        alloc = jax.jit(lambda: jnp.zeros(shape, self.dtype),
                        out_shardings=sharding)
        self.ks = alloc()
        self.vs = alloc()
        self._free = deque(range(num_slots))

    # -- slot leasing ----------------------------------------------------
    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def live_slots(self) -> int:
        return self.num_slots - len(self._free)

    @property
    def occupancy(self) -> float:
        return self.live_slots / self.num_slots

    def alloc(self) -> int:
        if not self._free:
            raise RuntimeError("KV pool exhausted: no free slot (the "
                               "scheduler must gate admissions on "
                               "free_slots)")
        return self._free.popleft()

    def alloc_many(self, n: int) -> List[int]:
        if n > len(self._free):
            raise RuntimeError(f"KV pool exhausted: asked for {n} slots, "
                               f"{len(self._free)} free")
        return [self._free.popleft() for _ in range(n)]

    def free(self, slot: int) -> None:
        if not 0 <= slot < self.num_slots:
            raise ValueError(f"slot {slot} out of range [0, {self.num_slots})")
        if slot in self._free:
            raise ValueError(f"slot {slot} double-freed")
        self._free.append(slot)

    # -- device-array handoff -------------------------------------------
    def adopt(self, ks, vs) -> None:
        """Swap in the pool arrays a donating jitted program returned (the
        old handles were consumed by donation — holding on to them would
        raise on next use)."""
        self.ks, self.vs = ks, vs


class PoolExhausted(RuntimeError):
    """Raised by PagedKVPool.alloc when no free page exists — the paged
    engine's signal to preempt a victim (or refuse admission)."""


class PagedKVPool:
    """Fixed-size KV PAGES + host-side free list, refcounts, and a
    content-addressed prefix index (serving v2, ISSUE 6).

    The slot pool above leases one `buf_len`-long cache row per request —
    HBM cost is `slots x buf_len` whatever the actual lengths. Here the
    unit is a PAGE of `page_size` token positions,

        (num_layers, num_pages + 1, local_kv_heads, page_size, head_dim)

    sharded over 'tp' on heads like everything else, and a request's
    logical cache row is a per-slot PAGE LIST (the engine's
    `(slots, max_pages)` page-table array). Pages are leased on demand as
    a request's cursor grows, so concurrency is bounded by live TOKENS,
    not worst-case rows — and identical prompt prefixes can SHARE pages:

    * refcount[p] = number of slot page-lists referencing page p. alloc()
      hands a free page at refcount 1; ref() adds a sharer; unref() drops
      one and returns the page to the free list (and drops its prefix-
      index entries) at zero — after every request retires the counts
      drain to zero, pinned in tests.
    * copy-on-write: a WRITER whose target page has refcount > 1 must
      materialise a private copy first (`copy_pages`, one bucketed device
      dispatch per engine step) — sharers keep the original bits.
    * prefix index: prompt pages register under a hash CHAIN key
      (key_j = (key_{j-1}, page_tokens)) with their valid tokens
      alongside, so a new prompt WALKS the chain page by page and may
      finish on a partial match inside the last candidate (visibility
      masks the rest). Content at a position is never mutated once written (writes
      only append; COW protects shared pages), so an indexed page stays
      valid until freed. Index entries hold NO refcount — sharing only
      happens against pages some live request still references, which is
      what lets the drain-to-zero invariant hold.

    The LAST page (index num_pages) is scratch: free slots' page tables
    and chunk-pad columns aim their writes at it, and nothing ever
    attends to it (the same quarantine trick as the slot pool's scratch
    row).

    `kv_dtype='int8'` (ISSUE 8) stores pages as int8 CODES with a parallel
    f32 scale array — one scale per (layer, page, head, position), i.e.
    per stored head-vector, so decode's append-only writes never have to
    requantize a page's earlier positions. `ks`/`vs` then become
    (codes, scales) TUPLES that flow through the same lease/COW/free
    refcount accounting (copy_pages copies both members); the decode
    programs quantize on write and dequantize the gathered page view
    (models/decode.py), so the attend math is unchanged. At the same HBM
    budget an int8 pool holds ~(itemsize x hd)/(hd + 4) x the tokens —
    the capacity win `bench.py --serving --kv_dtype int8` measures."""

    def __init__(self, model, mesh: Mesh, num_pages: int, page_size: int,
                 kv_dtype=None, flight=None):
        if num_pages < 1:
            raise ValueError(f"num_pages must be >= 1, got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if kv_dtype not in (None, "native", "int8", jnp.int8):
            raise ValueError(f"kv_dtype must be None/'native'/'int8', got "
                             f"{kv_dtype!r}")
        cfg = model.cfg
        # cp-sharded pages (ISSUE 18): the model's cp_size picks the pool
        # layout — each cp rank owns a disjoint contiguous slab of pages
        # [r*ppr, (r+1)*ppr) PLUS its own local scratch page, so the array
        # page dim is num_pages + cp and shards evenly over 'cp'. The host
        # accounting below stays rank-global (ids are global; the device
        # programs translate with `local_page_ids`); cp == 1 reproduces the
        # historical num_pages + 1 layout byte for byte.
        self.cp = max(1, int(getattr(model, "cp_size", 1)))
        if num_pages % self.cp:
            raise ValueError(
                f"num_pages {num_pages} must be divisible by cp "
                f"{self.cp} (each cp rank owns an equal page slab; the "
                f"engine rounds up before building the pool)")
        self.num_pages = num_pages
        self.pages_per_rank = num_pages // self.cp
        self.page_size = page_size
        self.scratch_page = num_pages          # never leased; pad target
        self.flight = flight  # obs.flight.FlightRecorder: pool anomalies
        self.kv_dtype = "int8" if kv_dtype in ("int8", jnp.int8) else None
        shape = (cfg.num_layers, num_pages + self.cp, cfg.kv_heads,
                 page_size, cfg.head_dim)
        pool_spec = CP_POOL_SPEC if self.cp > 1 else POOL_SPEC
        scale_spec = CP_KV_SCALE_SPEC if self.cp > 1 else KV_SCALE_SPEC
        if self.kv_dtype:
            self.dtype = jnp.int8
            self.pspec = (pool_spec, scale_spec)
            self._sharding = (NamedSharding(mesh, pool_spec),
                              NamedSharding(mesh, scale_spec))
            alloc = jax.jit(
                lambda: (jnp.zeros(shape, jnp.int8),
                         jnp.ones(shape[:-1], jnp.float32)),
                out_shardings=self._sharding)
        else:
            self.dtype = resolve_dtype(cfg.compute_dtype)
            self.pspec = pool_spec
            self._sharding = NamedSharding(mesh, pool_spec)
            alloc = jax.jit(lambda: jnp.zeros(shape, self.dtype),
                            out_shardings=self._sharding)
        self.mesh = mesh
        self.ks = alloc()
        self.vs = alloc()
        # per-OWNER free lists: rank r's slab can only back page-table
        # columns whose positions rank r attends (engine maps column j to
        # owner j // (max_pages/cp)); cp == 1 degenerates to one list
        ppr = self.pages_per_rank
        self._free = [deque(range(r * ppr, (r + 1) * ppr))
                      for r in range(self.cp)]
        self.refcount = np.zeros(num_pages, np.int32)
        # content-addressed prefix index (see class docstring)
        self._children = {}     # chain_key -> [(page_id, tokens_tuple)]
        self._page_keys = {}    # page_id -> parent chain_key (for dereg)
        self.cow_copies = 0
        self._copy_fns = {}
        self._import_fns = {}

    # -- page leasing -----------------------------------------------------
    @property
    def free_pages(self) -> int:
        return sum(len(f) for f in self._free)

    def free_pages_of(self, owner: int) -> int:
        return len(self._free[owner])

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - self.free_pages

    def alloc(self, owner: int = 0) -> int:
        """Lease a free page from `owner`'s slab (the cp rank that must
        physically hold it — column j of a page table belongs to rank
        j // (max_pages/cp)). cp == 1 has the single slab 0."""
        if not self._free[owner]:
            if self.flight is not None:
                self.flight.record("pool_exhausted", owner=owner,
                                   num_pages=self.num_pages)
            raise PoolExhausted(
                f"page pool exhausted (rank {owner}'s slab of "
                f"{self.pages_per_rank} pages fully leased) — the engine "
                f"preempts or the scheduler gates admission")
        page = self._free[owner].popleft()
        self.refcount[page] = 1
        return page

    def ref(self, page: int) -> None:
        assert self.refcount[page] > 0, f"ref of free page {page}"
        self.refcount[page] += 1

    def unref(self, page: int) -> None:
        if not 0 <= page < self.num_pages:
            raise ValueError(f"page {page} out of range [0, {self.num_pages})")
        if self.refcount[page] <= 0:
            raise ValueError(f"page {page} unref'd below zero")
        self.refcount[page] -= 1
        if self.refcount[page] == 0:
            self._deregister(page)
            self._free[page // self.pages_per_rank].append(page)

    # -- prefix index -----------------------------------------------------
    @staticmethod
    def chain_key(parent, tokens) -> tuple:
        """Content key of a page-aligned token run: the tokens chained onto
        the key of everything before them (two pages with identical tokens
        under DIFFERENT prefixes must not collide — K/V depend on the whole
        prefix)."""
        return (parent, tuple(int(t) for t in tokens))

    def register_prefix(self, parent, page: int, tokens) -> None:
        """Index a prompt page under its prefix chain. `tokens` are the
        page's VALID positions — `page_size` of them for a full page (the
        walk may continue past it), fewer for a partial prompt tail (later
        decode writes into the page land beyond the valid run and sharers
        mask them). A page already indexed (a shared donor re-announced by
        a sharer) is skipped — a missed duplicate is only a missed future
        share, never an error."""
        if page in self._page_keys:
            return
        tokens = tuple(int(t) for t in tokens)
        self._children.setdefault(parent, []).append((page, tokens))
        self._page_keys[page] = parent

    def children(self, parent):
        """Candidate next pages under a prefix chain: [(page, tokens)].
        A candidate matching only k < len(tokens) leading tokens is still
        shareable up to k — visibility masks the rest."""
        return self._children.get(parent, [])

    def _deregister(self, page: int) -> None:
        # the chain ROOT's parent key is None, so None cannot double as
        # the "not indexed" sentinel here
        if page not in self._page_keys:
            return
        parent = self._page_keys.pop(page)
        lst = [e for e in self._children.get(parent, []) if e[0] != page]
        if lst:
            self._children[parent] = lst
        else:
            self._children.pop(parent, None)

    # -- copy-on-write ----------------------------------------------------
    def _build_copy(self, m: int):
        sh = self._sharding
        if self.cp == 1:
            def fn(pk, pv, src, dst):
                # dim 1 is the page dim for codes (5-D) and scales (4-D)
                # alike, so one tree-mapped copy serves both pool layouts
                cp = lambda a: a.at[:, dst].set(a[:, src])
                return jax.tree.map(cp, pk), jax.tree.map(cp, pv)

            return jax.jit(fn, donate_argnums=(0, 1),
                           out_shardings=(sh, sh))

        # cp > 1: translate the GLOBAL ids to each rank's local slab inside
        # shard_map so the copy stays shard-local and collective-free (a
        # plain jit over the cp-sharded page dim with dynamic indices would
        # leave XLA free to materialize cross-rank gathers). COW pairs are
        # same-owner by construction (`copy_pages` checks), so a rank
        # either owns both sides (the real copy) or neither (a harmless
        # scratch self-copy, same as the pow2 pad entries).
        pspec = self.pspec

        def fn(pk, pv, src, dst):
            def cp_(a):
                ppr = a.shape[1] - 1
                ls = local_page_ids(src, ppr)
                ld = local_page_ids(dst, ppr)
                return a.at[:, ld].set(a[:, ls])

            return jax.tree.map(cp_, pk), jax.tree.map(cp_, pv)

        fn_sm = jax.shard_map(
            fn, mesh=self.mesh,
            in_specs=(pspec, pspec, P(None), P(None)),
            out_specs=(pspec, pspec))
        return jax.jit(fn_sm, donate_argnums=(0, 1))

    def copy_pages(self, pairs) -> None:
        """Materialise private copies: pairs of (src_page, dst_page), one
        device dispatch (padded to a pow2 bucket with harmless
        scratch->scratch self-copies so the jit variant count stays
        logarithmic)."""
        if not pairs:
            return
        if self.cp > 1:
            ppr = self.pages_per_rank
            for s, d in pairs:
                if s // ppr != d // ppr:
                    raise ValueError(
                        f"COW pair ({s} -> {d}) crosses cp slabs (owners "
                        f"{s // ppr} -> {d // ppr}); a page-table column's "
                        f"replacement must stay with its owning rank")
        m = 1
        while m < len(pairs):
            m *= 2
        src = np.full(m, self.scratch_page, np.int32)
        dst = np.full(m, self.scratch_page, np.int32)
        for i, (s, d) in enumerate(pairs):
            src[i], dst[i] = s, d
        if m not in self._copy_fns:
            self._copy_fns[m] = self._build_copy(m)
        ks, vs = self._copy_fns[m](self.ks, self.vs, jnp.asarray(src),
                                   jnp.asarray(dst))
        self.adopt(ks, vs)
        self.cow_copies += len(pairs)
        if self.flight is not None:
            self.flight.record("cow_copy", pages=len(pairs),
                               free_pages=self.free_pages)

    # -- host-side page transfer (serving fleet v1, ISSUE 19) -------------
    def _page_index(self, page: int) -> int:
        """GLOBAL page id -> index into the pool array's page dim. The
        cp-sharded layout interleaves one rank-local scratch entry after
        every rank's slab (page dim is num_pages + cp), so global page p
        lives at (p // ppr) * (ppr + 1) + p % ppr; cp == 1 degenerates to
        the identity."""
        if not 0 <= page < self.num_pages:
            raise ValueError(f"page {page} out of range "
                             f"[0, {self.num_pages})")
        ppr = self.pages_per_rank
        return (page // ppr) * (ppr + 1) + page % ppr

    def export_pages(self, pages):
        """Bulk host-side READ of `pages` (global ids, any order) for
        streaming to another pool (serving/transfer.py): returns (k, v)
        where each is a numpy array of shape
        (layers, len(pages), kv_heads, page_size, head_dim) — native
        pools — or an int8 (codes, scales) numpy pair for int8 pools
        (scales shaped (layers, len(pages), kv_heads, page_size)).

        The read materialises the GLOBAL head dim whatever 'tp' sharded
        it (jax presents addressable sharded arrays globally), so an
        importer at a DIFFERENT tp width just scatters the payload under
        its own sharding — the any-layout-to-any-layout reshard the
        cross-mesh transfer papers formalise, done host-side at page
        granularity. Pages stay leased; exporting does not change
        refcounts."""
        idx = np.asarray([self._page_index(int(p)) for p in pages],
                         np.int64)
        take = lambda a: np.asarray(a[:, idx])
        return jax.tree.map(take, self.ks), jax.tree.map(take, self.vs)

    def import_pages(self, k, v, owners=None) -> List[int]:
        """Bulk LEASE + WRITE of a payload produced by `export_pages` on
        another pool (possibly different tp/cp width): leases one page
        per payload entry (refcount 1 — the caller's page-table row owns
        them), scatters K and V in ONE donating device dispatch (pow2-
        bucketed like copy_pages, pads aimed at the scratch entry), and
        returns the global page ids in payload order. `owners[i]` names
        the cp slab page i must come from (page-table column ownership);
        default all slab 0 (cp == 1). On PoolExhausted every page leased
        so far is returned before the raise — no partial lease leaks."""
        if self.kv_dtype:
            if not (isinstance(k, tuple) and isinstance(v, tuple)):
                raise ValueError("int8 pool import needs (codes, scales) "
                                 "payload tuples (export_pages on an int8 "
                                 "pool produces them)")
            n, ps, hd = k[0].shape[1], k[0].shape[3], k[0].shape[4]
        else:
            if isinstance(k, tuple) or isinstance(v, tuple):
                raise ValueError("native pool cannot import an int8 "
                                 "(codes, scales) payload — kv_dtype must "
                                 "match across the transfer")
            n, ps, hd = k.shape[1], k.shape[3], k.shape[4]
        if ps != self.page_size:
            raise ValueError(f"payload page_size {ps} != pool page_size "
                             f"{self.page_size} (pages are the transfer "
                             f"unit; both sides must agree)")
        want_hd = (self.ks[0] if self.kv_dtype else self.ks).shape[4]
        if hd != want_hd:
            raise ValueError(f"payload head_dim {hd} != pool head_dim "
                             f"{want_hd} (different model shapes)")
        if owners is not None and len(owners) != n:
            raise ValueError(f"owners has {len(owners)} entries for {n} "
                             f"payload pages")
        pages: List[int] = []
        try:
            for i in range(n):
                pages.append(self.alloc(owners[i] if owners else 0))
        except PoolExhausted:
            for p in pages:
                self.unref(p)
            raise
        m = 1
        while m < n:
            m *= 2
        # pad entries aim at slab 0's scratch entry (array index ppr) and
        # rewrite it with payload page 0 — scratch is quarantined garbage
        # by contract, so the duplicate-index scatter is harmless
        idx = np.full(m, self.pages_per_rank, np.int32)
        for i, p in enumerate(pages):
            idx[i] = self._page_index(p)
        pad = lambda a: np.concatenate(
            [a, np.repeat(a[:, :1], m - n, axis=1)], axis=1) if m > n else a
        nk = jax.tree.map(pad, k)
        nv = jax.tree.map(pad, v)
        if m not in self._import_fns:
            self._import_fns[m] = self._build_import()
        ks, vs = self._import_fns[m](self.ks, self.vs, nk, nv,
                                     jnp.asarray(idx))
        self.adopt(ks, vs)
        return pages

    def _build_import(self):
        sh = self._sharding

        def fn(pk, pv, nk, nv, idx):
            # dim 1 is the page dim for codes (5-D) and scales (4-D)
            # alike; one tree-mapped scatter serves both pool layouts
            put = lambda a, b: a.at[:, idx].set(b.astype(a.dtype))
            return jax.tree.map(put, pk, nk), jax.tree.map(put, pv, nv)

        return jax.jit(fn, donate_argnums=(0, 1), out_shardings=(sh, sh))

    # -- device-array handoff ---------------------------------------------
    def adopt(self, ks, vs) -> None:
        self.ks, self.vs = ks, vs
