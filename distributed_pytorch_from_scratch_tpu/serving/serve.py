"""Offline serving benchmark CLI: loadgen -> continuous-batching engine.

`python -m distributed_pytorch_from_scratch_tpu.serving.serve \
     --ckpt_dir ... --tokenizer_path ... --rate 4 --num_requests 64`

Drives the continuous-batching engine (serving/engine.py) with a synthetic
Poisson/burst arrival stream (or a replayed trace) and reports the serving
metrics — TTFT / TPOT / queue-wait p50/p95, slot occupancy, tokens/s — as:

* ONE machine-readable JSON line on stdout (the bench.py convention),
* `serving_summary` + per-request `serve_request` MetricsWriter events and
  Chrome-trace spans (prefill / decode_step per dispatch) under --log_dir,
  so `scripts/summarize_run.py` and the Perfetto timeline render a serving
  run exactly like a training run.

`--random_init` serves fresh random weights at the flag shape (throughput
and latency depend on shapes, not values — checkpoint-free benchmarking,
the bench.py --decode convention). `--dry_run` shrinks everything to a
tiny CPU-runnable smoke (tier-1 coverage: the CLI surface cannot rot on
images without chips).
"""

from __future__ import annotations

import argparse
import json
import sys

import dataclasses

from ..cli import add_model_shape_args, build_model_config
from ..obs.runindex import run_stamp
from ..config import (BOS_TOKEN, EOS_TOKEN, MODEL_PRESETS, MeshConfig,
                      ModelConfig, model_preset)
from ..runtime.mesh import make_mesh

_DRY_CFG = ModelConfig(attn_dim=32, ffn_dim=64, num_heads=4, num_layers=2,
                       vocab_size=64, maxlen=64)
# dry-run drafter: even smaller than the dry target, so the smoke actually
# exercises the drafter-cheaper-than-target shape the feature assumes
_DRY_DRAFTER_CFG = ModelConfig(attn_dim=16, ffn_dim=32, num_heads=2,
                               num_layers=1, vocab_size=64, maxlen=64)


def get_serve_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__)
    g = p.add_argument_group("model")
    g.add_argument("--ckpt_dir", default=None,
                   help="serve this checkpoint (validated complete before "
                        "assembly); omit with --random_init/--dry_run")
    g.add_argument("--iter", type=int, default=None,
                   help="checkpoint iteration (default: latest)")
    g.add_argument("--random_init", action="store_true",
                   help="serve fresh random weights at the flag shape "
                        "(checkpoint-free load benchmarking)")
    g.add_argument("--tokenizer_path", "-t", default=None,
                   help="supplies vocab_size and the real EOS id; omit to "
                        "use --vocab_size and EOS id 1 (the shipped "
                        "tokenizer's convention)")
    g.add_argument("--vocab_size", type=int, default=1024,
                   help="vocab for --random_init without a tokenizer")
    g.add_argument("--family", choices=["llama", "gpt2"], default="llama")
    g.add_argument("--tp_size", type=int, default=1)
    add_model_shape_args(g)

    g = p.add_argument_group("engine")
    g.add_argument("--slots", type=int, default=8,
                   help="KV-pool slots = max concurrently decoding requests")
    g.add_argument("--buf_len", type=int, default=0,
                   help="per-slot cache length (0 = longest prompt + "
                        "--max_new_tokens + 2)")
    g.add_argument("--max_new_tokens", type=int, default=64)
    g.add_argument("--prefill_bucket", type=int, default=64,
                   help="prefill width bucket (prompts pad to a multiple "
                        "of this, not to the full buffer); 0 = off")
    g.add_argument("--max_prefill_batch", type=int, default=4,
                   help="max prompts per prefill dispatch (same-bucket "
                        "FIFO neighbours ride together)")
    g.add_argument("--queue_limit", type=int, default=0,
                   help="backpressure: max waiting requests (arrivals past "
                        "it are rejected and counted); 0 = unbounded")
    g.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy; > 0 samples with per-request seeds")
    g.add_argument("--decode_top_k", type=int, default=0)
    g.add_argument("--decode_top_p", type=float, default=0.0)
    g.add_argument("--decode_weight_dtype", choices=["native", "int8"],
                   default="native",
                   help="'int8' serves weight-only-quantized decode "
                        "weights (per-output-channel scales, dequant-on-"
                        "use inside the compiled programs — cuts the "
                        "weight-read HBM floor; ops/quant.py). Works for "
                        "both engines")

    g = p.add_argument_group("paged engine (serving v2)")
    g.add_argument("--paged", action="store_true",
                   help="serve through the PAGED engine: page-table KV "
                        "cache with COW prefix reuse, chunked prefill, and "
                        "the SLO-aware scheduler (docs/SERVING.md v2)")
    g.add_argument("--page_size", type=int, default=64,
                   help="--paged: tokens per KV page")
    g.add_argument("--kv_dtype", choices=["native", "int8"],
                   default="native",
                   help="--paged: KV-page storage dtype. 'int8' stores "
                        "block-scaled codes + per-head-vector scales "
                        "(~2x the tokens per HBM byte at hd 64; greedy "
                        "quality pinned in tests/test_quant.py); the "
                        "speculative drafter pool inherits it")
    g.add_argument("--paged_attn", choices=["gather", "pallas"],
                   default="gather",
                   help="--paged: the attend over the page table. "
                        "'gather' materializes the dense page view per "
                        "step (the oracle); 'pallas' walks the "
                        "(slots, max_pages) table in place on TPU "
                        "(ops/pallas/paged_attention.py — no per-step "
                        "HBM copy of the context, int8 dequant fused "
                        "into the block loop). Token-identical greedy "
                        "output by contract; non-TPU backends fall back "
                        "to gather with a one-time warning")
    g.add_argument("--num_pages", type=int, default=0,
                   help="--paged: page-pool HBM budget in pages (0 = "
                        "slots x ceil(buf_len/page_size), i.e. no "
                        "oversubscription — raise slots past the pool to "
                        "oversubscribe)")
    g.add_argument("--cp", type=int, default=1,
                   help="--paged: context-parallel ranks; the page pool "
                        "shards over the 'cp' mesh axis (each rank owns "
                        "1/cp of the pages, so per-chip KV bytes shrink "
                        "~1/cp at equal context), chunked prefill rings "
                        "the query chunk around cp, and decode combines "
                        "per-rank partial (out, lse). Greedy output is "
                        "token-identical to cp=1 (docs/SERVING.md, "
                        "ISSUE 18). The speculative drafter stays cp=1")
    g.add_argument("--prefill_chunk", type=int, default=128,
                   help="--paged: prefill positions per chunk; a live "
                        "stream's decode never stalls by more than one "
                        "chunk")
    g.add_argument("--slo_classes", default="interactive=0.25,standard=1.0,"
                                            "batch=8.0",
                   help="--paged: TTFT deadline classes, name=seconds "
                        "pairs (scheduler.parse_slo_classes)")
    g.add_argument("--default_class", default="standard",
                   help="--paged: class for requests that name none")
    g.add_argument("--class_mix", default="",
                   help="loadgen: draw request classes by weight, e.g. "
                        "'interactive=1,batch=1' (empty = default class)")
    g.add_argument("--tenants", type=int, default=1,
                   help="loadgen: spread requests over N tenants "
                        "(the fair-queuing axis)")
    g.add_argument("--shared_prefix_len", type=int, default=0,
                   help="loadgen: prepend one common random prefix of N "
                        "tokens to every prompt (system-prompt stand-in; "
                        "feeds the COW prefix cache)")
    g.add_argument("--interleave", action="store_true",
                   help="loadgen: alternate short/long prompts "
                        "(prompt_len_min / prompt_len_max) instead of "
                        "uniform lengths — the head-of-line stress")

    g = p.add_argument_group("speculative decoding (--paged only)")
    g.add_argument("--speculate", type=int, default=0, metavar="K",
                   help="draft K tokens per round with the drafter model "
                        "and verify them in ONE target dispatch (exact "
                        "rejection sampling — greedy output is token-"
                        "identical to the plain paged engine); 0 = off")
    g.add_argument("--drafter_model", choices=sorted(MODEL_PRESETS),
                   default="tiny",
                   help="drafter shape preset (vocab is forced to the "
                        "target's; ROADMAP's cheap-drafter default is "
                        "'tiny')")
    g.add_argument("--drafter_ckpt_dir", default=None,
                   help="load drafter weights from this checkpoint "
                        "(default: random init — fine for latency "
                        "benchmarks, useless acceptance on real text)")
    g.add_argument("--drafter_iter", type=int, default=None,
                   help="drafter checkpoint iteration (default: latest)")
    g.add_argument("--drafter_pages", type=int, default=0,
                   help="drafter page-pool budget in pages (0 = every "
                        "slot can hold its full drafter row); counts "
                        "against the serving HBM budget in bench A/Bs")
    g.add_argument("--debug_host_sampler", action="store_true",
                   help="ABLATION: switch to host-side sampling "
                        "(materialises full-vocab logits on the host every "
                        "step) — prices the host-round-trip cost the fused "
                        "in-program sampler (the production path since the "
                        "engines shipped) avoids; excludes --speculate")

    g = p.add_argument_group("loadgen")
    g.add_argument("--num_requests", type=int, default=32)
    g.add_argument("--rate", type=float, default=4.0,
                   help="poisson arrival rate, requests/second")
    g.add_argument("--arrival", choices=["poisson", "burst", "replay"],
                   default="poisson")
    g.add_argument("--replay", default=None,
                   help="jsonl trace for --arrival replay (loadgen.py "
                        "schema)")
    g.add_argument("--prompt_len_min", type=int, default=8)
    g.add_argument("--prompt_len_max", type=int, default=64)
    g.add_argument("--seed", type=int, default=0)

    g = p.add_argument_group("observability")
    g.add_argument("--trace_requests", action="store_true",
                   help="per-request span timelines (obs/reqtrace.py): "
                        "every request emits a request_trace event + a "
                        "Chrome-trace track under --log_dir, and the "
                        "summary carries the k-worst-TTFT/TPOT exemplars "
                        "WITH their timelines (docs/OBSERVABILITY.md)")
    g.add_argument("--flight_records", action="store_true",
                   help="anomaly flight recorder (obs/flight.py): pool "
                        "stats + scheduler decisions ring-buffered; "
                        "PoolExhausted preemptions and SLO-attainment "
                        "collapses dump flightdump_*.json to --log_dir")
    g.add_argument("--flight_ring", type=int, default=512,
                   help="--flight_records: ring capacity (events); "
                        "0 disables the recorder (train.py semantics)")
    g.add_argument("--metrics_port", type=int, default=None,
                   help="live telemetry exporter (obs/telemetry.py): "
                        "serve gauges/counters at http://127.0.0.1:PORT"
                        "/metrics.json (JSON) and /metrics (Prometheus "
                        "text); 0 = ephemeral (the bound port is printed "
                        "and lands in the summary record). A busy port "
                        "refuses loudly up front")
    g.add_argument("--rollup_interval", type=float, default=1.0,
                   help="--metrics_port: seconds between "
                        "telemetry_snapshot events mirrored into "
                        "metrics.jsonl (the fleet collector's food)")
    g.add_argument("--profile_on_anomaly", type=int, default=0,
                   metavar="STEPS",
                   help="arm a bounded jax.profiler window of N decode "
                        "steps when a flight dump fires (PoolExhausted "
                        "preemption, SLO collapse), cross-linked from "
                        "the dump's 'profile' field; needs "
                        "--flight_records; 0 = off")
    g.add_argument("--profile_every", type=int, default=0, metavar="N",
                   help="duty-cycled MEASURED attribution "
                        "(training/metrics.DutyCycleProfiler): every N "
                        "decode steps capture a --profile_window-step "
                        "jax.profiler window, parse it (obs/profparse) "
                        "and land a profile_attribution event in the "
                        "--log_dir metrics chain; 0 = off (exactly zero "
                        "cost: no captures, no events)")
    g.add_argument("--profile_window", type=int, default=4, metavar="W",
                   help="--profile_every: decode steps per capture "
                        "window (must be <= N)")
    g.add_argument("--profile_budget_mb", type=float, default=64.0,
                   help="--profile_every: total on-disk capture budget; "
                        "exhaustion stops sampling BETWEEN windows "
                        "(never mid-window), counted in the summary")
    g.add_argument("--metrics_max_mb", type=float, default=0.0,
                   help="rotate metrics.jsonl past N MiB (-> "
                        "metrics.001.jsonl ... via schema-valid "
                        "'rotated' continuation events; consumers "
                        "follow the chain); 0 = unbounded")
    g.add_argument("--control", choices=["off", "advise", "act"],
                   default="off",
                   help="the obs v5 control plane (obs/control.py, "
                        "serving/controller.py): 'advise' computes SLO/"
                        "admission and drift-retune decisions and lands "
                        "them in the decision ledger with applied=false "
                        "(nothing mutates); 'act' additionally moves the "
                        "knobs — prefill chunk, admission limit, "
                        "speculation K, pages_per_block — at registered "
                        "safe points only. 'off' (default) is zero-cost: "
                        "no advisor, no events, no record fields")
    g.add_argument("--control_interval", type=int, default=32,
                   help="--control: decode steps between SLO-controller "
                        "evaluations (the adaptation + cooldown window)")
    g.add_argument("--control_force", action="store_true",
                   help="--control act: let an online pages_per_block "
                        "retune overwrite a SWEPT block-cache entry "
                        "(default: the write is refused and the decision "
                        "lands applied=false with the refusal — online "
                        "never silently shadows a sweep)")

    g = p.add_argument_group("other")
    g.add_argument("--log_dir", default="serve_logs",
                   help="obs output: trace.jsonl/trace.json spans + "
                        "metrics.jsonl events")
    g.add_argument("--dry_run", action="store_true",
                   help="tiny random-init model + a 6-request burst on CPU "
                        "— the tier-1 smoke; ignores --ckpt_dir")
    args = p.parse_args(argv)
    if (args.decode_top_k or args.decode_top_p) and not args.temperature:
        p.error("--decode_top_k/--decode_top_p need --temperature > 0")
    if args.cp < 1:
        p.error(f"--cp must be >= 1, got {args.cp}")
    # class/tenant mixes and the page budget only matter to the paged
    # engine; a silent no-op would misreport what the run measured
    if not args.paged:
        if args.num_pages:
            p.error("--num_pages is a --paged knob")
        if args.kv_dtype != "native":
            p.error("--kv_dtype is a --paged knob (the slot pool stores "
                    "the compute dtype; only PagedKVPool quantizes)")
        if args.paged_attn != "gather":
            p.error("--paged_attn is a --paged knob (the slot engine has "
                    "no page table to walk)")
        if args.cp != 1:
            p.error("--cp is a --paged knob (only the page pool shards "
                    "over cp; the slot engine replicates its caches — "
                    "add --paged for long-context cp serving)")
        if args.class_mix:
            p.error("--class_mix needs --paged (the FIFO engine has no "
                    "SLO classes)")
        if args.tenants != 1:
            p.error("--tenants needs --paged (the FIFO engine ignores "
                    "tenants — the run would measure nothing fair)")
    if args.speculate:
        if not args.paged:
            p.error("--speculate runs over the paged cache; add --paged")
        if args.debug_host_sampler:
            p.error("--debug_host_sampler is the NON-speculative ablation "
                    "knob (a speculative round never materialises host "
                    "logits); drop --speculate to measure it")
        if args.drafter_iter is not None and not args.drafter_ckpt_dir:
            p.error("--drafter_iter needs --drafter_ckpt_dir (without one "
                    "the drafter is random-init and the iter is ignored)")
    elif (args.drafter_ckpt_dir or args.drafter_pages
          or args.drafter_iter is not None):
        p.error("--drafter_ckpt_dir/--drafter_iter/--drafter_pages need "
                "--speculate K")
    if args.arrival == "replay" and not args.replay and not args.dry_run:
        p.error("--arrival replay needs --replay PATH")
    if args.profile_on_anomaly and not args.flight_records:
        p.error("--profile_on_anomaly arms on flight-dump triggers; add "
                "--flight_records")
    if args.profile_every:
        if args.profile_on_anomaly:
            p.error("--profile_every excludes --profile_on_anomaly (both "
                    "drive the one-capture-at-a-time device profiler; "
                    "pick the duty cycle or the anomaly trigger)")
        if not args.log_dir:
            p.error("--profile_every needs a metrics dir: the parsed "
                    "profile_attribution events land in --log_dir's "
                    "metrics chain (point --log_dir somewhere writable)")
        if not 1 <= args.profile_window <= args.profile_every:
            p.error(f"--profile_window must be in [1, --profile_every] "
                    f"(a window longer than the duty period would re-arm "
                    f"mid-capture), got window {args.profile_window} with "
                    f"every {args.profile_every}")
        if args.profile_budget_mb <= 0:
            p.error(f"--profile_budget_mb must be > 0, got "
                    f"{args.profile_budget_mb}")
    if args.control != "off":
        if not args.paged:
            p.error("--control drives the paged engine's scheduler "
                    "admission and prefill chunking (the slot engine has "
                    "none of those knobs); add --paged")
        if args.control_interval < 1:
            p.error(f"--control_interval must be >= 1, got "
                    f"{args.control_interval}")
    if args.control_force and args.control != "act":
        p.error("--control_force needs --control act (only act mode "
                "writes the block cache; nothing can shadow a swept "
                "entry otherwise)")
    if args.metrics_port is not None and args.metrics_port < 0:
        p.error(f"--metrics_port must be >= 0 (0 = ephemeral), got "
                f"{args.metrics_port}")
    if args.metrics_port is not None and args.rollup_interval <= 0:
        p.error("--rollup_interval must be > 0 (seconds between "
                "telemetry_snapshot events)")
    if not args.dry_run and not args.random_init and not args.ckpt_dir:
        p.error("pick a weight source: --ckpt_dir, --random_init, or "
                "--dry_run")
    return args


def require_writable_dir(path: str, why: str) -> None:
    """Loud up-front refusal when an obs output dir cannot take writes:
    a traced run that silently drops its timelines is worse than no run
    (the flags' whole point is the post-mortem artifact)."""
    import os

    try:
        os.makedirs(path, exist_ok=True)
        probe = os.path.join(path, ".obs_write_probe")
        with open(probe, "w") as f:
            f.write("ok")
        os.remove(probe)
    except OSError as e:
        raise SystemExit(
            f"{why}: trace output dir {path!r} is not writable "
            f"({type(e).__name__}: {e}) — point --log_dir at a writable "
            f"directory or drop the flag")


def _load_params(args, model, mesh):
    import jax

    if args.random_init or args.dry_run or not args.ckpt_dir:
        return jax.device_put(model.init(jax.random.key(args.seed)),
                              model.shardings(mesh))
    from ..training.checkpoint import latest_step, load_checkpoint
    step = args.iter if args.iter is not None else latest_step(args.ckpt_dir)
    if step is None:
        raise SystemExit(f"no checkpoints found in {args.ckpt_dir}")
    # load_checkpoint refuses an incomplete shard set up front with the
    # missing-rank list (training/checkpoint.validate_checkpoint) — no
    # KeyError mid-assemble, no separate pre-check needed
    template = jax.eval_shape(lambda: model.init(jax.random.key(0)))
    params, _, _ = load_checkpoint(args.ckpt_dir, step, template,
                                   model.specs())
    print(f"serving checkpoint iter {step} from {args.ckpt_dir}",
          file=sys.stderr)
    return jax.device_put(params, model.shardings(mesh))


def _build_drafter(args, vocab_size: int, mesh, family: str):
    """Drafter model + params for --speculate: the named preset reshaped to
    the TARGET's vocab (the verify step compares distributions over one
    vocabulary), weights from --drafter_ckpt_dir or random init."""
    import jax

    if args.dry_run:
        dcfg = _DRY_DRAFTER_CFG
    else:
        dcfg = model_preset(args.drafter_model)
    dcfg = dataclasses.replace(
        dcfg, vocab_size=vocab_size,
        compute_dtype="bfloat16" if getattr(args, "bf16", True) and
        not args.dry_run else "float32")
    if family == "gpt2":
        from ..models.gpt2 import GPT2Transformer
        dmodel = GPT2Transformer(dcfg, tp_size=args.tp_size)
    else:
        from ..models.transformer import Transformer
        dmodel = Transformer(dcfg, tp_size=args.tp_size)
    if args.drafter_ckpt_dir:
        from ..training.checkpoint import latest_step, load_checkpoint
        step = (args.drafter_iter if args.drafter_iter is not None
                else latest_step(args.drafter_ckpt_dir))
        if step is None:
            raise SystemExit(
                f"no drafter checkpoints found in {args.drafter_ckpt_dir}")
        template = jax.eval_shape(lambda: dmodel.init(jax.random.key(0)))
        dparams, _, _ = load_checkpoint(args.drafter_ckpt_dir, step,
                                        template, dmodel.specs())
        print(f"drafter checkpoint iter {step} from {args.drafter_ckpt_dir}",
              file=sys.stderr)
    else:
        dparams = dmodel.init(jax.random.key(args.seed + 1))
    return dmodel, jax.device_put(dparams, dmodel.shardings(mesh))


def serve(args: argparse.Namespace) -> dict:
    import time as _time

    from ..obs import (FlightRecorder, RequestTracer, SpanTracer,
                       TelemetryExporter)
    from ..training.metrics import AnomalyProfiler, MetricsWriter
    from .engine import ContinuousBatchingEngine
    from .loadgen import replay_requests, run_loadgen, synthetic_requests

    if args.trace_requests or args.flight_records \
            or args.metrics_port is not None or args.profile_every:
        require_writable_dir(
            args.log_dir,
            "--trace_requests/--flight_records/--metrics_port/"
            "--profile_every")

    eos_id = 1  # the shipped tokenizer's EOS (tokenizer/tokenizer.json)
    vocab_size = args.vocab_size
    if args.tokenizer_path:
        from tokenizers import Tokenizer as HFTokenizer
        tok = HFTokenizer.from_file(args.tokenizer_path)
        vocab_size = tok.get_vocab_size()
        eos_id = tok.token_to_id(EOS_TOKEN)
        if eos_id is None or tok.token_to_id(BOS_TOKEN) is None:
            raise SystemExit(f"tokenizer {args.tokenizer_path} lacks the "
                             f"{BOS_TOKEN}/{EOS_TOKEN} specials")

    if args.dry_run:
        cfg = _DRY_CFG
        vocab_size = cfg.vocab_size
        args.slots, args.max_prefill_batch = 4, 2
        args.num_requests, args.arrival = 6, "burst"
        args.prompt_len_min, args.prompt_len_max = 4, 12
        args.max_new_tokens = min(args.max_new_tokens, 8)
        args.buf_len, args.prefill_bucket = 24, 8
        if args.paged:       # tiny pages so the smoke crosses boundaries
            args.page_size, args.prefill_chunk = 8, 8
            args.num_pages = 0
            if not args.class_mix:
                args.class_mix = "interactive=1,standard=1"
            args.shared_prefix_len = max(args.shared_prefix_len, 4)
    else:
        cfg = build_model_config(args, vocab_size)

    mesh = make_mesh(MeshConfig(tp=args.tp_size, cp=args.cp))
    if args.family == "gpt2":
        from ..models.gpt2 import GPT2Transformer
        model = GPT2Transformer(cfg, tp_size=args.tp_size, cp_size=args.cp)
    else:
        from ..models.transformer import Transformer
        model = Transformer(cfg, tp_size=args.tp_size, cp_size=args.cp)
    params = _load_params(args, model, mesh)

    if args.arrival == "replay" and args.replay:
        requests = replay_requests(args.replay)
    else:
        from .scheduler import parse_slo_classes
        mix = parse_slo_classes(args.class_mix) if args.class_mix else None
        requests = synthetic_requests(
            args.num_requests, args.prompt_len_min, args.prompt_len_max,
            args.max_new_tokens, vocab_size, seed=args.seed,
            rate=args.rate, arrival=args.arrival, class_mix=mix,
            tenants=args.tenants,
            shared_prefix_len=args.shared_prefix_len,
            interleave=args.interleave)
    longest = max(len(r.prompt) for r in requests)
    buf_len = args.buf_len or (longest + args.max_new_tokens + 2)
    cap = getattr(model, "max_decode_positions", None)
    if cap is not None and buf_len > cap:
        if cap < longest + 2:
            raise SystemExit(f"prompts need {longest + 2} positions but the "
                             f"model's position table has {cap}")
        print(f"Warning: clamping serve buffer {buf_len} -> {cap} (learned "
              f"position table size)", file=sys.stderr)
        buf_len = cap

    tracer = SpanTracer(args.log_dir, process_name="serve")
    writer = MetricsWriter(args.log_dir, process_index=0,
                           max_bytes=int(args.metrics_max_mb * 2**20))
    # live telemetry exporter (ISSUE 12): starts BEFORE the engine so a
    # hung prefill is still scrapeable; a busy port dies loudly here
    telemetry = None
    if args.metrics_port is not None:
        telemetry = TelemetryExporter(
            writer=writer, rollup_interval=args.rollup_interval)
        port = telemetry.start(args.metrics_port)
        print(f"telemetry exporter: http://127.0.0.1:{port}/metrics.json "
              f"(Prometheus text at /metrics)", file=sys.stderr)
    elif args.control != "off":
        # headless registry (no HTTP endpoint): controller decisions
        # cross-link a telemetry_snapshot emitted at decision time, so
        # the control plane needs the registry even without --metrics_port
        telemetry = TelemetryExporter(writer=writer)
    profiler = (AnomalyProfiler(args.log_dir,
                                window_steps=args.profile_on_anomaly,
                                writer=writer)
                if args.profile_on_anomaly and args.flight_ring > 0
                else None)
    duty = None
    if args.profile_every:
        from ..training.metrics import DutyCycleProfiler
        duty = DutyCycleProfiler(args.log_dir, args.profile_every,
                                 args.profile_window,
                                 args.profile_budget_mb, writer=writer)
    flight = (FlightRecorder(args.log_dir, maxlen=args.flight_ring,
                             profiler=profiler)
              if args.flight_records and args.flight_ring > 0 else None)
    rt = (RequestTracer(writer=writer, tracer=tracer, flight=flight,
                        clock=_time.monotonic)
          if args.trace_requests else None)
    controller = advisor = None
    try:
        kv_dtype = None if args.kv_dtype == "native" else args.kv_dtype
        wdtype = (None if args.decode_weight_dtype == "native"
                  else args.decode_weight_dtype)
        if args.paged:
            from .scheduler import parse_slo_classes
            paged_kw = dict(
                num_slots=args.slots, buf_len=buf_len, eos_id=eos_id,
                page_size=args.page_size, num_pages=args.num_pages,
                prefill_chunk=args.prefill_chunk,
                temperature=args.temperature, top_k=args.decode_top_k,
                top_p=args.decode_top_p, kv_dtype=kv_dtype,
                decode_weight_dtype=wdtype,
                paged_attn_impl=args.paged_attn,
                slo_classes=parse_slo_classes(args.slo_classes),
                default_class=args.default_class,
                max_queue=args.queue_limit, tracer=tracer, writer=writer,
                request_tracer=rt, flight=flight, telemetry=telemetry,
                duty_profiler=duty)
            if args.speculate:
                from .speculative import SpeculativeEngine
                dmodel, dparams = _build_drafter(args, cfg.vocab_size, mesh,
                                                 args.family)
                engine = SpeculativeEngine(
                    model, mesh, params, dmodel, dparams,
                    speculate_k=args.speculate,
                    drafter_pages=args.drafter_pages, **paged_kw)
            else:
                from .engine import PagedEngine
                engine = PagedEngine(
                    model, mesh, params,
                    debug_host_sampler=args.debug_host_sampler, **paged_kw)
        else:
            engine = ContinuousBatchingEngine(
                model, mesh, params, num_slots=args.slots, buf_len=buf_len,
                eos_id=eos_id, temperature=args.temperature,
                top_k=args.decode_top_k, top_p=args.decode_top_p,
                prefill_bucket=args.prefill_bucket,
                max_prefill_batch=args.max_prefill_batch,
                max_queue=args.queue_limit,
                debug_host_sampler=args.debug_host_sampler,
                decode_weight_dtype=wdtype,
                tracer=tracer, writer=writer,
                request_tracer=rt, flight=flight, telemetry=telemetry,
                duty_profiler=duty)
        if args.control != "off":
            from ..obs.control import RetuneAdvisor, control_safe_point
            from .controller import SLOController
            controller = SLOController(engine, args.control, writer=writer,
                                       telemetry=telemetry,
                                       interval=args.control_interval)
            # the engine's decorated _control_tick (its host-side decode
            # tick) is the safe point that drives tick()+apply_decisions()
            engine.controller = controller
            if duty is not None:
                # drift-driven retuning rides the duty profiler: the
                # on_attribution hook fires BETWEEN capture windows (a
                # registered safe point), with the parsed reconcile
                advisor = RetuneAdvisor(args.control, writer=writer,
                                        telemetry=telemetry)
                advisor.register_knob(
                    "prefill_chunk",
                    lambda: engine.prefill_chunk,
                    lambda v: setattr(engine, "prefill_chunk", int(v)),
                    lo=1)
                if args.speculate:
                    advisor.register_knob(
                        "speculate_k", lambda: engine.k,
                        lambda v: setattr(engine, "k", int(v)), lo=1)
                last_capture = {"id": None}
                if args.paged_attn == "pallas":
                    from ..ops.pallas.paged_attention import (
                        PagedBlockConfig, get_paged_block_config,
                        record_online_paged_config)
                    hd = cfg.attn_dim // cfg.num_heads
                    kvd = (None if args.kv_dtype == "native"
                           else args.kv_dtype)
                    advisor.register_knob(
                        "pages_per_block",
                        lambda: get_paged_block_config(
                            args.page_size, hd, kvd).pages_per_block,
                        lambda v: record_online_paged_config(
                            args.page_size, hd, kvd,
                            PagedBlockConfig(int(v)),
                            capture=last_capture["id"],
                            force=args.control_force),
                        lo=1)

                @control_safe_point
                def _on_attribution(fields):
                    # between capture windows: observe, then actuate —
                    # the decoration is the graftcheck registration
                    last_capture["id"] = (fields or {}).get("capture")
                    advisor.observe_attribution(fields)
                    from ..training.metrics import hbm_watermarks
                    marks = hbm_watermarks()
                    advisor.observe_hbm({"devices": marks or [],
                                         "available": marks is not None})
                    advisor.apply_decisions()

                duty.on_attribution = _on_attribution
        summary = run_loadgen(engine, requests)
    finally:
        # profiler before exporter before writer: an open capture window
        # finalises (and parses into its profile_attribution event), the
        # exporter's LAST snapshot event lands, then the jsonl stream
        # closes
        if profiler is not None:
            profiler.close()
        if duty is not None:
            duty.close()
        # control plane after the duty profiler (its close() can finalise
        # a window and hand the advisor one last reconcile) and before
        # the exporter/writer (ledger flushes are events)
        if advisor is not None:
            advisor.close()
        if controller is not None:
            controller.close()
        if telemetry is not None:
            telemetry.close()
        path = tracer.close()
        writer.close()
    fmt = lambda v: "-" if v is None else f"{v:.1f}"
    print(f"serve[{args.family} tp{args.tp_size}]: {summary['completed']}/"
          f"{summary['requests']} requests ({summary['rejected']} rejected) "
          f"in {summary['wall_s']:.1f}s — "
          f"{summary['tokens_per_sec']:.0f} tok/s, occupancy "
          f"{summary['slot_occupancy_mean']:.2f}, TTFT p50/p95 "
          f"{fmt(summary['ttft_ms_p50'])}/{fmt(summary['ttft_ms_p95'])}ms, "
          f"TPOT p50/p95 {fmt(summary['tpot_ms_p50'])}/"
          f"{fmt(summary['tpot_ms_p95'])}ms, queue p50/p95 "
          f"{fmt(summary['queue_wait_ms_p50'])}/"
          f"{fmt(summary['queue_wait_ms_p95'])}ms"
          + (f"; pad waste eliminated "
             f"{100 * summary['prefill_pad_waste_eliminated']:.0f}%"
             if summary["prefill_pad_waste_eliminated"] > 0 else "")
          + (f"; kv util {summary['kv_util_mean']:.2f}, prefix hits "
             f"{100 * summary['prefix_hit_rate']:.0f}%, "
             f"{summary['preemptions']} preempted"
             if "kv_util_mean" in summary else "")
          + (f"; spec k={summary['speculate_k']}: "
             f"{summary['accepted_tokens_per_dispatch']:.2f} tok/dispatch, "
             f"acceptance {100 * summary['acceptance_rate']:.0f}%"
             if "speculate_k" in summary else "")
          + (f"; trace {path}" if path else ""), file=sys.stderr)
    rec = {
        "metric": (f"serving tokens/sec ({args.family}, tp={args.tp_size}, "
                   + ("paged, " if args.paged else "")
                   + (f"speculate k={args.speculate} "
                      f"({args.drafter_model} drafter), "
                      if args.speculate else "")
                   + ("HOST-sampler ablation, "
                      if args.debug_host_sampler else "")
                   + f"slots={args.slots}, {args.arrival} arrivals"
                   + (f" @{args.rate:g}/s" if args.arrival == "poisson"
                      else "") + ")"),
        "value": summary["tokens_per_sec"],
        "unit": "tokens/sec (serving)",
        **{k: summary[k] for k in (
            "requests", "completed", "rejected", "invalid", "wall_s",
            "slot_occupancy_mean", "ttft_ms_p50", "ttft_ms_p95",
            "tpot_ms_p50", "tpot_ms_p95", "queue_wait_ms_p50",
            "queue_wait_ms_p95", "prefill_pad_waste_eliminated")},
    }
    for k in ("kv_dtype", "paged_attn", "cp", "pages_per_rank", "num_pages",
              "kv_util_mean", "kv_fragmentation_mean", "prefix_hit_rate",
              "cow_copies", "preemptions", "max_live",
              "max_interleaved_prefill_positions", "slo_attainment",
              "speculate_k", "spec_rounds", "accepted_tokens_per_dispatch",
              "acceptance_rate", "acceptance_rate_by_position",
              "rounds_per_request", "drafter_ms_total", "target_ms_total",
              "worst_ttft_rids", "worst_tpot_rids"):
        if k in summary:
            rec[k] = summary[k]
    if args.debug_host_sampler:
        rec["debug_host_sampler"] = True
    if args.decode_weight_dtype != "native":
        rec["decode_weight_dtype"] = args.decode_weight_dtype
    if args.trace_requests:
        rec["trace_requests"] = True
    if telemetry is not None and telemetry.port is not None:
        rec["metrics_port"] = telemetry.port
    if telemetry is not None:
        rec["telemetry_snapshots"] = telemetry.snapshots
    if controller is not None:
        rec["control"] = args.control
        rec["controller"] = controller.summary()
    if advisor is not None:
        rec["tuning"] = advisor.summary()
    if flight is not None:
        rec["flight_dumps"] = list(flight.dumps)
        for d in flight.dumps:
            print(f"flight dump written: {d}", file=sys.stderr)
    if profiler is not None:
        rec["anomaly_profiles"] = list(profiler.captures)
        rec["profile_attributions"] = profiler.attributions
        for d in profiler.captures:
            print(f"anomaly profile captured: {d}", file=sys.stderr)
    if duty is not None:
        rec["profile_captures"] = list(duty.captures)
        rec["profile_attributions"] = duty.attributions
        rec["profile_windows_skipped"] = duty.windows_skipped
        print(f"duty profiler: {len(duty.captures)} capture(s), "
              f"{duty.attributions} attributed, "
              f"{duty.bytes_used / 2**20:.1f} MiB used"
              + (f", {duty.windows_skipped} window(s) skipped after "
                 f"budget exhaustion" if duty.windows_skipped else ""),
              file=sys.stderr)
    # ISSUE 17: provenance stamp (config fingerprint + git rev) — the
    # run-forensics join key every summary record carries uniformly
    rec.update(run_stamp(vars(args)))
    print(json.dumps(rec))
    return summary


def main(argv=None) -> dict:
    return serve(get_serve_args(argv))


if __name__ == "__main__":
    main()
