"""Synthetic arrival driver + offline serving benchmark loop.

Serving performance is meaningless without an arrival process: a batch CLI
measures throughput at occupancy 1.0, hiding exactly the queueing and
slot-churn behaviour continuous batching exists to handle. This module
generates request streams —

* `poisson`: exponential inter-arrival gaps at `rate` req/s (the standard
  open-loop load model),
* `burst`: everything arrives at t=0 (closed-loop stress: worst-case queue
  depth and slot churn),
* `replay`: a jsonl file of `{"arrival": s, "prompt": [ids...],
  "max_new": n, "seed": s}` records (reproduce a captured trace),

— and drives the engine against the WALL CLOCK: a request is submitted
once its arrival offset has elapsed, the engine steps whenever it has live
work, and the driver sleeps only when idle before the next arrival. TTFT /
TPOT / queue-wait therefore include real queueing delay under load.

Prompts are uniform-random token ids: serving cost depends on shapes, not
token values, and random ids keep the benchmark checkpoint-free
(`bench.py` uses the same convention for --decode).
"""

from __future__ import annotations

import json
import time
from typing import List, Optional

import numpy as np

from .engine import ContinuousBatchingEngine, Request
from .scheduler import QueueFull


def synthetic_requests(num: int, prompt_len_min: int, prompt_len_max: int,
                       max_new: int, vocab_size: int, seed: int = 0,
                       rate: float = 4.0, arrival: str = "poisson",
                       class_mix: Optional[dict] = None, tenants: int = 1,
                       shared_prefix_len: int = 0,
                       interleave: bool = False) -> List[Request]:
    """`num` requests with random-id prompts and arrival offsets (seconds
    from t=0, sorted). Token ids avoid 0/1/2 (the BOS/EOS/UNK convention)
    so a random prompt cannot start with a spurious EOS.

    Serving-v2 knobs (all optional, all deterministic under `seed`):
    `class_mix` draws each request's SLO class by weight ({name: w});
    `tenants` spreads requests round-robin over t0..tN-1 (the fair-queuing
    axis); `shared_prefix_len` > 0 prepends ONE common random prefix to
    every prompt (a system-prompt stand-in — the COW prefix cache's food);
    `interleave` alternates short (prompt_len_min) and long
    (prompt_len_max) prompts instead of drawing uniformly — the
    head-of-line-prefill stress the chunked prefill exists to fix."""
    if arrival not in ("poisson", "burst"):
        raise ValueError(f"arrival must be poisson|burst, got {arrival!r}")
    if not 3 <= prompt_len_min <= prompt_len_max:
        raise ValueError(f"need 3 <= prompt_len_min <= prompt_len_max, got "
                         f"[{prompt_len_min}, {prompt_len_max}]")
    if tenants < 1:
        raise ValueError(f"tenants must be >= 1, got {tenants}")
    rng = np.random.default_rng(seed)
    if arrival == "burst":
        at = np.zeros(num)
    else:
        if rate <= 0:
            raise ValueError(f"poisson arrivals need rate > 0, got {rate}")
        at = np.cumsum(rng.exponential(1.0 / rate, size=num))
    names, weights = None, None
    if class_mix:
        names = sorted(class_mix)
        w = np.asarray([float(class_mix[n]) for n in names], np.float64)
        if (w < 0).any() or w.sum() <= 0:
            raise ValueError(f"class_mix weights must be >= 0 and sum > 0, "
                             f"got {class_mix}")
        weights = w / w.sum()
    shared = [int(t) for t in
              rng.integers(3, vocab_size, size=shared_prefix_len)]
    out = []
    for i in range(num):
        if interleave:
            plen = prompt_len_min if i % 2 == 0 else prompt_len_max
        else:
            plen = int(rng.integers(prompt_len_min, prompt_len_max + 1))
        prompt = shared + [int(t) for t in
                           rng.integers(3, vocab_size, size=plen)]
        cls = (str(names[int(rng.choice(len(names), p=weights))])
               if names else None)
        out.append(Request(rid=i, prompt=prompt, max_new=max_new,
                           seed=seed + i, arrival=float(at[i]),
                           tenant=f"t{i % tenants}", slo_class=cls))
    return out


def replay_requests(path: str) -> List[Request]:
    """Load a captured request trace (jsonl, one record per request)."""
    out = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            out.append(Request(
                rid=rec.get("rid", i), prompt=list(rec["prompt"]),
                max_new=int(rec.get("max_new", 64)),
                seed=int(rec.get("seed", i)),
                arrival=float(rec.get("arrival", 0.0))))
    return sorted(out, key=lambda r: r.arrival)


def _pctl(vals: List[Optional[float]], q: float) -> Optional[float]:
    vals = [v for v in vals if v is not None]
    if not vals:
        return None
    return float(np.percentile(np.asarray(vals, np.float64), q))


def worst_request_exemplars(engine, done, k: int = 3) -> Optional[dict]:
    """The k-worst TTFT and TPOT requests WITH their span timelines (from
    the engine's request tracer) — the difference between counting SLO
    misses and explaining them. None when tracing is off."""
    rt = getattr(engine, "rt", None)
    if rt is None:
        return None
    ms = 1e3

    def exemplars(key):
        ranked = sorted((r for r in done if key(r) is not None),
                        key=key, reverse=True)[:k]
        out = []
        for r in ranked:
            rec = rt.timeline(r.rid)
            out.append({
                "rid": r.rid, "trace_id": r.trace_id,
                "ttft_ms": None if r.ttft_s is None
                else round(r.ttft_s * ms, 3),
                "tpot_ms": None if r.tpot_s is None
                else round(r.tpot_s * ms, 3),
                "preemptions": r.preemptions,
                "slo_class": r.slo_class,
                "timeline": rec["spans"] if rec else None,
            })
        return out

    return {"k": k,
            "worst_ttft": exemplars(lambda r: r.ttft_s),
            "worst_tpot": exemplars(lambda r: r.tpot_s)}


def run_loadgen(engine: ContinuousBatchingEngine, requests: List[Request],
                clock=time.monotonic, sleep=time.sleep,
                k_worst: int = 3) -> dict:
    """Drive `engine` through the arrival stream; returns the summary dict
    (percentiles in ms; throughput over the wall window). Refused
    submissions never crash the run — backpressure (QueueFull) counts as
    `rejected` (the scheduler's own counter, so it agrees with
    engine.stats()), a malformed request (e.g. a replayed prompt longer
    than the engine's buffer) as `invalid` — the metrics of everything
    that DID serve are the point of the benchmark."""
    import sys

    pending = sorted(requests, key=lambda r: r.arrival)
    t0 = clock()
    i = 0
    invalid = 0
    while i < len(pending) or engine.has_work():
        now = clock() - t0
        while i < len(pending) and pending[i].arrival <= now:
            try:
                # stamp the PLANNED arrival as the submit time: the host
                # loop only gets here between dispatches, so the open-loop
                # queue-wait/TTFT must include the time the request sat
                # waiting for the loop, not start when the loop noticed it
                pending[i].submit_t = t0 + pending[i].arrival
                engine.submit(pending[i])
            except QueueFull:
                pass  # counted by the scheduler (engine.stats()["rejected"])
            except ValueError as e:
                invalid += 1
                print(f"loadgen: request {pending[i].rid} invalid: {e}",
                      file=sys.stderr)
            i += 1
        if engine.has_work():
            engine.step()
        elif i < len(pending):
            sleep(min(0.05, max(0.0, pending[i].arrival - (clock() - t0))))
    wall = max(clock() - t0, 1e-9)
    done = engine.completed
    stats = engine.stats()
    ms = 1e3
    summary = {
        "requests": len(requests),
        "completed": len(done),
        # backpressure rejections (the scheduler's counter, so this agrees
        # with engine.stats()["rejected"]); malformed requests separately
        "rejected": stats["rejected"],
        "invalid": invalid,
        "wall_s": round(wall, 4),
        "generated_tokens": stats["generated_tokens"],
        "tokens_per_sec": round(stats["generated_tokens"] / wall, 2),
        "decode_steps": stats["decode_steps"],
        "slot_occupancy_mean": stats["slot_occupancy_mean"],
        "prefill_pad_waste_eliminated":
            stats.get("prefill_pad_waste_eliminated", 0.0),
        "ttft_ms_p50": _pctl([r.ttft_s and r.ttft_s * ms for r in done], 50),
        "ttft_ms_p95": _pctl([r.ttft_s and r.ttft_s * ms for r in done], 95),
        "tpot_ms_p50": _pctl([r.tpot_s and r.tpot_s * ms for r in done], 50),
        "tpot_ms_p95": _pctl([r.tpot_s and r.tpot_s * ms for r in done], 95),
        "queue_wait_ms_p50": _pctl(
            [r.queue_wait_s and r.queue_wait_s * ms for r in done], 50),
        "queue_wait_ms_p95": _pctl(
            [r.queue_wait_s and r.queue_wait_s * ms for r in done], 95),
    }
    if "kv_util_mean" in stats:        # the paged engine's extra telemetry
        summary.update({k: stats[k] for k in (
            "kv_dtype", "paged_attn", "cp", "pages_per_rank", "num_pages",
            "kv_util_mean", "kv_fragmentation_mean", "pages_in_use_mean",
            "prefix_hit_rate", "cow_copies", "preemptions", "max_live",
            "max_interleaved_prefill_positions")})
    if "speculate_k" in stats:         # the speculative engine's telemetry
        summary.update({k: stats[k] for k in (
            "speculate_k", "spec_rounds", "accepted_tokens_per_dispatch",
            "acceptance_rate", "acceptance_rate_by_position",
            "rounds_per_request", "drafter_ms_total", "target_ms_total")})
    att = slo_attainment(engine, done)
    if att is not None:
        summary["slo_attainment"] = att
        # SLO-class attainment COLLAPSE is an anomaly worth a post-mortem
        # artifact, not just a percentage: freeze the flight ring while
        # the pool/scheduler history that produced it is still in there
        flight = getattr(engine, "flight", None)
        if flight is not None:
            # classes whose collapse the engine already dumped ONLINE
            # (PagedEngine._account_slo) don't need a second post-run dump
            dumped = getattr(engine, "slo_collapsed", set())
            for name, c in sorted(att.items()):
                if (c["completed"] >= 4 and c["attained"] < 0.5
                        and name not in dumped):
                    flight.dump(
                        {"kind": "slo_attainment_collapse",
                         "slo_class": name, **c},
                        tag="slo_collapse")
    exemplars = worst_request_exemplars(engine, done, k=k_worst)
    if exemplars is not None:
        summary["worst_ttft_rids"] = [e["rid"]
                                      for e in exemplars["worst_ttft"]]
        summary["worst_tpot_rids"] = [e["rid"]
                                      for e in exemplars["worst_tpot"]]
    if engine.writer is not None:
        if exemplars is not None:
            # the k-worst requests WITH their timelines as one event, so
            # summarize_run.py renders the waterfall without re-joining
            # request_trace records against percentile tails
            engine.writer.event("request_exemplars", **exemplars)
        engine.writer.event("serving_summary", **summary)
        if "kv_util_mean" in stats:
            # token-granular occupancy as its own event stream, so the
            # staged r9 session (and summarize_run.py) can pull the page
            # economics without parsing the whole summary
            engine.writer.event("paged_kv_stats", **{k: stats[k] for k in (
                "page_size", "kv_dtype", "cp", "pages_per_rank",
                "num_pages", "pages_in_use_mean",
                "kv_util_mean", "kv_fragmentation_mean", "prefix_hit_rate",
                "prefix_hit_tokens", "cow_copies", "preemptions",
                "max_live", "max_interleaved_prefill_positions")})
        if "speculate_k" in stats:
            # the speculative round economics as their own event, so the
            # staged r10 k-sweep (and summarize_run.py) can rank k by
            # acceptance and drafter-vs-target wall without re-parsing
            engine.writer.event("spec_decode_stats", **{k: stats[k] for k in (
                "speculate_k", "spec_rounds",
                "accepted_tokens_per_dispatch", "acceptance_rate",
                "acceptance_rate_by_position", "rounds_per_request",
                "drafter_ms_total", "target_ms_total",
                "drafter_num_pages", "drafter_pages_in_use",
                "drafter_page_bytes", "target_page_bytes")})
    return summary


def run_fleet_loadgen(router, requests: List[Request],
                      clock=time.monotonic, sleep=time.sleep,
                      session_key=None) -> dict:
    """run_loadgen generalized to a FleetRouter (serving fleet v1,
    ISSUE 19): the arrival stream submits through the router — scored
    dispatch, session affinity keyed by `session_key(req)` (default the
    request's tenant: a multi-turn chat reuses its tenant's replica and
    its KV prefix) — and every engine step advances the WHOLE fleet.

    The summary is fleet-level: throughput sums the replicas, latency
    percentiles pool every completion, `fleet_slo_attainment` folds the
    replicas' live per-class counters exactly as obs.collector's rollup
    does, and `per_replica` carries each engine's dispatched/completed/
    prefix_hit_rate so a skewed router shows up in one read. Router
    dispatch overhead rides along (`dispatch_ms_p50` — the < 1 ms CPU
    pin)."""
    import sys

    from ..obs.telemetry import fleet_slo_attainment

    if session_key is None:
        session_key = lambda r: r.tenant
    pending = sorted(requests, key=lambda r: r.arrival)
    t0 = clock()
    i = 0
    invalid = 0
    done: List[Request] = []
    while i < len(pending) or router.has_work():
        now = clock() - t0
        while i < len(pending) and pending[i].arrival <= now:
            try:
                pending[i].submit_t = t0 + pending[i].arrival
                router.submit(pending[i], session=session_key(pending[i]))
            except QueueFull:
                pass  # counted by the router (fleet-wide refusal)
            except ValueError as e:
                invalid += 1
                print(f"fleet loadgen: request {pending[i].rid} invalid: "
                      f"{e}", file=sys.stderr)
            i += 1
        if router.has_work():
            done.extend(router.step())
        elif i < len(pending):
            sleep(min(0.05, max(0.0, pending[i].arrival - (clock() - t0))))
    wall = max(clock() - t0, 1e-9)
    ms = 1e3
    rstats = router.stats()
    engines = [(name, eng) for name, eng in router.replicas]
    generated = sum(e.generated_tokens for _, e in engines)
    per_replica = {}
    for name, eng in engines:
        st = eng.stats()
        per_replica[name] = {
            "dispatched": rstats["dispatched"].get(name, 0),
            "completed": st["completed"],
            "generated_tokens": st["generated_tokens"],
            "rejected": st["rejected"],
            "prefix_hit_rate": st.get("prefix_hit_rate", 0.0),
            "preemptions": st.get("preemptions", 0),
            "num_pages": st.get("num_pages"),
            "pages_in_use_mean": st.get("pages_in_use_mean"),
        }
    summary = {
        "requests": len(requests),
        "completed": len(done),
        "rejected": rstats["rejected"],
        "invalid": invalid,
        "wall_s": round(wall, 4),
        "generated_tokens": generated,
        "fleet_tokens_per_sec": round(generated / wall, 2),
        "replicas": rstats["replicas"],
        "dispatch_ms_p50": rstats["dispatch_ms_p50"],
        "dispatch_ms_p95": rstats["dispatch_ms_p95"],
        "session_spills": rstats["spills"],
        "ttft_ms_p50": _pctl([r.ttft_s and r.ttft_s * ms for r in done], 50),
        "ttft_ms_p95": _pctl([r.ttft_s and r.ttft_s * ms for r in done], 95),
        "tpot_ms_p50": _pctl([r.tpot_s and r.tpot_s * ms for r in done], 50),
        "tpot_ms_p95": _pctl([r.tpot_s and r.tpot_s * ms for r in done], 95),
        "queue_wait_ms_p50": _pctl(
            [r.queue_wait_s and r.queue_wait_s * ms for r in done], 50),
        "queue_wait_ms_p95": _pctl(
            [r.queue_wait_s and r.queue_wait_s * ms for r in done], 95),
        "per_replica": per_replica,
    }
    # fold the replicas' LIVE per-class counters the same way the fleet
    # collector does, so the loadgen summary and the rollup agree
    slo_inputs = []
    for _, eng in engines:
        counts = getattr(eng, "_slo_counts", None)
        if counts:
            slo_inputs.append({cls: (c[0], c[1])
                               for cls, c in counts.items()})
    att = fleet_slo_attainment(slo_inputs) if slo_inputs else None
    if att:
        summary["fleet_slo_attainment"] = att
    if router.writer is not None:
        router.writer.event("fleet_serving_summary", **summary)
    return summary


def slo_attainment(engine, done) -> Optional[dict]:
    """Per-deadline-class TTFT attainment: of the requests that COMPLETED
    in each class, the fraction whose TTFT met the class budget (plus the
    class sizes, so 100% of 2 requests reads differently from 100% of
    2000). None for engines without SLO classes (the FIFO slot engine)."""
    classes = getattr(engine.scheduler, "classes", None)
    if not classes:
        return None
    out = {}
    for name, deadline in sorted(classes.items()):
        reqs = [r for r in done if r.slo_class == name]
        if not reqs:
            continue
        hit = sum(1 for r in reqs
                  if r.ttft_s is not None and r.ttft_s <= deadline)
        out[name] = {"deadline_s": deadline, "completed": len(reqs),
                     "attained": round(hit / len(reqs), 4)}
    return out or None
