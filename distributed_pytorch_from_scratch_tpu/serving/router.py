"""Prefix-cache-aware multi-replica router (serving fleet v1, ISSUE 19).

The front door for N `PagedEngine` replicas of one checkpoint (possibly
different tp/cp widths — replicas are opaque behind submit/step). Each
request is dispatched by PREDICTED prefix-cache hit blended with
least-loaded:

    score(r) = w_prefix * predicted_hit(r) / len(prompt)
             - w_load   * (live(r) + queued(r)) / slots(r)
             - w_pool   * (1 - free_pages(r) / num_pages(r))

The prediction needs no round trip: the router maintains a SHADOW of
each replica's content-addressed hash-chain prefix index
(`kv_manager.PagedKVPool` — chain key = (parent, page_tokens)), updated
from its own dispatch/retire stream. The predictor walks the shadow
with exactly `PagedEngine._try_share`'s algorithm (page-aligned,
lead-match, capped at len(prompt)-1, a partial match ends the walk), so
on a shared-prefix burst the predicted hits equal the replica's actual
`prefix_hit_tokens` counters — a law the tests pin. (Exact in the
concurrently-live regime: a donor whose pages deregistered between
admission waves — completed with no surviving sharer before the
follower admitted — makes the shadow an upper bound, since the router
retires registrations at completion fold, one step later.) Load/headroom terms
read the same three gauges the telemetry endpoints export (serve/live +
serve/queue_depth, serve/free_pages) — in-process replicas are read
directly, remote ones would be scraped.

Session affinity: `submit(req, session=...)` pins a session to the
replica that served it last (its KV prefix lives there), and a full
replica SPILLS to the best-scoring alternative with a loud
`session_spill` writer event — never a silent drop. Only a fleet-wide
QueueFull propagates to the caller.

The router threads `TraceContext` through every hop: its own
RequestTracer records submit -> route -> handoff, the replica continues
the trace (engine.submit adopts `req.trace_ctx`), and the two records
merge into one waterfall (`obs.reqtrace.merge_traces`) — three hops
once the replica itself disaggregates (serving/transfer.py).
"""

from __future__ import annotations

import time
from typing import Dict, List, Tuple

from .engine import Request
from .kv_manager import PagedKVPool
from .scheduler import QueueFull


class _ShadowIndex:
    """One replica's prefix index, mirrored host-side. Runs are keyed
    like the pool's chain (`PagedKVPool.chain_key`) and REFCOUNTED per
    registered request, because that is the pool-side lifetime: a page
    deregisters when its last referencing request releases it."""

    def __init__(self, page_size: int):
        self.ps = int(page_size)
        # chain_key(parent) -> {page_tokens_tuple: refcount}
        self._runs: Dict[object, Dict[tuple, int]] = {}

    def _chain(self, ids) -> List[Tuple[object, tuple]]:
        ps, out, parent = self.ps, [], None
        for j in range(-(-len(ids) // ps)):
            toks = tuple(int(t) for t in ids[j * ps:(j + 1) * ps])
            out.append((parent, toks))
            parent = PagedKVPool.chain_key(parent, toks)
        return out

    def register(self, ids) -> None:
        for parent, toks in self._chain(ids):
            d = self._runs.setdefault(parent, {})
            d[toks] = d.get(toks, 0) + 1

    def retire(self, ids) -> None:
        for parent, toks in self._chain(ids):
            d = self._runs.get(parent)
            if not d or toks not in d:
                continue
            d[toks] -= 1
            if d[toks] <= 0:
                del d[toks]
                if not d:
                    del self._runs[parent]

    def predict(self, ids) -> int:
        """Prompt positions the replica would serve from shared pages —
        the exact mirror of PagedEngine._try_share's walk."""
        ps = self.ps
        s, parent, hits = 0, None, 0
        while s % ps == 0:
            cap = len(ids) - 1 - s
            if cap <= 0:
                break
            window = tuple(int(t) for t in ids[s:s + min(ps, cap)])
            best_toks, best = None, 0
            for toks in self._runs.get(parent, ()):
                n = 0
                for a, b in zip(toks, window):
                    if a != b:
                        break
                    n += 1
                if n > best:
                    best_toks, best = toks, n
            if best == 0:
                break
            hits += best
            s += best
            if best < ps:
                break                       # partial match ends the walk
            parent = PagedKVPool.chain_key(parent, best_toks)
        return hits


class FleetRouter:
    """Dispatch + fold for an in-process fleet of PagedEngine replicas.

    `replicas`: list of engines, or (name, engine) pairs; names default
    to r0, r1, ... and survive restarts (`replace_replica` swaps the
    process behind a name and resets its shadow — the new pool is
    empty)."""

    def __init__(self, replicas, prefix_weight: float = 4.0,
                 load_weight: float = 1.0, pool_weight: float = 1.0,
                 writer=None, telemetry=None, request_tracer=None,
                 clock=time.monotonic):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.replicas: List[Tuple[str, object]] = [
            r if isinstance(r, tuple) else (f"r{i}", r)
            for i, r in enumerate(replicas)]
        names = [n for n, _ in self.replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        self.prefix_weight = float(prefix_weight)
        self.load_weight = float(load_weight)
        self.pool_weight = float(pool_weight)
        self.writer = writer
        self.telemetry = telemetry
        self.rt = request_tracer            # the router's OWN tracer hop
        self._clock = clock
        self._shadow: Dict[str, _ShadowIndex] = {
            n: _ShadowIndex(e.page_size) for n, e in self.replicas}
        self._sessions: Dict[object, str] = {}
        self._live: Dict[int, Tuple[str, list]] = {}   # rid -> (name, ids)
        self.predicted: Dict[int, Tuple[str, int]] = {}
        self.dispatch_ms: List[float] = []
        self.dispatched: Dict[str, int] = {n: 0 for n, _ in self.replicas}
        self.spills = 0
        self.rejected = 0

    # -- scoring ----------------------------------------------------------
    def _engine(self, name: str):
        for n, e in self.replicas:
            if n == name:
                return e
        raise KeyError(name)

    def predict(self, name: str, prompt) -> int:
        return self._shadow[name].predict(prompt)

    def _score(self, name: str, eng, prompt) -> Tuple[float, int]:
        hit = self._shadow[name].predict(prompt)
        load = (eng.live_requests + eng.scheduler.pending) / eng.num_slots
        pool = eng.pool
        pressure = 1.0 - pool.free_pages / pool.num_pages
        score = (self.prefix_weight * hit / max(len(prompt), 1)
                 - self.load_weight * load
                 - self.pool_weight * pressure)
        return score, hit

    # -- dispatch ---------------------------------------------------------
    def submit(self, req: Request, session=None) -> str:
        """Route + enqueue one request; returns the chosen replica name.
        Raises QueueFull only when EVERY replica refused."""
        t0 = time.perf_counter()
        scored = []                          # (-score, order, name, hit)
        for i, (name, eng) in enumerate(self.replicas):
            score, hit = self._score(name, eng, req.prompt)
            scored.append((-score, i, name, hit))
        scored.sort()
        order = [(name, hit) for _, _, name, hit in scored]
        pinned = self._sessions.get(session) if session is not None \
            else None
        if pinned is not None:
            order = ([(n, h) for n, h in order if n == pinned]
                     + [(n, h) for n, h in order if n != pinned])
        if self.rt is not None:
            self.rt.begin(req)
        last_err = None
        for k, (name, hit) in enumerate(order):
            eng = self._engine(name)
            if self.rt is not None:
                # closes the routing span; the replica's tracer continues
                # the trace from here (engine.submit adopts trace_ctx)
                ctx = self.rt.export_context(req, "route")
                req.trace_ctx = ctx.to_wire() if ctx is not None else None
            try:
                eng.submit(req)
            except QueueFull as e:
                last_err = e
                if pinned == name and session is not None:
                    # affinity spill: loud, never a silent drop
                    self.spills += 1
                    if self.writer is not None:
                        self.writer.event("session_spill", session=session,
                                          rid=req.rid, pinned=name,
                                          queued=eng.scheduler.pending)
                    if self.telemetry is not None:
                        self.telemetry.counter("fleet/session_spills",
                                               self.spills)
                continue
            ids = list(req.prompt)
            self._shadow[name].register(ids)
            self._live[req.rid] = (name, ids)
            self.predicted[req.rid] = (name, hit)
            self.dispatched[name] += 1
            if session is not None:
                self._sessions[session] = name
            if self.rt is not None:
                self.rt.retire(req, t=self._clock())
            self.dispatch_ms.append((time.perf_counter() - t0) * 1e3)
            return name
        self.rejected += 1
        if self.rt is not None:
            self.rt.retire(req, t=self._clock())
        raise last_err if last_err is not None else QueueFull(
            "no replica accepted the request")

    def replace_replica(self, name: str, engine, reshard=None) -> None:
        """Attach a RESTARTED replica under an existing name. The shadow
        resets (a fresh process holds no pages) and sessions keep their
        pin — the name is the address, not the process. In-flight
        requests on the old process are the caller's loss to re-submit.

        `reshard`: optional dict describing a heterogeneous restart (the
        new engine serves a different layout — reshard/plan summary:
        src/dst layouts, bytes moved, op counts); folded into the
        replica_restart event so forensics sees width changes."""
        for i, (n, _) in enumerate(self.replicas):
            if n == name:
                self.replicas[i] = (name, engine)
                break
        else:
            raise KeyError(f"no replica named {name!r}")
        self._shadow[name] = _ShadowIndex(engine.page_size)
        for rid, (rname, _) in list(self._live.items()):
            if rname == name:
                del self._live[rid]
        if self.writer is not None:
            extra = {"reshard": reshard} if reshard else {}
            self.writer.event("replica_restart", replica=name, **extra)

    # -- the fleet loop ---------------------------------------------------
    def step(self) -> List[Request]:
        """Advance every replica one engine step; fold completions and
        release their shadow registrations (mirroring the pool-side
        refcount drop at _release_slot)."""
        done: List[Request] = []
        for name, eng in self.replicas:
            for req in eng.step():
                ent = self._live.pop(req.rid, None)
                if ent is not None:
                    self._shadow[ent[0]].retire(ent[1])
                done.append(req)
        return done

    def has_work(self) -> bool:
        return any(e.has_work() for _, e in self.replicas)

    def run_to_completion(self) -> List[Request]:
        out: List[Request] = []
        while self.has_work():
            out.extend(self.step())
        return out

    # -- aggregate view ---------------------------------------------------
    def stats(self) -> dict:
        ms = sorted(self.dispatch_ms)
        pct = lambda q: (ms[min(len(ms) - 1, int(q * (len(ms) - 1)))]
                         if ms else 0.0)
        return {
            "replicas": [n for n, _ in self.replicas],
            "dispatched": dict(self.dispatched),
            "spills": self.spills,
            "rejected": self.rejected,
            "dispatch_ms_p50": round(pct(0.50), 4),
            "dispatch_ms_p95": round(pct(0.95), 4),
            "sessions": len(self._sessions),
        }
