"""Online SLO/admission controller (obs v5, ISSUE 16).

The serving half of the control plane: per-class TTFT attainment and
pool/queue gauges — the same numbers the telemetry plane already
exports — drive chunk-size, speculation-K, and admission-rate
adaptation under shifting loadgen traffic. Every adaptation lands as a
`controller_decision` ledger event cross-linked (`snapshot_seq`) to the
telemetry snapshot that triggered it: the controller emits one
`telemetry_snapshot` at decision time, so the trigger state is IN the
stream the post-hoc ledger reads, not reconstructed from memory.

Control discipline (graftcheck `controller-discipline`): `tick()` only
observes and proposes; knobs move exclusively inside
`apply_decisions()`, which the engine invokes from its
`@control_safe_point`-decorated host-side decode tick — the same safe
point the flight recorder and duty profiler already own (device work
for the step is host-side, nothing is traced).

The rules, deliberately small and directional:

* attainment < `target` with a deep queue (pending > 2x live) ->
  clamp admission: halve `max_queue` (an unlimited queue clamps to
  half the current depth) — shedding load beats missing every SLO;
* attainment < `target` with a shallow queue -> halve `prefill_chunk`:
  decode interleaves sooner, TTFT head-of-line blocking shrinks;
* attainment >= `recover_target` across the window -> relax: restore
  `max_queue` toward its configured value (x2 per window), then
  `prefill_chunk` toward its configured value;
* speculative acceptance < 0.5 -> K-1 (draft work is being thrown
  away); acceptance > 0.9 -> K+1 (the draft is under-used).

Per-knob cooldown (`cooldown` evaluation windows) keeps one shift from
thrashing a knob before its effect is measurable — the post-decision
window is the ledger's "measured effect" column.
"""

from __future__ import annotations

import sys
import time
from typing import Dict, List, Optional

from ..obs.control import MODE_INDEX, CONTROL_MODES


def _pctl_ms(vals: List[Optional[float]], q: float) -> Optional[float]:
    vals = sorted(v for v in vals if v is not None)
    if not vals:
        return None
    i = min(len(vals) - 1, int(round(q / 100.0 * (len(vals) - 1))))
    return round(vals[i] * 1e3, 3)


class SLOController:
    """Observe-propose-actuate over a live PagedEngine. `tick(step)` is
    called once per decode step from the engine's safe point; it
    evaluates every `interval` steps and queues decisions; the engine
    then calls `apply_decisions()` (act mode) from the same decorated
    safe point."""

    def __init__(self, engine, mode: str, writer=None, telemetry=None,
                 interval: int = 32, target: float = 0.90,
                 recover_target: float = 0.98, min_completed: int = 4,
                 cooldown: int = 2, clock=time.monotonic):
        if mode not in CONTROL_MODES:
            raise ValueError(f"control mode must be one of "
                             f"{CONTROL_MODES}, got {mode!r}")
        if interval < 1:
            raise ValueError(f"control interval must be >= 1, got "
                             f"{interval}")
        self.engine = engine
        self.mode = mode
        self.writer = writer
        self.telemetry = telemetry
        self.interval = interval
        self.target = target
        self.recover_target = recover_target
        self.min_completed = min_completed
        self.cooldown = cooldown
        self.clock = clock
        self.decisions: List[dict] = []
        self._pending: List[dict] = []
        self._cool: Dict[str, int] = {}
        self._done_seen = 0
        self._seq = 0
        self._t_start = clock()
        self._first_applied_t: Optional[float] = None
        # the configured values are the recovery ceiling: the controller
        # degrades under pressure and restores toward them, never past
        self._init_prefill = int(getattr(engine, "prefill_chunk", 1))
        self._init_max_queue = int(getattr(engine.scheduler, "max_queue",
                                           0))
        self._init_k = int(getattr(engine, "k", 0))
        if telemetry is not None and mode != "off":
            telemetry.gauge("ctl/mode", MODE_INDEX[mode])

    # -- observe + propose ---------------------------------------------
    def tick(self, step: int) -> None:
        if self.mode == "off" or step == 0 or step % self.interval:
            return
        for k in list(self._cool):
            self._cool[k] -= 1
            if self._cool[k] <= 0:
                del self._cool[k]
        done = self.engine.completed
        window = done[self._done_seen:]
        self._done_seen = len(done)
        pending = self.engine.scheduler.pending
        live = len(self.engine._slot_req)
        att = self._attainment(window)
        evidence = {"step": step, "queue_depth": pending, "live": live,
                    "window_completed": len(window),
                    "attainment": att}
        worst = min((c["attained"] for c in att.values()), default=None) \
            if att else None
        if worst is not None and len(window) >= self.min_completed:
            if worst < self.target:
                if pending > max(2 * live, 4):
                    self._propose_admission_clamp(pending, evidence)
                else:
                    self._propose("prefill_chunk", "slo_miss_ttft",
                                  lambda old: max(1, old // 2), evidence)
            elif worst >= self.recover_target:
                self._propose_recovery(evidence)
        self._propose_speculation(evidence)

    def _attainment(self, window) -> dict:
        classes = getattr(self.engine.scheduler, "classes", None) or {}
        out = {}
        for name, deadline in sorted(classes.items()):
            reqs = [r for r in window if r.slo_class == name]
            if not reqs:
                continue
            hit = sum(1 for r in reqs
                      if r.ttft_s is not None and r.ttft_s <= deadline)
            out[name] = {"completed": len(reqs),
                         "attained": round(hit / len(reqs), 4)}
        return out

    def _propose_admission_clamp(self, pending: int, evidence: dict):
        def clamp(old):
            return max(2, (pending if old == 0 else old) // 2)
        self._propose("max_queue", "slo_miss_queue", clamp, evidence)

    def _propose_recovery(self, evidence: dict):
        mq = self.engine.scheduler.max_queue
        if mq != self._init_max_queue and mq != 0:
            def relax(old):
                new = old * 2
                # doubling past the configured value restores it exactly
                # (0 = unlimited has no "past": any clamp restores to 0)
                if self._init_max_queue == 0 \
                        or new >= self._init_max_queue:
                    return self._init_max_queue
                return new
            self._propose("max_queue", "recovered", relax, evidence)
        elif self.engine.prefill_chunk < self._init_prefill:
            self._propose("prefill_chunk", "recovered",
                          lambda old: min(self._init_prefill, old * 2),
                          evidence)

    def _propose_speculation(self, evidence: dict):
        if not hasattr(self.engine, "k"):
            return
        stats = self.engine.stats()
        acc = stats.get("acceptance_rate")
        if acc is None or not stats.get("spec_rounds"):
            return
        ev = dict(evidence, acceptance_rate=acc)
        if acc < 0.5:
            self._propose("speculate_k", "spec_acceptance_low",
                          lambda old: max(1, old - 1), ev)
        elif acc > 0.9:
            self._propose("speculate_k", "spec_acceptance_high",
                          lambda old: min(self._init_k * 2, old + 1), ev)

    # -- the ledger ----------------------------------------------------
    def _get(self, knob: str) -> int:
        if knob == "max_queue":
            return int(self.engine.scheduler.max_queue)
        return int(getattr(self.engine, {"prefill_chunk": "prefill_chunk",
                                         "speculate_k": "k"}[knob]))

    def _set(self, knob: str, value: int) -> None:
        if knob == "max_queue":
            self.engine.scheduler.max_queue = int(value)
        elif knob == "prefill_chunk":
            self.engine.prefill_chunk = int(value)
        else:
            self.engine.k = int(value)

    def _propose(self, knob: str, trigger: str, fn, evidence: dict):
        if knob in self._cool:
            return
        old = self._get(knob)
        new = int(fn(old))
        if new == old:
            return
        self._cool[knob] = self.cooldown
        self._seq += 1
        # the triggering telemetry snapshot lands IN the stream now, so
        # the ledger's cross-link resolves post-hoc (seq = how many
        # snapshot events this process has emitted, 1-based)
        snap_seq = (self.telemetry.emit_snapshot()
                    if self.telemetry is not None else 0)
        d = {"knob": knob, "old": old, "new": new, "trigger": trigger,
             "evidence": evidence, "mode": self.mode, "seq": self._seq,
             "snapshot_seq": snap_seq, "t": round(self.clock(), 4)}
        if self.mode == "act":
            self._pending.append(d)
        else:
            d["applied"] = False
            self._emit(d)

    def _emit(self, d: dict) -> None:
        self.decisions.append(d)
        if self.writer is not None:
            self.writer.event("controller_decision", **d)
        if self.telemetry is not None:
            self.telemetry.gauge("ctl/decisions", len(self.decisions))
        print(f"controller[{self.mode}]: {d['knob']} {d['old']} -> "
              f"{d['new']} ({d['trigger']}"
              + ("" if d["applied"] else "; not applied") + ")",
              file=sys.stderr)

    def apply_decisions(self) -> int:
        """Actuate queued act-mode decisions. MUST be called from a
        `@control_safe_point` function (graftcheck-enforced)."""
        applied = 0
        while self._pending:
            d = self._pending.pop(0)
            self._set(d["knob"], d["new"])
            d["applied"] = True
            d["t"] = round(self.clock(), 4)
            if self._first_applied_t is None:
                self._first_applied_t = self.clock()
            applied += 1
            self._emit(d)
        return applied

    def close(self) -> None:
        while self._pending:
            d = self._pending.pop(0)
            d["applied"] = False
            d["note"] = "unapplied at run end (no safe point reached)"
            self._emit(d)

    # -- the continuous gate's food ------------------------------------
    def windows(self, done=None) -> Optional[dict]:
        """Pre- vs post-first-actuation windows over the completed
        requests — what `check_bench_regression --controller` gates. None
        until a decision has actually been applied."""
        if self._first_applied_t is None:
            return None
        done = self.engine.completed if done is None else done
        t1 = self._first_applied_t

        def metrics(reqs, t_lo, t_hi):
            dur = max(t_hi - t_lo, 1e-9)
            toks = sum(len(r.tokens) for r in reqs)
            return {"completed": len(reqs), "generated_tokens": toks,
                    "tokens_per_sec": round(toks / dur, 2),
                    "wall_s": round(dur, 4),
                    "ttft_ms_p95": _pctl_ms([r.ttft_s for r in reqs], 95),
                    "tpot_ms_p95": _pctl_ms([r.tpot_s for r in reqs], 95)}

        fin = [r for r in done if r.finish_t is not None]
        pre = [r for r in fin if r.finish_t <= t1]
        post = [r for r in fin if r.finish_t > t1]
        t_end = max((r.finish_t for r in fin), default=self.clock())
        return {"pre": metrics(pre, self._t_start, t1),
                "post": metrics(post, t1, t_end)}

    def summary(self) -> dict:
        last = self.decisions[-1] if self.decisions else None
        out = {"mode": self.mode, "decisions": len(self.decisions),
               "applied": sum(1 for d in self.decisions if d["applied"]),
               "last_knob": last["knob"] if last else None}
        w = self.windows()
        if w is not None:
            out["windows"] = w
        return out
