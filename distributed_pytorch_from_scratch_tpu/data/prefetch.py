"""Host-side input prefetching: overlap collate/stack with device compute.

The reference's DataLoader leans on torch's `num_workers` machinery (it sets
`num_workers=0`, so even there the host blocks — `/root/reference/dataset.py:58-68`).
Here one background thread assembles the NEXT dispatch's batches while the
device executes the current one (VERDICT r2 weak #6): the C++ indexed collate
(`csrc/dataloader.cpp`) releases the GIL for its whole gather+pad pass, and
the `--steps_per_dispatch` megabatch `np.stack` happens on the thread too, so
the main thread's per-dispatch host time collapses to a queue pop.

Double buffering (depth=2) is enough: the consumer is never more than one
window ahead, and deeper queues only add memory.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Iterator, Optional

import numpy as np

BATCH_KEYS = ("input_ids", "target_ids", "position_ids")


def window_stream(batches: Iterable[dict], size: int,
                  skip: int = 0) -> Iterator[list]:
    """Group an epoch's batches into lists of `size` (the dispatch window),
    skipping the first `skip` batches (resume). The final partial window is
    yielded too — callers decide its fate (train drops partial accum groups,
    dispatches partial spd windows)."""
    buf = []
    for i, b in enumerate(batches):
        if i < skip:
            continue
        buf.append(b)
        if len(buf) == size:
            yield buf
            buf = []
    if buf:
        yield buf


def stack_window(bufs: list) -> dict:
    """One (w, b, t) megabatch from w collated batches — the host half of a
    `--steps_per_dispatch`/`--grad_accum` dispatch."""
    return {k: np.stack([b[k] for b in bufs]) for k in BATCH_KEYS}


class Prefetcher:
    """Iterate `src` on a daemon thread, applying `transform` there, with a
    bounded queue between producer and consumer.

    Exceptions from the source/transform re-raise at the consumer's next
    pull. `close()` (also on exhaustion) stops the thread promptly — the
    producer polls a stop flag around its bounded puts, so an abandoned
    epoch does not leak a blocked thread. Tracks `wait_time` (seconds the
    CONSUMER spent blocked) so the host-overlap win is measurable.

    `tracer`: optional obs.SpanTracer — each window's collate+stack work
    records a "prefetch_window" span on the producer thread, so the
    timeline shows the input pipeline's own track next to the train loop
    (queue-blocked time is excluded: the span covers source+transform only).
    """

    _DONE = object()

    def __init__(self, src: Iterable, depth: int = 2,
                 transform: Optional[Callable] = None, tracer=None):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self.wait_time = 0.0
        self.pulls = 0

        def worker():
            try:
                it = iter(src)
                while True:
                    t0 = tracer.now() if tracer is not None else None
                    try:
                        item = next(it)
                    except StopIteration:
                        break
                    if transform is not None:
                        item = transform(item)
                    if tracer is not None:
                        tracer.complete("prefetch_window", t0,
                                        cat="data_prep")
                    self._put_until_stopped(item)
                    if self._stop.is_set():
                        return
                self._put_until_stopped(self._DONE)
            except BaseException as e:  # re-raised at the consumer
                self._put_until_stopped(e)

        self._thread = threading.Thread(target=worker, daemon=True,
                                        name="input-prefetch")
        self._thread.start()

    def _put_until_stopped(self, item):
        """Bounded put that gives up when close() is called — an abandoned
        epoch never leaks a blocked producer thread."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def __iter__(self):
        return self

    def __next__(self):
        t0 = time.perf_counter()
        item = self._q.get()
        self.wait_time += time.perf_counter() - t0
        self.pulls += 1
        if item is self._DONE:
            self.close()
            raise StopIteration
        if isinstance(item, BaseException):
            self.close()
            raise item
        return item

    def close(self):
        self._stop.set()
