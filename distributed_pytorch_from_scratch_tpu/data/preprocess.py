"""FineWeb parquet -> {train, validation} text JSON.

Replaces `/root/reference/preprocess_data.py` with the same semantics and an
identical output schema (`{"train": [str], "validation": [str]}`), so files
produced by either implementation interoperate:

* keep texts with <= `max_chars` characters (reference filters at 2000,
  `preprocess_data.py:27-28`);
* shuffle with a seeded RNG;
* split `val_ratio` (reference: 1%, `preprocess_data.py:14,31`) into
  validation, rest into train.
"""

from __future__ import annotations

import argparse
import json
import os
import random
from typing import List


def preprocess(parquet_path: str, output_path: str, max_chars: int = 2000,
               val_ratio: float = 0.01, seed: int = 0) -> dict:
    import pandas as pd  # host-side only

    df = pd.read_parquet(parquet_path)
    texts: List[str] = [t for t in df["text"].tolist() if len(t) <= max_chars]
    rng = random.Random(seed)
    rng.shuffle(texts)
    n_val = max(1, int(len(texts) * val_ratio))
    data = {"train": texts[n_val:], "validation": texts[:n_val]}
    os.makedirs(os.path.dirname(os.path.abspath(output_path)), exist_ok=True)
    with open(output_path, "w") as f:
        json.dump(data, f)
    print(f"preprocess: {len(data['train'])} train / {len(data['validation'])} "
          f"validation texts -> {output_path}")
    return data


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--parquet_path", "-i", required=True)
    p.add_argument("--output_path", "-o", required=True)
    p.add_argument("--max_chars", type=int, default=2000)
    p.add_argument("--val_ratio", type=float, default=0.01)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    preprocess(args.parquet_path, args.output_path, args.max_chars,
               args.val_ratio, args.seed)


if __name__ == "__main__":
    main()
