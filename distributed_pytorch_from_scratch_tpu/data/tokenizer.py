"""Byte-level BPE tokenizer: training and offline pre-tokenization.

Replaces `/root/reference/train_tokenizer.py` and
`/root/reference/pre_tokenize.py`. The HF `tokenizers` Rust library is kept —
it is host-side and TPU-agnostic (SURVEY §2.3), and keeping it means the
reference's shipped `tokenizer/tokenizer.json` loads unchanged here and vice
versa. Output token-JSON schema is byte-compatible with the reference
(`pre_tokenize.py:43-48`):

    {"train": [[int]], "validation": [[int]],
     "special_ids": {"<BOS>": id, "<EOS>": id, "<UNK>": id},
     "vocab_size": int}
"""

from __future__ import annotations

import argparse
import json
import os
from typing import Dict, Iterable, List

from ..config import BOS_TOKEN, EOS_TOKEN, UNK_TOKEN


def train_bpe(data_path: str, output_path: str, vocab_size: int = 30000,
              split: str = "train"):
    """Train a byte-level BPE tokenizer with BOS/EOS/UNK specials and save
    `tokenizer.json` (reference `train_tokenizer.py:30-54`)."""
    from tokenizers import Tokenizer
    from tokenizers.decoders import ByteLevel as ByteLevelDecoder
    from tokenizers.models import BPE
    from tokenizers.pre_tokenizers import ByteLevel as ByteLevelPreTokenizer
    from tokenizers.trainers import BpeTrainer

    with open(data_path) as f:
        texts: List[str] = json.load(f)[split]

    tokenizer = Tokenizer(BPE(unk_token=UNK_TOKEN))
    tokenizer.pre_tokenizer = ByteLevelPreTokenizer()
    tokenizer.decoder = ByteLevelDecoder()
    trainer = BpeTrainer(vocab_size=vocab_size,
                         special_tokens=[BOS_TOKEN, EOS_TOKEN, UNK_TOKEN])
    tokenizer.train_from_iterator(iter(texts), trainer=trainer)

    out_dir = os.path.dirname(os.path.abspath(output_path))
    os.makedirs(out_dir, exist_ok=True)
    tokenizer.save(output_path)
    print(f"tokenizer: vocab={tokenizer.get_vocab_size()} "
          f"BOS={tokenizer.token_to_id(BOS_TOKEN)} "
          f"EOS={tokenizer.token_to_id(EOS_TOKEN)} "
          f"UNK={tokenizer.token_to_id(UNK_TOKEN)} -> {output_path}")

    # round-trip self-check (reference train_tokenizer.py:56-67)
    for t in ["good morning", "hello world", "this is a test"]:
        ids = tokenizer.encode(t).ids
        decoded = tokenizer.decode(ids).strip()
        assert decoded == t, f"round-trip failed: {t!r} -> {decoded!r}"
    return tokenizer


def pre_tokenize(input_file: str, output_file: str, tokenizer_file: str,
                 splits: Iterable[str] = ("train", "validation"),
                 backend: str = "auto") -> Dict:
    """Apply a saved tokenizer to each split; write token-id JSON
    (reference `pre_tokenize.py:20-52`).

    backend: 'native' (the framework's C++ BPE, csrc/dataloader.cpp),
    'hf' (the HF tokenizers library the reference uses), or 'auto' — native
    when it builds AND passes its load-time parity self-check, else hf.
    """
    from tokenizers import Tokenizer

    with open(input_file) as f:
        data = json.load(f)
    tokenizer = Tokenizer.from_file(tokenizer_file)

    native = None
    if backend in ("auto", "native"):
        try:
            from .native import NativeBPE
            native = NativeBPE(tokenizer_file,
                               extra_probes=[t for split in splits
                                             for t in data[split][:64]])
            print("pre_tokenize: using native C++ BPE encoder")
        except Exception as e:
            if backend == "native":
                raise
            print(f"pre_tokenize: native encoder unavailable ({e}); "
                  f"falling back to HF tokenizers")
    if native is not None and native.added_tokens:
        # HF matches literal added-token strings (e.g. "<EOS>") inside raw
        # text; the native scanner does not. Scan the WHOLE corpus — the old
        # 64-samples-per-split probe let later occurrences diverge silently
        # (ADVICE r1) — and route to HF when any occurrence exists.
        hit = next((s for split in splits for t in data[split]
                    for s in native.added_tokens if s in t), None)
        if hit is not None:
            if backend == "native":
                raise ValueError(
                    f"corpus contains the added-token string {hit!r}, which "
                    f"the native encoder cannot match; use backend='hf'")
            print(f"pre_tokenize: corpus contains added-token string "
                  f"{hit!r}; using HF tokenizers for exact parity")
            native = None

    out: Dict = {}
    for split in splits:
        if native is not None:
            out[split] = [native.encode(t) for t in data[split]]
        else:
            encoded = tokenizer.encode_batch(data[split])
            out[split] = [e.ids for e in encoded]
        lens = [len(ids) for ids in out[split]] or [0]
        print(f"pre_tokenize: {split}: n={len(out[split])} "
              f"max={max(lens)} avg={sum(lens)/max(len(lens),1):.2f}")
    out["special_ids"] = {
        BOS_TOKEN: tokenizer.token_to_id(BOS_TOKEN),
        EOS_TOKEN: tokenizer.token_to_id(EOS_TOKEN),
        UNK_TOKEN: tokenizer.token_to_id(UNK_TOKEN),
    }
    out["vocab_size"] = tokenizer.get_vocab_size()

    os.makedirs(os.path.dirname(os.path.abspath(output_file)), exist_ok=True)
    with open(output_file, "w") as f:
        json.dump(out, f, ensure_ascii=False)
    return out


def parse_args(argv=None):
    """Parse-only entry (the staged-session preflight test validates the
    hardware session's command lines against this exact parser)."""
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    t = sub.add_parser("train", help="train a BPE tokenizer")
    t.add_argument("--data_path", "-d", required=True)
    t.add_argument("--vocab_size", "-v", type=int, default=30000)
    t.add_argument("--output_path", "-o", required=True)

    e = sub.add_parser("encode", help="pre-tokenize splits to token JSON")
    e.add_argument("--input_file", "-i", required=True)
    e.add_argument("--output_file", "-o", required=True)
    e.add_argument("--tokenizer_file", "-t", required=True)
    e.add_argument("--splits", "-s", nargs="+", default=["train", "validation"])
    e.add_argument("--backend", choices=["auto", "native", "hf"],
                   default="auto")
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    if args.cmd == "train":
        train_bpe(args.data_path, args.output_path, args.vocab_size)
    else:
        pre_tokenize(args.input_file, args.output_file, args.tokenizer_file,
                     args.splits, backend=args.backend)


if __name__ == "__main__":
    main()
