"""Token dataset + batch iterator (host-side, numpy — no torch).

Replaces `/root/reference/dataset.py` (`ShakespeareDataset` + `collate_fn` +
`get_dataloader`). Collate semantics are identical (`dataset.py:40-55`):

    input_ids  = [BOS] + tokens, padded with EOS
    target_ids = tokens + [EOS], padded with IGNORE_INDEX   (shift-by-one LM)
    position_ids = arange

One deliberate deviation for XLA: the reference pads each batch to its own
max length (`dataset.py:41`), which under jit would recompile per batch shape.
We pad every batch to a fixed `pad_to` length (default: model maxlen). The
loss is unchanged — padded targets are IGNORE_INDEX and masked out of the CE
mean — only the padding compute differs. Sequences longer than maxlen-1 are
truncated with a warning, like the reference (`dataset.py:33-37`).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional

import numpy as np

from ..config import BOS_TOKEN, EOS_TOKEN, IGNORE_INDEX, UNK_TOKEN


class TokenDataset:
    """Loads the token-JSON produced by `data.tokenizer.pre_tokenize` (or the
    reference's `pre_tokenize.py` — same schema)."""

    def __init__(self, data_path: str, split: str, maxlen: int):
        assert split in ("train", "validation"), (
            f"expected split 'train' or 'validation', got {split!r}")
        assert os.path.exists(data_path), f"data file not found: {data_path}"
        with open(data_path) as f:
            self.data = json.load(f)
        if split not in self.data:
            raise ValueError(
                f"split {split!r} not in {data_path}; available: "
                f"{list(self.data.keys())}")
        self.split = split
        self.maxlen = maxlen
        self.bos: int = self.data["special_ids"][BOS_TOKEN]
        self.eos: int = self.data["special_ids"][EOS_TOKEN]
        self.unk: int = self.data["special_ids"][UNK_TOKEN]
        self.vocab_size: int = self.data["vocab_size"]
        self._warned = False

    def __len__(self) -> int:
        return len(self.data[self.split])

    def __getitem__(self, idx: int) -> List[int]:
        tokens = self.data[self.split][idx]
        if len(tokens) > self.maxlen - 1:  # reserve one slot for BOS/EOS shift
            if not self._warned:
                print(f"Warning: sequence longer than maxlen-1 "
                      f"({len(tokens)} > {self.maxlen - 1}); truncating "
                      f"(further warnings suppressed)")
                self._warned = True
            tokens = tokens[: self.maxlen - 1]
        return tokens

    def packed(self):
        """(packed int32, offsets int64) — the whole split concatenated, for
        the native indexed-collate fast path (csrc collate_indexed gathers
        rows straight from this buffer; truncation to maxlen-1 happens in
        C++ via its `cap` argument). Built lazily, cached."""
        if not hasattr(self, "_packed"):
            seqs = self.data[self.split]
            lens = np.fromiter((len(s) for s in seqs), np.int64, len(seqs))
            offsets = np.zeros(len(seqs) + 1, np.int64)
            np.cumsum(lens, out=offsets[1:])
            packed = np.empty(int(offsets[-1]), np.int32)
            for i, s in enumerate(seqs):
                packed[offsets[i]:offsets[i + 1]] = s
            self._packed = (packed, offsets)
        return self._packed


def collate(batch: List[List[int]], bos: int, eos: int, ignore_idx: int,
            pad_to: Optional[int] = None) -> Dict[str, np.ndarray]:
    """Reference `collate_fn` (`dataset.py:40-55`) with fixed-shape padding."""
    max_len = max(len(x) for x in batch)
    width = (pad_to if pad_to is not None else max_len + 1)
    assert width >= max_len + 1, f"pad_to {width} < longest sequence + 1"
    n = len(batch)
    input_ids = np.full((n, width), eos, dtype=np.int32)
    target_ids = np.full((n, width), ignore_idx, dtype=np.int32)
    for i, toks in enumerate(batch):
        L = len(toks)
        input_ids[i, 0] = bos
        input_ids[i, 1 : L + 1] = toks
        target_ids[i, :L] = toks
        target_ids[i, L] = eos
    position_ids = np.tile(np.arange(width, dtype=np.int32)[None, :], (n, 1))
    return {"input_ids": input_ids, "target_ids": target_ids,
            "position_ids": position_ids}


@dataclass
class DataLoader:
    """Epoch-aware shuffling batch iterator.

    Mirrors the reference's `torch.utils.data.DataLoader(shuffle=True)` use
    (`dataset.py:58-68`) minus torch. `drop_last=True` for training keeps
    every batch the same shape (no recompiles); the reference's final partial
    batch is instead carried into the next epoch's order.

    `backend` selects the collate implementation like the tokenizer's
    backend flag: 'native' = the C++ `collate_batch` (csrc/dataloader.cpp),
    'numpy' = the pure-Python path, 'auto' = native when the library builds
    (byte-equality of the two is asserted in tests/test_native_data.py).
    """

    dataset: TokenDataset
    batch_size: int
    ignore_idx: int = IGNORE_INDEX
    shuffle: bool = True
    seed: int = 0
    pad_to: Optional[int] = None
    drop_last: bool = True
    backend: str = "auto"

    def __post_init__(self):
        if self.backend not in ("auto", "native", "numpy"):
            raise ValueError(f"backend must be auto|native|numpy, "
                             f"got {self.backend!r}")
        use_native = False
        if self.backend in ("auto", "native"):
            from .native import native_available
            use_native = native_available()
            if self.backend == "native" and not use_native:
                raise RuntimeError("native collate requested but the C++ "
                                   "library is unavailable")
        self._use_native = use_native

    def _collate(self, batch: List[List[int]]) -> Dict[str, np.ndarray]:
        ds = self.dataset
        if self._use_native:
            from .native import native_collate
            return native_collate(batch, ds.bos, ds.eos, self.ignore_idx,
                                  self.pad_to)
        return collate(batch, ds.bos, ds.eos, self.ignore_idx, self.pad_to)

    def __len__(self) -> int:
        n = len(self.dataset)
        return n // self.batch_size if self.drop_last else (
            (n + self.batch_size - 1) // self.batch_size)

    def epoch(self, epoch: int = 0) -> Iterator[Dict[str, np.ndarray]]:
        n = len(self.dataset)
        order = np.arange(n)
        if self.shuffle:
            order = np.random.RandomState(self.seed + epoch).permutation(n)
        bs = self.batch_size
        end = n - n % bs if self.drop_last else n
        if self._use_native:
            # Indexed fast path: ONE GIL-released C++ call gathers the rows
            # from the packed corpus, truncates, and collates — no per-row
            # Python list handling. Byte-identical to the slow path
            # (tests/test_native_data.py).
            from .native import native_collate_indexed
            ds = self.dataset
            packed, offsets = ds.packed()
            cap = ds.maxlen - 1
            for st in range(0, end, bs):
                idxs = order[st : st + bs]
                if self.pad_to is None:
                    lens = offsets[idxs + 1] - offsets[idxs]
                    width = int(min(lens.max(), cap)) + 1
                else:
                    width = self.pad_to
                yield native_collate_indexed(packed, offsets, idxs, cap,
                                             width, ds.bos, ds.eos,
                                             self.ignore_idx)
            return
        for st in range(0, end, bs):
            idxs = order[st : st + bs]
            batch = [self.dataset[int(i)] for i in idxs]
            yield self._collate(batch)

    def __iter__(self):
        return self.epoch(0)


@dataclass
class PackedDataLoader:
    """Classic packed-stream LM loader — zero padding compute (beyond the
    reference, which pads each row to the batch max, `dataset.py:40-55`).

    Per epoch: shuffle the documents, frame each as [BOS] + tokens + [EOS],
    concatenate into one stream, and cut fixed (batch, maxlen) chunks with
    the shift-by-one target (`target[t] = input[t+1]`; the last target of a
    row is the next row's first token). Every batch is identical shape with
    no IGNORE_INDEX padding, so with avg document length << maxlen the
    per-step useful-token fraction goes from ~avg_len/maxlen to 1.0.

    Semantics deviations from the docs-mode loader, both standard for GPT
    training and documented here: (a) documents can span chunk boundaries,
    and attention may cross document boundaries within a row (EOS/BOS
    separators mark them); (b) position_ids restart per ROW, not per
    document; (c) no truncation — long documents simply span chunks.
    Exposes the same interface the train loop consumes (`dataset`,
    `__len__`, `epoch`).
    """

    dataset: TokenDataset
    batch_size: int
    maxlen: int
    shuffle: bool = True
    seed: int = 0

    def __post_init__(self):
        ds = self.dataset
        seqs = ds.data[ds.split]
        # Frame every document ONCE into a cached [BOS]+doc+[EOS] buffer;
        # each epoch is then a pure gather of shuffled spans (no
        # per-element Python work on the epoch boundary, where it would
        # serialize ahead of the prefetch thread).
        lens = np.fromiter((len(s) for s in seqs), np.int64, len(seqs))
        self._offsets = np.zeros(len(seqs) + 1, np.int64)
        np.cumsum(lens + 2, out=self._offsets[1:])
        self._total = int(self._offsets[-1])
        if self._total - 1 < self.batch_size * self.maxlen:
            raise ValueError(
                f"packed mode needs at least batch_size*maxlen+1 = "
                f"{self.batch_size * self.maxlen + 1} framed tokens, "
                f"corpus has {self._total}")
        self._framed = np.empty(self._total, np.int32)
        for i, s in enumerate(seqs):
            o = int(self._offsets[i])
            self._framed[o] = ds.bos
            self._framed[o + 1 : o + 1 + len(s)] = s
            self._framed[o + 1 + len(s)] = ds.eos

    def __len__(self) -> int:
        return (self._total - 1) // (self.batch_size * self.maxlen)

    def epoch(self, epoch: int = 0) -> Iterator[Dict[str, np.ndarray]]:
        n_docs = len(self._offsets) - 1
        if self.shuffle:
            order = np.random.RandomState(self.seed + epoch).permutation(
                n_docs)
            stream = np.concatenate(
                [self._framed[self._offsets[i]:self._offsets[i + 1]]
                 for i in order])
        else:
            stream = self._framed
        bs, T = self.batch_size, self.maxlen
        span = bs * T
        pos = np.tile(np.arange(T, dtype=np.int32)[None, :], (bs, 1))
        for st in range(0, self._total - 1 - span + 1, span):
            seg = stream[st : st + span + 1]
            yield {"input_ids": seg[:-1].reshape(bs, T),
                   "target_ids": seg[1:].reshape(bs, T),
                   "position_ids": pos}

    def __iter__(self):
        return self.epoch(0)


def get_dataloader(data_path: str, batch_size: int,
                   ignore_idx: int = IGNORE_INDEX, split: str = "train",
                   maxlen: int = 1000, shuffle: bool = True, seed: int = 0,
                   pad_to: Optional[int] = None,
                   drop_last: Optional[bool] = None,
                   backend: str = "auto",
                   data_mode: str = "docs") -> "DataLoader | PackedDataLoader":
    """Reference-parity factory (`dataset.py:58-68`).

    `data_mode='packed'` returns the zero-padding packed-stream loader
    instead (training only; see PackedDataLoader)."""
    ds = TokenDataset(data_path, split, maxlen)
    if data_mode == "packed":
        # training-only mode; the docs-path knobs cannot take effect — an
        # explicit non-default request must fail loudly, not silently
        if split != "train":
            raise ValueError("data_mode='packed' is a TRAINING data mode; "
                             "evaluation is per-document (split='validation' "
                             "uses data_mode='docs')")
        bad = [name for name, val, dflt in [
            ("pad_to", pad_to, None), ("drop_last", drop_last, None),
            ("backend", backend, "auto"),
            ("ignore_idx", ignore_idx, IGNORE_INDEX)] if val != dflt]
        if bad:
            raise ValueError(f"data_mode='packed' ignores {bad}; remove "
                             f"them (chunks are always fixed-shape and "
                             f"assembled in numpy)")
        return PackedDataLoader(ds, batch_size, maxlen, shuffle, seed)
    if data_mode != "docs":
        raise ValueError(f"data_mode must be 'docs' or 'packed', "
                         f"got {data_mode!r}")
    if pad_to is None:
        pad_to = maxlen
    if drop_last is None:
        drop_last = split == "train"
    return DataLoader(ds, batch_size, ignore_idx, shuffle, seed, pad_to,
                      drop_last, backend)
