"""ctypes binding for the native C++ data-path library (csrc/dataloader.cpp).

Build model: the shared library is compiled on demand with g++ (cached next
to the source; pybind11 is not in this image, so the C ABI + ctypes is the
binding). Everything degrades gracefully: if no compiler is available the
callers fall back to the HF tokenizer / numpy collate paths.

`NativeBPE` self-verifies on construction: it encodes a battery of probe
texts with both the native encoder and the HF tokenizer and refuses to load
(raises) on any mismatch — the compact Unicode tables in the C++ scanner
cover common scripts, and this check catches any corpus where that matters.
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
from typing import List, Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "csrc", "dataloader.cpp")
_LIB = os.path.join(os.path.dirname(_SRC), "libdistdata.so")

_lib = None
_lib_err: Optional[str] = None

PROBE_TEXTS = [
    "Nice to meet you, it's a test",
    "hello   world\n\nnew  paragraph",
    "don't you'll we've I'm he'd they're",
    "numbers 123 45.67 8,900 and (punct)!?;:--\"quotes\"",
    " leading and trailing  ",
    "tabs\tand\nnewlines \n mixed",
    "CamelCase ALLCAPS mIxEd",
    "unicode: café naïve über буквы",
    "",
    "a",
]


def _build() -> Optional[str]:
    """Compile the shared library if needed; returns an error string or None."""
    if os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
        return None
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _LIB],
            check=True, capture_output=True, text=True, timeout=120)
        return None
    except FileNotFoundError:
        return "g++ not found"
    except subprocess.CalledProcessError as e:
        return f"g++ failed: {e.stderr[:500]}"
    except subprocess.TimeoutExpired:
        return "g++ timed out"


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded library, building it on first use; None if unavailable."""
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    _lib_err = _build()
    if _lib_err is not None:
        return None
    lib = ctypes.CDLL(_LIB)
    lib.tok_create.restype = ctypes.c_void_p
    lib.tok_create.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32, ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int32, ctypes.c_int32]
    lib.tok_encode.restype = ctypes.c_int32
    lib.tok_encode.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_char), ctypes.c_int32,
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int32), ctypes.c_int32]
    lib.tok_free.argtypes = [ctypes.c_void_p]
    lib.collate_batch.argtypes = [ctypes.POINTER(ctypes.c_int32)] * 2 + \
        [ctypes.c_int32] * 5 + [ctypes.POINTER(ctypes.c_int32)] * 3
    lib.collate_indexed.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32)] + [ctypes.c_int32] * 6 + \
        [ctypes.POINTER(ctypes.c_int32)] * 3
    _lib = lib
    return _lib


def native_available() -> bool:
    return get_lib() is not None


class NativeBPE:
    """Byte-level BPE encoder backed by the C++ library, loaded from a HF
    `tokenizer.json`. Construction verifies parity against the HF encoder on
    PROBE_TEXTS (+ optional caller-provided samples) and raises on mismatch."""

    def __init__(self, tokenizer_json: str, verify_against_hf: bool = True,
                 extra_probes: Optional[List[str]] = None):
        lib = get_lib()
        if lib is None:
            raise RuntimeError(f"native library unavailable: {_lib_err}")
        self._lib = lib
        spec = json.load(open(tokenizer_json))
        if spec["model"]["type"] != "BPE":
            raise ValueError(f"unsupported model type {spec['model']['type']}")
        pre = spec.get("pre_tokenizer") or {}
        self.add_prefix_space = bool(pre.get("add_prefix_space", False))

        vocab = spec["model"]["vocab"]
        # Added tokens (BOS/EOS/UNK) participate only as whole strings; the
        # encode path never produces them from text (the reference feeds
        # specials via collate, not the tokenizer). HF *does* match a
        # literal added-token string appearing in raw text, so callers must
        # route such corpora to the HF path — `added_tokens` is exposed for
        # that scan (see data.tokenizer.pre_tokenize; ADVICE r1).
        self.added_tokens = [at["content"]
                             for at in spec.get("added_tokens", [])]
        toks = list(vocab.keys())
        ids = [vocab[t] for t in toks]
        merges = spec["model"]["merges"]
        ml = [(m[0] if isinstance(m, (list, tuple)) else m.split(" ")[0])
              for m in merges]
        mr = [(m[1] if isinstance(m, (list, tuple)) else m.split(" ")[1])
              for m in merges]

        tok_arr = (ctypes.c_char_p * len(toks))(
            *[t.encode("utf-8") for t in toks])
        id_arr = (ctypes.c_int32 * len(ids))(*ids)
        ml_arr = (ctypes.c_char_p * len(ml))(*[x.encode("utf-8") for x in ml])
        mr_arr = (ctypes.c_char_p * len(mr))(*[x.encode("utf-8") for x in mr])
        unk_token = spec["model"].get("unk_token")
        unk_id = -1
        if unk_token is not None:
            unk_id = vocab.get(unk_token, -1)
            if unk_id < 0:
                for at in spec.get("added_tokens", []):
                    if at["content"] == unk_token:
                        unk_id = at["id"]
        self._tok = lib.tok_create(tok_arr, id_arr, len(toks),
                                   ml_arr, mr_arr, len(ml), unk_id)
        self._buf = (ctypes.c_int32 * (1 << 16))()

        if verify_against_hf:
            self._verify(tokenizer_json, (extra_probes or []) + PROBE_TEXTS)

    def _verify(self, tokenizer_json: str, probes: List[str]) -> None:
        try:
            from tokenizers import Tokenizer as HFTokenizer
        except ImportError:
            return  # nothing to verify against
        hf = HFTokenizer.from_file(tokenizer_json)
        for text in probes:
            if self.encode(text) != hf.encode(text).ids:
                raise RuntimeError(
                    f"native BPE disagrees with HF tokenizers on {text!r}; "
                    f"use the HF path for this corpus")

    def encode(self, text: str) -> List[int]:
        data = text.encode("utf-8")
        # explicit byte length: embedded NULs must not truncate (c_char_p
        # marshalling would stop at the first NUL)
        aps = 1 if self.add_prefix_space else 0
        n = self._lib.tok_encode(self._tok, data, len(data), aps,
                                 self._buf, len(self._buf))
        while n > len(self._buf):  # buffer too small: grow and re-encode
            self._buf = (ctypes.c_int32 * (2 * n))()
            n = self._lib.tok_encode(self._tok, data, len(data), aps,
                                     self._buf, len(self._buf))
        return list(self._buf[:n])

    def __del__(self):
        if getattr(self, "_tok", None) and getattr(self, "_lib", None):
            self._lib.tok_free(self._tok)


def native_collate(batch: List[List[int]], bos: int, eos: int,
                   ignore_idx: int, width: Optional[int] = None) -> dict:
    """C++ collate with the reference's exact semantics
    (`/root/reference/dataset.py:40-55`); same output dict as
    data.dataset.collate. `width=None` pads to the longest row + 1, the same
    default rule as `collate(pad_to=None)`."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_lib_err}")
    import itertools

    n = len(batch)
    lens_py = list(map(len, batch))
    longest = max(lens_py, default=0)
    if width is None:
        width = longest + 1
    assert width >= longest + 1, (
        f"pad width {width} < longest sequence + 1 ({longest + 1}); callers "
        f"must truncate to width-1 first (dataset.TokenDataset does)")
    flat = np.fromiter(itertools.chain.from_iterable(batch), np.int32,
                       sum(lens_py))
    lens = np.asarray(lens_py, np.int32)
    input_ids = np.empty((n, width), np.int32)
    target_ids = np.empty((n, width), np.int32)
    position_ids = np.empty((n, width), np.int32)
    as_p = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    lib.collate_batch(as_p(flat), as_p(lens), n, width, bos, eos, ignore_idx,
                      as_p(input_ids), as_p(target_ids), as_p(position_ids))
    return {"input_ids": input_ids, "target_ids": target_ids,
            "position_ids": position_ids}


def native_collate_indexed(packed: np.ndarray, offsets: np.ndarray,
                           idxs: np.ndarray, cap: int, width: int,
                           bos: int, eos: int, ignore_idx: int) -> dict:
    """Whole-batch gather + truncate + collate in ONE C++ call over the
    packed corpus (csrc/dataloader.cpp::collate_indexed). `cap` is the
    maxlen-1 truncation limit TokenDataset applies; `width` the fixed pad
    length. ctypes releases the GIL for the call's duration, so a prefetch
    thread runs it concurrently with the training loop."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError(f"native library unavailable: {_lib_err}")
    assert packed.dtype == np.int32 and offsets.dtype == np.int64
    n = len(idxs)
    idxs = np.ascontiguousarray(idxs, np.int32)
    # Mirror native_collate's guard: the C++ side clamps rows to width-1
    # defensively, which would otherwise turn an undersized width into
    # silently truncated batches (ADVICE r2) instead of the error the
    # numpy path raises.
    if n:
        idx64 = idxs.astype(np.int64)
        longest = int(min((offsets[idx64 + 1] - offsets[idx64]).max(), cap))
        assert width >= longest + 1, (
            f"pad width {width} < longest selected row + 1 ({longest + 1}) "
            f"after cap {cap}")
    input_ids = np.empty((n, width), np.int32)
    target_ids = np.empty((n, width), np.int32)
    position_ids = np.empty((n, width), np.int32)
    as_p = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
    lib.collate_indexed(
        as_p(packed), offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        as_p(idxs), n, cap, width, bos, eos, ignore_idx,
        as_p(input_ids), as_p(target_ids), as_p(position_ids))
    return {"input_ids": input_ids, "target_ids": target_ids,
            "position_ids": position_ids}
