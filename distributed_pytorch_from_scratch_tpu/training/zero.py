"""ZeRO-1: shard the Adam moments over the data-parallel axis.

Absent from the reference (plain per-rank `optim.Adam`,
`/root/reference/train.py:83` — every rank keeps full moments; SURVEY §2.4
"ZeRO ❌"). On TPU this is a *layout* decision, not new algorithm code: the
moments get a PartitionSpec that additionally shards their first free,
dp-divisible dimension over 'dp', and `jit`'s out_shardings pin them there.
XLA's SPMD partitioner then computes each moment update (and the parameter
delta) on the dp shard that owns it and all-gathers the updated parameters —
the ZeRO-1 reduce-scatter/update/all-gather schedule, derived by the
compiler instead of hand-written NCCL (the scaling-book recipe).

Memory: Adam moments are 2x param bytes; sharding them over dp cuts
per-device optimizer memory to 2/dp — the dominant saving at dp >= 4.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"


def zero1_specs(specs: Any, shapes: Any, mesh: Mesh,
                dp_axis: str = DP_AXIS) -> Any:
    """Moment PartitionSpecs: each param spec extended with `dp_axis` on the
    first unsharded dimension whose size divides by the dp axis size.

    Leaves where no dimension qualifies (e.g. tiny norm gains with every dim
    taken or indivisible) stay on their param spec — replicated over dp, like
    plain Adam. `shapes` is any pytree with `.shape`/`.ndim` leaves matching
    `specs` (e.g. from `jax.eval_shape`).
    """
    dp = mesh.shape[dp_axis]

    def one(spec: P, shaped) -> P:
        if dp == 1:
            return spec
        spec_t = tuple(spec) + (None,) * (shaped.ndim - len(tuple(spec)))
        for i, (s, d) in enumerate(zip(spec_t, shaped.shape)):
            if s is None and d % dp == 0 and d > 0:
                return P(*spec_t[:i], dp_axis, *spec_t[i + 1:])
        return spec

    return jax.tree.map(one, specs, shapes,
                        is_leaf=lambda x: isinstance(x, P))


def zero1_moment_shardings(model, mesh: Mesh) -> Any:
    """NamedSharding pytree for the Adam mu/nu trees of `model` on `mesh`."""
    shapes = jax.eval_shape(model.init, jax.random.key(0))
    specs = zero1_specs(model.specs(), shapes, mesh)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))
