"""ZeRO 1/2/3: shard the weight-update state over the data-parallel axis.

Absent from the reference (plain per-rank `optim.Adam`,
`/root/reference/train.py:83` — every rank keeps full moments; SURVEY §2.4
"ZeRO ❌"). The ladder, following "Automatic Cross-Replica Sharding of
Weight Update in Data-Parallel Training" (PAPERS.md):

* **Stage 1** — Adam moments get a PartitionSpec that additionally shards
  their first free, dp-divisible dimension over 'dp', and `jit`'s
  out_shardings pin them there. XLA's SPMD partitioner then computes each
  moment update (and the parameter delta) on the dp shard that owns it and
  all-gathers the updated parameters — the reduce-scatter/update/all-gather
  schedule, derived by the compiler instead of hand-written NCCL (the
  scaling-book recipe). Optimizer memory: 2/dp x param bytes.

* **Stage 2** — gradients too: `build_bucketed_grad_fn(zero_stage=2)` swaps
  each bucket's all-reduce for a RE­DUCE-SCATTER (`ops/overlap.
  bucketed_reduce_scatter` — same bucket boundaries, HALF the wire bytes),
  so every dp rank receives only the 1/dp grad shard it updates; the int8
  wire reuses PR 8's quantized ring stopped after its reduce-scatter half
  (`quantized_reduce_scatter`). The optimizer update is then fully local
  per shard and ONE parameter all-gather per step (XLA inserts it to meet
  the replicated param out_sharding) replaces the grads' gather half.
  Grad + optimizer memory: (1 + 2)/dp x param bytes.

* **Stage 3** — the parameters themselves: `zero3_specs` extends the param
  specs with a 'dp' dim (skipping the stacked layer axis so the scan still
  slices per layer), `build_zero3_grad_fn` runs the loss with params
  ENTERING shard_map dp-sharded, and the model's layer scan ring-all-
  gathers each layer's leaves on entry (`zero3_layer_gather`, called from
  `_layer_body` under the `zero3_axis` field — INSIDE the remat boundary,
  so gathered weights are recomputed rather than saved and peak param HBM
  is full/dp + one gathered layer). The backward derives the grad
  reduce-scatter for free: `ring_all_gather`'s transpose is the conjugate
  ppermute ring, handing each rank the dp-summed cotangent of exactly its
  own shard. Param + grad + optimizer memory: 4/dp x param bytes per
  device — the unlock for configs whose full replica exceeds HBM x tp.

Scope (stages 2/3): dense models, pp=1, and sequence_parallel whenever
tp > 1 — the same per-leaf cotangent bookkeeping scope as the bucketed
reducer; the refusals below are loud.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.overlap import (bucketed_psum, bucketed_reduce_scatter,
                           ring_all_gather)

DP_AXIS = "dp"


def _zero_dim(spec: P, shaped, dp: int, start: int = 0) -> int:
    """Index of the first dimension of `shaped` at or after `start` that
    `spec` leaves unsharded and whose size divides by `dp`; -1 when none
    qualifies (the leaf stays replicated over dp). The ONE dim-selection
    rule shared by the stage-1 moment specs, the stage-2 grad scatter and
    the stage-3 param specs/per-layer gather — they must never disagree,
    or a grad shard would land on a layout its moment doesn't own."""
    if dp == 1:
        return -1
    spec_t = tuple(spec) + (None,) * (shaped.ndim - len(tuple(spec)))
    for i, (s, d) in enumerate(zip(spec_t, shaped.shape)):
        if i >= start and s is None and d % dp == 0 and d > 0:
            return i
    return -1


def _extend_spec(spec: P, shaped, dim: int, dp_axis: str) -> P:
    if dim < 0:
        return spec
    spec_t = tuple(spec) + (None,) * (shaped.ndim - len(tuple(spec)))
    return P(*spec_t[:dim], dp_axis, *spec_t[dim + 1:])


def zero1_specs(specs: Any, shapes: Any, mesh: Mesh,
                dp_axis: str = DP_AXIS) -> Any:
    """Moment PartitionSpecs: each param spec extended with `dp_axis` on the
    first unsharded dimension whose size divides by the dp axis size.

    Leaves where no dimension qualifies (e.g. tiny norm gains with every dim
    taken or indivisible) stay on their param spec — replicated over dp, like
    plain Adam. `shapes` is any pytree with `.shape`/`.ndim` leaves matching
    `specs` (e.g. from `jax.eval_shape`).
    """
    dp = mesh.shape[dp_axis]

    def one(spec: P, shaped) -> P:
        return _extend_spec(spec, shaped, _zero_dim(spec, shaped, dp),
                            dp_axis)

    return jax.tree.map(one, specs, shapes,
                        is_leaf=lambda x: isinstance(x, P))


@functools.lru_cache(maxsize=32)
def _eval_shapes(model) -> Any:
    """Abstract param-tree shapes for `model`. Cached: both model families
    are frozen, value-hashable dataclasses, and `jax.eval_shape` of the
    full init — pure host work, but a whole trace — would otherwise rerun
    on every trace of the ZeRO-3 layer body (fwd + checkpoint fwd + bwd
    replay) and on every specs/shardings call."""
    return jax.eval_shape(model.init, jax.random.key(0))


def zero1_moment_shardings(model, mesh: Mesh) -> Any:
    """NamedSharding pytree for the Adam mu/nu trees of `model` on `mesh`."""
    specs = zero1_specs(model.specs(), _eval_shapes(model), mesh)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


# ----------------------------------------------------------- ZeRO-3 layout --

@functools.lru_cache(maxsize=32)
def zero3_dims(model, dp: int) -> Any:
    """Per-leaf ZeRO-3 partition dims for `model`'s param tree (STACKED
    layout): -1 = replicated over dp, else the dim index `dp_axis` shards.

    The layers subtree skips dim 0 — that's the stacked num_layers axis the
    forward scan slices per layer, so sharding it would hand each dp rank a
    DIFFERENT model; each layer leaf shards within-layer instead (its
    in-scan gather dim is this value minus 1). Non-layer leaves (embedding,
    final norm, lm_head/pos tables) use the plain stage-1 rule.

    Cached per (model, dp) — the result is a static int tree consulted on
    every layer-body trace; treat it as read-only."""
    specs = model.specs()
    shapes = _eval_shapes(model)
    out = {}
    for key, sub in specs.items():
        start = 1 if key == "layers" else 0
        out[key] = jax.tree.map(
            lambda s, sh: _zero_dim(s, sh, dp, start=start),
            sub, shapes[key], is_leaf=lambda x: isinstance(x, P))
    return out


def zero3_specs(model, mesh: Mesh, dp_axis: str = DP_AXIS) -> Any:
    """PartitionSpec tree for ZeRO-3 params (and their grads/moments —
    all three live on the same layout, so the Adam update is fully local)."""
    specs = model.specs()
    shapes = _eval_shapes(model)
    dims = zero3_dims(model, mesh.shape[dp_axis])
    return jax.tree.map(
        lambda s, sh, d: _extend_spec(s, sh, d, dp_axis),
        specs, shapes, dims, is_leaf=lambda x: isinstance(x, P))


def zero3_shardings(model, mesh: Mesh, dp_axis: str = DP_AXIS) -> Any:
    """NamedSharding pytree for ZeRO-3 params/grads/moments on `mesh`."""
    return jax.tree.map(lambda s: NamedSharding(mesh, s),
                        zero3_specs(model, mesh, dp_axis),
                        is_leaf=lambda x: isinstance(x, P))


def zero3_layer_gather(model, layer_params: Any,
                       axis: str = DP_AXIS) -> Any:
    """Gather ONE layer's dp-sharded leaves back to their tp-local shapes
    (ring all-gather per leaf; `ops/overlap.ring_all_gather`).

    Called from the model's `_layer_body` when `model.zero3_axis` is set —
    i.e. inside the layer scan AND inside the remat boundary, which is what
    bounds gathered-weight liveness to one layer: the scan structurally
    frees the gather before the next iteration, and remat replays (rather
    than saves) it for the backward. The transpose of each gather is the
    conjugate ring reduce-scatter, so the backward also produces each
    rank's dp-SUMMED grad shard without an explicit all-reduce."""
    from jax import lax
    dp = lax.axis_size(axis)  # static: mesh shape is trace-time known
    if dp == 1:
        return layer_params
    dims = zero3_dims(model, dp)["layers"]
    return jax.tree.map(
        lambda a, d: a if d < 0 else ring_all_gather(a, axis, d - 1),
        layer_params, dims)


def _check_bucketed_scope(model, what: str) -> None:
    """The shared stage>=2 / bucketed-reducer scope refusals."""
    if model.is_moe:
        raise ValueError(
            f"{what} does not compose with MoE: expert grads are "
            f"ep-sharded, not batch-replicated — use the default reducer")
    if model.pp_size > 1:
        raise ValueError(
            f"{what} requires pp_size == 1: non-layer params are "
            f"pp-replicated and their reduction axes depend on the "
            f"pipeline head layout — use the default reducer")
    if model.tp_size > 1 and not model.sequence_parallel:
        raise ValueError(
            f"{what} with tp > 1 requires sequence_parallel: the non-SP "
            f"path all-reduces inside every row-parallel layer, so "
            f"per-shard cotangent bookkeeping is depth-dependent — use "
            f"the default reducer (or turn SP on)")


# ------------------------------------------------- bucketed grad reduction --

def _spec_axes(spec: P) -> set:
    """Mesh axes a PartitionSpec shards over (entries may be axis names or
    tuples of them)."""
    out = set()
    for entry in tuple(spec):
        if entry is None:
            continue
        for a in (entry if isinstance(entry, tuple) else (entry,)):
            out.add(a)
    return out


def build_bucketed_grad_fn(model, mesh: Mesh, loss_mode: str = "vocab_parallel",
                           bucket_mb: float = 25.0, reduce_dtype=None,
                           zero_stage: int = 1):
    """(params, ids, tgt, pos) -> (loss, grads) with the data-parallel
    gradient reduction issued in size-bounded BUCKETS instead of the
    shard_map transpose's end-of-step whole-tree blob.

    How: the loss AND its gradient are taken per-shard (jax.value_and_grad
    INSIDE one shard_map), so no automatic boundary reduction happens for
    the grads; the batch-axis sums the transpose would have inserted are
    issued explicitly by `ops.overlap.bucketed_psum` — one flattened psum
    per <= bucket_mb bucket, each depending only on its own cotangents, so
    XLA can launch it as soon as the backward produces them and hide the
    wire under the remaining backward compute. `reduce_dtype` compresses
    the wire only; grads return to f32 before the optimizer's master
    accumulate (EQuARX-style, no stochastic rounding): jnp.bfloat16
    casts around the psum (bound pinned in tests/test_overlap.py),
    jnp.int8 routes each bucket through the block-scaled quantized ring
    (`ops/overlap.quantized_allreduce`; bound pinned in
    tests/test_quant.py).

    `zero_stage=2` swaps each dp bucket's all-reduce for a REDUCE-SCATTER
    (`ops/overlap.bucketed_reduce_scatter` — identical buckets, HALF the
    wire bytes): every leaf with a free dp-divisible dim (the `zero1_specs`
    rule, so the grad shard lands exactly on its moment's layout) comes
    back as this rank's 1/dp shard, declared dp-sharded in the out_specs;
    the int8 wire routes through `quantized_reduce_scatter`, PR 8's ring
    stopped after its reduce-scatter half. Residual axes (cp, and 'tp' for
    SP-replicated leaves) are summed AFTER the scatter on the 1/dp shard;
    leaves with no qualifying dim fall back to the stage-1 psum. The
    optimizer then updates only owned shards and XLA's all-gather of the
    fresh params (to meet the replicated out_sharding) replaces the grads'
    gather half — the ZeRO-2 schedule.

    Which axes each leaf reduces over: the batch axes (dp/ep/cp — params
    are replicated over them, data varies), plus 'tp' for tp-REPLICATED
    leaves when sequence parallelism is on (norm gains / row-linear biases
    then see only t/tp tokens per shard, so their local grads are partial
    sums; without SP those grads are tp-invariant — identical on every
    shard — and summing them would scale by tp). Value-parity with the
    transpose's reduction is pinned in tests/test_overlap.py (stage 1)
    and tests/test_zero.py (stage 2).

    Legacy-jax note (this container's 0.4.x shard_map, check_rep=False):
    the transpose of lax.psum is psum there, so per-shard cotangents
    inflate by the axis-size product of every psum they cross. Under SP
    (or tp=1) that product is UNIFORM across leaves — the batch-axis loss
    psum plus the vocab-parallel CE's tp psum; every other SP collective
    (all_gather / psum_scatter / ppermute) transposes value-correctly —
    and the inflation is measured at trace time with a two-line probe and
    divided out, instead of version-sniffing jax. Parity with the
    whole-tree reducer is pinned in tests/test_overlap.py, which fails
    loudly if a jax upgrade changes the transpose semantics.

    Scope: dense models on pp=1 meshes, with sequence_parallel on
    whenever tp > 1. MoE routes through ep-sharded expert params, pp
    shards the layer stack, and the non-SP tp path crosses a psum per
    row-linear (depth-dependent inflation) — all need per-leaf variance
    bookkeeping the static spec cannot express; the default whole-tree
    path handles them.
    """
    _check_bucketed_scope(model, "bucketed DP grad reduction")
    if zero_stage not in (1, 2):
        raise ValueError(f"build_bucketed_grad_fn handles zero_stage 1 "
                         f"(all-reduce) or 2 (reduce-scatter), got "
                         f"{zero_stage}; stage 3 is build_zero3_grad_fn")
    specs = model.specs()
    batch_axes = ("dp", "ep", "cp")
    sp = model.sequence_parallel
    leaf_specs = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    dp = mesh.shape[DP_AXIS]
    if zero_stage >= 2:
        shapes = _eval_shapes(model)
        leaf_shapes = jax.tree.leaves(shapes)
        scatter_dims = [_zero_dim(s, sh, dp)
                        for s, sh in zip(leaf_specs, leaf_shapes)]
        grad_specs = zero1_specs(specs, shapes, mesh)
    else:
        scatter_dims = [-1] * len(leaf_specs)
        grad_specs = specs

    def shard_fn(params, input_ids, target_ids, position_ids):
        loss, grads = jax.value_and_grad(
            lambda p: model.loss_shard(p, input_ids, target_ids,
                                       position_ids, mode=loss_mode))(params)
        # Measure (don't version-sniff) the per-shard cotangent inflation:
        # each probe differentiates a bare psum over the crossed axes, so
        # it returns the axis-size product under the legacy
        # psum-transposes-to-psum semantics and 1.0 wherever the transpose
        # is value-preserving. Every leaf crosses the batch-axis loss psum
        # and the CE's tp psum exactly once (the SP/tp=1 scope guarantees
        # no others), so the correction is one uniform scalar —
        # constant-folded by XLA.
        k = (jax.grad(lambda z: jax.lax.psum(z, batch_axes))(1.0)
             * jax.grad(lambda z: jax.lax.psum(z, ("tp",)))(1.0))
        grads = jax.tree.map(lambda g: g / k, grads)
        flat, treedef = jax.tree.flatten(grads)
        assert len(flat) == len(leaf_specs)
        groups: "dict[tuple, list[int]]" = {}
        for i, spec in enumerate(leaf_specs):
            axes = batch_axes
            if sp and "tp" not in _spec_axes(spec):
                axes = batch_axes + ("tp",)
            groups.setdefault(axes, []).append(i)
        out = list(flat)
        for axes, idxs in groups.items():
            if zero_stage >= 2:
                scat = [i for i in idxs if scatter_dims[i] >= 0]
                idxs = [i for i in idxs if scatter_dims[i] < 0]
                if scat:
                    shards = bucketed_reduce_scatter(
                        [flat[i] for i in scat],
                        [scatter_dims[i] for i in scat], DP_AXIS,
                        other_axes=tuple(a for a in axes if a != DP_AXIS),
                        bucket_mb=bucket_mb, reduce_dtype=reduce_dtype)
                    for i, r in zip(scat, shards):
                        out[i] = r
            if idxs:
                reduced = bucketed_psum([flat[i] for i in idxs], axes,
                                        bucket_mb=bucket_mb,
                                        reduce_dtype=reduce_dtype)
                for i, r in zip(idxs, reduced):
                    out[i] = r
        return loss, jax.tree.unflatten(treedef, out)

    batch_spec = P(("dp", "ep"), "cp")
    fn = jax.shard_map(shard_fn, mesh=mesh,
                       in_specs=(specs, batch_spec, batch_spec, batch_spec),
                       out_specs=(P(), grad_specs))
    if not model._zigzag:
        return fn

    from ..ops.ring_attention import zigzag_perm

    def zz(params, input_ids, target_ids, position_ids):
        # masked token-mean CE is permutation-invariant (make_loss's rule)
        perm = zigzag_perm(input_ids.shape[1], model.cp_size)
        return fn(params, input_ids[:, perm], target_ids[:, perm],
                  position_ids[:, perm])

    return zz


# ---------------------------------------------- ZeRO-3 gather-on-demand fn --

def build_zero3_grad_fn(model, mesh: Mesh, loss_mode: str = "vocab_parallel",
                        bucket_mb: float = 25.0, dp_axis: str = DP_AXIS):
    """(params, ids, tgt, pos) -> (loss, grads) with params AND grads
    dp-sharded end to end — the ZeRO-3 schedule.

    Params enter shard_map on `zero3_specs` layouts (each leaf's free
    dp-divisible dim sharded; the stacked layer axis deliberately skipped).
    Per-shard, the non-layer leaves (embedding, final norm, head/position
    tables) ring-all-gather once at their use sites; the LAYER leaves stay
    sharded and gather per layer inside the model's scan body (the
    `zero3_axis` hook, inside the remat boundary), so peak gathered-param
    HBM is one layer plus the head/embedding — `full/dp + one layer` for
    the dominant stack. The backward needs no explicit dp grad reduction
    at all: every gather's transpose is the conjugate ring reduce-scatter,
    handing this rank the dp-SUMMED cotangent of exactly its own shard —
    ZeRO-2's halved wire, derived by autodiff. Residual reductions (cp,
    'tp' for SP-replicated leaves, and dp for the few leaves too small to
    shard) go through `bucketed_psum` on the already-scattered shards.

    Requires a remat'ing model (remat True or 'dots'): without remat,
    autodiff would SAVE each layer's gathered weights as backward
    residuals and the full replica would rematerialise in HBM. Scope
    otherwise matches the bucketed reducer: dense, pp=1, SP whenever
    tp > 1. The legacy psum-transpose inflation is probed and divided out
    exactly as in `build_bucketed_grad_fn` (ppermute rings transpose
    value-correctly, so the gathers add no inflation of their own).
    """
    _check_bucketed_scope(model, "ZeRO-3 (gather-on-demand params)")
    if model.remat is False:
        raise ValueError(
            "ZeRO-3 requires a rematerialising model (remat=True or "
            "'dots'): without remat, autodiff saves every layer's GATHERED "
            "weights as backward residuals, recreating the full param "
            "replica the stage exists to eliminate")
    dp = mesh.shape[dp_axis]
    zmodel = dataclasses.replace(model, zero3_axis=dp_axis)
    specs = model.specs()
    pspecs = zero3_specs(model, mesh, dp_axis)
    dims = zero3_dims(model, dp)
    batch_axes = ("dp", "ep", "cp")
    sp = model.sequence_parallel
    leaf_specs = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    leaf_dims = jax.tree.leaves(dims)

    def shard_fn(params, input_ids, target_ids, position_ids):
        def loss_of(p):
            full = {}
            for key, sub in p.items():
                if key == "layers":
                    full[key] = sub  # gathered per layer inside the scan
                else:
                    full[key] = jax.tree.map(
                        lambda a, d: a if d < 0 else
                        ring_all_gather(a, dp_axis, d),
                        sub, dims[key])
            return zmodel.loss_shard(full, input_ids, target_ids,
                                     position_ids, mode=loss_mode)

        loss, grads = jax.value_and_grad(loss_of)(params)
        # the same trace-time inflation probe as build_bucketed_grad_fn:
        # only the loss psum and the CE tp psum inflate; the gather rings
        # (ppermute + slice updates) transpose value-correctly
        k = (jax.grad(lambda z: jax.lax.psum(z, batch_axes))(1.0)
             * jax.grad(lambda z: jax.lax.psum(z, ("tp",)))(1.0))
        grads = jax.tree.map(lambda g: g / k, grads)
        flat, treedef = jax.tree.flatten(grads)
        assert len(flat) == len(leaf_specs)
        groups: "dict[tuple, list[int]]" = {}
        for i, (spec, d) in enumerate(zip(leaf_specs, leaf_dims)):
            # dp-sharded leaves: the gather transpose already dp-summed
            # this shard; only the residual axes remain
            axes = tuple(a for a in batch_axes if d < 0 or a != dp_axis)
            if sp and "tp" not in _spec_axes(spec):
                axes = axes + ("tp",)
            if axes:
                groups.setdefault(axes, []).append(i)
        out = list(flat)
        for axes, idxs in groups.items():
            reduced = bucketed_psum([flat[i] for i in idxs], axes,
                                    bucket_mb=bucket_mb)
            for i, r in zip(idxs, reduced):
                out[i] = r
        return loss, jax.tree.unflatten(treedef, out)

    batch_spec = P(("dp", "ep"), "cp")
    fn = jax.shard_map(shard_fn, mesh=mesh,
                       in_specs=(pspecs, batch_spec, batch_spec, batch_spec),
                       out_specs=(P(), pspecs))
    if not model._zigzag:
        return fn

    from ..ops.ring_attention import zigzag_perm

    def zz(params, input_ids, target_ids, position_ids):
        perm = zigzag_perm(input_ids.shape[1], model.cp_size)
        return fn(params, input_ids[:, perm], target_ids[:, perm],
                  position_ids[:, perm])

    return zz
