"""ZeRO-1: shard the Adam moments over the data-parallel axis.

Absent from the reference (plain per-rank `optim.Adam`,
`/root/reference/train.py:83` — every rank keeps full moments; SURVEY §2.4
"ZeRO ❌"). On TPU this is a *layout* decision, not new algorithm code: the
moments get a PartitionSpec that additionally shards their first free,
dp-divisible dimension over 'dp', and `jit`'s out_shardings pin them there.
XLA's SPMD partitioner then computes each moment update (and the parameter
delta) on the dp shard that owns it and all-gathers the updated parameters —
the ZeRO-1 reduce-scatter/update/all-gather schedule, derived by the
compiler instead of hand-written NCCL (the scaling-book recipe).

Memory: Adam moments are 2x param bytes; sharding them over dp cuts
per-device optimizer memory to 2/dp — the dominant saving at dp >= 4.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.overlap import bucketed_psum

DP_AXIS = "dp"


def zero1_specs(specs: Any, shapes: Any, mesh: Mesh,
                dp_axis: str = DP_AXIS) -> Any:
    """Moment PartitionSpecs: each param spec extended with `dp_axis` on the
    first unsharded dimension whose size divides by the dp axis size.

    Leaves where no dimension qualifies (e.g. tiny norm gains with every dim
    taken or indivisible) stay on their param spec — replicated over dp, like
    plain Adam. `shapes` is any pytree with `.shape`/`.ndim` leaves matching
    `specs` (e.g. from `jax.eval_shape`).
    """
    dp = mesh.shape[dp_axis]

    def one(spec: P, shaped) -> P:
        if dp == 1:
            return spec
        spec_t = tuple(spec) + (None,) * (shaped.ndim - len(tuple(spec)))
        for i, (s, d) in enumerate(zip(spec_t, shaped.shape)):
            if s is None and d % dp == 0 and d > 0:
                return P(*spec_t[:i], dp_axis, *spec_t[i + 1:])
        return spec

    return jax.tree.map(one, specs, shapes,
                        is_leaf=lambda x: isinstance(x, P))


def zero1_moment_shardings(model, mesh: Mesh) -> Any:
    """NamedSharding pytree for the Adam mu/nu trees of `model` on `mesh`."""
    shapes = jax.eval_shape(model.init, jax.random.key(0))
    specs = zero1_specs(model.specs(), shapes, mesh)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


# ------------------------------------------------- bucketed grad reduction --

def _spec_axes(spec: P) -> set:
    """Mesh axes a PartitionSpec shards over (entries may be axis names or
    tuples of them)."""
    out = set()
    for entry in tuple(spec):
        if entry is None:
            continue
        for a in (entry if isinstance(entry, tuple) else (entry,)):
            out.add(a)
    return out


def build_bucketed_grad_fn(model, mesh: Mesh, loss_mode: str = "vocab_parallel",
                           bucket_mb: float = 25.0, reduce_dtype=None):
    """(params, ids, tgt, pos) -> (loss, grads) with the data-parallel
    gradient reduction issued in size-bounded BUCKETS instead of the
    shard_map transpose's end-of-step whole-tree blob.

    How: the loss AND its gradient are taken per-shard (jax.value_and_grad
    INSIDE one shard_map), so no automatic boundary reduction happens for
    the grads; the batch-axis sums the transpose would have inserted are
    issued explicitly by `ops.overlap.bucketed_psum` — one flattened psum
    per <= bucket_mb bucket, each depending only on its own cotangents, so
    XLA can launch it as soon as the backward produces them and hide the
    wire under the remaining backward compute. `reduce_dtype` compresses
    the wire only; grads return to f32 before the optimizer's master
    accumulate (EQuARX-style, no stochastic rounding): jnp.bfloat16
    casts around the psum (bound pinned in tests/test_overlap.py),
    jnp.int8 routes each bucket through the block-scaled quantized ring
    (`ops/overlap.quantized_allreduce`; bound pinned in
    tests/test_quant.py).

    Which axes each leaf reduces over: the batch axes (dp/ep/cp — params
    are replicated over them, data varies), plus 'tp' for tp-REPLICATED
    leaves when sequence parallelism is on (norm gains / row-linear biases
    then see only t/tp tokens per shard, so their local grads are partial
    sums; without SP those grads are tp-invariant — identical on every
    shard — and summing them would scale by tp). Value-parity with the
    transpose's reduction is pinned in tests/test_overlap.py.

    Legacy-jax note (this container's 0.4.x shard_map, check_rep=False):
    the transpose of lax.psum is psum there, so per-shard cotangents
    inflate by the axis-size product of every psum they cross. Under SP
    (or tp=1) that product is UNIFORM across leaves — the batch-axis loss
    psum plus the vocab-parallel CE's tp psum; every other SP collective
    (all_gather / psum_scatter / ppermute) transposes value-correctly —
    and the inflation is measured at trace time with a two-line probe and
    divided out, instead of version-sniffing jax. Parity with the
    whole-tree reducer is pinned in tests/test_overlap.py, which fails
    loudly if a jax upgrade changes the transpose semantics.

    Scope: dense models on pp=1 meshes, with sequence_parallel on
    whenever tp > 1. MoE routes through ep-sharded expert params, pp
    shards the layer stack, and the non-SP tp path crosses a psum per
    row-linear (depth-dependent inflation) — all need per-leaf variance
    bookkeeping the static spec cannot express; the default whole-tree
    path handles them.
    """
    if model.is_moe:
        raise ValueError(
            "bucketed DP grad reduction does not compose with MoE: expert "
            "grads are ep-sharded, not batch-replicated — use the default "
            "reducer")
    if model.pp_size > 1:
        raise ValueError(
            "bucketed DP grad reduction requires pp_size == 1: non-layer "
            "params are pp-replicated and their reduction axes depend on "
            "the pipeline head layout — use the default reducer")
    if model.tp_size > 1 and not model.sequence_parallel:
        raise ValueError(
            "bucketed DP grad reduction with tp > 1 requires "
            "sequence_parallel: the non-SP path all-reduces inside every "
            "row-parallel layer, so per-shard cotangent bookkeeping is "
            "depth-dependent — use the default reducer (or turn SP on)")
    specs = model.specs()
    batch_axes = ("dp", "ep", "cp")
    sp = model.sequence_parallel
    leaf_specs = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))

    def shard_fn(params, input_ids, target_ids, position_ids):
        loss, grads = jax.value_and_grad(
            lambda p: model.loss_shard(p, input_ids, target_ids,
                                       position_ids, mode=loss_mode))(params)
        # Measure (don't version-sniff) the per-shard cotangent inflation:
        # each probe differentiates a bare psum over the crossed axes, so
        # it returns the axis-size product under the legacy
        # psum-transposes-to-psum semantics and 1.0 wherever the transpose
        # is value-preserving. Every leaf crosses the batch-axis loss psum
        # and the CE's tp psum exactly once (the SP/tp=1 scope guarantees
        # no others), so the correction is one uniform scalar —
        # constant-folded by XLA.
        k = (jax.grad(lambda z: jax.lax.psum(z, batch_axes))(1.0)
             * jax.grad(lambda z: jax.lax.psum(z, ("tp",)))(1.0))
        grads = jax.tree.map(lambda g: g / k, grads)
        flat, treedef = jax.tree.flatten(grads)
        assert len(flat) == len(leaf_specs)
        groups: "dict[tuple, list[int]]" = {}
        for i, spec in enumerate(leaf_specs):
            axes = batch_axes
            if sp and "tp" not in _spec_axes(spec):
                axes = batch_axes + ("tp",)
            groups.setdefault(axes, []).append(i)
        out = list(flat)
        for axes, idxs in groups.items():
            reduced = bucketed_psum([flat[i] for i in idxs], axes,
                                    bucket_mb=bucket_mb,
                                    reduce_dtype=reduce_dtype)
            for i, r in zip(idxs, reduced):
                out[i] = r
        return loss, jax.tree.unflatten(treedef, out)

    batch_spec = P(("dp", "ep"), "cp")
    fn = jax.shard_map(shard_fn, mesh=mesh,
                       in_specs=(specs, batch_spec, batch_spec, batch_spec),
                       out_specs=(P(), specs))
    if not model._zigzag:
        return fn

    from ..ops.ring_attention import zigzag_perm

    def zz(params, input_ids, target_ids, position_ids):
        # masked token-mean CE is permutation-invariant (make_loss's rule)
        perm = zigzag_perm(input_ids.shape[1], model.cp_size)
        return fn(params, input_ids[:, perm], target_ids[:, perm],
                  position_ids[:, perm])

    return zz
