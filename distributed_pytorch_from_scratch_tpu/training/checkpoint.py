"""Sharded checkpointing with the reference's filename convention + resume.

The reference saves one `.pth` per TP rank, metadata encoded in the filename
`tprank-{r}_iter-{n}_loss-{avg:.4f}.pth`, re-parsed by regex at eval time
(`/root/reference/train.py:121-133`, `test.py:94-95`), with retention pruning
via `--reserv_last_n_ckpts`. It never saves optimizer/step state, so training
cannot resume (SURVEY §5.4).

Here: same per-TP-shard layout and filename convention (extension `.npz`),
each shard keyed by mesh coordinate, but the checkpoint also carries the Adam
moments and step count so `--resume` restarts training exactly. Arrays are
sliced/reassembled along whichever dimension the param's PartitionSpec marks
as 'tp' — the checkpoint format is mesh-independent (save at TP=8, load at
TP=2: the global arrays are identical).
"""

from __future__ import annotations

import glob
import os
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .optim import AdamState

CKPT_RE = re.compile(r"tprank-(\d+)_iter-(\d+)_loss-(.+?)\.npz$")

# One jitted identity-copy shared by every async save: jit caches by tree
# structure/shape, so each (params, opt) layout compiles once per run. The
# copy gives the writer thread buffers that survive the train step's
# donate_argnums (device_get on a donated-away array would raise) at the
# cost of one transient on-device replica of params + moments.
_SNAPSHOT = jax.jit(lambda tree: jax.tree.map(jnp.copy, tree))


class AsyncSaveHandle:
    """Join handle for a background checkpoint write (`async_write=True`).

    The write happens on a daemon thread: device->host transfer, per-rank
    slicing, npz writes, retention pruning. `join()` blocks until the files
    are on disk and returns their paths (re-raising any writer exception).
    """

    def __init__(self, step: int):
        self.step = step
        self._paths: List[str] = []
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None

    def _run(self, fn) -> None:
        def wrapped():
            try:
                self._paths = fn()
            except BaseException as e:  # surfaced at join()
                self._error = e
        self._thread = threading.Thread(target=wrapped, daemon=True)
        self._thread.start()

    def join(self) -> List[str]:
        if self._thread is not None:
            self._thread.join()
        if self._error is not None:
            raise self._error
        return self._paths


def _tp_dim(spec: P) -> Optional[int]:
    for i, axis in enumerate(spec):
        if axis == "tp" or (isinstance(axis, tuple) and "tp" in axis):
            return i
    return None


def _flatten(tree: Any, prefix: str) -> Dict[str, Any]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = prefix + "".join(
            f"/{p.key}" if hasattr(p, "key") else f"/{p.idx}" for p in path)
        flat[key] = leaf
    return flat


def _shard_slice(arr: np.ndarray, spec: P, rank: int, tp_size: int) -> np.ndarray:
    dim = _tp_dim(spec)
    if dim is None or tp_size == 1:
        return arr
    n = arr.shape[dim] // tp_size
    sl = [slice(None)] * arr.ndim
    sl[dim] = slice(rank * n, (rank + 1) * n)
    return arr[tuple(sl)]


def _get_leafwise(tree: Any) -> Any:
    """device->host one LEAF at a time (np.asarray assembles each leaf's
    addressable shards; dp/tp-sharded global arrays come back as their
    full numpy values with no device-side collective). The whole-tree
    `jax.device_get` it replaces materialised every transfer before the
    first byte was written; leaf-wise streaming keeps the transient
    device->host working set to one leaf, which is what lets dp-sharded
    ZeRO-2/3 state save through this path without a full-tree gather
    stall (the npz format still holds global values, so any mesh/stage
    can reload the file — resharding happens at device_put)."""
    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)


def save_checkpoint(save_dir: str, step: int, avg_loss: float, params: Any,
                    specs: Any, tp_size: int,
                    opt_state: Optional[AdamState] = None,
                    reserve_last_n: int = -1,
                    async_write: bool = False,
                    tracer=None,
                    zero_stage: int = 0,
                    mesh_axes=None) -> "List[str] | AsyncSaveHandle":
    """Write one npz per TP rank; returns the paths written.

    Works unchanged for ZeRO-sharded state (dp-sharded moments at stage
    1/2, dp-sharded params+moments at stage 3): leaves stream through the
    host one at a time (`_get_leafwise`) and land as GLOBAL arrays, so
    the on-disk format stays mesh- and stage-independent — a dp4 ZeRO-3
    run reloads on a dp2 ZeRO-1 mesh by plain device_put. `zero_stage` is
    recorded as `__zero_stage__` metadata (observability only; loaders
    ignore it).

    `async_write=True` returns an `AsyncSaveHandle` instead: the arrays are
    snapshotted on-device (one jitted copy, so later donated train steps
    can't invalidate them), then a daemon thread performs the device->host
    transfer and file writes while training continues. The train loop joins
    the previous handle before issuing the next save, bounding in-flight
    saves to one. This removes the per-save stall the synchronous path has
    (full params + both Adam moments over D2H — ~1.5 GB at the 124M-param
    BASELINE config) from the hot loop.

    `tracer`: optional obs.SpanTracer — the D2H+slice+write work records a
    "checkpoint_write" span on whichever thread performs it (the async
    writer shows up as its own track in the timeline).

    `mesh_axes`: the saving mesh (a live Mesh, or (axis, size) pairs) for
    the ``__layout__`` stamp — mesh shape + per-leaf PartitionSpec + zero
    stage, everything the reshard planner needs to load this checkpoint
    onto a DIFFERENT mesh (reshard/layout.py). Defaults to the tp-only
    mesh the filename convention already implies; `assemble` skips
    ``__``-prefixed members, so pre-stamp readers are unaffected.
    """
    os.makedirs(save_dir, exist_ok=True)
    from ..reshard.layout import make_layout
    layout = make_layout(mesh_axes if mesh_axes is not None
                         else (("tp", tp_size),), specs,
                         zero_stage=zero_stage)

    def write(params, opt_state) -> List[str]:
        t0 = tracer.now() if tracer is not None else None
        paths = _write(params, opt_state)
        if tracer is not None:
            tracer.complete("checkpoint_write", t0, cat="checkpoint",
                            step=step, files=len(paths))
        return paths

    def _write(params, opt_state) -> List[str]:
        params_np = _get_leafwise(params)
        flat_p = _flatten(params_np, "param")
        flat_s = _flatten(specs, "param")
        flat_opt: Dict[str, Any] = {}
        if opt_state is not None:
            flat_opt.update(_flatten(_get_leafwise(opt_state.mu), "mu"))
            flat_opt.update(_flatten(_get_leafwise(opt_state.nu), "nu"))
            # moments shard exactly like their params
            flat_s.update({k.replace("param", "mu", 1): v for k, v in
                           _flatten(specs, "param").items()})
            flat_s.update({k.replace("param", "nu", 1): v for k, v in
                           _flatten(specs, "param").items()})

        paths = []
        for rank in range(tp_size):
            shard = {}
            for key, arr in {**flat_p, **flat_opt}.items():
                shard[key] = _shard_slice(np.asarray(arr), flat_s[key], rank,
                                          tp_size)
            shard["__step__"] = np.asarray(step, np.int64)
            shard["__tp_size__"] = np.asarray(tp_size, np.int64)
            shard["__has_opt__"] = np.asarray(opt_state is not None)
            shard["__zero_stage__"] = np.asarray(zero_stage, np.int64)
            shard["__layout__"] = np.asarray(layout.to_json())
            path = os.path.join(
                save_dir, f"tprank-{rank}_iter-{step}_loss-{avg_loss:.4f}.npz")
            # Atomic publish: a hard kill mid-write (preemption grace
            # expiring) must never leave a truncated file at a
            # CKPT_RE-matching name, or the next --resume would pick it as
            # newest and crash. The .tmp suffix keeps the partial file
            # invisible to list_checkpoints; rename is atomic on POSIX.
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                np.savez(f, **shard)
            os.replace(tmp, path)
            paths.append(path)

        if reserve_last_n > 0:
            prune_checkpoints(save_dir, reserve_last_n, tp_size)
        return paths

    if not async_write:
        return write(params, opt_state)

    snap_p = _SNAPSHOT(params)
    snap_o = _SNAPSHOT(opt_state) if opt_state is not None else None
    handle = AsyncSaveHandle(step)
    handle._run(lambda: write(snap_p, snap_o))
    return handle


def prune_checkpoints(save_dir: str, reserve_last_n: int, tp_size: int) -> None:
    """Keep only the newest N iterations per rank
    (reference `train.py:127-132`)."""
    for rank in range(tp_size):
        ckpts = glob.glob(os.path.join(save_dir, f"tprank-{rank}_iter-*_loss-*.npz"))
        ckpts.sort(key=lambda p: int(CKPT_RE.search(os.path.basename(p)).group(2)))
        for old in ckpts[:-reserve_last_n]:
            os.remove(old)


def list_checkpoints(save_dir: str, rank: int = 0) -> List[Tuple[int, str]]:
    """(iter, path) pairs for one rank, sorted by iter
    (reference `test.py:94-95`)."""
    out = []
    for p in glob.glob(os.path.join(save_dir, f"tprank-{rank}_iter-*_loss-*.npz")):
        m = CKPT_RE.search(os.path.basename(p))
        if m:
            out.append((int(m.group(2)), p))
    return sorted(out)


def _unflatten_into(template: Any, flat: Dict[str, np.ndarray], prefix: str) -> Any:
    paths = jax.tree_util.tree_flatten_with_path(template)[0]
    treedef = jax.tree.structure(template)
    leaves = []
    for path, _ in paths:
        key = prefix + "".join(
            f"/{p.key}" if hasattr(p, "key") else f"/{p.idx}" for p in path)
        leaves.append(flat[key])
    return jax.tree.unflatten(treedef, leaves)


def find_rank_shards(ckpt_dir: str, step: int, ext: str = "npz"
                     ) -> Dict[int, str]:
    """{rank: path} for `tprank-{r}_iter-{step}_loss-*.{ext}` files — the
    single owner of the reference filename contract
    (`/root/reference/train.py:121-126`), shared by the npz loader and the
    torch-checkpoint importer (interop.py, ext='pth')."""
    pat = re.compile(rf"tprank-(\d+)_iter-(\d+)_loss-(.+?)\.{ext}$")
    rank_files: Dict[int, str] = {}
    for p in glob.glob(os.path.join(ckpt_dir,
                                    f"tprank-*_iter-{step}_loss-*.{ext}")):
        m = pat.search(os.path.basename(p))
        if m and int(m.group(2)) == step:
            rank_files[int(m.group(1))] = p
    return rank_files


def validate_checkpoint(ckpt_dir: str, step: int, ext: str = "npz"
                        ) -> Tuple[int, Dict[int, str]]:
    """Refuse an incomplete shard set EARLY, before any assembly work.

    Returns (tp_size, {rank: path}) when every rank shard of iteration
    `step` is present. Raises FileNotFoundError naming the missing rank
    list otherwise — a partial copy (one rank file lost in transfer) used
    to surface as a cryptic KeyError mid-assemble in `find_rank_shards`
    consumers; the serving loader (serving/serve.py), `load_checkpoint`,
    and the torch-checkpoint interop all validate through here now.

    The expected rank count comes from the `__tp_size__` metadata any one
    npz shard carries; formats without it (ext='pth') fall back to
    max(rank)+1, which still catches every hole below the highest
    surviving rank."""
    rank_files = find_rank_shards(ckpt_dir, step, ext=ext)
    if not rank_files:
        raise FileNotFoundError(f"no checkpoint for iter {step} in "
                                f"{ckpt_dir}")
    tp_size = None
    if ext == "npz":
        any_rank = next(iter(rank_files))
        try:
            tp_size = int(np.load(rank_files[any_rank])["__tp_size__"])
        except KeyError:  # pre-metadata file: fall back to the rank span
            tp_size = None
    if tp_size is None:
        tp_size = max(rank_files) + 1
    missing = sorted(set(range(tp_size)) - set(rank_files))
    if missing:
        raise FileNotFoundError(
            f"checkpoint iter {step} was written with tp_size={tp_size} but "
            f"shard files for rank(s) {missing} are missing from {ckpt_dir} "
            f"— restore the missing rank file(s) or re-save the checkpoint")
    return tp_size, rank_files


def load_checkpoint(save_dir: str, step: int, params_template: Any,
                    specs: Any, with_opt: bool = False):
    """Reassemble global arrays from all per-rank shards of iteration `step`.

    Returns (params, opt_state | None, step).
    """
    tp_size, rank_files = validate_checkpoint(save_dir, step)
    shards = {r: dict(np.load(rank_files[r])) for r in range(tp_size)}

    flat_specs = _flatten(specs, "param")

    def assemble(prefix: str) -> Dict[str, np.ndarray]:
        out = {}
        for key in shards[0]:
            if not key.startswith(prefix + "/"):
                continue
            spec_key = "param" + key[len(prefix):]
            dim = _tp_dim(flat_specs[spec_key])
            if dim is None or tp_size == 1:
                out[key] = shards[0][key]
            else:
                out[key] = np.concatenate(
                    [shards[r][key] for r in range(tp_size)], axis=dim)
        return out

    params = _unflatten_into(params_template, assemble("param"), "param")

    opt_state = None
    if with_opt and bool(shards[0]["__has_opt__"]):
        mu = _unflatten_into(params_template,
                             {k: v for k, v in assemble("mu").items()}, "mu")
        nu = _unflatten_into(params_template,
                             {k: v for k, v in assemble("nu").items()}, "nu")
        opt_state = AdamState(step=np.asarray(int(shards[0]["__step__"]),
                                              np.int32), mu=mu, nu=nu)
    return params, opt_state, int(shards[0]["__step__"])


def latest_step(save_dir: str) -> Optional[int]:
    ckpts = list_checkpoints(save_dir, rank=0)
    return ckpts[-1][0] if ckpts else None
