"""Training metrics: TensorBoard scalars + JSONL fallback + device memory.

Reference parity (`/root/reference/train.py:85,117-120`): `train/ce_loss`,
`train/lr` and a per-rank reserved-memory scalar go to TensorBoard
(`tensorboardX`). We keep tensorboardX when importable and always mirror to a
plain `metrics.jsonl` (grep-able, no proto deps). The reference's
`torch.cuda.memory_reserved` becomes `jax.Device.memory_stats()`.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import jax


class MetricsWriter:
    def __init__(self, log_dir: str):
        os.makedirs(log_dir, exist_ok=True)
        self._jsonl = open(os.path.join(log_dir, "metrics.jsonl"), "a")
        self._tb = None
        try:
            from tensorboardX import SummaryWriter  # optional
            self._tb = SummaryWriter(log_dir=log_dir)
        except Exception:
            pass

    def scalar(self, tag: str, value: float, step: int) -> None:
        self._jsonl.write(json.dumps(
            {"tag": tag, "value": float(value), "step": int(step),
             "ts": time.time()}) + "\n")
        self._jsonl.flush()
        if self._tb is not None:
            self._tb.add_scalar(tag, value, step)

    def text(self, tag: str, value: str, step: int = 0) -> None:
        self._jsonl.write(json.dumps(
            {"tag": tag, "text": value, "step": int(step)}) + "\n")
        self._jsonl.flush()
        if self._tb is not None:
            self._tb.add_text(tag, value, step)

    def close(self) -> None:
        self._jsonl.close()
        if self._tb is not None:
            self._tb.close()


def device_memory_gib(device: Optional[jax.Device] = None) -> float:
    """Bytes in use on the device, in GiB (analogue of
    `torch.cuda.memory_reserved`, reference `train.py:119`)."""
    if device is None:
        device = jax.devices()[0]
    stats = getattr(device, "memory_stats", lambda: None)()
    if not stats:
        return 0.0
    return stats.get("bytes_in_use", 0) / 1024 ** 3
