"""Training metrics: TensorBoard scalars + JSONL fallback + device memory.

Reference parity (`/root/reference/train.py:85,117-120`): `train/ce_loss`,
`train/lr` and a per-rank reserved-memory scalar go to TensorBoard
(`tensorboardX`). We keep tensorboardX when importable and always mirror to a
plain `metrics.jsonl` (grep-able, no proto deps). The reference's
`torch.cuda.memory_reserved` becomes `jax.Device.memory_stats()`.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional

import jax


class MetricsWriter:
    """Multihost-safe: process 0 writes `metrics.jsonl`, every other
    process writes `metrics.proc{i}.jsonl`, so concurrent processes
    sharing one log dir never interleave lines in one file. TensorBoard
    stays per-rank (its tfevents filenames embed hostname+pid, so writers
    never clobber even in a shared dir) — per-host curves are how
    multi-host divergence is compared. Also a context manager, so the
    file handle closes on error paths."""

    def __init__(self, log_dir: str, process_index: Optional[int] = None,
                 max_bytes: int = 0):
        from ..runtime.mesh import process_info
        if process_index is None:
            process_index = process_info()[0]
        self.process_index = process_index
        os.makedirs(log_dir, exist_ok=True)
        name = ("metrics.jsonl" if process_index == 0
                else f"metrics.proc{process_index}.jsonl")
        self.path = os.path.join(log_dir, name)
        # size-based rotation (ISSUE 12): once the current file passes
        # max_bytes, a schema-valid `rotated` event naming the NEXT file
        # is appended as its LAST line and the stream continues there
        # (metrics.jsonl -> metrics.001.jsonl -> ...). The old file is
        # never renamed, so a live tailer's open handle stays valid and
        # follows the chain (obs/collector.JsonlTailer). 0 = unbounded.
        self.max_bytes = max_bytes
        self._base, self._ext = os.path.splitext(self.path)
        self._gen = 0
        self._jsonl = open(self.path, "a")
        # the obs watchdog writes events from its daemon thread while the
        # train loop writes scalars — serialize, or lines tear
        self._lock = threading.Lock()
        self._closed = False
        self._tb = None
        try:
            from tensorboardX import SummaryWriter  # optional
            self._tb = SummaryWriter(log_dir=log_dir)
        except Exception:
            pass

    def _write(self, rec: dict) -> None:
        with self._lock:
            if self._closed:
                return
            self._jsonl.write(json.dumps(rec) + "\n")
            self._jsonl.flush()
            if self.max_bytes and self._jsonl.tell() >= self.max_bytes:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        from ..obs.schema import EVENT_SCHEMA_VERSION
        self._gen += 1
        nxt = f"{self._base}.{self._gen:03d}{self._ext}"
        self._jsonl.write(json.dumps(
            {"tag": "rotated", "ts": time.time(),
             "schema_version": EVENT_SCHEMA_VERSION,
             "next": os.path.basename(nxt), "generation": self._gen}) + "\n")
        self._jsonl.close()
        self.path = nxt
        self._jsonl = open(nxt, "a")

    def scalar(self, tag: str, value: float, step: int) -> None:
        self._write({"tag": tag, "value": float(value), "step": int(step),
                     "ts": time.time()})
        # post-close writes drop entirely: tensorboardX would resurrect a
        # fresh, never-flushed event file on a late add_scalar
        if self._tb is not None and not self._closed:
            self._tb.add_scalar(tag, value, step)

    def text(self, tag: str, value: str, step: int = 0) -> None:
        self._write({"tag": tag, "text": value, "step": int(step)})
        if self._tb is not None and not self._closed:
            self._tb.add_text(tag, value, step)

    def event(self, tag: str, step: Optional[int] = None, **fields) -> None:
        """Structured one-off record (goodput summary, sentinel/watchdog
        events, cost analysis, request traces) — jsonl only; TB has no
        sane rendering for these. Every event carries `schema_version`
        (obs/schema.py) so consumers can fail loudly on drift instead of
        silently dropping sections."""
        from ..obs.schema import EVENT_SCHEMA_VERSION
        rec = {"tag": tag, "ts": time.time(),
               "schema_version": EVENT_SCHEMA_VERSION, **fields}
        if step is not None:
            rec["step"] = int(step)
        self._write(rec)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._jsonl.close()
        if self._tb is not None:
            self._tb.close()

    def __enter__(self) -> "MetricsWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# Peak bf16 FLOP/s per chip by device_kind, most-specific prefix first
# (v5p must not fall into the 'TPU v5' bucket). Used for MFU reporting.
PEAK_FLOPS = [
    ("TPU v7", 2307e12),       # Ironwood: 4.6 PFLOP/s fp8 -> ~2.3 bf16
    ("TPU v6 lite", 918e12),   # v6e / Trillium
    ("TPU v6", 918e12),
    ("TPU v5p", 459e12),
    ("TPU v5 lite", 197e12),   # v5e
    ("TPU v5", 197e12),
    ("TPU v4", 275e12),
]

_warned_unknown_kind = set()


def chip_peak_flops(device: Optional[jax.Device] = None) -> float:
    kind = (device or jax.devices()[0]).device_kind
    for prefix, v in PEAK_FLOPS:
        if kind.startswith(prefix):
            return v
    if kind not in _warned_unknown_kind:  # once per kind, not per call
        _warned_unknown_kind.add(kind)
        import sys
        print(f"Warning: unknown device_kind {kind!r} — assuming v5e peak "
              f"({197e12 / 1e12:.0f} TFLOP/s); MFU numbers are unreliable "
              f"until PEAK_FLOPS (training/metrics.py) gains an entry",
              file=sys.stderr)  # bench.py's stdout is machine-parsed
    return 197e12  # unknown: assume v5e


def model_flops_per_step(cfg, batch: int, seqlen: int, params=None) -> float:
    """Model FLOPs for one fwd+bwd train step (no remat recompute counted):
    6N_active per token + the 12*L*h*T^2*hd attention term. For MoE models
    only the top_k experts a token is routed through count (the standard
    active-parameter MFU convention); dropped-token underflow is ignored.

    `params`: pass the actual param pytree for families whose shape differs
    from the llama formula baked into `cfg.num_params()` (the gpt2 family's
    2-matmul MLP + tied head would otherwise overcount N by ~1/3 of the
    FFN)."""
    if params is not None:
        import jax

        n = sum(int(x.size) for x in jax.tree.leaves(params))
    else:
        n = cfg.num_params()
    if getattr(cfg, "num_experts", 0):
        inactive = ((cfg.num_experts - cfg.moe_top_k)
                    * 3 * cfg.attn_dim * cfg.ffn_dim)
        n -= cfg.num_layers * max(0, inactive)
    return (6 * n * batch * seqlen
            + 12 * cfg.num_layers * batch * cfg.num_heads
            * seqlen * seqlen * cfg.head_dim)


class ProfilerTrace:
    """Start/stop `jax.profiler` tracing over a step window — the TPU
    analogue of the reference's (absent) torch profiler; SURVEY §5.1. View
    the trace with TensorBoard's profile plugin or xprof."""

    def __init__(self, log_dir: str, start_step: int, num_steps: int):
        self.log_dir = os.path.join(log_dir, "profile")
        self.start_step = start_step
        self.stop_step = start_step + num_steps
        self._active = False
        self._done = False

    def maybe_start(self, step: int) -> None:
        # ">= start" rather than "inside the window": the caller's step
        # counter may jump by steps_per_dispatch and clear the whole window
        # in one hop — the trace then starts at the first boundary past
        # start_step and covers at least num_steps (`_done` stops it from
        # restarting every later step)
        if not self._active and not self._done and step >= self.start_step:
            os.makedirs(self.log_dir, exist_ok=True)
            jax.profiler.start_trace(self.log_dir)
            self._active = True

    def maybe_stop(self, step: int, sync=None) -> None:
        """`sync`: a device value from the last profiled step (e.g. the loss);
        dispatch is async, so without blocking on it stop_trace would fire
        while the profiled steps are still executing and truncate the trace."""
        if self._active and step >= self.stop_step:
            if sync is not None:
                jax.block_until_ready(sync)
            jax.profiler.stop_trace()
            self._active = False
            self._done = True
            import sys
            # stderr: serve.py/bench.py reserve stdout for the one
            # machine-parsed JSON record
            print(f"profiler trace written to {self.log_dir}",
                  file=sys.stderr)

    def close(self, sync=None) -> None:
        if self._active:
            if sync is not None:
                jax.block_until_ready(sync)
            jax.profiler.stop_trace()
            self._active = False
            import sys
            print(f"profiler trace written to {self.log_dir} (window "
                  f"overlapped the end of training; it may cover fewer "
                  f"steps than requested)", file=sys.stderr)


def _dir_bytes(path: str) -> int:
    """Recursive on-disk size of a capture dir (the duty sampler's disk
    budget is charged per finished window)."""
    total = 0
    for dirpath, _, files in os.walk(path):
        for name in files:
            try:
                total += os.path.getsize(os.path.join(dirpath, name))
            except OSError:
                pass
    return total


def emit_profile_attribution(writer, capture_dir: str, trigger: str,
                             steps: int, analytic=None) -> Optional[dict]:
    """Parse a FINISHED capture dir (obs/profparse) and land it as one
    versioned `profile_attribution` MetricsWriter event (ISSUE 15): the
    measured phase taxonomy, and — when the caller supplies the analytic
    phase report its run was priced with — the full measured-vs-analytic
    reconcile. A capture that fails to parse still lands an event (with
    `error` and empty phases): a window that silently vanished is the
    rot mode the measured plane exists to kill. Returns the event's
    fields (sans tag), or None when parsing failed."""
    from ..obs import profparse
    try:
        measured = profparse.parse_capture(capture_dir)
    except (ValueError, OSError) as e:
        if writer is not None:
            writer.event("profile_attribution", capture=capture_dir,
                         trigger=trigger, steps=int(steps), phases={},
                         error=f"{type(e).__name__}: {e}")
        return None
    fields = {
        "capture": capture_dir,
        "trigger": trigger,
        "steps": int(steps),
        "phases": profparse.phase_ms_map(measured),
        "device_busy_ms": measured["device_busy_ms"],
        "host_gap_ms": measured["host_gap_ms"],
        "events": measured["events"],
        "devices": measured["devices"],
    }
    if analytic is not None:
        fields["reconcile"] = profparse.reconcile(measured, analytic,
                                                  steps=steps)
    if writer is not None:
        writer.event("profile_attribution", **fields)
    return fields


class DutyCycleProfiler:
    """Duty-cycled continuous device profiling (ISSUE 15): every `every`
    dispatches, capture a bounded `jax.profiler` window of `window`
    dispatches, parse it at stop (obs/profparse), and land a versioned
    `profile_attribution` event — so a long run accumulates MEASURED
    attribution points instead of one hand-triggered capture.

    Same thread contract as `AnomalyProfiler`: `tick()` runs on the host
    loop (the thread that owns the device queue) once per dispatch, and
    reuses `ProfilerTrace`'s window mechanics (the stop blocks on `sync`
    so a window never truncates). The disk budget (`budget_mb`) is
    charged per FINISHED capture and checked only between windows — an
    open window always completes ("never mid-window"); once the budget
    is exhausted, further due windows are counted in `windows_skipped`
    with a one-time loud note, and the run keeps going unprofiled.

    The first window opens at the `every`-th tick, not the first — the
    initial dispatches are compile/layout churn a steady-state
    attribution must not average in."""

    def __init__(self, log_dir: str, every: int, window: int = 4,
                 budget_mb: float = 64.0, writer=None, analytic=None,
                 on_attribution=None):
        if every < 1:
            raise ValueError(f"profile_every must be >= 1, got {every}")
        if not 1 <= window <= every:
            raise ValueError(
                f"profile window must be in [1, profile_every] (a window "
                f"longer than the duty period would re-arm mid-capture): "
                f"got window {window}, every {every}")
        if budget_mb <= 0:
            raise ValueError(f"profile_budget_mb must be > 0, got "
                             f"{budget_mb}")
        if writer is None:
            raise ValueError(
                "duty-cycled profiling needs a MetricsWriter: the parsed "
                "profile_attribution events ARE the product — a capture "
                "nothing reads is the pre-ISSUE-15 state")
        self.log_dir = log_dir
        self.every = every
        self.window = window
        self.budget_bytes = int(budget_mb * 2**20)
        self.writer = writer
        self.analytic = analytic     # profparse.analytic_phase_report(...)
        # ISSUE 16: called with each parsed capture's event fields right
        # after the window FINISHES — i.e. between capture windows, the
        # documented control-plane safe point (obs/control.RetuneAdvisor
        # hooks here; never mid-window, never inside a traced function)
        self.on_attribution = on_attribution
        self._ticks = 0
        self._trace: Optional[ProfilerTrace] = None
        self._started_tick = 0
        self._capture_no = 0
        self.captures: List[str] = []       # capture dirs written
        self.capture_steps: List[int] = []  # dispatches each one covered
        self.attributions = 0               # events successfully parsed
        self.windows_skipped = 0            # due windows past the budget
        self.bytes_used = 0
        self.exhausted = False

    def tick(self, step: int = 0, sync=None) -> None:
        """Once per dispatch from the host loop. `sync`: a device value
        from this dispatch (the stop barrier). Window boundaries count
        in TICKS (dispatches), not the caller's step numbers — a
        steps_per_dispatch > 1 loop advances `step` by N per tick, and
        pricing the window in that domain would close it N x early."""
        if self._trace is not None:
            self._trace.maybe_stop(self._ticks, sync=sync)
            if self._trace._done:
                self._finish(end_tick=self._ticks)
        # not elif: a window finishing exactly on a duty boundary must
        # not swallow the window due at that same tick — W == N means
        # back-to-back capture, not half the documented cadence
        if self._trace is None and self._ticks \
                and self._ticks % self.every == 0:
            if self.exhausted:
                self.windows_skipped += 1
            else:
                self._start()
        self._ticks += 1

    def _start(self) -> None:
        self._capture_no += 1
        d = os.path.join(self.log_dir,
                         f"profile_duty_{self._capture_no:03d}")
        self._trace = ProfilerTrace(d, start_step=self._ticks,
                                    num_steps=self.window)
        self._started_tick = self._ticks
        self._trace.maybe_start(self._ticks)
        self.captures.append(self._trace.log_dir)

    def _finish(self, end_tick: int) -> None:
        trace, self._trace = self._trace, None
        # the dispatches this capture ACTUALLY covered: a close()-forced
        # window is shorter than `window`, and attributing it at the
        # full count would deflate measured_step_ms (and the record the
        # regression gate checks) by the truncation factor. `end_tick`
        # is the last tick the window saw: the stop path passes the
        # in-flight tick index; close() passes _ticks - 1 (the counter
        # already advanced past the final dispatch).
        steps = max(1, min(self.window, end_tick - self._started_tick))
        self.capture_steps.append(steps)
        self.bytes_used += _dir_bytes(trace.log_dir)
        if self.bytes_used >= self.budget_bytes and not self.exhausted:
            self.exhausted = True
            import sys
            print(f"duty profiler: disk budget exhausted after "
                  f"{self._capture_no} capture(s) "
                  f"({self.bytes_used / 2**20:.1f} MiB >= "
                  f"{self.budget_bytes / 2**20:.1f} MiB) — sampling "
                  f"stops; skipped windows are counted in the summary",
                  file=sys.stderr)
        fields = emit_profile_attribution(self.writer, trace.log_dir,
                                          "duty", steps, self.analytic)
        if fields is not None:
            self.attributions += 1
            if self.on_attribution is not None:
                self.on_attribution(fields)

    def close(self, sync=None) -> None:
        """Finish an open window at run end (shorter than requested beats
        an unparsed truncated capture) and attribute it."""
        if self._trace is not None:
            self._trace.close(sync=sync)
            self._finish(end_tick=self._ticks - 1)


class AnomalyProfiler:
    """Anomaly-triggered device profiling (ISSUE 12): when a flight dump
    fires (sentinel halt, watchdog stall, PoolExhausted preemption, SLO
    collapse), ARM a bounded `jax.profiler` window so the dump cross-links
    a device timeline of the steps right after the anomaly — instead of
    only host-side ring contents.

    Split across threads by design: `arm()` may be called from ANY thread
    (the watchdog's dump path included) and only records the request under
    a lock; the actual `jax.profiler` start/stop runs inside `tick()`,
    which the host loop calls once per dispatch — the same thread that
    owns the device queue (reusing `ProfilerTrace`'s window mechanics, so
    the stop blocks on `sync` and never truncates the profiled steps).
    `max_captures` bounds what an anomaly storm can spend: device tracing
    is the one obs tool too expensive to leave on, which is why it is
    armed by anomalies rather than always-on."""

    def __init__(self, log_dir: str, window_steps: int = 4,
                 max_captures: int = 1, writer=None, analytic=None):
        if window_steps < 1:
            raise ValueError(f"profile window must be >= 1 step, got "
                             f"{window_steps}")
        self.log_dir = log_dir
        self.window_steps = window_steps
        self.max_captures = max_captures
        # ISSUE 15: anomaly captures flow through the SAME parse as the
        # duty sampler's — when a writer is attached, every finished
        # window lands a profile_attribution event tagged with its
        # anomaly trigger, so flight dumps cross-link an ATTRIBUTED
        # timeline, not just a dir
        self.writer = writer
        self.analytic = analytic
        self.attributions = 0
        self._lock = threading.Lock()
        self._pending = None          # (tag, capture_dir) awaiting a tick
        self._armed_total = 0
        self._trace: Optional[ProfilerTrace] = None  # tick-thread only
        self._trace_tag: Optional[str] = None
        self._trace_started = 0       # step the open window started at
        self._last_step = 0           # the host loop's latest tick step
        self.captures = []            # capture dirs actually written

    def arm(self, tag: str) -> Optional[str]:
        """Reserve a capture for the NEXT tick; returns the directory the
        profile will land in (the flight dump stamps it), or None when the
        capture budget is spent or a capture is already pending/active —
        an anomaly storm profiles once, not once per dump."""
        with self._lock:
            if self._armed_total >= self.max_captures or \
                    self._pending is not None or self._trace is not None:
                return None
            self._armed_total += 1
            path = os.path.join(
                self.log_dir,
                f"profile_anomaly_{tag}_{self._armed_total:02d}")
            self._pending = (tag, path)
        return os.path.join(path, "profile")  # ProfilerTrace's subdir

    def tick(self, step: int, sync=None) -> None:
        """Drive the armed window from the host loop (one thread). The
        window opens at this step and closes `window_steps` later;
        `sync` is a device value from the last dispatched step, so the
        stop never fires while profiled steps are still executing."""
        with self._lock:
            pending = self._pending
            self._pending = None
        self._last_step = step
        if pending is not None and self._trace is None:
            tag, path = pending
            self._trace = ProfilerTrace(path, start_step=step,
                                        num_steps=self.window_steps)
            self._trace_tag = tag
            self._trace_started = step
            self._trace.maybe_start(step)
            self.captures.append(self._trace.log_dir)
        elif self._trace is not None:
            self._trace.maybe_stop(step, sync=sync)
            if self._trace._done:
                self._attribute()

    def _attribute(self) -> None:
        """Parse the finished anomaly window into a profile_attribution
        event (tick/close-thread only; no-op without a writer). The step
        count is what the window ACTUALLY covered — a close()-forced
        window is shorter than window_steps, and attributing it at the
        full count would deflate the measured per-step ms."""
        trace, self._trace = self._trace, None
        tag, self._trace_tag = self._trace_tag, None
        steps = max(1, min(self.window_steps,
                           self._last_step - self._trace_started))
        if self.writer is not None:
            if emit_profile_attribution(
                    self.writer, trace.log_dir, f"anomaly:{tag}",
                    steps, self.analytic) is not None:
                self.attributions += 1

    def close(self, sync=None) -> None:
        """Finish an open window at run end (shorter than requested beats
        a truncated unreadable capture). An ARMED window the loop never
        ticked again (the anomaly fired on the run's last step) still
        captures whatever device activity remains right now — the dump's
        cross-linked path must point at a readable trace, not at
        nothing."""
        with self._lock:
            pending = self._pending
            self._pending = None
        if pending is not None and self._trace is None:
            tag, path = pending
            self._trace = ProfilerTrace(path, start_step=0, num_steps=1)
            self._trace_tag = tag
            # never ticked: whatever close() captures counts as one step
            self._trace_started = self._last_step
            self._trace.maybe_start(0)
            self.captures.append(self._trace.log_dir)
        if self._trace is not None:
            self._trace.close(sync=sync)
            self._attribute()


def allreduce_p50_us(mesh, axis: str = "tp", nbytes: int = 4 * 1024 * 1024,
                     iters: int = 30) -> float:
    """p50 latency of a single all-reduce over `axis` (BASELINE metric #2).

    Shared by `bench.py` (real ICI number when tp > 1) and
    `__graft_entry__.dryrun_multichip` (virtual-CPU correctness-grade
    number). Timing syncs via `.item()` D2H copy — `block_until_ready`
    returns early for chained executions on the axon platform.
    """
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..ops.collectives import reduce_from

    x = jnp.ones((nbytes // 4,), jnp.float32)
    f = jax.jit(jax.shard_map(lambda x: reduce_from(x, axis), mesh=mesh,
                              in_specs=(P(),), out_specs=P()))
    jax.block_until_ready(f(x))  # compile
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        f(x)[0].item()  # D2H sync
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2] * 1e6


def device_memory_stats(device: Optional[jax.Device] = None) \
        -> Optional[dict]:
    """One device's `memory_stats()`, or **None when the backend has no
    stats** (the CPU backend returns None; some platform backends raise).
    Callers must render None as 'unavailable' — the pre-ISSUE-15 code
    folded it into 0, exporting a fake 0-GiB watermark that reads as "this
    run used no HBM" on every chip-less box (the silent-zero fix)."""
    if device is None:
        # local: in a multi-process run, jax.devices()[0] can belong to
        # another process — MemoryStats on a non-addressable device raises
        device = jax.local_devices()[0]
    try:
        stats = getattr(device, "memory_stats", lambda: None)()
    except Exception:  # platform backends without stats raise, not None
        return None
    return stats or None


def device_memory_gib(device: Optional[jax.Device] = None) \
        -> Optional[float]:
    """Bytes in use on the device, in GiB (analogue of
    `torch.cuda.memory_reserved`, reference `train.py:119`) — or None
    when the backend reports no memory stats (say 'n/a', never 0)."""
    stats = device_memory_stats(device)
    if stats is None:
        return None
    return stats.get("bytes_in_use", 0) / 1024 ** 3


def hbm_watermarks() -> Optional[List[dict]]:
    """Per-local-device HBM watermark snapshot (ISSUE 15): one dict per
    addressable device with `bytes_in_use`, `peak_bytes` (the high-water
    mark, when the backend tracks one) and `limit_bytes`. None when NO
    local device reports stats — the unavailable case stays a distinct
    value, not an all-zeros list."""
    out = []
    for d in jax.local_devices():
        stats = device_memory_stats(d)
        if stats is None:
            continue
        out.append({
            "device": f"{d.platform}:{d.id}",
            "bytes_in_use": int(stats.get("bytes_in_use", 0)),
            "peak_bytes": int(stats.get("peak_bytes_in_use",
                                        stats.get("bytes_in_use", 0))),
            "limit_bytes": int(stats.get("bytes_limit")
                               or stats.get("bytes_reservable_limit") or 0),
        })
    return out or None


def publish_hbm(telemetry=None, writer=None, step: Optional[int] = None,
                pool_accounted_bytes: Optional[int] = None,
                event: bool = False) -> Optional[List[dict]]:
    """Publish live HBM watermark gauges (and optionally one
    `hbm_watermark` event) from `memory_stats()` (ISSUE 15).

    Gauges: `hbm/available` (0/1 — an unavailable backend is exported
    LOUDLY as 0-available, never as 0 bytes), and when available
    `hbm/bytes_in_use` / `hbm/peak_bytes` / `hbm/limit_bytes` (worst
    local device — the watermark that OOMs first) plus per-device
    `hbm/d<i>/...` gauges. `pool_accounted_bytes` (the PagedKVPool's
    pages_in_use x page_bytes) rides as `hbm/kv_accounted_bytes` and the
    `hbm/kv_accounted_frac` cross-check — accounted pool bytes over
    measured bytes-in-use; a fraction drifting toward 0 while the pool
    thinks it is full means something else is eating the device.

    Returns the per-device snapshot (None when unavailable) so callers
    can reuse it without a second stats round."""
    marks = hbm_watermarks()
    if telemetry is not None:
        telemetry.gauge("hbm/available", 0.0 if marks is None else 1.0)
        if marks is not None:
            telemetry.gauge("hbm/bytes_in_use",
                            max(m["bytes_in_use"] for m in marks))
            telemetry.gauge("hbm/peak_bytes",
                            max(m["peak_bytes"] for m in marks))
            telemetry.gauge("hbm/limit_bytes",
                            max(m["limit_bytes"] for m in marks))
            for i, m in enumerate(marks):
                telemetry.gauge(f"hbm/d{i}/bytes_in_use", m["bytes_in_use"])
                telemetry.gauge(f"hbm/d{i}/peak_bytes", m["peak_bytes"])
        if pool_accounted_bytes is not None:
            telemetry.gauge("hbm/kv_accounted_bytes", pool_accounted_bytes)
            if marks is not None:
                in_use = max(m["bytes_in_use"] for m in marks)
                if in_use:
                    telemetry.gauge("hbm/kv_accounted_frac",
                                    pool_accounted_bytes / in_use)
    if event and writer is not None:
        fields = {"devices": marks or [],
                  "available": marks is not None}
        if pool_accounted_bytes is not None:
            fields["pool_accounted_bytes"] = int(pool_accounted_bytes)
        writer.event("hbm_watermark", step=step, **fields)
    return marks
