"""Activation-memory estimates + the per-config remat policy selector.

The remat ladder ('false' fastest, 'dots' bounded residuals, 'true' lowest
memory — models/transformer.py) has so far been picked by hand per preset.
`select_remat` picks it from an itemised activation-memory estimate against
the chip's HBM budget, so `--remat auto` (train.py / bench.py) runs the
fastest policy that fits and steps down only when the numbers say so. The
estimate is deliberately conservative (a `margin` headroom for XLA temps
and fusion scratch); bench.py's OOM fallback ladder remains the safety net
behind it.
"""

from __future__ import annotations

from typing import Optional

# itemised per-layer residual footprint, in units of (b * t * dtype_bytes):
#   'false' — everything autodiff saves on the flash path: layer input,
#             2 norm outputs, q/k/v (k/v at the kv width), rope'd q/k,
#             flash o + attn-proj input, wo output, gate/up/silu*up, down
#             output  ->  ~9d + 4kd + 3f per token
#   'dots'  — matmul outputs + the pinned flash o/lse only: q/k/v, o,
#             wo out, gate/up, down out  ->  ~4d + 2kd + 2f
#   'true'  — the layer-boundary carry only  ->  d
_LAYER_UNITS = {
    "false": lambda d, kd, f: 9 * d + 4 * kd + 3 * f,
    "dots": lambda d, kd, f: 4 * d + 2 * kd + 2 * f,
    "true": lambda d, kd, f: d,
}


def zero_state_bytes_per_param(zero_stage: int, dp: int,
                               cfg=None) -> float:
    """f32 bytes of RESIDENT train-state per parameter per dp rank under
    the ZeRO ladder (params + grads + 2 Adam moments; training/zero.py):

        stage 0:  4 + 4 + 8            = 16
        stage 1:  4 + 4 + 8/dp         (moments dp-sharded)
        stage 2:  4 + 4/dp + 8/dp      (grads reduce-scattered too)
        stage 3:  (4 + 4 + 8)/dp + transient gathered working set

    Stage 3's transient term (one gathered layer + the gathered non-layer
    leaves that live through the step) needs `cfg` for the layer split;
    it is charged as 4 bytes x (per-layer params + embed/head params) on
    top of the 16/dp resident floor. The itemised table lives in
    docs/PERF.md ("ZeRO ladder") and tests/test_attribution.py pins both
    against each other.
    """
    dp = max(dp, 1)
    if zero_stage <= 0 or dp == 1:
        return 16.0
    if zero_stage == 1:
        return 8.0 + 8.0 / dp
    if zero_stage == 2:
        return 4.0 + 12.0 / dp
    # stage 3: everything resident is sharded; the gather working set is
    # one layer (the scan bound) plus the embedding/head/final-norm leaves
    # gathered at their use sites and saved as backward residuals
    extra = 0.0
    if cfg is not None:
        P = cfg.num_params()
        nonlayer = (2 * cfg.vocab_size * cfg.attn_dim + cfg.vocab_size
                    + cfg.attn_dim)
        per_layer = max((P - nonlayer) / max(cfg.num_layers, 1), 0.0)
        extra = 4.0 * (per_layer + nonlayer) / max(P, 1)
    return 16.0 / dp + extra


def estimate_step_gib(cfg, batch: int, seqlen: int, remat: str,
                      tp: int = 1, world: int = 1,
                      dtype_bytes: int = 2, zero_stage: int = 0,
                      dp: int = 1) -> float:
    """Peak-HBM estimate (GiB, per device) for one fwd+bwd+adam train step.

    Fixed state: params + grads (f32) + 2 Adam moments (f32) — 16 bytes
    per parameter un-sharded, shrunk by the ZeRO ladder per
    `zero_state_bytes_per_param` (stage 1 moments/dp, stage 2 +grads/dp,
    stage 3 everything/dp + the gathered working set) — replicated over tp
    for the norm/embed parts but sharded for the big matrices:
    approximated as P * state_bytes / max(tp, 1) + 10% for the replicated
    remainder. (Pre-ZeRO-ladder versions of this estimate ignored
    optimizer sharding entirely, overestimating every --zero1 run by
    8 x P x (1 - 1/dp) bytes; `--remat auto` now sees the real budget.)
    Activations shard over tp (the t or head dim); the batch shards over
    dp/ep, folded into `world / tp`.
    """
    remat = str(remat).lower()
    if remat not in _LAYER_UNITS:
        raise ValueError(f"remat must be one of {sorted(_LAYER_UNITS)}, "
                         f"got {remat!r}")
    d, f, L = cfg.attn_dim, cfg.ffn_dim, cfg.num_layers
    kd = cfg.kv_dim
    if cfg.num_experts:
        # each token's residuals touch top_k expert FFNs plus the dispatch
        # buffers (~capacity_factor x the dense width)
        f = int(f * max(cfg.moe_top_k, 1) * cfg.moe_capacity_factor / 2)
    P = cfg.num_params()
    dp_like = max(world // max(tp, 1), 1)
    b_local = max(batch // dp_like, 1)
    tok = b_local * seqlen

    state = zero_state_bytes_per_param(zero_stage, dp, cfg)
    fixed = P * state / max(tp, 1) * 1.10
    acts = L * tok * _LAYER_UNITS[remat](d, kd, f) * dtype_bytes / max(tp, 1)
    # flash lse rows (f32) are saved on every policy that keeps o/lse
    if remat != "true":
        acts += L * b_local * cfg.num_heads * seqlen * 4 / max(tp, 1)
    # the head: logits in f32 for the CE (vocab-parallel: sharded over tp)
    # appear twice at the bwd peak (value + cotangent)
    logits = 2 * tok * cfg.padded_vocab_size(tp) * 4 / max(tp, 1)
    # transient optimizer update working set ~ one f32 param tree at the
    # optimizer's RESIDENT layout (fully dp-local under ZeRO-3)
    opt_scratch = P * 4 / max(tp, 1)
    if zero_stage >= 3:
        opt_scratch /= max(dp, 1)
    return (fixed + acts + logits + opt_scratch) / 1024 ** 3


_warned_assumed_budget = []


def hbm_budget_gib(default: float = 16.0) -> float:
    """Per-device HBM, from the live backend when one is attached. A
    backend with no `memory_stats()` (the CPU test mesh) falls back to
    `default` (the v5e figure) — LOUDLY, once per process: a silently
    assumed budget is the same silent-zero rot mode as the fake 0-GiB
    watermark (ISSUE 15), and `--remat auto` decisions made on it must
    be attributable to the assumption."""
    try:
        import jax
        dev = jax.local_devices()[0]
        stats = getattr(dev, "memory_stats", lambda: None)() or {}
        limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
        if limit:
            return limit / 1024 ** 3
    except Exception:  # noqa: BLE001 — sizing must never kill the caller
        pass
    if not _warned_assumed_budget:
        _warned_assumed_budget.append(True)
        import sys
        print(f"note: this backend reports no memory_stats — HBM budget "
              f"UNAVAILABLE, assuming {default:g} GiB (v5e); remat/memory "
              f"decisions sized against the assumption, not the chip",
              file=sys.stderr)
    return default


def select_remat(cfg, batch: int, seqlen: int, tp: int = 1, world: int = 1,
                 budget_gib: Optional[float] = None,
                 margin: float = 0.75, verbose: bool = True,
                 zero_stage: int = 0, dp: int = 1) -> str:
    """The fastest remat policy whose estimated peak fits margin * budget.

    Returns a REMAT_CHOICES key ('false' | 'dots' | 'true'). margin=0.75
    leaves a quarter of HBM for XLA temps, fusion scratch, and the
    donation-transition double-buffering the estimate cannot see.

    `zero_stage`/`dp` size the train state per the ZeRO ladder (see
    `estimate_step_gib`) so `--remat auto` picks against the budget the
    stage actually leaves. Stage 3 never picks 'false': without remat,
    autodiff saves every layer's GATHERED weights as backward residuals —
    the full replica the stage exists to eliminate (the train CLI refuses
    the explicit combination with the same rationale).
    """
    budget = budget_gib if budget_gib is not None else hbm_budget_gib()
    usable = budget * margin
    picked = "true"
    sizes = {}
    policies = ("false", "dots", "true")
    if zero_stage >= 3:
        policies = ("dots", "true")
    for policy in policies:
        sizes[policy] = estimate_step_gib(cfg, batch, seqlen, policy,
                                          tp=tp, world=world,
                                          zero_stage=zero_stage, dp=dp)
        if sizes[policy] <= usable:
            picked = policy
            break
    if verbose:
        import sys
        est = ", ".join(f"{p}={v:.2f}GiB" for p, v in sizes.items())
        zn = f", zero{zero_stage} dp{dp}" if zero_stage else ""
        print(f"remat auto: picked '{picked}' (estimates {est}; budget "
              f"{budget:.1f} GiB x margin {margin}{zn})", file=sys.stderr)
    return picked
