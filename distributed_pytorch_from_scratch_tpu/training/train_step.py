"""The jitted training step: loss + grads + Adam/OneCycle update.

The TPU-native analogue of the reference's hot loop body
(`/root/reference/train.py:94-109`): one XLA program per step — forward,
backward (shard_map transpose inserts the conjugate collectives), optimizer
update — with params and optimizer state donated so updates happen in-place
in HBM (no reallocation per step; the reference relies on torch's in-place
`optimizer.step()` for the same effect).
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config import OptimizerConfig, TrainConfig
from ..models.transformer import Transformer
from .optim import AdamState, adam_update
from .zero import zero1_moment_shardings


def build_train_step(model: Transformer, mesh, ocfg: OptimizerConfig,
                     loss_mode: str = "vocab_parallel",
                     zero1: bool = False, moment_shardings=None):
    """Returns jitted
    (params, opt_state, input_ids, target_ids, position_ids)
      -> (params, opt_state, loss).

    With `zero1=True` the Adam moments are pinned to dp-sharded layouts
    (see training/zero.py): XLA computes each moment/param update on the dp
    shard that owns it and all-gathers the fresh params — ZeRO-1, derived by
    the partitioner. `moment_shardings` lets the caller pass the tree it
    already built (from `zero1_moment_shardings`) for `device_put`-ing the
    initial state, so there is exactly one source of the moment layout;
    derived here when omitted.
    """
    loss_fn = model.make_loss(mesh, mode=loss_mode)
    grad_fn = jax.value_and_grad(loss_fn)

    def step(params, opt_state: AdamState, input_ids, target_ids, position_ids):
        loss, grads = grad_fn(params, input_ids, target_ids, position_ids)
        params, opt_state = adam_update(ocfg, params, grads, opt_state)
        return params, opt_state, loss

    if not zero1:
        return jax.jit(step, donate_argnums=(0, 1))

    param_sh = model.shardings(mesh)
    moment_sh = (moment_shardings if moment_shardings is not None
                 else zero1_moment_shardings(model, mesh))
    scalar = NamedSharding(mesh, P())
    opt_sh = AdamState(step=scalar, mu=moment_sh, nu=moment_sh)
    return jax.jit(step, donate_argnums=(0, 1),
                   out_shardings=(param_sh, opt_sh, scalar))


def build_eval_loss(model: Transformer, mesh, loss_mode: str = "vocab_parallel"):
    return jax.jit(model.make_loss(mesh, mode=loss_mode))
