"""The jitted training step: loss + grads + Adam/OneCycle update.

The TPU-native analogue of the reference's hot loop body
(`/root/reference/train.py:94-109`): one XLA program per step — forward,
backward (shard_map transpose inserts the conjugate collectives), optimizer
update — with params and optimizer state donated so updates happen in-place
in HBM (no reallocation per step; the reference relies on torch's in-place
`optimizer.step()` for the same effect).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..config import OptimizerConfig
from ..models.transformer import Transformer
from .optim import AdamState, adam_update, global_norm
from .zero import (build_bucketed_grad_fn, build_zero3_grad_fn,
                   zero1_moment_shardings, zero3_shardings)


def resolve_zero_stage(zero, zero1: bool = False) -> int:
    """The ZeRO stage from the `zero`/`zero1` kwargs: explicit `zero`
    wins; `zero1=True` is the PR 4-era alias for stage 1. The ONE owner
    of the precedence rule — the builders and the train CLI both resolve
    through here."""
    if zero is not None:
        stage = int(zero)
        if stage not in (0, 1, 2, 3):
            raise ValueError(f"zero stage must be 0..3, got {zero!r}")
        return stage
    return 1 if zero1 else 0


_resolve_stage = resolve_zero_stage  # internal alias used by the builders


def _make_grad_fn(model: Transformer, mesh, loss_mode: str,
                  dp_reduce_bucket_mb: float = 0.0, dp_reduce_dtype=None,
                  zero_stage: int = 0):
    """(params, ids, tgt, pos) -> (loss, grads): the transpose-derived
    whole-tree reducer by default; with dp_reduce_bucket_mb > 0 the
    bucketed-overlap reducer (training/zero.build_bucketed_grad_fn — DP
    psums issued per size-bounded bucket, optionally bf16/int8 on the
    wire). zero_stage=2 swaps the bucketed all-reduce for the bucketed
    REDUCE-SCATTER (grads come back dp-sharded, half the wire bytes);
    zero_stage=3 is the gather-on-demand path (params AND grads dp-sharded,
    training/zero.build_zero3_grad_fn) — both default bucket_mb to 25 when
    the caller left it 0, since their wire IS the bucketed one."""
    if zero_stage >= 3:
        if dp_reduce_dtype is not None:
            # the CLIs refuse this with their own message; the builder is
            # the backstop so a library caller can't silently lose the
            # compressed wire it asked for
            raise ValueError(
                "dp_reduce_dtype with zero stage 3: the ZeRO-3 grad "
                "reduce-scatter rides the parameter all-gather's "
                "transpose (an f32 ppermute ring), so a compressed wire "
                "would silently not apply — use stage 2, whose bucketed "
                "reduce-scatter carries the compressed payload")
        return build_zero3_grad_fn(model, mesh, loss_mode,
                                   bucket_mb=dp_reduce_bucket_mb or 25.0)
    if zero_stage == 2:
        return build_bucketed_grad_fn(model, mesh, loss_mode,
                                      bucket_mb=dp_reduce_bucket_mb or 25.0,
                                      reduce_dtype=dp_reduce_dtype,
                                      zero_stage=2)
    if dp_reduce_bucket_mb:
        return build_bucketed_grad_fn(model, mesh, loss_mode,
                                      bucket_mb=dp_reduce_bucket_mb,
                                      reduce_dtype=dp_reduce_dtype)
    return jax.value_and_grad(model.make_loss(mesh, mode=loss_mode))


def _step_body(model: Transformer, mesh, ocfg: OptimizerConfig,
               loss_mode: str, with_grad_norm: bool = False,
               dp_reduce_bucket_mb: float = 0.0, dp_reduce_dtype=None,
               zero_stage: int = 0):
    """The one train-step body shared by both builders: grad + Adam/OneCycle.
    Keeping it single-sourced means the scanned (multi-step) program can
    never silently diverge from the per-step one.

    `with_grad_norm=True` (the train CLI's mode) makes the third output
    `(loss, grad_norm)` instead of `loss` — computed on-device inside the
    same program, fetched only at the loop's logging-interval D2H, so the
    sentinel costs no extra syncs."""
    grad_fn = _make_grad_fn(model, mesh, loss_mode,
                            dp_reduce_bucket_mb, dp_reduce_dtype,
                            zero_stage=zero_stage)

    def step(params, opt_state: AdamState, input_ids, target_ids,
             position_ids):
        loss, grads = grad_fn(params, input_ids, target_ids, position_ids)
        # grad norm: optim.global_norm — the SAME reduction the clipper
        # uses, so the logged/sentinel-watched norm equals the one
        # acted on (and XLA can CSE the two when both are present)
        out = (loss, global_norm(grads)) if with_grad_norm else loss
        params, opt_state = adam_update(ocfg, params, grads, opt_state)
        return params, opt_state, out

    return step


def _jit_with_zero(fn, model, mesh, zero_stage, moment_shardings,
                   loss_sharding):
    """jit `fn` with donated params/opt state; under a ZeRO stage, pin the
    state to its sharded layouts (training/zero.py) so XLA derives the
    stage's schedule:

    * stage 1 — Adam moments dp-sharded, params replicated: the
      partitioner computes each moment/param update on the owning dp shard
      and all-gathers the fresh params.
    * stage 2 — same out_shardings as stage 1; the grads ARRIVE dp-sharded
      from the bucketed reduce-scatter (zero1-layout, so the update is
      local to the moment shard) and the params' end-of-step all-gather
      replaces the grad reduction's gather half.
    * stage 3 — params AND moments pinned to `zero3_shardings`: grads come
      back on the same layout from the gather transposes, the Adam update
      is fully local (no collective at all in the optimizer), and the
      fresh params REST sharded — the next step's forward re-gathers per
      layer.

    `moment_shardings` lets the caller pass the tree it already built for
    `device_put`-ing the initial state, so there is exactly one source of
    the moment layout; derived here when omitted.

    The ids/tgt/pos batch buffers are deliberately NOT donated: XLA
    donation is strictly input->output aliasing, and the int32 batch
    stack has no compatible output to alias — donating it frees nothing
    and warns on every compile. Donation hygiene is instead VERIFIED:
    obs/introspect reports the program's aliased bytes, so a refactor
    that silently breaks the params/opt donation (e.g. a dtype change
    un-aliasing the Adam moments) shows up in the train log's compile
    report instead of as a quiet 2x optimizer-state footprint."""
    donate = (0, 1)
    if zero_stage >= 3:
        param_sh = zero3_shardings(model, mesh)
        moment_sh = (moment_shardings if moment_shardings is not None
                     else param_sh)
    else:
        # Stage 0 pins its outputs too (moments on the params' own
        # shardings): without out_shardings XLA picks output layouts
        # freely, and on this jax/XLA a dozen small leaves (norm gains,
        # biases) come back in a layout that does NOT match their donated
        # input — the donation is silently dropped and those leaves
        # double-buffer. Found by graftcheck's donation-aliased contract
        # (ISSUE 11); value-parity is covered by the stage-0 train tests.
        param_sh = model.shardings(mesh)
        if moment_shardings is not None:
            moment_sh = moment_shardings
        else:
            moment_sh = (zero1_moment_shardings(model, mesh)
                         if zero_stage else param_sh)
    scalar = NamedSharding(mesh, P())
    opt_sh = AdamState(step=scalar, mu=moment_sh, nu=moment_sh)

    def shard_tree(spec):
        # isinstance-P first: PartitionSpec is tuple-like on older jax
        if isinstance(spec, P):
            return NamedSharding(mesh, spec)
        return tuple(shard_tree(s) for s in spec)

    return jax.jit(fn, donate_argnums=donate,
                   out_shardings=(param_sh, opt_sh,
                                  shard_tree(loss_sharding)))


def build_train_step(model: Transformer, mesh, ocfg: OptimizerConfig,
                     loss_mode: str = "vocab_parallel",
                     zero1: bool = False, moment_shardings=None,
                     with_grad_norm: bool = False,
                     dp_reduce_bucket_mb: float = 0.0, dp_reduce_dtype=None,
                     zero: "int | None" = None):
    """Returns jitted
    (params, opt_state, input_ids, target_ids, position_ids)
      -> (params, opt_state, loss)            [default]
      -> (params, opt_state, (loss, gnorm))   [with_grad_norm=True]

    `dp_reduce_bucket_mb > 0` swaps the whole-tree DP grad reduction for
    the bucketed-overlap reducer (with `dp_reduce_dtype=jnp.bfloat16` for
    a compressed wire) — see training/zero.build_bucketed_grad_fn.

    `zero` picks the ZeRO stage (0..3; supersedes the `zero1` bool, kept
    as an alias for stage 1). Stage 2 routes grads through the bucketed
    reduce-scatter; stage 3 additionally expects params (and the initial
    moments) device_put at `zero3_shardings` — they rest dp-sharded and
    the forward gathers per layer.
    """
    stage = _resolve_stage(zero, zero1)
    step = _step_body(model, mesh, ocfg, loss_mode,
                      with_grad_norm=with_grad_norm,
                      dp_reduce_bucket_mb=dp_reduce_bucket_mb,
                      dp_reduce_dtype=dp_reduce_dtype, zero_stage=stage)
    out_spec = (P(), P()) if with_grad_norm else P()
    return _jit_with_zero(step, model, mesh, stage, moment_shardings,
                          out_spec)


def build_train_step_multi(model: Transformer, mesh, ocfg: OptimizerConfig,
                           loss_mode: str = "vocab_parallel",
                           zero1: bool = False, moment_shardings=None,
                           with_grad_norm: bool = False,
                           dp_reduce_bucket_mb: float = 0.0,
                           dp_reduce_dtype=None,
                           zero: "int | None" = None):
    """Multi-step-per-dispatch variant: one jitted program runs
    `lax.scan` over a leading steps axis of the batch.

    (params, opt_state, input_ids(N,B,T), target_ids(N,B,T),
     position_ids(N,B,T)) -> (params, opt_state, losses(N))

    Identical training to N calls of `build_train_step`'s program (the scan
    body IS `_step_body`, same Adam/OneCycle state threading) but with ONE
    host dispatch, so the host->device round-trip is amortised N-fold. On a
    directly-attached chip that saves ~100us/step; through a remote/tunneled
    runtime it is the difference between dispatch-bound and compute-bound
    training. The reference has no analogue — its hot loop is necessarily
    one `optimizer.step()` per Python iteration
    (`/root/reference/train.py:94-109`).
    """
    stage = _resolve_stage(zero, zero1)
    step = _step_body(model, mesh, ocfg, loss_mode,
                      with_grad_norm=with_grad_norm,
                      dp_reduce_bucket_mb=dp_reduce_bucket_mb,
                      dp_reduce_dtype=dp_reduce_dtype, zero_stage=stage)

    def multi_step(params, opt_state: AdamState, input_ids, target_ids,
                   position_ids):
        def body(carry, batch):
            p, o, out = step(*carry, *batch)
            return (p, o), out

        (params, opt_state), outs = jax.lax.scan(
            body, (params, opt_state), (input_ids, target_ids, position_ids))
        # with_grad_norm: outs is (losses(N), gnorms(N)) — scan stacks each
        return params, opt_state, outs

    out_spec = (P(None), P(None)) if with_grad_norm else P(None)
    return _jit_with_zero(multi_step, model, mesh, stage, moment_shardings,
                          out_spec)


def build_grad_accum_step(model: Transformer, mesh, ocfg: OptimizerConfig,
                          loss_mode: str = "vocab_parallel",
                          zero1: bool = False, moment_shardings=None,
                          with_grad_norm: bool = False,
                          dp_reduce_bucket_mb: float = 0.0,
                          dp_reduce_dtype=None,
                          zero: "int | None" = None):
    """Gradient accumulation: ONE optimizer step from the MEAN of the
    microbatch gradients.

    (params, opt_state, input_ids(A,B,T), target_ids(A,B,T),
     position_ids(A,B,T)) -> (params, opt_state, mean_loss)

    Semantics are torch-DDP-style mean-of-means: each microbatch's masked
    token-mean CE and its gradient get equal weight regardless of how many
    valid tokens each holds (identical to a single A*B batch whenever the
    valid counts match). Peak activation memory stays that of ONE microbatch
    — the scan carries only the f32 grad sum — so effective batch scales
    without scaling HBM. The reference has no accumulation (SURVEY
    non-goals); this is the TPU-native extension of its loop.
    """
    stage = _resolve_stage(zero, zero1)
    grad_fn = _make_grad_fn(model, mesh, loss_mode,
                            dp_reduce_bucket_mb, dp_reduce_dtype,
                            zero_stage=stage)

    def step(params, opt_state: AdamState, input_ids, target_ids,
             position_ids):
        zeros = jax.tree.map(jnp.zeros_like, params)

        def body(acc, batch):
            loss_sum, g_sum = acc
            loss, g = grad_fn(params, *batch)
            return (loss_sum + loss, jax.tree.map(jnp.add, g_sum, g)), None

        (loss_sum, g_sum), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), zeros),
            (input_ids, target_ids, position_ids))
        a = input_ids.shape[0]
        grads = jax.tree.map(lambda x: x / a, g_sum)
        # the norm of the MEAN gradient — the quantity Adam actually sees
        out = ((loss_sum / a, global_norm(grads)) if with_grad_norm
               else loss_sum / a)
        params, opt_state = adam_update(ocfg, params, grads, opt_state)
        return params, opt_state, out

    out_spec = (P(), P()) if with_grad_norm else P()
    return _jit_with_zero(step, model, mesh, stage, moment_shardings,
                          out_spec)


