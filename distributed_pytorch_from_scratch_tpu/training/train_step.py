"""The jitted training step: loss + grads + Adam/OneCycle update.

The TPU-native analogue of the reference's hot loop body
(`/root/reference/train.py:94-109`): one XLA program per step — forward,
backward (shard_map transpose inserts the conjugate collectives), optimizer
update — with params and optimizer state donated so updates happen in-place
in HBM (no reallocation per step; the reference relies on torch's in-place
`optimizer.step()` for the same effect).
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp

from ..config import OptimizerConfig, TrainConfig
from ..models.transformer import Transformer
from .optim import AdamState, adam_update


def build_train_step(model: Transformer, mesh, ocfg: OptimizerConfig,
                     loss_mode: str = "vocab_parallel"):
    """Returns jitted
    (params, opt_state, input_ids, target_ids, position_ids)
      -> (params, opt_state, loss)."""
    loss_fn = model.make_loss(mesh, mode=loss_mode)
    grad_fn = jax.value_and_grad(loss_fn)

    def step(params, opt_state: AdamState, input_ids, target_ids, position_ids):
        loss, grads = grad_fn(params, input_ids, target_ids, position_ids)
        params, opt_state = adam_update(ocfg, params, grads, opt_state)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))


def build_eval_loss(model: Transformer, mesh, loss_mode: str = "vocab_parallel"):
    return jax.jit(model.make_loss(mesh, mode=loss_mode))
