"""Adam + OneCycle LR schedule, hand-rolled (from scratch, like the rest).

The reference uses `optim.Adam(params, lr)` + `OneCycleLR(optimizer, max_lr,
total_steps, pct_start=warmup/max_steps)` (`/root/reference/train.py:83-84`).
This module reproduces torch's semantics exactly:

* Adam: bias-corrected first/second moments, eps inside the sqrt's
  denominator (torch defaults, betas=(0.9, 0.999), eps=1e-8). With
  `weight_decay > 0` the update is torch.optim.AdamW's instead: decoupled
  decay `p *= 1 - lr*wd` applied before the moment update, never entering
  the moments.
* OneCycleLR (torch defaults): two cosine phases —
  warmup  `initial_lr = max_lr/div_factor -> max_lr` over pct_start,
  anneal  `max_lr -> initial_lr/final_div_factor` over the rest;
  and because torch's `cycle_momentum=True` default applies to Adam via its
  betas, **beta1 is cycled too**: max_momentum (0.95) -> base_momentum (0.85)
  during warmup and back up during annealing. (torch overwrites Adam's 0.9
  beta1 at scheduler construction — subtle but real, and we match it.)

Equivalence against torch.optim itself is asserted in
tests/test_optim.py (torch-CPU is available in the image for testing only;
the framework itself never imports torch).

The optimizer state pytree mirrors the param pytree, so the same
PartitionSpecs shard it: each TP rank keeps Adam moments only for its own
weight shard — the same property the reference gets from per-rank
`optim.Adam(model.parameters())` (`train.py:83`).

ZeRO contract (training/zero.py): `adam_update` is deliberately
stage-oblivious. Every per-leaf operation below is elementwise, so when
the moments (ZeRO-1), the grads (ZeRO-2, from the bucketed
reduce-scatter) and/or the params (ZeRO-3) arrive dp-sharded on MATCHING
layouts, XLA computes the update on whichever dp shard owns the data —
the sharded-weight-update schedule falls out of the layouts alone, and
this module cannot drift out of sync with a stage it never sees. The two
cross-leaf reductions (`global_norm`, `clip_by_global_norm`) are global
sums at the jit level, so the clip threshold and the logged grad norm are
stage-invariant (XLA partial-sums per shard and all-reduces one scalar).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..config import OptimizerConfig


class AdamState(NamedTuple):
    step: jax.Array      # int32 scalar
    mu: Any              # first moment, same pytree as params
    nu: Any              # second moment


def init_adam_state(params: Any) -> AdamState:
    zeros = lambda p: jnp.zeros_like(p)
    return AdamState(
        step=jnp.zeros((), jnp.int32),
        mu=jax.tree.map(zeros, params),
        nu=jax.tree.map(zeros, params),
    )


def _anneal_cos(start: float, end: float, pct: jax.Array) -> jax.Array:
    return end + (start - end) / 2.0 * (1.0 + jnp.cos(jnp.pi * pct))


def onecycle_lr(cfg: OptimizerConfig, step: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(lr, beta1) at optimizer step `step` (0-based, i.e. the schedule value
    used by the (step+1)-th update — torch applies the initial lr at
    construction and steps the scheduler after each optimizer.step())."""
    total = cfg.max_steps
    pct_start = cfg.warmup_steps / cfg.max_steps
    # torch's phase boundaries: warmup ends at pct_start*total - 1, annealing
    # at total - 1 (OneCycleLR._schedule_phases).
    up_end = float(pct_start * total) - 1.0
    down_end = float(total) - 1.0
    initial_lr = cfg.lr / cfg.div_factor
    min_lr = initial_lr / cfg.final_div_factor

    stepf = step.astype(jnp.float32) if hasattr(step, "astype") else float(step)
    up_pct = jnp.clip(stepf / jnp.maximum(up_end, 1e-9), 0.0, 1.0)
    down_pct = jnp.clip((stepf - up_end) / jnp.maximum(down_end - up_end, 1e-9),
                        0.0, 1.0)
    in_warmup = stepf <= up_end

    lr = jnp.where(in_warmup,
                   _anneal_cos(initial_lr, cfg.lr, up_pct),
                   _anneal_cos(cfg.lr, min_lr, down_pct))
    if cfg.cycle_momentum:
        beta1 = jnp.where(in_warmup,
                          _anneal_cos(cfg.max_momentum, cfg.base_momentum, up_pct),
                          _anneal_cos(cfg.base_momentum, cfg.max_momentum, down_pct))
    else:
        beta1 = jnp.asarray(cfg.betas[0], jnp.float32)
    return lr, beta1


def cosine_lr(cfg: OptimizerConfig, step: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Linear warmup over warmup_steps -> cosine decay to
    cosine_min_ratio * lr at max_steps. beta1 stays fixed (momentum cycling
    is a OneCycle-ism). The standard pretraining schedule; the reference
    only has OneCycle (`/root/reference/train.py:84`)."""
    stepf = step.astype(jnp.float32) if hasattr(step, "astype") else float(step)
    warm = float(max(cfg.warmup_steps, 1))
    total = float(max(cfg.max_steps - cfg.warmup_steps, 1))
    min_lr = cfg.lr * cfg.cosine_min_ratio
    warm_lr = cfg.lr * jnp.minimum(1.0, (stepf + 1.0) / warm)
    pct = jnp.clip((stepf - cfg.warmup_steps) / total, 0.0, 1.0)
    decay_lr = _anneal_cos(cfg.lr, min_lr, pct)
    lr = jnp.where(stepf < cfg.warmup_steps, warm_lr, decay_lr)
    return lr, jnp.asarray(cfg.betas[0], jnp.float32)


def schedule_lr(cfg: OptimizerConfig, step: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(lr, beta1) for this step under cfg.lr_schedule."""
    if cfg.lr_schedule == "cosine":
        return cosine_lr(cfg, step)
    if cfg.lr_schedule != "onecycle":
        raise ValueError(f"unknown lr_schedule {cfg.lr_schedule!r} "
                         "(choices: 'onecycle', 'cosine')")
    return onecycle_lr(cfg, step)


def global_norm(grads: Any) -> jnp.ndarray:
    """Global L2 norm over a gradient pytree, reduced in float32 — shared
    by the clipper below and the train step's logged/sentinel-watched
    grad norm (training/train_step.py), so the two can never diverge."""
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for g in jax.tree.leaves(grads)))


def clip_by_global_norm(grads: Any, max_norm: float) -> Any:
    """torch `clip_grad_norm_` semantics: one L2 norm over every grad leaf,
    scaled by max_norm/(norm + 1e-6) only when the norm exceeds max_norm."""
    norm = global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype),
                        grads)


def adam_update(cfg: OptimizerConfig, params: Any, grads: Any,
                state: AdamState) -> Tuple[Any, AdamState]:
    """One Adam(W) step with this step's scheduled (lr, beta1)
    (OneCycle incl. cycled beta1, or warmup+cosine — cfg.lr_schedule).

    Matches torch.optim.Adam's update exactly:
        mu    <- b1*mu + (1-b1)*g
        nu    <- b2*nu + (1-b2)*g^2
        p     <- p - lr * (mu/(1-b1^t)) / (sqrt(nu/(1-b2^t)) + eps)
    and torch.optim.AdamW's when cfg.weight_decay > 0 (decay applied to p
    first; tests/test_optim.py asserts both against torch.optim itself).
    """
    if cfg.clip_grad_norm is not None:
        grads = clip_by_global_norm(grads, cfg.clip_grad_norm)
    step = state.step  # 0-based count of completed steps
    lr, beta1 = schedule_lr(cfg, step)
    beta2 = cfg.betas[1]
    t = (step + 1).astype(jnp.float32)
    # Bias correction with a *cycled* beta1: torch computes `1 - beta1**t`
    # with the CURRENT beta1 (the scheduler rewrites param_groups), so we do
    # the same.
    bc1 = 1.0 - jnp.power(beta1, t)
    bc2 = 1.0 - jnp.power(jnp.asarray(beta2, jnp.float32), t)

    def upd(p, g, m, v):
        g = g.astype(p.dtype)
        if cfg.weight_decay:
            # torch.optim.AdamW: p.mul_(1 - lr*wd) BEFORE the Adam step
            # (decoupled decay — never enters the moments)
            p = p * (1.0 - lr * cfg.weight_decay)
        m_new = beta1 * m + (1.0 - beta1) * g
        v_new = beta2 * v + (1.0 - beta2) * (g * g)
        m_hat = m_new / bc1
        v_hat = v_new / bc2
        p_new = p - lr * m_hat / (jnp.sqrt(v_hat) + cfg.eps)
        return p_new, m_new, v_new

    flat_p, treedef = jax.tree.flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_m = treedef.flatten_up_to(state.mu)
    flat_v = treedef.flatten_up_to(state.nu)
    out = [upd(p, g, m, v) for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)]
    new_p = treedef.unflatten([o[0] for o in out])
    new_m = treedef.unflatten([o[1] for o in out])
    new_v = treedef.unflatten([o[2] for o in out])
    return new_p, AdamState(step=step + 1, mu=new_m, nu=new_v)
