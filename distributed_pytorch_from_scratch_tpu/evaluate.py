"""Evaluation entry point: per-checkpoint validation loss + greedy decoding.

`python -m distributed_pytorch_from_scratch_tpu.evaluate --ckpt_dir ... --data_path ... --tokenizer_path ...`

Capability parity with `/root/reference/test.py`, with its defects fixed:

* the reference crashes at `test.py:124` (`ckpt_path[-1]` indexes the last
  *character* of a path string instead of the last checkpoint) — here the
  newest checkpoint is selected properly;
* its validation "avg loss" divides a sum of per-batch means by the dataset
  size (`test.py:80`), correct only because bs=1 — here it divides by the
  number of batches;
* its greedy decode re-runs a growing full-sequence forward every token with
  no KV cache (`test.py:145-152`). The default decoder here is the KV-cache
  prefill+step path (models/decode.py): one fixed-shape compile, O(t) per
  token. `--no_kv_cache` selects the reference-parity full-recompute path
  (still a single fixed-shape jitted step over a padded buffer, since
  per-length recompiles would be pathological under XLA).
"""

from __future__ import annotations

import argparse
import os
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .cli import add_model_shape_args, build_model_config
from .config import BOS_TOKEN, EOS_TOKEN, IGNORE_INDEX, MeshConfig
from .data.dataset import get_dataloader
from .models.transformer import Transformer
from .obs import SpanTracer
from .runtime.mesh import batch_feeder, init_multihost, make_mesh
from .training.checkpoint import list_checkpoints, load_checkpoint
from .training.metrics import MetricsWriter

# The reference's eight fixed decode prompts (`test.py:126-135`).
DECODE_PROMPTS = [
    "Nice to meet you, it's",
    "Great empire never falls, it only",
    "Your majesty, it's my duty ",
    "I shall be glad ",
    "What a glory to ",
    "Shame for the weak, it's",
    "The brave man ne",
    "Poor old man, it's",
]


def get_eval_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__)
    g = p.add_argument_group("distributed")
    g.add_argument("--tp_size", type=int, default=1)
    g.add_argument("--dp_size", type=int, default=1,
                   help="shard validation batches over a 'dp' mesh axis "
                        "(ragged final batches are padded with IGNORE_INDEX "
                        "rows, which the masked CE mean drops exactly)")
    g.add_argument("--cp_size", type=int, default=1,
                   help="context-parallel axis: the validation forward "
                        "shards the sequence over 'cp' (ring attention), "
                        "and decoding routes through the PAGED serving "
                        "engine's cp-sharded page pool (ring chunked "
                        "prefill + cp-local decode; contiguous layout — "
                        "zigzag or --no_kv_cache decode on the cp=1 path)")
    g.add_argument("--cp_layout", choices=["contiguous", "zigzag"],
                   default="contiguous",
                   help="sequence layout over the cp ring (see train.py)")
    g.add_argument("--cp_impl", choices=["ring", "ulysses"], default="ring",
                   help="attention schedule for the cp-sharded validation "
                        "forward. NOTE: decode has no ulysses path — with "
                        "--cp_size > 1 a ulysses-trained config must decode "
                        "via --cp_impl ring (the weights are identical; "
                        "cp_impl only changes the attention schedule, not "
                        "the checkpoint) or --no_kv_cache")

    g = p.add_argument_group("data")
    g.add_argument("--data_path", "-d", required=True)
    g.add_argument("--tokenizer_path", "-t", required=True)

    g = p.add_argument_group("model")
    g.add_argument("--family", choices=["llama", "gpt2"], default="llama",
                   help="must match the trained model family; both decode "
                        "via the KV-cache decoder (gpt2's buffer is capped "
                        "at its learned position table)")
    g.add_argument("--ckpt_dir", required=True)
    add_model_shape_args(g)

    g = p.add_argument_group("decode")
    g.add_argument("--max_decode_len", type=int, default=128)
    g.add_argument("--no_kv_cache", action="store_true",
                   help="use the reference-parity full-recompute decode "
                        "instead of the KV-cache decoder (models/decode.py)")
    g.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy argmax (reference rule, test.py:149); "
                        "> 0 samples from softmax(logits/T) (KV-cache "
                        "decoder only)")
    g.add_argument("--decode_top_k", type=int, default=0,
                   help="with --temperature > 0: sample from the k most "
                        "likely tokens (0 = full distribution)")
    g.add_argument("--decode_top_p", type=float, default=0.0,
                   help="with --temperature > 0: nucleus sampling — keep "
                        "the smallest set of tokens whose probability mass "
                        "reaches p (0 = off; composes with --decode_top_k)")

    g = p.add_argument_group("other")
    g.add_argument("--random_seed", type=int, default=0)
    g.add_argument("--coordinator", type=str, default=None,
                   help="multi-host DCN rendezvous host:port (same contract "
                        "as train.py; omit on a single host)")
    g.add_argument("--num_processes", type=int, default=None)
    g.add_argument("--process_id", type=int, default=None)
    g.add_argument("--batch_size", type=int, default=8,
                   help="validation batch size (the reference pins 1, "
                        "test.py:105, which makes a 20-checkpoint sweep "
                        "pathologically slow; the sweep averages per-"
                        "DOCUMENT means, so the reported loss is exactly "
                        "batch-size independent, and ragged final batches "
                        "are padded with IGNORE_INDEX rows)")
    args = p.parse_args(argv)
    if args.temperature and args.no_kv_cache:
        # fail at parse time, not after the multi-checkpoint val sweep
        p.error("--temperature requires the KV-cache decoder "
                "(drop --no_kv_cache)")
    if (args.decode_top_k or args.decode_top_p) and not args.temperature:
        p.error("--decode_top_k/--decode_top_p only shape SAMPLED decoding; "
                "set --temperature > 0 (greedy ignores them)")
    if not 0.0 <= args.decode_top_p <= 1.0:
        p.error(f"--decode_top_p must be in [0, 1], got "
                f"{args.decode_top_p}")
    return args


def _pad_batch(batch, rows: int):
    """Pad a ragged final batch (drop_last=False) up to `rows` rows so its
    leading dim keeps dividing the dp mesh axis. Padding rows carry
    IGNORE_INDEX targets, so the masked CE mean is unchanged exactly."""
    have = batch["input_ids"].shape[0]
    if have == rows:
        return batch
    pad = rows - have
    return {
        "input_ids": np.concatenate(
            [batch["input_ids"],
             np.zeros((pad, batch["input_ids"].shape[1]), np.int32)]),
        "target_ids": np.concatenate(
            [batch["target_ids"],
             np.full((pad, batch["target_ids"].shape[1]), IGNORE_INDEX,
                     np.int32)]),
        "position_ids": np.concatenate(
            [batch["position_ids"],
             np.tile(batch["position_ids"][:1], (pad, 1))]),
    }


def calc_val_loss(loss_fn, params, dataloader, batch_rows: int,
                  feed=jnp.asarray, collect=np.asarray) -> float:
    """Mean of per-document CE means — the reference's bs=1 sweep semantics
    (`test.py:58-80`) at any batch size (every document's token-mean weighs
    equally, so --batch_size only changes dispatch count, not the number),
    with its sum-of-means / len(dataset) bug (`test.py:80`) fixed by
    dividing by the real document count. `loss_fn` = `model.make_doc_loss`:
    the sweep rides the same vocab-parallel CE as training — no (b, t, V)
    logits gather."""
    total, docs = 0.0, 0
    for batch in dataloader.epoch(0):
        batch = _pad_batch(batch, batch_rows)
        means, real = loss_fn(params,
                              feed(batch["input_ids"]),
                              feed(batch["target_ids"]),
                              feed(batch["position_ids"]))
        means, real = collect(means), collect(real)
        total += float(means[real].sum())
        docs += int(real.sum())
    return total / max(docs, 1)


def make_greedy_decoder(model: Transformer, mesh, buf_len: int):
    """One fixed-shape jitted step: (params, buffer(1,buf_len), cur_len) ->
    argmax token id at position cur_len-1.

    The decode buffer is REPLICATED over the dp/cp mesh axes (in_specs
    P(None, None)), like models/decode.py: `model.make_forward`'s
    P('dp','cp') batch sharding would split the single row over dp and the
    sequence over cp — and `model` here is the cp=1 twin, whose dense
    attention on a cp-sharded chunk would silently drop cross-chunk
    attention."""
    from jax.sharding import PartitionSpec as P

    fwd = jax.jit(jax.shard_map(
        model.forward_shard, mesh=mesh,
        in_specs=(model.specs(), P(None, None), P(None, None)),
        out_specs=P(None, None, "tp")))

    def step(params, buf, cur_len):
        logits = fwd(params, buf, jnp.tile(jnp.arange(buf_len)[None, :], (1, 1)))
        last = jax.lax.dynamic_index_in_dim(logits[0], cur_len - 1, axis=0,
                                            keepdims=False)
        return jnp.argmax(last[: model.cfg.vocab_size])

    return jax.jit(step)


def greedy_decode(model: Transformer, mesh, params, tokenizer, prompts,
                  bos_id: int, eos_id: int,
                  max_decode_len: int = 128,
                  use_kv_cache: bool = True,
                  temperature: float = 0.0,
                  top_k: int = 0,
                  top_p: float = 0.0,
                  seed: int = 0) -> List[Tuple[str, str]]:
    texts = [t.strip() for t in prompts]
    encoded = {t: tokenizer.encode(t).ids for t in texts}
    # one fixed buffer for every prompt (single compile); leave room for BOS
    # and at least one generated token even if a prompt is near the cap
    buf_len = max(max_decode_len + 1, max(len(i) for i in encoded.values()) + 2)
    # models with learned position embeddings (gpt2 family) hard-cap the
    # buffer at maxlen — positions past the table would silently clip to
    # its last row and degrade generations
    cap = getattr(model, "max_decode_positions", None)
    if cap is not None and buf_len > cap:
        longest = max(len(i) for i in encoded.values())
        if cap < longest + 2:
            raise SystemExit(
                f"prompts need {longest + 2} positions but the model's "
                f"learned position table has only {cap}")
        print(f"Warning: clamping decode buffer {buf_len} -> {cap} (learned "
              f"position table size); reduce --max_decode_len to silence")
        buf_len = cap

    cp = getattr(model, "cp_size", 1)

    if use_kv_cache:
        # serving engines (serving/engine.py), one compiled decode step
        # shared across prompts: at cp=1 the continuous-batching engine
        # prefills in length buckets; at cp>1 the PAGED engine shards its
        # page pool over 'cp' (ring chunked prefill + cp-local decode,
        # each rank holding 1/cp of the KV pages — it rounds page budgets
        # to cp multiples internally). Both are token-identical to the
        # fused GreedyDecoder for greedy decode (tests/test_serving.py,
        # tests/test_serving_cp.py), and the eval CLI exercises the same
        # lowering production serving uses.
        if cp > 1:
            from .serving.engine import PagedEngine as _Engine
        else:
            from .serving.engine import ContinuousBatchingEngine as _Engine
        from .serving.engine import decode_prompts

        prompts = [[bos_id] + encoded[t] for t in texts]
        engine = _Engine(
            model, mesh, params, num_slots=min(len(prompts), 8),
            buf_len=buf_len, eos_id=eos_id, temperature=temperature,
            top_k=top_k, top_p=top_p)
        # same TOTAL-length budget as the fused path's max_total_len
        gens = decode_prompts(
            engine, prompts,
            [max(0, max_decode_len + 1 - len(pr)) for pr in prompts],
            base_seed=seed)
        decoded_texts = [tokenizer.decode(encoded[t] + gen).strip()
                         for t, gen in zip(texts, gens)]
    else:
        step = make_greedy_decoder(model, mesh, buf_len)
        decoded_texts = []
        for text in texts:
            ids = encoded[text]
            buf = np.full((1, buf_len), eos_id, dtype=np.int32)
            buf[0, 0] = bos_id
            buf[0, 1 : len(ids) + 1] = ids
            cur = len(ids) + 1
            # stop when total length (incl. BOS) exceeds max_decode_len, like
            # the reference (`test.py:152`), or the buffer fills
            while cur < buf_len and cur <= max_decode_len:
                nxt = int(step(params, jnp.asarray(buf), cur))
                if nxt == eos_id:
                    break
                buf[0, cur] = nxt
                cur += 1
            decoded_texts.append(tokenizer.decode(buf[0, 1:cur].tolist()).strip())

    out = []
    for text, decoded in zip(texts, decoded_texts):
        ids = encoded[text]
        # The decode must extend the prompt (reference asserts this,
        # test.py:159, and crashes when the tokenizer's vocab cannot
        # round-trip a prompt byte — e.g. punctuation unseen in training).
        # Warn and split on the round-tripped prompt instead of dying.
        roundtrip = tokenizer.decode(ids).strip()
        if text in decoded:
            out.append((text, decoded[len(text):]))
        elif roundtrip and roundtrip in decoded:
            print(f"Warning: tokenizer cannot round-trip prompt {text!r} "
                  f"(becomes {roundtrip!r}); splitting on the round-trip")
            out.append((text, decoded[decoded.index(roundtrip) + len(roundtrip):]))
        else:
            raise AssertionError(
                f"decode must extend the prompt: {text!r} not in {decoded!r}")
    return out


def evaluate(args: argparse.Namespace) -> dict:
    from tokenizers import Tokenizer as HFTokenizer

    # Multi-host rendezvous before any backend use (no-op single host).
    # Only process 0's host needs the checkpoint files and writes reports;
    # every process runs the (collective) forward passes.
    init_multihost(getattr(args, "coordinator", None),
                   num_processes=args.num_processes,
                   process_id=args.process_id)
    nproc = jax.process_count()
    is_main = jax.process_index() == 0

    # maxlen is needed before the config (dataloader truncation + cp
    # divisibility); build_model_config re-derives the same value
    from .config import ModelConfig, model_preset
    preset = model_preset(args.model) if args.model else ModelConfig()
    maxlen = preset.maxlen if args.maxlen is None else args.maxlen

    if args.batch_size % args.dp_size != 0:
        raise SystemExit(f"--batch_size {args.batch_size} must be divisible "
                         f"by --dp_size {args.dp_size}")
    if maxlen % args.cp_size != 0:
        raise SystemExit(f"--maxlen {maxlen} must be divisible by "
                         f"--cp_size {args.cp_size}")
    if args.cp_size > 1 and args.cp_impl == "ulysses" \
            and not args.no_kv_cache:
        # VERDICT r5 #5: refuse loudly instead of silently requiring the
        # ring path — cp decoding (the paged engine's query ring over
        # cp-local pages) runs the ring schedule only, and a ulysses-
        # trained config would otherwise crash deeper in with an opaque
        # ValueError.
        raise SystemExit(
            f"--cp_impl ulysses has no KV-decode path (cp decoding is "
            f"ring-only: cp serving rings the prefill queries over "
            f"cp-local pages). "
            f"A ulysses-trained checkpoint is layout-identical to a ring "
            f"one — cp_impl only changes the attention schedule — so rerun "
            f"with --cp_impl ring, or --no_kv_cache, or --cp_size 1 (got "
            f"--cp_size {args.cp_size})")
    mesh = make_mesh(MeshConfig(dp=args.dp_size, tp=args.tp_size,
                                cp=args.cp_size))
    dataloader = get_dataloader(args.data_path, args.batch_size, IGNORE_INDEX,
                                split="validation", maxlen=maxlen,
                                shuffle=False, drop_last=False)
    vocab_size = dataloader.dataset.vocab_size
    cfg = build_model_config(args, vocab_size)
    # val loss runs the full dp x cp x tp mesh (pp/ep stay 1 at eval).
    # Decoding: with the contiguous layout cp>1 routes through the paged
    # serving engine (cp-sharded page pool, ring chunked prefill +
    # cp-local decode); the zigzag layout permutes the cache order, and
    # the full-recompute path (--no_kv_cache) is single-device dense
    # attention — both decode on the cp=1 path.
    dec_cp = (args.cp_size if (args.cp_layout == "contiguous"
                               and not args.no_kv_cache) else 1)
    if args.family == "gpt2":
        from .models.gpt2 import GPT2Transformer
        model_val = GPT2Transformer(cfg, tp_size=args.tp_size,
                                    cp_size=args.cp_size,
                                    cp_impl=args.cp_impl,
                                    cp_layout=args.cp_layout)
        model = GPT2Transformer(cfg, tp_size=args.tp_size, cp_size=dec_cp)
    else:
        model_val = Transformer(cfg, tp_size=args.tp_size,
                                cp_size=args.cp_size,
                                cp_impl=args.cp_impl,
                                cp_layout=args.cp_layout)
        model = Transformer(cfg, tp_size=args.tp_size, cp_size=dec_cp)
    template = model.init(jax.random.key(args.random_seed))
    loss_fn = model_val.make_doc_loss(mesh)
    feed = batch_feeder(mesh)
    if nproc > 1:
        # per-document means come back dp-sharded; replicate across hosts
        # before the host fetch (tiny (b,)-vectors — negligible traffic)
        from jax.sharding import NamedSharding, PartitionSpec
        _rep = jax.jit(lambda t: t,
                       out_shardings=NamedSharding(mesh, PartitionSpec()))
        collect = lambda x: np.asarray(_rep(x))
    else:
        collect = np.asarray

    if nproc > 1:
        from jax.experimental import multihost_utils
        ckpts = list_checkpoints(args.ckpt_dir, rank=0) if is_main else []
        # broadcast needs equal shapes on every process: count first
        n_ck = int(multihost_utils.broadcast_one_to_all(
            np.int64(len(ckpts) if is_main else 0)))
        its = np.full(n_ck, -1, np.int64)
        if is_main:
            its[:] = [it for it, _ in ckpts]
        its = multihost_utils.broadcast_one_to_all(its)
        paths = {it: path for it, path in ckpts} if is_main else {}

        def load_params(it):
            t = (load_checkpoint(args.ckpt_dir, it, template,
                                 model.specs())[0] if is_main else template)
            return multihost_utils.broadcast_one_to_all(t)
        ckpt_iters = [int(i) for i in its]
    else:
        ckpts = list_checkpoints(args.ckpt_dir, rank=0)
        paths = {it: path for it, path in ckpts}

        def load_params(it):
            return load_checkpoint(args.ckpt_dir, it, template,
                                   model.specs())[0]
        ckpt_iters = [it for it, _ in ckpts]
    if not ckpt_iters:
        raise SystemExit(f"no checkpoints found in {args.ckpt_dir}")
    if is_main:
        print(f"found {len(ckpt_iters)} checkpoints")

    writer = MetricsWriter(os.path.join(args.ckpt_dir, "val")) if is_main \
        else None
    # eval gets its own host timeline (same Chrome-trace format as train):
    # per-checkpoint restore + val sweep + decode, proc 0 only
    tracer = SpanTracer(os.path.join(args.ckpt_dir, "val"), enabled=is_main)
    report_path = os.path.join(args.ckpt_dir, "val", "val.txt")
    results = {}
    params = None
    try:
        with open(report_path if is_main else os.devnull, "a") as f:
            f.write("Ckpt -> Validation loss\n")
            for it in ckpt_iters:
                with tracer.span("restore", cat="checkpoint", ckpt=it):
                    params = jax.device_put(load_params(it),
                                            model.shardings(mesh))
                with tracer.span("val_loss", cat="eval", ckpt=it):
                    avg = calc_val_loss(loss_fn, params, dataloader,
                                        args.batch_size, feed=feed,
                                        collect=collect)
                if is_main:
                    print(f"iter {it}: val loss {avg:.4f}")
                    f.write(f"{paths.get(it, f'iter-{it}')} -> {avg:.4f}\n")
                    writer.scalar("val/loss", avg, it)
                results[it] = avg

        # params now holds the NEWEST checkpoint (the reference meant to do this
        # but indexed a string, test.py:124)
        tokenizer = HFTokenizer.from_file(args.tokenizer_path)
        bos_id, eos_id = dataloader.dataset.bos, dataloader.dataset.eos
        assert tokenizer.token_to_id(BOS_TOKEN) == bos_id
        assert tokenizer.token_to_id(EOS_TOKEN) == eos_id
        with tracer.span("decode", cat="eval", prompts=len(DECODE_PROMPTS)):
            decoded = greedy_decode(model, mesh, params, tokenizer,
                                    DECODE_PROMPTS,
                                    bos_id, eos_id, args.max_decode_len,
                                    use_kv_cache=not args.no_kv_cache,
                                    temperature=args.temperature,
                                    top_k=args.decode_top_k,
                                    top_p=args.decode_top_p,
                                    seed=args.random_seed)
        with open(report_path if is_main else os.devnull, "a") as f:
            f.write("\n\nInput texts -> Decoded texts\n")
            for prompt, completion in decoded:
                if is_main:
                    print(f"{prompt} -> {completion}")
                f.write(f"{prompt} -> {completion}\n")
    finally:
        # a failed sweep/decode still finalises trace.json (the timeline of
        # a PARTIAL eval is the one you actually want) and closes handles
        tracer.close()
        if writer is not None:
            writer.close()
    return {"val_losses": results, "decoded": decoded}


def main(argv=None):
    evaluate(get_eval_args(argv))


if __name__ == "__main__":
    main()
