"""Training entry point.

`python -m distributed_pytorch_from_scratch_tpu.train --tp_size N --data_path tokens.json ...`

Capability parity with `/root/reference/train.py` (flags `train.py:25-52`,
loop `train.py:55-146`), TPU-native:

* no `mp.spawn`/NCCL rendezvous — one process drives all visible chips via a
  ('dp','tp') mesh (`--dp_size` is the BASELINE config-5 extension; the
  reference is TP-only);
* dtype is an explicit flag (`--bf16`), not the DTYPE env var;
* the step is one donated jitted XLA program (see training/train_step.py);
* checkpoints carry optimizer state, so `--resume` continues exactly — the
  reference can only save (`train.py:121-133`), never resume;
* same logging surface: avg CE loss, lr, device memory, checkpoint filenames
  with iter/loss metadata, retention pruning.
"""

from __future__ import annotations

import argparse
import math
import os
import signal
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import multihost_utils

from .config import (IGNORE_INDEX, MODEL_PRESETS, REMAT_CHOICES, MeshConfig,
                     ModelConfig, OptimizerConfig, model_preset)
from .data.dataset import get_dataloader
from .data.prefetch import Prefetcher, stack_window, window_stream
from .models.transformer import Transformer
from .obs import TrainObserver, analyze_compiled, format_analysis
from .obs.runindex import run_stamp
from .runtime.mesh import (batch_feeder, init_multihost, make_mesh,
                           process_info)
from .training.checkpoint import (latest_step, load_checkpoint,
                                  save_checkpoint)
from .training.metrics import (MetricsWriter, ProfilerTrace,
                               chip_peak_flops, device_memory_gib,
                               model_flops_per_step, publish_hbm)
from .training.optim import init_adam_state, schedule_lr
from .training.train_step import (build_grad_accum_step, build_train_step,
                                  build_train_step_multi, resolve_zero_stage)
from .training.zero import zero1_moment_shardings


def _map_moments(opt_state, fn):
    """Apply `fn` (a params-tree transform, e.g. model.to_canonical) to the
    Adam moments — they shard/reshape exactly like their params. Identity
    transforms return the state unchanged."""
    return opt_state.__class__(step=opt_state.step, mu=fn(opt_state.mu),
                               nu=fn(opt_state.nu))


def get_train_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__)

    g = p.add_argument_group("distributed")
    g.add_argument("--tp_size", type=int, default=1)
    g.add_argument("--dp_size", type=int, default=1)
    g.add_argument("--cp_size", type=int, default=1,
                   help="context-parallel (sequence) axis size")
    g.add_argument("--cp_impl", choices=["ring", "ulysses"], default="ring")
    g.add_argument("--cp_layout", choices=["contiguous", "zigzag"],
                   default="contiguous",
                   help="zigzag: each cp shard gets an equally early+late "
                        "pair of sequence sub-chunks, balancing causal ring "
                        "work ~2x (ring impl only; needs maxlen %% (2*cp)==0)")
    g.add_argument("--sequence_parallel", action="store_true",
                   help="Megatron-style SP: shard inter-block activations "
                        "over the tp axis (reduce-scatter/all-gather instead "
                        "of all-reduce)")
    g.add_argument("--tp_overlap", choices=["off", "ring", "ring_q"],
                   default="off",
                   help="'ring' decomposes the SP tp collectives into ring "
                        "collective matmuls (ops/overlap.py): each ppermute "
                        "hop hides under the partial dot of the chunk in "
                        "hand, fwd and bwd; 'ring_q' puts int8 codes + "
                        "per-row scales on every hop (half the bf16 chunk "
                        "bytes; pinned bounds in tests/test_quant.py); "
                        "requires --sequence_parallel. 'off' stays "
                        "bit-identical to the monolithic path")
    g.add_argument("--zero", type=int, choices=[0, 1, 2, 3], default=None,
                   help="ZeRO stage over the dp axis (training/zero.py): "
                        "1 shards the Adam moments (2/dp optimizer memory); "
                        "2 also reduce-SCATTERS the grads (half the DP wire "
                        "bytes at identical buckets — implies the bucketed "
                        "reducer; --dp_reduce_dtype int8 rides the "
                        "quantized ring's reduce-scatter half) with one "
                        "param all-gather per step; 3 also shards the "
                        "PARAMS, gathered per layer on demand in fwd/bwd "
                        "(peak param HBM full/dp + one layer — the unlock "
                        "for models whose replica exceeds HBM x tp). "
                        "Stages 2/3: dense models, --pp_size 1, and "
                        "--sequence_parallel whenever tp > 1; stage 3 "
                        "needs remat (dots/true/auto) and an f32 "
                        "--dp_reduce_dtype")
    g.add_argument("--zero1", action="store_true",
                   help="alias for --zero 1 (the PR 4-era flag): shard "
                        "Adam moments over the dp axis")
    g.add_argument("--dp_reduce_bucket_mb", type=float, default=0.0,
                   help="bucketed DP/ZeRO-1 gradient reduction: issue one "
                        "psum per <= N-MiB bucket (overlappable with the "
                        "remaining backward) instead of the end-of-step "
                        "whole-tree blob; 0 = off (the default transpose-"
                        "derived reducer). Dense models, --pp_size 1")
    g.add_argument("--dp_reduce_dtype", choices=["f32", "bf16", "int8"],
                   default="f32",
                   help="wire dtype for the bucketed DP grad reduce: 'bf16' "
                        "halves the reduction bytes, 'int8' quarters them "
                        "via the EQuARX-style block-scaled quantized ring "
                        "(ops/overlap.quantized_allreduce; f32 master "
                        "accumulate either way). Needs "
                        "--dp_reduce_bucket_mb > 0")
    g.add_argument("--ep_size", type=int, default=1,
                   help="expert-parallel axis size (MoE: experts shard over "
                        "'ep'; requires --num_experts; 'ep' also shards the "
                        "batch for the dense sublayers)")
    g.add_argument("--pp_size", type=int, default=1,
                   help="pipeline-parallel axis size: layers shard into "
                        "pp stages, microbatches flow through a GPipe "
                        "schedule (both model families)")
    g.add_argument("--pp_microbatches", type=int, default=0,
                   help="microbatches per pipeline step (default pp_size; "
                        "more microbatches = smaller bubble fraction "
                        "(pp-1)/(m+pp-1) but smaller per-microbatch work)")
    g.add_argument("--pp_remat_steps", action="store_true",
                   help="rematerialise each pipeline step: backward "
                        "residuals shrink to the (mb, t, d) step carries "
                        "(the 1F1B-style memory cut) for ~33%% recompute")
    g.add_argument("--pp_schedule", choices=["gpipe", "interleaved"],
                   default="gpipe",
                   help="'interleaved' = Megatron-style virtual stages: "
                        "each device owns pp_virtual round-robin layer "
                        "blocks and microbatches circulate the ring "
                        "pp_virtual times — bubble drops from "
                        "(pp-1)/(m+pp-1) to (pp-1)/(pp_virtual*m+pp-1) at "
                        "the cost of pp_virtual x more ppermute hops")
    g.add_argument("--pp_virtual", type=int, default=2,
                   help="virtual stages per device for "
                        "--pp_schedule interleaved (num_layers must "
                        "divide by pp_size*pp_virtual)")

    g = p.add_argument_group("training")
    g.add_argument("--lr", type=float, default=3e-4)
    g.add_argument("--clip_grad_norm", type=float, default=None,
                   help="global-norm gradient clipping (torch "
                        "clip_grad_norm_ semantics); off by default like "
                        "the reference")
    g.add_argument("--warmup_steps", type=int, default=2000)
    g.add_argument("--weight_decay", type=float, default=0.0,
                   help="decoupled weight decay (torch.optim.AdamW "
                        "semantics); 0 = plain Adam, the reference's setup")
    g.add_argument("--lr_schedule", choices=["onecycle", "cosine"],
                   default="onecycle",
                   help="'onecycle' = reference parity (torch OneCycleLR "
                        "incl. beta1 cycling); 'cosine' = linear warmup + "
                        "cosine decay to --cosine_min_ratio x lr, beta1 "
                        "fixed")
    g.add_argument("--cosine_min_ratio", type=float, default=0.1,
                   help="--lr_schedule cosine: final lr as a fraction of "
                        "--lr")
    g.add_argument("--max_steps", type=int, default=20000)
    g.add_argument("--log_interval", type=int, default=100)
    g.add_argument("--save_interval", type=int, default=1000)
    g.add_argument("--save_dir", type=str, default="./checkpoints")
    # keep the reference's (misspelled) flag name as an alias, train.py:40
    g.add_argument("--reserve_last_n_ckpts", "--reserv_last_n_ckpts",
                   type=int, default=-1)
    g.add_argument("--batch_size", "-b", type=int, default=32)
    g.add_argument("--bf16", action="store_true",
                   help="bf16 matmuls/activations (params and loss stay f32)")
    g.add_argument("--loss_mode", choices=["vocab_parallel", "gather"],
                   default="vocab_parallel")
    g.add_argument("--resume", action="store_true",
                   help="resume from the latest checkpoint in --save_dir")
    g.add_argument("--steps_per_dispatch", type=int, default=1,
                   help="run N optimizer steps per device dispatch "
                        "(lax.scan over a stacked megabatch): bitwise the "
                        "same training, N-fold fewer host round-trips; "
                        "logs/saves land on dispatch boundaries")
    g.add_argument("--grad_accum", type=int, default=1,
                   help="gradient accumulation: each optimizer step averages "
                        "the grads of N microbatches (effective batch "
                        "N*batch_size at one microbatch's activation "
                        "memory); exclusive with --steps_per_dispatch > 1")

    g = p.add_argument_group("model")
    g.add_argument("--family", choices=["llama", "gpt2"], default="llama",
                   help="model family: 'llama' = the reference architecture "
                        "(RoPE/RMSNorm/SwiGLU), 'gpt2' = LayerNorm/GELU/"
                        "learned positions/tied embeddings (models/gpt2.py; "
                        "composes with dp/tp/cp/SP/pp/ep like llama — GQA "
                        "is the one llama-only feature)")
    g.add_argument("--model", choices=sorted(MODEL_PRESETS), default=None,
                   help="named shape preset (BASELINE configs: '45m' is the "
                        "reference shape, 'gpt2-124m' is config 3); explicit "
                        "dim flags below override preset fields")
    g.add_argument("--attn_dim", type=int, default=None)
    g.add_argument("--ffn_dim", type=int, default=None)
    g.add_argument("--num_heads", type=int, default=None)
    g.add_argument("--num_kv_heads", type=int, default=None,
                   help="grouped-query attention: K/V heads shared across "
                        "query-head groups (llama family; default = "
                        "num_heads, i.e. plain MHA like the reference)")
    g.add_argument("--num_layers", type=int, default=None)
    g.add_argument("--maxlen", type=int, default=None)
    g.add_argument("--num_experts", type=int, default=None,
                   help="Mixture-of-Experts: swap every layer's FFN for N "
                        "routed experts (both families; default 0 = dense "
                        "FFN like the reference)")
    g.add_argument("--moe_top_k", type=int, default=None,
                   help="experts activated per token (default 2)")
    g.add_argument("--moe_capacity_factor", type=float, default=None,
                   help="per-expert slot headroom; overflow tokens fall "
                        "through the residual (default 2.0)")
    g.add_argument("--remat", choices=sorted(REMAT_CHOICES) + ["auto"],
                   default="true",
                   help="per-layer rematerialisation: 'true' = lowest "
                        "memory, 'dots' = fastest that still bounds "
                        "residuals (see models/transformer.py); 'auto' = "
                        "the fastest policy whose activation-memory "
                        "estimate fits the chip "
                        "(training/memory.select_remat)")
    g.add_argument("--seq_bucket", type=int, default=0,
                   help="pad-aware sequence bucketing: pad each batch's "
                        "sequence dim up to a multiple of N (cleanly "
                        "tiled matmuls; 128 = the TPU lane width), tell "
                        "attention the real maxlen (pad tiles are "
                        "skipped, attn_t_real) and mask the pad targets "
                        "in the CE (IGNORE_INDEX). 0 = off; needs "
                        "--cp_size 1")

    g = p.add_argument_group("data")
    g.add_argument("--data_path", "-d", type=str, required=True)
    g.add_argument("--data_mode", choices=["docs", "packed"], default="docs",
                   help="'docs' = one document per row, padded to maxlen "
                        "(reference semantics, dataset.py:40-55); 'packed' "
                        "= concatenate shuffled BOS/EOS-framed documents "
                        "and cut fixed (batch, maxlen) chunks — zero "
                        "padding compute (classic GPT packing; documents "
                        "may span rows and attention may cross doc "
                        "boundaries within a row)")

    g = p.add_argument_group("observability")
    g.add_argument("--no_trace", action="store_true",
                   help="disable the host step-timeline tracer (on by "
                        "default; writes trace.jsonl + Perfetto-loadable "
                        "trace.json to the logs dir — docs/OBSERVABILITY.md)")
    g.add_argument("--no_sentinel", action="store_true",
                   help="disable the training-health sentinel (non-finite "
                        "loss/grad-norm halts with a state dump; loss "
                        "spikes are flagged)")
    g.add_argument("--sentinel_spike_factor", type=float, default=3.0,
                   help="flag a loss spike when interval loss > factor x "
                        "EMA (<= 0 disables spike detection only)")
    g.add_argument("--watchdog_secs", type=float, default=300.0,
                   help="hang watchdog: log a loud per-process report when "
                        "no dispatch completes for this many seconds "
                        "(0 disables)")
    g.add_argument("--flight_ring", type=int, default=256,
                   help="anomaly flight recorder: keep the last N spans/"
                        "heartbeats in a ring that sentinel halts and "
                        "watchdog stalls dump as flightdump_*.json "
                        "(docs/OBSERVABILITY.md; 0 disables)")
    g.add_argument("--metrics_port", type=int, default=None,
                   help="live telemetry exporter (obs/telemetry.py): step "
                        "time, tokens/s, MFU, goodput buckets at "
                        "http://127.0.0.1:PORT/metrics.json and /metrics "
                        "(Prometheus text). Multi-process runs bind "
                        "PORT + process_index; 0 = ephemeral. A busy "
                        "port refuses loudly up front")
    g.add_argument("--rollup_interval", type=float, default=5.0,
                   help="--metrics_port: seconds between "
                        "telemetry_snapshot events mirrored into "
                        "metrics.jsonl (the fleet collector's food)")
    g.add_argument("--profile_on_anomaly", type=int, default=0,
                   metavar="STEPS",
                   help="arm a bounded jax.profiler window of N dispatches "
                        "when a flight dump fires (sentinel halt, watchdog "
                        "stall), cross-linked from the dump's 'profile' "
                        "field; needs --flight_ring > 0; 0 = off")
    g.add_argument("--profile_every", type=int, default=0, metavar="N",
                   help="duty-cycled MEASURED attribution "
                        "(training/metrics.DutyCycleProfiler): every N "
                        "dispatches capture a --profile_window-dispatch "
                        "jax.profiler window, parse it (obs/profparse) "
                        "and land a versioned profile_attribution event "
                        "with the measured-vs-analytic reconcile; 0 = off "
                        "(exactly zero cost: no captures, no events)")
    g.add_argument("--profile_window", type=int, default=4, metavar="W",
                   help="--profile_every: dispatches per capture window "
                        "(must be <= N — a window longer than the duty "
                        "period would re-arm mid-capture)")
    g.add_argument("--profile_budget_mb", type=float, default=64.0,
                   help="--profile_every: total on-disk capture budget; "
                        "once exhausted, sampling stops BETWEEN windows "
                        "(never mid-window) with a logged skip counter")
    g.add_argument("--control", choices=["off", "advise", "act"],
                   default="off",
                   help="the obs v5 control plane (obs/control.py): the "
                        "drift advisor consumes each duty-cycled "
                        "measured-vs-analytic reconcile and the live HBM "
                        "watermarks and lands versioned tuning_decision "
                        "ledger events. 'advise' records without acting; "
                        "'act' applies at safe points — the training "
                        "knob (dp bucket MiB) is init-boundary, so its "
                        "decisions land applied=false and take effect at "
                        "the next launch. 'off' (default) is zero-cost: "
                        "no advisor, no events, no record fields")

    g = p.add_argument_group("other")
    g.add_argument("--random_seed", type=int, default=0)
    g.add_argument("--profile_steps", type=int, default=0,
                   help="trace N steps with jax.profiler (written to "
                        "SAVE_DIR/logs/profile; view in TensorBoard/xprof)")
    g.add_argument("--debug_nans", action="store_true",
                   help="jax.config.debug_nans: fail fast on the first "
                        "non-finite value (the functional analogue of a "
                        "sanitizer — SURVEY §5.2)")
    g.add_argument("--coordinator", type=str, default=None,
                   help="multi-host DCN rendezvous address host:port "
                        "(or set COORDINATOR_ADDRESS); omit on a single "
                        "host — the reference's --master_addr/--master_port "
                        "equivalent, /root/reference/train.py:30-31")
    g.add_argument("--num_processes", type=int, default=None,
                   help="multi-host: total process count (TPU pods "
                        "autodetect this; needed for CPU multi-process runs)")
    g.add_argument("--process_id", type=int, default=None,
                   help="multi-host: this process's id (see --num_processes)")
    args = p.parse_args(argv)
    if args.metrics_port is not None:
        # the serve.py refusals, verbatim: a run whose snapshot mirror
        # silently never starts is the traceless-run failure mode
        if args.metrics_port < 0:
            p.error(f"--metrics_port must be >= 0 (0 = ephemeral), got "
                    f"{args.metrics_port}")
        if args.rollup_interval <= 0:
            p.error("--rollup_interval must be > 0 (seconds between "
                    "telemetry_snapshot events)")
    if args.profile_every:
        # one jax.profiler capture at a time (ProfilerTrace's window
        # mechanics): the duty sampler cannot share the device profiler
        # with the fixed-window or anomaly-armed modes
        if args.profile_steps:
            p.error("--profile_every excludes --profile_steps (one "
                    "jax.profiler capture window at a time; the duty "
                    "sampler subsumes the fixed window)")
        if args.profile_on_anomaly:
            p.error("--profile_every excludes --profile_on_anomaly (both "
                    "drive the one-capture-at-a-time device profiler; "
                    "pick the duty cycle or the anomaly trigger)")
        if not 1 <= args.profile_window <= args.profile_every:
            p.error(f"--profile_window must be in [1, --profile_every] "
                    f"(a window longer than the duty period would re-arm "
                    f"mid-capture), got window {args.profile_window} with "
                    f"every {args.profile_every}")
        if args.profile_budget_mb <= 0:
            p.error(f"--profile_budget_mb must be > 0, got "
                    f"{args.profile_budget_mb}")
    if args.control != "off" and not args.profile_every:
        p.error("--control feeds on the duty profiler's measured "
                "reconciles (drift is what drives retuning); add "
                "--profile_every N")
    return args


def _bucket_window(window: dict, t_pad: int) -> dict:
    """Pad a host batch window's sequence dim up to `t_pad` (sequence
    bucketing): ids pad with 0 (any valid token — masked), targets with
    IGNORE_INDEX (the CE mask), positions extend edge-wise (clipped by the
    rope table, and masked anyway). Works on (B, T) and stacked (N, B, T)
    windows alike."""
    def pad(a, fill=None):
        extra = t_pad - a.shape[-1]
        if extra <= 0:
            return a
        width = [(0, 0)] * (a.ndim - 1) + [(0, extra)]
        if fill is None:
            return np.pad(a, width, mode="edge")
        return np.pad(a, width, constant_values=fill)

    return {"input_ids": pad(window["input_ids"], 0),
            "target_ids": pad(window["target_ids"], IGNORE_INDEX),
            "position_ids": pad(window["position_ids"])}


class _ShutdownFlag:
    """Preemption-safe shutdown: SIGTERM/SIGINT set a flag the train loop
    polls each step, so it saves a final checkpoint and exits cleanly.

    This is the failure-recovery story the reference lacks entirely
    (`mp.spawn(join=True)` — any signal just kills the job, SURVEY §5.3);
    on preemptible TPU VMs the eviction notice arrives as SIGTERM, making
    this the idiomatic TPU equivalent of elastic-training hooks. Handlers
    are only installed on the main thread (signal.signal raises elsewhere)
    and restored on exit so embedding callers (tests) are unaffected.
    """

    def __init__(self):
        self.requested = False
        self._installed = []
        if threading.current_thread() is threading.main_thread():
            for sig in (signal.SIGTERM, signal.SIGINT):
                prev = signal.signal(sig, self._handle)
                self._installed.append((sig, prev))

    def _handle(self, signum, frame):
        self.requested = True
        # Graceful shutdown can take a full train step + checkpoint write;
        # restore the previous handlers immediately so a SECOND signal
        # force-quits instead of being swallowed.
        self.restore()

    def restore(self):
        while self._installed:
            sig, prev = self._installed.pop()
            signal.signal(sig, prev)


def train(args: argparse.Namespace) -> dict:
    if args.debug_nans:
        jax.config.update("jax_debug_nans", True)
    # Multi-host rendezvous before any backend use (no-op on single host;
    # tests/test_multihost.py drives the underlying init across processes).
    init_multihost(getattr(args, "coordinator", None),
                   num_processes=args.num_processes,
                   process_id=args.process_id)
    nproc = jax.process_count()
    is_main = jax.process_index() == 0
    mesh_cfg = MeshConfig(dp=args.dp_size, tp=args.tp_size, cp=args.cp_size,
                          ep=args.ep_size, pp=args.pp_size)
    if mesh_cfg.world_size > jax.device_count():
        raise SystemExit(
            f"mesh {args.dp_size}x{args.pp_size}x{args.cp_size}x"
            f"{args.ep_size}x{args.tp_size} needs {mesh_cfg.world_size} "
            f"devices; only {jax.device_count()} visible "
            f"({jax.devices()[0].platform}). For CPU testing set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=N")
    # model shape: preset fields, overridden by any explicit dim flag
    # (reference shape = the '45m' preset = /root/reference/constants.py:9-17)
    preset = model_preset(args.model) if args.model else ModelConfig()
    pick = lambda flag, dflt: dflt if flag is None else flag
    maxlen = pick(args.maxlen, preset.maxlen)

    if maxlen % args.cp_size != 0:
        raise SystemExit(f"--maxlen {maxlen} must be divisible by "
                         f"--cp_size {args.cp_size} (sequence is sharded "
                         f"over the 'cp' mesh axis)")
    if args.batch_size % (args.dp_size * args.ep_size) != 0:
        raise SystemExit(f"--batch_size {args.batch_size} must be divisible "
                         f"by dp_size*ep_size "
                         f"{args.dp_size * args.ep_size} (the batch shards "
                         f"over both axes)")
    mesh = make_mesh(mesh_cfg)

    # One metrics/trace dir per process in multi-host runs (the reference
    # keeps one TB dir per rank, `/root/reference/train.py:85`); TB event
    # files and profiler traces from two writers in one dir clobber.
    # Created before model/data setup so the observer's timeline covers
    # init and checkpoint restore too.
    proc_idx = process_info()[0]
    logs_dir = os.path.join(args.save_dir, "logs") if nproc == 1 else \
        os.path.join(args.save_dir, "logs", f"proc{proc_idx}")
    writer = MetricsWriter(logs_dir, process_index=proc_idx)
    # live telemetry (ISSUE 12): per-process exporter endpoint — process i
    # binds base+i so a multi-host launch script can compute every
    # replica's scrape target from one flag; dies loudly on a busy port
    telemetry = None
    if args.metrics_port is not None:
        from .obs import TelemetryExporter
        telemetry = TelemetryExporter(
            writer=writer, process_index=proc_idx,
            rollup_interval=args.rollup_interval)
        base = args.metrics_port
        bound = telemetry.start(base + proc_idx if base else 0)
        print(f"telemetry exporter[p{proc_idx}]: "
              f"http://127.0.0.1:{bound}/metrics.json")
    observer = TrainObserver(
        logs_dir, writer=writer, trace=not args.no_trace,
        watchdog_secs=args.watchdog_secs, sentinel=not args.no_sentinel,
        spike_factor=args.sentinel_spike_factor,
        process_index=proc_idx, flight_ring=args.flight_ring,
        profile_on_anomaly=args.profile_on_anomaly)
    duty = None  # DutyCycleProfiler, built once the model shape is known
    advisor = None  # RetuneAdvisor (obs v5), rides the duty profiler

    try:
        dataloader = get_dataloader(args.data_path, args.batch_size,
                                    IGNORE_INDEX, split="train",
                                    maxlen=maxlen, shuffle=True,
                                    seed=args.random_seed,
                                    data_mode=args.data_mode)
        vocab_size = dataloader.dataset.vocab_size
        cfg = ModelConfig(attn_dim=pick(args.attn_dim, preset.attn_dim),
                          ffn_dim=pick(args.ffn_dim, preset.ffn_dim),
                          num_heads=pick(args.num_heads, preset.num_heads),
                          num_kv_heads=pick(args.num_kv_heads,
                                            preset.num_kv_heads),
                          num_layers=pick(args.num_layers, preset.num_layers),
                          num_experts=pick(args.num_experts, preset.num_experts),
                          moe_top_k=pick(args.moe_top_k, preset.moe_top_k),
                          moe_capacity_factor=pick(args.moe_capacity_factor,
                                                   preset.moe_capacity_factor),
                          vocab_size=vocab_size, maxlen=maxlen,
                          compute_dtype="bfloat16" if args.bf16 else "float32")
        # ZeRO stage: explicit --zero wins; --zero1 is the stage-1 alias
        # (the precedence rule lives in training/train_step.py)
        zero_stage = resolve_zero_stage(args.zero, args.zero1)
        remat_key = args.remat
        if remat_key == "auto":
            from .training.memory import select_remat
            remat_key = select_remat(cfg, args.batch_size, maxlen,
                                     tp=args.tp_size,
                                     world=mesh_cfg.world_size,
                                     zero_stage=zero_stage,
                                     dp=args.dp_size)
        t_bucket = 0
        if args.seq_bucket:
            if args.seq_bucket < 1 or args.seq_bucket % 128:
                raise SystemExit(
                    f"--seq_bucket must be a positive multiple of 128 (the "
                    f"TPU lane width), got {args.seq_bucket}")
            if args.cp_size > 1:
                raise SystemExit("--seq_bucket needs --cp_size 1 (the "
                                 "ring/ulysses paths shard the sequence "
                                 "and mask by global positions)")
            if cfg.num_experts:
                raise SystemExit(
                    "--seq_bucket does not compose with MoE: the router "
                    "sees every position, so pad tokens would claim "
                    "expert-capacity slots and inflate the aux losses")
            t_bucket = (-(-maxlen // args.seq_bucket)) * args.seq_bucket
            if t_bucket == maxlen:
                t_bucket = 0  # already aligned: nothing to pad
            else:
                print(f"seq bucketing: dispatching t={maxlen} batches in "
                      f"t={t_bucket} buffers (attention skips the pad "
                      f"tiles; CE masks the pad targets; tok/s and MFU "
                      f"count real tokens)")
        attn_t_real = maxlen if t_bucket else None
        if zero_stage == 3 and args.dp_reduce_dtype != "f32":
            # before the generic needs-a-bucket check: adding a bucket
            # would not make a compressed wire apply to stage 3
            raise SystemExit(
                f"--dp_reduce_dtype {args.dp_reduce_dtype} with --zero 3: "
                f"the ZeRO-3 grad reduce-scatter rides the parameter "
                f"all-gather's transpose (an f32 ppermute ring), so the "
                f"compressed wire would silently not apply — use it with "
                f"--zero 2, whose bucketed reduce-scatter carries the "
                f"{args.dp_reduce_dtype} payload")
        if (args.dp_reduce_dtype != "f32" and not args.dp_reduce_bucket_mb
                and zero_stage != 2):
            raise SystemExit(f"--dp_reduce_dtype {args.dp_reduce_dtype} "
                             f"needs --dp_reduce_bucket_mb > 0 (the "
                             f"compressed wire is a property of the "
                             f"bucketed reducer; --zero 2 implies it)")
        if args.dp_reduce_bucket_mb and args.pp_size > 1:
            raise SystemExit("--dp_reduce_bucket_mb needs --pp_size 1 "
                             "(pp-replicated leaves' reduction axes depend "
                             "on the pipeline head layout)")
        if args.dp_reduce_bucket_mb and cfg.num_experts:
            raise SystemExit("--dp_reduce_bucket_mb does not compose with "
                             "MoE (expert grads are ep-sharded, not "
                             "batch-replicated)")
        if zero_stage >= 2:
            # the stage-2/3 grad paths ride the bucketed reducer's scope
            # (training/zero.py) — refuse HERE with actionable messages
            # instead of a ValueError mid-build
            if cfg.num_experts:
                raise SystemExit(
                    f"--zero {zero_stage} does not compose with MoE: expert "
                    f"grads are ep-sharded, not batch-replicated — use "
                    f"--zero 1 (moment sharding only) for MoE runs")
            if args.pp_size > 1:
                raise SystemExit(
                    f"--zero {zero_stage} needs --pp_size 1: non-layer "
                    f"params are pp-replicated and their reduction axes "
                    f"depend on the pipeline head layout — use --zero 1 "
                    f"under pp")
            if args.tp_size > 1 and not args.sequence_parallel:
                raise SystemExit(
                    f"--zero {zero_stage} with --tp_size {args.tp_size} "
                    f"needs --sequence_parallel: the non-SP path "
                    f"all-reduces inside every row-parallel layer, so "
                    f"per-shard cotangent bookkeeping is depth-dependent "
                    f"(turn SP on, or drop to --zero 1)")
        if zero_stage == 3 and remat_key == "false":
            raise SystemExit(
                "--zero 3 needs rematerialisation (--remat dots/true/"
                "auto): without remat, autodiff saves every layer's "
                "GATHERED weights as backward residuals, recreating the "
                "full param replica the stage exists to eliminate")
        if zero_stage == 2 and not args.dp_reduce_bucket_mb:
            print("zero 2: grads reduce-scatter in 25 MiB buckets "
                  "(--dp_reduce_bucket_mb to tune)")
        if args.family == "gpt2":
            from .models.gpt2 import GPT2Transformer
            model = GPT2Transformer(cfg, tp_size=args.tp_size,
                                    cp_size=args.cp_size, cp_impl=args.cp_impl,
                                    cp_layout=args.cp_layout,
                                    sequence_parallel=args.sequence_parallel,
                                    tp_overlap=args.tp_overlap,
                                    ep_size=args.ep_size, pp_size=args.pp_size,
                                    pp_microbatches=args.pp_microbatches,
                                    pp_remat_steps=args.pp_remat_steps,
                                    pp_schedule=args.pp_schedule,
                                    pp_virtual=args.pp_virtual,
                                    remat=REMAT_CHOICES[remat_key],
                                    attn_t_real=attn_t_real)
        else:
            model = Transformer(cfg, tp_size=args.tp_size,
                            cp_size=args.cp_size, cp_impl=args.cp_impl,
                            cp_layout=args.cp_layout,
                            sequence_parallel=args.sequence_parallel,
                            tp_overlap=args.tp_overlap,
                            ep_size=args.ep_size, pp_size=args.pp_size,
                            pp_microbatches=args.pp_microbatches,
                            pp_remat_steps=args.pp_remat_steps,
                            pp_schedule=args.pp_schedule,
                            pp_virtual=args.pp_virtual,
                            remat=REMAT_CHOICES[remat_key],
                            attn_t_real=attn_t_real)
        ocfg = OptimizerConfig(lr=args.lr, warmup_steps=args.warmup_steps,
                               max_steps=args.max_steps,
                               clip_grad_norm=args.clip_grad_norm,
                               weight_decay=args.weight_decay,
                               lr_schedule=args.lr_schedule,
                               cosine_min_ratio=args.cosine_min_ratio)

        params = model.init(jax.random.key(args.random_seed))
        # count from the actual pytree: exact for every family (cfg.num_params()
        # hardcodes the llama layout — untied head, SwiGLU, no position table)
        n_params = sum(int(x.size) for x in jax.tree.leaves(params))
        moe_note = (f", {cfg.num_experts} experts (top-{cfg.moe_top_k})"
                    if cfg.num_experts else "")
        print(f"model[{args.family}]: {n_params/1e6:.2f}M params{moe_note}, "
              f"vocab={vocab_size}, "
              f"mesh=dp{args.dp_size} x pp{args.pp_size} x cp{args.cp_size} x "
              f"ep{args.ep_size} x tp{args.tp_size}, "
              f"compute={cfg.compute_dtype}"
              + (f", zero={zero_stage}" if zero_stage else ""))
        opt_state = init_adam_state(params)
        start_step = 0
        if args.resume:
            if nproc > 1:
                # Only process 0's host is assumed to hold the checkpoint files
                # (it is the only writer — see schedule_save). It loads and
                # broadcasts host trees; every process supplies its freshly
                # initialised tree as the shape/dtype template.
                last = latest_step(args.save_dir) if is_main else None
                last = int(multihost_utils.broadcast_one_to_all(
                    np.int64(-1 if last is None else last)))
                if last >= 0:
                    # Elastic restarts are single-process only: detect a
                    # layout mismatch on the loading process, agree on it
                    # everywhere, and refuse LOUDLY with the offline fix
                    # (a half-elastic broadcast would feed every process a
                    # tree its mesh does not own).
                    mismatch = 0
                    if is_main:
                        from .reshard import (layouts_equal, make_layout,
                                              resolve_source_layout)
                        src_lay, _ = resolve_source_layout(
                            args.save_dir, last,
                            specs=model.canonical_specs())
                        dst_lay = make_layout(mesh, model.canonical_specs(),
                                              zero_stage=zero_stage)
                        mismatch = 0 if layouts_equal(src_lay, dst_lay) \
                            else 1
                    mismatch = int(multihost_utils.broadcast_one_to_all(
                        np.int64(mismatch)))
                    if mismatch:
                        raise SystemExit(
                            f"--resume mesh mismatch: the checkpoint at "
                            f"{args.save_dir} iter {last} was saved under "
                            f"a different layout than this "
                            f"{nproc}-process run's mesh. In-process "
                            f"elastic resharding is single-process only; "
                            f"reshard the files offline first: python "
                            f"scripts/reshard_ckpt.py --src "
                            f"{args.save_dir} --dst <dir> --tp "
                            f"{args.tp_size} --dp {args.dp_size} --zero "
                            f"{zero_stage} --model <preset>")
                    tmpl_p = model.to_canonical(params)
                    tmpl_o = _map_moments(opt_state, model.to_canonical)
                    if is_main:
                        with observer.span("checkpoint", "restore", step=last):
                            ck_p, ck_o, start_step = load_checkpoint(
                                args.save_dir, last, tmpl_p,
                                model.canonical_specs(), with_opt=True)
                        if ck_o is None:
                            ck_o = tmpl_o
                    else:
                        ck_p, ck_o, start_step = tmpl_p, tmpl_o, 0
                    ck_p, ck_o = multihost_utils.broadcast_one_to_all((ck_p, ck_o))
                    start_step = int(multihost_utils.broadcast_one_to_all(
                        np.int64(start_step)))
                    params = model.from_canonical(ck_p)
                    opt_state = _map_moments(ck_o, model.from_canonical)
                    print(f"resumed from iter {start_step} in {args.save_dir} "
                          f"(broadcast from process 0)")
            else:
                last = latest_step(args.save_dir)
                if last is not None:
                    from .reshard import (layouts_equal, make_layout,
                                          resolve_source_layout)
                    src_lay, _ = resolve_source_layout(
                        args.save_dir, last, specs=model.canonical_specs())
                    dst_lay = make_layout(mesh, model.canonical_specs(),
                                          zero_stage=zero_stage)
                    if layouts_equal(src_lay, dst_lay):
                        with observer.span("checkpoint", "restore",
                                           step=last):
                            params, opt_state, start_step = load_checkpoint(
                                args.save_dir, last,
                                model.to_canonical(params),
                                model.canonical_specs(), with_opt=True)
                        params = model.from_canonical(params)
                        if opt_state is None:
                            opt_state = init_adam_state(params)
                        else:
                            opt_state = _map_moments(opt_state,
                                                     model.from_canonical)
                        print(f"resumed from iter {start_step} in "
                              f"{args.save_dir}")
                    else:
                        # ELASTIC restart: the checkpoint's mesh is not this
                        # run's mesh. Route through the reshard plan — each
                        # leaf stream-assembles once on the host and lands
                        # directly on its TARGET sharding (ZeRO ownership
                        # re-derives on this mesh via the same _zero_dim
                        # rule the optimizer uses), then record the lineage
                        # for run forensics.
                        if model._interleaved:
                            raise SystemExit(
                                "--resume across meshes with interleaved "
                                "pipeline stages is not supported: the "
                                "on-device tree is a permutation of the "
                                "canonical checkpoint tree (from_canonical "
                                "is layout-dependent) — resume on the "
                                "saving mesh, or use a non-interleaved "
                                "schedule")
                        from .reshard import HostMeter, stream_load
                        if zero_stage >= 3:
                            from .training.zero import zero3_shardings
                            p_sh = zero3_shardings(model, mesh)
                        else:
                            p_sh = model.shardings(mesh)
                        m_sh = (zero1_moment_shardings(model, mesh)
                                if zero_stage in (1, 2) else p_sh)
                        meter = HostMeter()
                        with observer.span("checkpoint", "reshard_restore",
                                           step=last):
                            params, ck_o, start_step, info = stream_load(
                                args.save_dir, last,
                                model.to_canonical(params),
                                model.canonical_specs(), dst_lay, p_sh,
                                moment_shardings=m_sh, with_opt=True,
                                meter=meter)
                        opt_state = (ck_o if ck_o is not None
                                     else init_adam_state(params))
                        writer.event(
                            "reshard_event", src_layout=info["src"],
                            dst_layout=info["dst"],
                            bytes_moved=info["bytes_moved"],
                            plan_ops=info["ops"], wall_ms=info["wall_ms"],
                            step=start_step,
                            peak_host_bytes=meter.peak)
                        print(f"elastic resume: iter {start_step} "
                              f"resharded {info['src']} -> {info['dst']} "
                              f"({info['bytes_moved']} bytes moved, "
                              f"{info['wall_ms']} ms)")

        if zero_stage >= 3:
            # ZeRO-3: params REST dp-sharded (zero3_specs); the step's
            # forward gathers each layer on demand. Moments share the
            # layout, so the Adam update is fully local per shard.
            from .training.zero import zero3_shardings
            shardings = zero3_shardings(model, mesh)
        else:
            shardings = model.shardings(mesh)
        params = jax.device_put(params, shardings)
        moment_sh = (zero1_moment_shardings(model, mesh)
                     if zero_stage in (1, 2) else shardings)
        opt_state = jax.device_put(
            opt_state, opt_state.__class__(
                step=jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()),
                mu=moment_sh, nu=moment_sh))

        spd = max(1, args.steps_per_dispatch)
        accum = max(1, args.grad_accum)
        if accum > 1 and spd > 1:
            raise SystemExit("--grad_accum and --steps_per_dispatch > 1 "
                             "are mutually exclusive")
        if spd > 1 and args.max_steps % spd != 0:
            print(f"note: --max_steps {args.max_steps} is not a multiple of "
                  f"--steps_per_dispatch {spd}: the final "
                  f"{args.max_steps % spd}-step tail triggers a one-time XLA "
                  f"recompile (pick a divisible pair to avoid it)")
        builder_kwargs = dict(zero=zero_stage,
                              moment_shardings=(moment_sh if zero_stage
                                                else None),
                              with_grad_norm=True,
                              dp_reduce_bucket_mb=args.dp_reduce_bucket_mb,
                              dp_reduce_dtype={"bf16": jnp.bfloat16,
                                               "int8": jnp.int8}.get(
                                                   args.dp_reduce_dtype))
        if accum > 1:
            step_fn = build_grad_accum_step(model, mesh, ocfg, args.loss_mode,
                                            **builder_kwargs)
        elif spd > 1:
            step_fn = build_train_step_multi(model, mesh, ocfg, args.loss_mode,
                                             **builder_kwargs)
        else:
            step_fn = build_train_step(model, mesh, ocfg, args.loss_mode,
                                       **builder_kwargs)

        # single-process: jnp.asarray; multi-host: global-array assembly from
        # per-process shards (every process iterates the identical dataloader)
        feed = batch_feeder(mesh)
        # profile a window shortly after start so compile+layout churn is over
        profiler = ProfilerTrace(logs_dir, start_step=start_step + 3,
                                 num_steps=args.profile_steps)
        if args.profile_every:
            # duty-cycled measured attribution (ISSUE 15): the analytic
            # phase report this run is priced with rides along, so every
            # parsed capture lands a full measured-vs-analytic reconcile
            from .obs.attribution import attribution as _attr, chip_key_for
            from .obs.profparse import analytic_phase_report
            from .training.metrics import DutyCycleProfiler
            chip = chip_key_for(jax.local_devices()[0].device_kind)
            analytic = analytic_phase_report(_attr(
                cfg, args.batch_size, maxlen, remat=remat_key,
                family=args.family, tp=args.tp_size,
                sp=args.sequence_parallel, tp_overlap=args.tp_overlap,
                dp=args.dp_size, dp_bucket_mb=args.dp_reduce_bucket_mb,
                dp_reduce_dtype=args.dp_reduce_dtype, chip=chip,
                world=mesh_cfg.world_size, zero_stage=zero_stage))
            duty = DutyCycleProfiler(
                logs_dir, args.profile_every, args.profile_window,
                args.profile_budget_mb, writer=writer, analytic=analytic)
            if args.control != "off":
                # drift-driven retuning (ISSUE 16): every parsed capture's
                # reconcile feeds the advisor BETWEEN windows — the hook
                # below is the registered safe point. dp bucket MiB is
                # baked into the compiled step, so it is an init-boundary
                # knob (no setter): act-mode decisions are recorded and
                # land at the next launch
                from .obs.control import RetuneAdvisor, control_safe_point
                advisor = RetuneAdvisor(args.control, writer=writer,
                                        telemetry=telemetry)
                advisor.register_knob(
                    "dp_bucket_mb", lambda: args.dp_reduce_bucket_mb,
                    integer=False)

                @control_safe_point
                def _on_attribution(fields):
                    advisor.observe_attribution(fields)
                    advisor.apply_decisions()

                duty.on_attribution = _on_attribution
        flops_step = model_flops_per_step(
            cfg, args.batch_size, maxlen,
            params=params if args.family == "gpt2" else None)
        peak_flops = chip_peak_flops() * mesh_cfg.world_size

        # The steady-shape program is AOT-compiled explicitly (under a traced
        # "compile" span) and introspected once — cost_analysis FLOPs, bytes,
        # per-collective comm, peak HBM — then called directly each dispatch.
        # Odd shapes (the max_steps tail window) and backends that reject AOT
        # calls fall back to the jit wrapper, whose recompile lands inside the
        # "step" span.
        aot = {"shape": None, "fn": None}

        def run_step(p, o, ids, tgt, pos, steps_in, step_no):
            # pin only the STEADY shape: a shrunk tail / partial epoch-end
            # window (spd mode) must not claim the AOT slot, or the
            # introspection would describe a program the run barely
            # executes and every full window would miss the cache
            steady = accum > 1 or steps_in == spd
            if aot["shape"] is None and steady:
                aot["shape"] = ids.shape
                with observer.span("compile", step=step_no):
                    try:
                        aot["fn"] = step_fn.lower(p, o, ids, tgt, pos).compile()
                    except Exception as e:
                        print(f"note: AOT compile unavailable "
                              f"({type(e).__name__}: {e}); introspection "
                              f"skipped, using the jit path")
                if aot["fn"] is not None:
                    analysis = analyze_compiled(aot["fn"])
                    # SPMD HLO is per-device: the hand-rolled global estimate
                    # spreads over world_size devices (and x steps_in for the
                    # scanned/accumulated multi-batch programs)
                    expected = flops_step * steps_in / mesh_cfg.world_size
                    observer.report_compiled(analysis, flops_step,
                                             steps_in_program=steps_in,
                                             expected_flops=expected,
                                             step=step_no)
                    if is_main:
                        print(format_analysis(analysis, model_flops=expected))
            fn = aot["fn"] if (aot["fn"] is not None
                               and ids.shape == aot["shape"]) else step_fn
            with observer.span("step", step=step_no):
                try:
                    return fn(p, o, ids, tgt, pos)
                except (TypeError, ValueError):
                    if fn is step_fn:
                        raise
                    # AOT input validation (shape/layout/sharding mismatch)
                    # surfaces before execution — nothing donated yet — so
                    # downgrading to the jit wrapper, which reshards freely,
                    # is safe
                    aot["fn"] = None
                    return step_fn(p, o, ids, tgt, pos)

        # with accumulation one optimizer step consumes `accum` batches
        steps_per_epoch = len(dataloader) // accum
        if steps_per_epoch == 0:
            if args.data_mode == "packed":
                raise SystemExit(
                    f"packed corpus yields {len(dataloader)} chunks of "
                    f"batch_size*maxlen = {args.batch_size * maxlen} tokens but "
                    f"one optimizer step needs {accum} chunk(s) (grad_accum): "
                    f"zero steps per epoch — reduce --batch_size/--maxlen/"
                    f"--grad_accum")
            raise SystemExit(
                f"dataset has {len(dataloader.dataset)} sequences but one "
                f"optimizer step needs {args.batch_size * accum} "
                f"(batch_size x grad_accum, drop_last): zero steps per epoch — "
                f"reduce --batch_size/--grad_accum")
        max_epoch = math.ceil(args.max_steps / steps_per_epoch)
        # resume continues the data stream too: same seeded per-epoch order,
        # skipping the batches already consumed
        start_epoch = start_step // steps_per_epoch
        skip_batches = (start_step % steps_per_epoch) * accum
        # accumulate the loss on-device; a float() sync every step would
        # serialize host dispatch with device execution
        accum_loss, n = jnp.zeros((), jnp.float32), start_step
        # the sentinel piggybacks on the logging-interval sync: last dispatch's
        # on-device grad norm + the per-interval mean loss, no extra D2H
        last_gnorm = None
        last_cum, last_log_n = 0.0, start_step
        t_start, tokens_since, steps_since = time.time(), 0, 0
        useful_since = 0  # non-IGNORE_INDEX targets: real tokens vs padding
        done = False
        shutdown = _ShutdownFlag()

        _last_poll = [None]

        def shutdown_agreed(step=None) -> bool:
            """Cross-host-consistent shutdown decision. schedule_save runs a
            collective in multi-host mode, so acting on a process-local signal
            would send one process into an all-gather the others never enter
            (deadlock). Every process contributes its local flag and the
            MAX (any-of) is what all of them act on — same collective cost as
            a broadcast, but a SIGTERM delivered to only one host (some
            schedulers signal a single rank) still wins a shutdown checkpoint
            everywhere (ADVICE r4). The gather blocks on device_get, so inside
            the loop (`step` given) it runs only once per log_interval steps:
            preemption reaction lags up to that many steps, and host dispatch
            stays async in between."""
            if nproc == 1:
                return shutdown.requested
            if step is not None:
                if (_last_poll[0] is not None
                        and step - _last_poll[0] < args.log_interval):
                    return False
                _last_poll[0] = step
            return bool(np.max(multihost_utils.process_allgather(
                np.int32(shutdown.requested))))
        last_saved = start_step
        pending_save = None  # at most one async checkpoint write in flight
        replicate_fn = []  # lazily-built jitted all-gather for multi-host saves

        def join_save():
            nonlocal pending_save
            if pending_save is not None:
                with observer.span("checkpoint", "join_save",
                                   step=pending_save.step):
                    paths = pending_save.join()
                print(f"saved checkpoint iter {pending_save.step}: {paths[0]}" +
                      (f" (+{len(paths)-1} shards)" if len(paths) > 1 else ""))
                pending_save = None

        def schedule_save(step):
            with observer.span("checkpoint", "schedule_save", step=step):
                _schedule_save(step)

        def _schedule_save(step):
            nonlocal pending_save, last_saved
            avg = float(accum_loss) / (step - start_step)
            join_save()  # bound in-flight async writes to one
            save_params = model.to_canonical(params)
            save_opt = _map_moments(opt_state, model.to_canonical)
            if nproc > 1:
                # Cross-host shards are not addressable from this process, so
                # `jax.device_get` inside the writer would fail. All-gather to
                # every host (XLA collective — all processes must participate),
                # then only process 0 touches the filesystem. Params and the two
                # Adam moments gather SEQUENTIALLY and land in host RAM one at a
                # time, so peak extra device memory is one param-tree — still
                # O(full model) per device transiently, which under --zero1
                # means saves need that much headroom (per-host shard files
                # would remove even that; not needed at this framework's
                # scales).
                if not replicate_fn:
                    replicate_fn.append(jax.jit(
                        lambda t: t, out_shardings=jax.tree.map(
                            lambda _: jax.sharding.NamedSharding(
                                mesh, jax.sharding.PartitionSpec()),
                            save_params)))

                def gather_host(tree):
                    rep = replicate_fn[0](tree)
                    if is_main:
                        return jax.device_get(rep)
                    jax.block_until_ready(rep)  # serialize; buffers free on drop
                    return None

                host_p = gather_host(save_params)
                host_mu = gather_host(save_opt.mu)
                host_nu = gather_host(save_opt.nu)
                if not is_main:
                    last_saved = step
                    return
                save_params = host_p
                save_opt = save_opt.__class__(
                    step=np.asarray(int(jax.device_get(save_opt.step)), np.int32),
                    mu=host_mu, nu=host_nu)
            pending_save = save_checkpoint(
                args.save_dir, step, avg, save_params,
                model.canonical_specs(), args.tp_size, save_opt,
                reserve_last_n=args.reserve_last_n_ckpts,
                async_write=True, tracer=observer.tracer,
                zero_stage=zero_stage, mesh_axes=mesh)
            last_saved = step

        def shutdown_save(step):
            """Shared by both shutdown exits (per-batch poll and post-loop)."""
            if step > last_saved:
                schedule_save(step)
            print(f"shutdown requested: checkpointed at step {step}; "
                  f"restart with --resume to continue")

        multi = accum > 1 or spd > 1
        host_wait, host_dispatches = 0.0, 0
        prefetcher = None  # closed in the finally on ANY exit (thread cleanup)
        try:
            for epoch in range(start_epoch, max_epoch):
                # One background thread assembles the NEXT dispatch's window
                # (C++ collate + the spd/accum megabatch np.stack) while the
                # device executes the current one; the main thread's per-
                # dispatch host cost collapses to a queue pop (VERDICT r2
                # weak #6). Windows are per-epoch: a partial spd window at the
                # epoch boundary simply dispatches smaller (same math, batch n
                # -> step n mapping unchanged), and a partial accum group is
                # dropped below, exactly like the pre-prefetch loop.
                prefetcher = Prefetcher(
                    window_stream(dataloader.epoch(epoch),
                                  accum if accum > 1 else spd,
                                  skip=skip_batches if epoch == start_epoch
                                  else 0),
                    depth=2,
                    transform=stack_window if multi else (lambda bufs: bufs[0]),
                    tracer=observer.tracer)
                windows = iter(prefetcher)
                while True:
                    wait_before = prefetcher.wait_time
                    try:
                        with observer.span("data_wait"):
                            window = next(windows)
                    except StopIteration:
                        break
                    # Shutdown poll once per WINDOW: buffered/prefetched batches
                    # were never trained on, so dropping them loses nothing —
                    # resume re-reads them. Dispatch is async, so a signal
                    # arriving mid-execution is caught here before the next
                    # dispatch launches.
                    if shutdown_agreed(n):
                        prefetcher.close()
                        shutdown_save(n)
                        done = True
                        break
                    if accum > 1 and window["input_ids"].shape[0] < accum:
                        # partial accumulation group at the epoch end: drop it
                        # (drop_last at the optimizer-step level) so every epoch
                        # performs exactly steps_per_epoch steps — the resume
                        # math (start_epoch/skip_batches) relies on that
                        continue
                    prev_n = n
                    if args.profile_steps:
                        profiler.maybe_start(n)
                    if multi:
                        rem = args.max_steps - n
                        if accum == 1 and window["input_ids"].shape[0] > rem:
                            # shrink the final window so the run ends exactly on
                            # max_steps (one-time recompile at the tail shape)
                            window = {k: v[:rem] for k, v in window.items()}
                        steps_in = window["input_ids"].shape[0] if accum == 1 \
                            else accum
                    else:
                        steps_in = 1
                    # bucket-pad the dispatched buffers only; `window`
                    # keeps the real shape for the token accounting below
                    w_feed = (_bucket_window(window, t_bucket) if t_bucket
                              else window)
                    with observer.span("h2d"):
                        ids = feed(w_feed["input_ids"])
                        tgt = feed(w_feed["target_ids"])
                        pos = feed(w_feed["position_ids"])
                    params, opt_state, out = run_step(params, opt_state, ids,
                                                      tgt, pos, steps_in, n)
                    if multi:
                        losses, gnorms = out
                        # accumulation: `losses` is already the one step's mean
                        loss = losses if accum > 1 else jnp.sum(losses)
                        last_gnorm = gnorms if accum > 1 else gnorms[-1]
                    else:
                        loss, last_gnorm = out
                    n += 1 if accum > 1 else steps_in
                    tokens_since += window["input_ids"].size
                    useful_since += int((window["target_ids"]
                                         != IGNORE_INDEX).sum())
                    steps_since += steps_in
                    observer.heartbeat(n, tokens=window["input_ids"].size,
                                       steps=steps_in, sync=loss)
                    if duty is not None:
                        # the duty window's start/stop boundaries; `loss`
                        # is this dispatch's device value (stop barrier)
                        duty.tick(n, sync=loss)
                    # only DISPATCHED pulls count toward the ms/dispatch wait
                    # metric (dropped partial groups and the end-of-epoch
                    # sentinel would deflate it)
                    host_wait += prefetcher.wait_time - wait_before
                    host_dispatches += 1
                    if args.profile_steps:
                        profiler.maybe_stop(n, sync=loss)
                    accum_loss = accum_loss + loss
                    if n // args.log_interval > prev_n // args.log_interval:
                        lr, _ = schedule_lr(ocfg, jnp.asarray(n - 1))
                        # the one blocking D2H of the interval: cumulative loss
                        # + last dispatch's grad norm ride the same sync
                        with observer.span("step", "device_sync", step=n):
                            cum = float(accum_loss)
                            gnorm = (float(last_gnorm)
                                     if last_gnorm is not None else None)
                        avg = cum / (n - start_step)
                        interval_loss = (cum - last_cum) / max(n - last_log_n, 1)
                        dt = time.time() - t_start
                        tps = tokens_since / max(dt, 1e-9)
                        useful = useful_since / max(tokens_since, 1)
                        mfu = (flops_step * steps_since) / max(dt, 1e-9) / peak_flops
                        # None = the backend reports no memory_stats (CPU):
                        # say so loudly; a 0.00 GiB watermark here misread
                        # as "no HBM used" on every chip-less box (ISSUE 15)
                        mem = device_memory_gib()
                        mem_s = (f"{mem:.2f} GiB" if mem is not None
                                 else "n/a (no memory stats)")
                        print(f"step {n}/{args.max_steps} -> avg loss {avg:.4f}, "
                              f"lr {float(lr):.8f}, {tps/1e3:.1f}k tok/s "
                              f"({useful*100:.0f}% useful), "
                              f"MFU {mfu*100:.1f}%, mem {mem_s}")
                        writer.scalar("train/ce_loss", avg, n)
                        writer.scalar("train/lr", float(lr), n)
                        writer.scalar("train/tokens_per_sec", tps, n)
                        writer.scalar("train/useful_token_frac", useful, n)
                        writer.scalar("train/mfu", mfu, n)
                        if mem is not None:  # never export a fake 0
                            writer.scalar("device_memory_gib", mem, n)
                        # live HBM watermarks (ISSUE 15): per-device
                        # gauges + one hbm_watermark event per interval
                        # ('unavailable' exported loudly on CPU)
                        marks = publish_hbm(telemetry=telemetry,
                                            writer=writer, step=n,
                                            event=True)
                        if advisor is not None:
                            # proposals only — actuation stays at the
                            # on_attribution safe point (or close())
                            advisor.observe_hbm(
                                {"devices": marks or [],
                                 "available": marks is not None})
                        if gnorm is not None:
                            writer.scalar("train/grad_norm", gnorm, n)
                        if telemetry is not None:
                            # same numbers the log line prints — the live
                            # endpoint view; the goodput buckets ride too
                            # (a dict copy per log interval, not per step)
                            telemetry.gauge("train/tokens_per_sec", tps)
                            telemetry.gauge("train/mfu", mfu)
                            telemetry.gauge("train/loss_avg", avg)
                            telemetry.gauge(
                                "train/step_time_ms",
                                1e3 * dt / max(steps_since, 1))
                            telemetry.counter("train/step", n)
                            gsum = observer.goodput.summary()
                            telemetry.gauge("train/goodput",
                                            gsum["goodput"])
                            for b, v in gsum["buckets_s"].items():
                                telemetry.gauge(f"train/bucket_s/{b}", v)
                        last_cum, last_log_n = cum, n
                        t_start, tokens_since, steps_since = time.time(), 0, 0
                        useful_since = 0
                        # after the metrics land on disk: a non-finite interval
                        # raises TrainingHealthError through the finally below
                        observer.check_health(n, interval_loss, gnorm)
                    if n // args.save_interval > prev_n // args.save_interval:
                        schedule_save(n)
                    if n >= args.max_steps:
                        done = True
                        break
                prefetcher.close()
                print(f"epoch {epoch + 1}/{max_epoch} finished")
                if done:
                    break
            # A signal that lands during the run's FINAL dispatch exits the loop
            # via the max_steps break without passing the per-batch poll — it
            # must still checkpoint the trained state (the pre-multi-dispatch
            # code polled after every step and caught this window). The
            # n > last_saved guard keeps a signal the poll already handled from
            # printing the shutdown message twice.
            if n > last_saved and shutdown_agreed():
                shutdown_save(n)
        finally:
            # On ANY exit (including a raising step): stop the prefetch thread
            # (else it busy-polls its full queue forever), let the in-flight
            # async write finish so no truncated npz is left behind, and put the
            # previous signal handlers back so embedding callers keep Ctrl-C.
            # The observer closes here too, so a sentinel halt still leaves a
            # complete trace.json + goodput summary behind; the writer closes
            # last (the observer logs its summary through it).
            if prefetcher is not None:
                prefetcher.close()
            shutdown.restore()
            join_save()
            # duty profiler before the observer/writer: an open capture
            # window finalises + parses into its profile_attribution
            # event while the jsonl stream is still writable
            if duty is not None:
                duty.close()
                if duty.captures or duty.windows_skipped:
                    print(f"duty profiler: {len(duty.captures)} capture(s) "
                          f"({duty.attributions} attributed, "
                          f"{duty.bytes_used / 2**20:.1f} MiB of "
                          f"{duty.budget_bytes / 2**20:.0f} MiB budget"
                          + (f", {duty.windows_skipped} window(s) skipped "
                             f"after budget exhaustion"
                             if duty.windows_skipped else "") + ")")
            # advisor after the duty profiler (whose close() can hand it
            # one last reconcile), before the writer its ledger lands in
            if advisor is not None:
                advisor.close()
                s = advisor.summary()
                if s["decisions"]:
                    print(f"control[{s['mode']}]: {s['decisions']} "
                          f"decision(s), {s['applied']} applied, last "
                          f"knob {s['last_knob']}")
            observer.close(print_summary=is_main)
            # exporter after the observer (its final snapshot is the
            # run's last registry state), before the writer it mirrors to
            if telemetry is not None:
                telemetry.close()
            writer.close()

        final_avg = float(accum_loss) / max(n - start_step, 1)
        profiler.close(sync=accum_loss)
        if host_dispatches:
            print(f"input pipeline: host waited "
                  f"{1e3 * host_wait / host_dispatches:.2f} ms/dispatch for "
                  f"data ({host_dispatches} dispatches; collate+stack ran on "
                  f"the prefetch thread)")
        print(f"training finished at step {n}, avg loss {final_avg:.4f}")
        # ISSUE 17: provenance stamp — the run-forensics join key every
        # summary record carries uniformly (bench/serve/train)
        out = {"steps": n, "avg_loss": final_avg,
               **run_stamp(vars(args))}
        if advisor is not None:  # zero-cost off: no field when off
            out["control"] = advisor.summary()
        return out
    except BaseException:
        # Exceptions BEFORE the loop's own try/finally (bad data path,
        # validation SystemExits, model-init failures) must not leak the
        # watchdog thread or the open trace/metrics handles when train()
        # is embedded (tests call it repeatedly). Both closes are
        # idempotent, so the happy path's finally running first is fine.
        if duty is not None:
            duty.close()
        if advisor is not None:
            advisor.close()
        observer.close(print_summary=False)
        if telemetry is not None:
            telemetry.close()
        writer.close()
        raise


def main(argv=None):
    train(get_train_args(argv))


if __name__ == "__main__":
    main()
