"""TPU-native distributed training framework.

Brand-new JAX/XLA/pjit/Pallas implementation with the capabilities of
`ldh127/distributed_pytorch_from_scratch` (Megatron-style tensor parallelism
from first principles), re-designed TPU-first. See SURVEY.md at the repo root
for the reference analysis and build plan.
"""

__version__ = "0.1.0"

from .runtime import compat as _compat  # noqa: F401  (must precede jax use)
from .config import (
    BOS_TOKEN, EOS_TOKEN, UNK_TOKEN, IGNORE_INDEX,
    EvalConfig, MeshConfig, ModelConfig, OptimizerConfig, TrainConfig,
)
from .models.gpt2 import GPT2Transformer
from .models.transformer import Transformer
from .models.vanilla import VanillaGPT2, VanillaTransformer
from .runtime.mesh import make_mesh, tp_mesh, single_device_mesh
