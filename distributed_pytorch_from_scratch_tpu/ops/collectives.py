"""The four conjugate communication primitives, TPU-native.

These re-express the reference's `torch.autograd.Function` collectives
(`/root/reference/models/comm_ops.py`) over a named mesh axis, for use inside
`jax.shard_map`-partitioned code. The conjugate-pair structure (Megatron's
f/g operators) maps directly onto JAX primitives whose transposes are already
the right thing:

  reference op                      JAX primitive          transpose
  ------------------------------    -------------------    -------------------
  Copy    (fwd id, bwd all-reduce,  lax.pvary              lax.psum
           comm_ops.py:47-60)
  Reduce  (fwd all-reduce, bwd id,  lax.psum               lax.pvary
           comm_ops.py:31-44)
  Split   (fwd slice, bwd gather,   slice at axis_index    zero-pad + psum
           comm_ops.py:7-28)                                (== all-gather)
  Gather  (fwd all-gather, bwd      lax.all_gather         lax.psum_scatter
           slice, comm_ops.py:63-83)                        (== slice when the
                                                            cotangent is the
                                                            1/n-scaled mean)

so no custom VJPs are needed: JAX's vma (varying-manual-axes) machinery
derives exactly the Megatron conjugate gradients.

Unlike the reference, the ops do NOT short-circuit when the axis has size 1
(its `tp_size == 1` early-outs, `comm_ops.py:13-14,37-38,57-58,70-71`):
XLA compiles size-1 collectives to nothing, and the vma type system needs the
ops to run so values keep consistent varying/invariant tags on every mesh
shape (a size-1 'tp' axis otherwise leaves stale varying-over-tp tags that
break out_specs replication checks).

All ops MUST be called from inside `shard_map` code partitioned over `axis`.
"""

from __future__ import annotations

import jax
from jax import lax


def _axis_size(axis: str) -> int:
    return lax.axis_size(axis)


def copy_to(x: jax.Array, axis: str = "tp") -> jax.Array:
    """Identity forward; all-reduce(SUM) backward.

    Megatron's f operator — placed at the input of a column-parallel block so
    each shard's input-gradient contributions are summed
    (reference `Copy`, `/root/reference/models/comm_ops.py:47-60`).

    No-op when `x` is already varying over `axis`: an already-varying input
    got its tag from an upstream collective (e.g. the sequence-parallel
    all-gather) whose own transpose performs the gradient sum — a second
    pvary would be ill-typed, and the psum belongs to that producer.
    """
    vma = getattr(jax.typeof(x), "vma", frozenset()) or frozenset()
    axes = (axis,) if isinstance(axis, str) else tuple(axis)
    need = tuple(a for a in axes if a not in vma)
    if not need:
        return x
    if hasattr(lax, "pcast"):
        return lax.pcast(x, need, to="varying")
    return lax.pvary(x, need)


def reduce_from(x: jax.Array, axis: str = "tp") -> jax.Array:
    """All-reduce(SUM) forward; identity backward.

    Megatron's g operator — sums partial outputs of a row-parallel block
    (reference `Reduce`, `/root/reference/models/comm_ops.py:31-44`).
    """
    return lax.psum(x, axis)


def split_to(x: jax.Array, axis: str = "tp") -> jax.Array:
    """Slice the last dim to this shard's chunk forward; all-gather backward.

    (reference `Split`, `/root/reference/models/comm_ops.py:7-28`.)
    `x` must be replicated over `axis`; the transpose of the slice under
    shard_map reassembles the full cotangent, which is exactly the
    all-gather-and-concat the reference's `Split.backward` performs.
    """
    n = _axis_size(axis)
    dim = x.shape[-1]
    assert dim % n == 0, f"last dim {dim} not divisible by axis size {n}"
    shard = dim // n
    idx = lax.axis_index(axis)
    return lax.dynamic_slice_in_dim(x, idx * shard, shard, axis=-1)


def gather_from(x: jax.Array, axis: str = "tp", tiled_axis: int = -1) -> jax.Array:
    """All-gather shards along the last dim forward; slice backward.

    (reference `Gather`, `/root/reference/models/comm_ops.py:63-83`.)
    The JAX transpose is psum_scatter, which generalises the reference's
    slice-the-grad rule: when every shard holds an identical (replicated)
    cotangent scaled by 1/n — the situation the reference relies on, since
    each rank computes the same loss from the same gathered logits —
    psum_scatter reproduces the sliced gradient.
    """
    return lax.all_gather(x, axis, axis=tiled_axis, tiled=True)


def reduce_scatter(x: jax.Array, axis: str = "tp", scatter_axis: int = -1) -> jax.Array:
    """Sum across the axis, scattering the result (each shard keeps a chunk).

    Absent from the reference (NCCL reduce-scatter unused) but required for
    sequence-parallel and ZeRO-style extensions — SURVEY §5.8.
    """
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis % x.ndim,
                            tiled=True)


def all_to_all(x: jax.Array, axis: str, split_axis: int, concat_axis: int) -> jax.Array:
    """All-to-all: re-shard from one tensor dim to another over `axis`.

    The Ulysses sequence-parallel primitive (head<->sequence swap); no
    reference counterpart (SURVEY §2.4: Ulysses absent).
    """
    return lax.all_to_all(x, axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def ring_permute(x: jax.Array, axis: str, shift: int = 1) -> jax.Array:
    """One ring hop over `axis` in an EXPLICIT direction.

    `shift` is the perm direction, not an offset convenience: shift=+k
    builds the forward ring perm [(i, (i+k) % n)] — rank i SENDS to i+k, so
    after s hops of shift=+1 rank r HOLDS the value originated by rank
    (r - s) mod n. shift=-k is the reverse ring. TPU ICI rings are
    bidirectional, so both directions cost the same; the overlap kernels
    (ops/overlap.py) pin shift=+1 for every hop — the all-gather ring walks
    chunk origins DOWN (r-s) while the reduce ring walks accumulator
    destinations UP (r + n-1-s), and both statements assume the forward
    perm. Callers composing with them must use the same convention (the
    ring-CP attention does: ops/ring_attention.py rotates k/v with
    shift=+1). shift=0 would silently self-send; refused.
    """
    if shift == 0:
        raise ValueError("ring_permute needs an explicit nonzero shift "
                         "(direction); shift=0 would self-send every rank")
    n = _axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def axis_index(axis: str = "tp") -> jax.Array:
    """This shard's coordinate along `axis` (lax.axis_index).

    Pipeline live-gating contract: the pp bubble predicates derive ONLY
    from (pipeline step, axis_index('pp')) — never from data — so every
    member of a tp/ep/sp group (which shares a pp stage, hence the same
    index) agrees on the branch, keeping the collectives inside the live
    branch uniform. Code that adds new gating must preserve this: a
    predicate mixing in axis_index of a NON-pp axis would diverge within
    the group and deadlock its collectives.
    """
    return lax.axis_index(axis)
