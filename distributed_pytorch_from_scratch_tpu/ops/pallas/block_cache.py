"""Shared JSON persistence for the Pallas block-autotuner tables.

Both kernel families keep a small in-memory table of tuned block shapes
— flash (`flash_attention.py`: (t_bucket, head_dim, dtype, backend) ->
BlockConfig) and paged (`paged_attention.py`: (page_size, head_dim,
kv_dtype, backend) -> PagedBlockConfig) — persisted as JSON so one
on-chip sweep serves every later run. The env-var/merge/atomic-publish
mechanics are identical and MUST NOT drift independently (a key-format
drift between writer and reader silently un-tunes every dispatch), so
they live here once: keys serialize as ':'-joined parts, values as the
config's tuple, unreadable/garbled files are ignored (the table keeps
its defaults), and writes publish atomically via os.replace (the
training/checkpoint.py convention).
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, Tuple


def default_cache_path(env_var: str, filename: str) -> str:
    return os.environ.get(
        env_var,
        os.path.join(os.path.expanduser("~"), ".cache", "dpfs_tpu",
                     filename))


def load_json_table(path: str, table: Dict, parse_key: Callable,
                    parse_cfg: Callable) -> int:
    """Merge `path`'s JSON into `table`; returns entries read. `parse_key`
    maps the split ':' parts to a table key, `parse_cfg` the stored list
    to a config — either raising ValueError/TypeError skips just that
    entry. Unreadable/garbled files are ignored entirely."""
    try:
        with open(path) as f:
            raw = json.load(f)
    except (OSError, ValueError):
        return 0
    n = 0
    for key, blocks in raw.items():
        try:
            k = parse_key(key.split(":"))
            cfg = parse_cfg(blocks)
        # IndexError: a key with too few ':' parts (the parse_key
        # lambdas index into the split) — malformed like the rest, and
        # this load runs lazily inside kernel dispatch, so one bad
        # entry must never crash a run
        except (ValueError, TypeError, IndexError):
            continue  # skip malformed entries, keep the rest
        table[k] = cfg
        n += 1
    return n


def save_json_table(path: str, table: Dict[Tuple, object]) -> str:
    """Write `table` (key tuple -> config with .as_tuple()) to `path`
    atomically; returns the path."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    raw = {":".join(str(p) for p in key): list(cfg.as_tuple())
           for key, cfg in sorted(table.items())}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(raw, f, indent=1)
    os.replace(tmp, path)  # atomic publish, like training/checkpoint.py
    return path
