"""Shared JSON persistence for the Pallas block-autotuner tables.

Both kernel families keep a small in-memory table of tuned block shapes
— flash (`flash_attention.py`: (t_bucket, head_dim, dtype, backend) ->
BlockConfig) and paged (`paged_attention.py`: (page_size, head_dim,
kv_dtype, backend) -> PagedBlockConfig) — persisted as JSON so one
on-chip sweep serves every later run. The env-var/merge/atomic-publish
mechanics are identical and MUST NOT drift independently (a key-format
drift between writer and reader silently un-tunes every dispatch), so
they live here once: keys serialize as ':'-joined parts, values as the
config's tuple, unreadable/garbled files are ignored (the table keeps
its defaults), and writes publish atomically via os.replace (the
training/checkpoint.py convention).

Cache format v2 (ISSUE 16): every entry carries PROVENANCE —
`{source: sweep|online, capture, ts}` — because the control plane can
now refresh entries from a live run's own captures, and an online
retune must never silently shadow a hardware sweep. The on-disk shape
is `{"version": 2, "entries": {key: {"blocks": [...], "source": ...,
"capture": ..., "ts": ...}}}`. A v1 flat file ({key: [blocks]}) is
migrated LOUDLY on load: one stderr note, entries adopted with
`source: "sweep"` (the conservative read — pre-provenance entries came
from offline sweeps, and "sweep" is the protected class). Writing an
`online` entry over a `sweep` one refuses without `force=True`
(`--force` at the CLI surfaces).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Callable, Dict, Optional, Tuple

CACHE_VERSION = 2

#: provenance a pre-v2 / meta-less entry adopts: offline sweeps were the
#: only writer before ISSUE 16, and "sweep" is the shadowing-protected
#: class — adopting "online" would let the next online write clobber it
DEFAULT_PROVENANCE = {"source": "sweep", "capture": None, "ts": None}


def default_cache_path(env_var: str, filename: str) -> str:
    return os.environ.get(
        env_var,
        os.path.join(os.path.expanduser("~"), ".cache", "dpfs_tpu",
                     filename))


def _parse_raw(raw, path: str):
    """Split a loaded JSON document into (entries, migrated): v2 wraps
    entries under {"version": 2, "entries": ...}; a v1 flat dict of
    key -> blocks-list migrates loudly (never a silent KeyError on the
    missing wrapper, never a silent adoption either)."""
    if not isinstance(raw, dict):
        raise ValueError("cache root is not a JSON object")
    if "entries" in raw or "version" in raw:
        v = raw.get("version")
        if not isinstance(v, int) or v > CACHE_VERSION:
            raise ValueError(f"cache version {v!r} is newer than this "
                             f"reader (v{CACHE_VERSION})")
        entries = raw.get("entries")
        if not isinstance(entries, dict):
            raise ValueError("cache 'entries' is not a JSON object")
        return entries, False
    # v1: flat {key: [blocks...]} — migrate, loudly
    if not raw:
        return {}, False
    print(f"block cache: migrating pre-provenance (v1) cache {path} — "
          f"{len(raw)} entr{'y' if len(raw) == 1 else 'ies'} adopted as "
          f"source=sweep (re-save rewrites it as v{CACHE_VERSION})",
          file=sys.stderr)
    return raw, True


def load_json_table(path: str, table: Dict, parse_key: Callable,
                    parse_cfg: Callable,
                    meta: Optional[Dict] = None) -> int:
    """Merge `path`'s JSON into `table`; returns entries read. `parse_key`
    maps the split ':' parts to a table key, `parse_cfg` the stored
    blocks list to a config — either raising ValueError/TypeError skips
    just that entry. Unreadable/garbled files are ignored entirely.
    `meta` (key -> provenance dict), when given, receives each entry's
    {source, capture, ts} — v1 entries and malformed provenance adopt
    DEFAULT_PROVENANCE."""
    try:
        with open(path) as f:
            raw = json.load(f)
        entries, _ = _parse_raw(raw, path)
    except (OSError, ValueError):
        return 0
    n = 0
    for key, val in entries.items():
        blocks = val.get("blocks") if isinstance(val, dict) else val
        try:
            k = parse_key(key.split(":"))
            cfg = parse_cfg(blocks)
        # IndexError: a key with too few ':' parts (the parse_key
        # lambdas index into the split) — malformed like the rest, and
        # this load runs lazily inside kernel dispatch, so one bad
        # entry must never crash a run
        except (ValueError, TypeError, IndexError):
            continue  # skip malformed entries, keep the rest
        table[k] = cfg
        if meta is not None:
            if isinstance(val, dict) and val.get("source") in ("sweep",
                                                               "online"):
                meta[k] = {"source": val["source"],
                           "capture": val.get("capture"),
                           "ts": val.get("ts")}
            else:
                meta[k] = dict(DEFAULT_PROVENANCE)
        n += 1
    return n


def save_json_table(path: str, table: Dict[Tuple, object],
                    meta: Optional[Dict] = None) -> str:
    """Write `table` (key tuple -> config with .as_tuple()) to `path`
    atomically as a v2 document; returns the path. Provenance comes
    from `meta` (key -> {source, capture, ts}); entries without one
    adopt DEFAULT_PROVENANCE."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    meta = meta or {}
    entries = {}
    for key, cfg in sorted(table.items()):
        prov = meta.get(key) or dict(DEFAULT_PROVENANCE)
        entries[":".join(str(p) for p in key)] = {
            "blocks": list(cfg.as_tuple()),
            "source": prov.get("source", "sweep"),
            "capture": prov.get("capture"),
            "ts": prov.get("ts"),
        }
    raw = {"version": CACHE_VERSION, "entries": entries}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(raw, f, indent=1)
    os.replace(tmp, path)  # atomic publish, like training/checkpoint.py
    return path


def write_online_entry(path: str, key: Tuple, cfg, parse_key: Callable,
                       parse_cfg: Callable, capture: Optional[str] = None,
                       force: bool = False) -> str:
    """Persist ONE online-retuned entry into the cache at `path`
    (read-modify-write against the file, not a caller's in-memory
    table, so concurrent sweeps elsewhere in the file survive).

    Refuses (ValueError) to shadow an existing `source: sweep` entry
    unless `force` — an online heuristic overruling a measured hardware
    sweep must be an explicit operator decision (--force), never a
    silent table write."""
    table: Dict = {}
    meta: Dict = {}
    load_json_table(path, table, parse_key, parse_cfg, meta=meta)
    prev = meta.get(key)
    if prev is not None and prev.get("source") == "sweep" and not force:
        raise ValueError(
            f"refusing to shadow swept block-cache entry "
            f"{':'.join(str(p) for p in key)} in {path} with an online "
            f"retune (swept entries are measured ground truth; pass "
            f"--force to overrule)")
    table[key] = cfg
    meta[key] = {"source": "online", "capture": capture,
                 "ts": int(time.time())}
    return save_json_table(path, table, meta=meta)
