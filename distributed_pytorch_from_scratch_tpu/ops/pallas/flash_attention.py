"""Blockwise causal flash attention for TPU, written in Pallas.

The fused HBM-friendly attention path the reference lacks: its naive
attention materialises the full (b, heads, t, t) score tensor in device
memory (`/root/reference/models/model.py:73-77`). This kernel streams
K/V blocks through VMEM with an online softmax, so HBM traffic and
residual memory are O(t) instead of O(t^2), and the q@k^T / softmax / @v
chain is fused into one MXU-resident loop.

Math matches `ops.attention.causal_attention_xla` exactly: masked
positions get an additive -10000 there, which underflows to probability
exactly 0.0 in the f32 softmax whenever any real score exceeds
-9900 or so (always, in practice); here masked positions are hard-zeroed,
giving the same result.

Forward + backward are both Pallas kernels wired through `jax.custom_vjp`
(the backward recomputes p = exp(s - logsumexp) blockwise from the saved
row-logsumexp, the standard flash-attention-2 scheme). Runs compiled on
TPU and in interpreter mode on CPU (used by the cluster-free tests).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

MASK = -1e30  # hard mask; equivalent to the XLA path's -10000 (see module doc)

# Swept on v5e at the reference shape (b*h=256, t=1000->1024, hd=64):
# 1024x1024 runs the fwd kernel 2.0x and fwd+bwd 1.8x faster than the
# previous 512x1024 default (2.45ms vs 4.93ms fwd; 5.77ms vs 10.58ms
# fwd+bwd per layer) — fewer grid steps amortize the VMEM pipeline better
# at these small head dims. Blocks clamp to the padded sequence length, so
# shorter sequences are unaffected. The backward kernels are swept
# separately (they keep larger per-block VMEM working sets).
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
DEFAULT_BWD_BLOCK_Q = 1024
DEFAULT_BWD_BLOCK_K = 1024


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _out_struct(shape, dtype, like: jax.Array) -> jax.ShapeDtypeStruct:
    """ShapeDtypeStruct carrying the varying-manual-axes tag of `like`, so
    the kernel composes with shard_map's vma type checking (the kernel runs
    per-shard on tp-varying values inside the TP transformer)."""
    vma = getattr(jax.typeof(like), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


# ---------------------------------------------------------------- forward


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, scale: float, t_real: int,
                block_q: int, block_k: int, num_kb: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, MASK)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Entire block above the causal diagonal, or entirely padding: skip.
    block_live = (ki * block_k <= qi * block_q + block_q - 1) & (
        ki * block_k < t_real) & (qi * block_q < t_real)

    @pl.when(block_live)
    def _compute():
        # Dot in the INPUT dtype with f32 accumulation: for bf16 inputs the
        # result is identical to upcasting first (bf16->f32 is exact, the MXU
        # accumulates f32 either way) but runs in one MXU pass instead of the
        # multi-pass f32 decomposition.
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # (bq, bk)

        row = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        col = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where((col > row) | (col >= t_real), MASK, s)

        m_prev = m_ref[:]                                    # (bq, 1)
        l_prev = l_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)                      # (bq, 1)
        p = jnp.exp(s - m_new)                               # (bq, bk)
        l_ref[:] = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[:] = m_new
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # (bq, d)
        acc_ref[:] = acc_ref[:] * alpha + pv

    @pl.when(ki == num_kb - 1)
    def _finalize():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)  # padded q rows only
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:] + jnp.log(l_safe)          # (bq, 1)


def _fwd_call(q, k, v, *, t_real: int, block_q: int, block_k: int):
    bh, t_pad, d = q.shape
    num_qb = t_pad // block_q
    num_kb = t_pad // block_k
    scale = 1.0 / math.sqrt(d)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, t_real=t_real,
        block_q=block_q, block_k=block_k, num_kb=num_kb)

    flops = 4 * t_real * t_real * d * bh // 2  # causal: half the square
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, num_qb, num_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _out_struct((bh, t_pad, d), q.dtype, q),
            _out_struct((bh, t_pad, 1), jnp.float32, q),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=flops, bytes_accessed=q.size * 3 * q.dtype.itemsize,
            transcendentals=t_real * t_real * bh // 2),
        interpret=_interpret(),
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------- backward


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, scale: float, t_real: int,
               block_q: int, block_k: int, num_kb: int):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    block_live = (ki * block_k <= qi * block_q + block_q - 1) & (
        ki * block_k < t_real) & (qi * block_q < t_real)

    @pl.when(block_live)
    def _compute():
        # Input-dtype dots + f32 accumulation throughout (see _fwd_kernel);
        # ds is cast back to the input dtype before its dot — the standard
        # flash-attention-2 bf16 backward. For f32 inputs every cast is a
        # no-op, keeping the tight-tolerance CPU tests exact.
        s = jax.lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        row = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        col = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where((col > row) | (col >= t_real), MASK, s)
        p = jnp.exp(s - lse_ref[0])                          # (bq, bk)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0],
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = (p * (dp - delta_ref[0]) * scale).astype(q_ref.dtype)
        dq_acc[:] += jax.lax.dot_general(
            ds, k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_kb - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, scale: float, t_real: int,
                block_q: int, block_k: int, num_qb: int):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    block_live = (qi * block_q + block_q - 1 >= ki * block_k) & (
        qi * block_q < t_real) & (ki * block_k < t_real)

    @pl.when(block_live)
    def _compute():
        # Input-dtype dots + f32 accumulation; pt/dst cast back to the input
        # dtype before their dots (see _dq_kernel).
        st = jax.lax.dot_general(k_ref[0], q_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * scale
        col = ki * block_k + jax.lax.broadcasted_iota(    # key index
            jnp.int32, (block_k, block_q), 0)
        row = qi * block_q + jax.lax.broadcasted_iota(    # query index
            jnp.int32, (block_k, block_q), 1)
        st = jnp.where((col > row) | (col >= t_real) | (row >= t_real),
                       MASK, st)
        pt = jnp.exp(st - jnp.transpose(lse_ref[0]))         # (bk, bq)
        dv_acc[:] += jax.lax.dot_general(
            pt.astype(do_ref.dtype), do_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dpt = jax.lax.dot_general(
            v_ref[0], do_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bk, bq)
        dst = (pt * (dpt - jnp.transpose(delta_ref[0])) * scale
               ).astype(q_ref.dtype)
        dk_acc[:] += jax.lax.dot_general(
            dst, q_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_qb - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, *, scale: float, t_real: int):
    """Single-block backward: when the whole (padded) sequence fits one
    block, compute dq/dk/dv in ONE kernel — s and p are built once and dp
    is shared, 5 MXU dots instead of the split kernels' 7, one launch
    instead of two. Grid is (bh,) only.

    Refs here are (t, d)/(t, 1): the leading batch*heads dim is a squeezed
    (None) block dim, so reads/writes are whole-block `[...]` with no ref
    indexing — `ref[0]` discharges to a vma-mismatched dynamic_slice under
    the shard_map interpreter."""
    q, k, v, do = q_ref[...], k_ref[...], v_ref[...], do_ref[...]
    t_pad = q.shape[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    row = jax.lax.broadcasted_iota(jnp.int32, (t_pad, t_pad), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (t_pad, t_pad), 1)
    live = (col <= row) & (col < t_real) & (row < t_real)
    s = jnp.where(live, s, MASK)
    p = jnp.exp(s - lse_ref[...])                            # (t, t) f32
    # dv[kt, d] = sum_qt p[qt, kt] * do[qt, d]
    dv_ref[...] = jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = (p * (dp - delta_ref[...]) * scale).astype(q.dtype)
    dq_ref[...] = jax.lax.dot_general(
        ds, k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)
    # dk[kt, d] = sum_qt ds[qt, kt] * q[qt, d]
    dk_ref[...] = jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dk_ref.dtype)


def _bwd_call(q, k, v, o, lse, do, *, t_real: int, block_q: int, block_k: int):
    bh, t_pad, d = q.shape
    num_qb = t_pad // block_q
    num_kb = t_pad // block_k
    scale = 1.0 / math.sqrt(d)

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
                    keepdims=True)                           # (bh, t_pad, 1)

    # Fused path gate: under the CPU interpreter inside shard_map (vma tags
    # present), the discharged kernel jaxpr fails shard_map's vma check on
    # plain elementwise ops (the split kernels pass only because their ops
    # sit inside pl.when/cond, which unifies vma). Compiled TPU execution
    # never discharges, so real hardware always takes the fused path; the
    # CPU grad tests outside shard_map still cover its math.
    interp_vma = _interpret() and getattr(jax.typeof(q), "vma", None)
    if num_qb == 1 and num_kb == 1 and not interp_vma:
        spec_td = pl.BlockSpec((None, t_pad, d), lambda b: (b, 0, 0))
        spec_t1 = pl.BlockSpec((None, t_pad, 1), lambda b: (b, 0, 0))
        return pl.pallas_call(
            functools.partial(_bwd_fused_kernel, scale=scale, t_real=t_real),
            grid=(bh,),
            in_specs=[spec_td, spec_td, spec_td, spec_td, spec_t1, spec_t1],
            out_specs=[spec_td, spec_td, spec_td],
            out_shape=[_out_struct((bh, t_pad, d), q.dtype, q),
                       _out_struct((bh, t_pad, d), k.dtype, q),
                       _out_struct((bh, t_pad, d), v.dtype, q)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel",)),
            interpret=_interpret(),
        )(q, k, v, do, lse, delta)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, t_real=t_real,
                          block_q=block_q, block_k=block_k, num_kb=num_kb),
        grid=(bh, num_qb, num_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=_out_struct((bh, t_pad, d), q.dtype, q),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, t_real=t_real,
                          block_q=block_q, block_k=block_k, num_qb=num_qb),
        grid=(bh, num_kb, num_qb),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            _out_struct((bh, t_pad, d), k.dtype, q),
            _out_struct((bh, t_pad, d), v.dtype, q),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------- public


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    bwd_block_q: int = None,
                    bwd_block_k: int = None) -> jax.Array:
    """Causal flash attention. q, k, v: (b, heads, t, head_dim).

    Drop-in replacement for `causal_attention_xla`
    (`/root/reference/models/model.py:73-77` semantics). Sequence length is
    padded to the block size internally; padded keys are masked, padded
    query rows are sliced off. `bwd_block_*` tune the dq/dkv kernels
    independently of the forward (default: the swept DEFAULT_BWD_* values).
    """
    b, h, t, d = q.shape
    if bwd_block_q is None:
        bwd_block_q = DEFAULT_BWD_BLOCK_Q
    if bwd_block_k is None:
        bwd_block_k = DEFAULT_BWD_BLOCK_K
    for name, blk in (("block_q", block_q), ("block_k", block_k),
                      ("bwd_block_q", bwd_block_q),
                      ("bwd_block_k", bwd_block_k)):
        if blk % 128 or blk & (blk - 1):
            raise ValueError(
                f"{name} must be a power-of-two multiple of 128, got {blk}")
    # Clamp blocks to the next power of two >= t so that max(bq, bk) is a
    # common multiple of both and t_pad divides evenly into full q AND k
    # blocks (a non-power-of-two clamp once left q rows >= block_q
    # unwritten). Padded blocks are skipped by the kernels' block_live
    # guards, so over-padding costs only grid overhead. All four block
    # sizes share one t_pad, so the bwd blocks participate in the clamp.
    pow2 = max(128, 1 << (t - 1).bit_length())
    bq = min(block_q, pow2)
    bk = min(block_k, pow2)
    bbq = min(bwd_block_q, pow2)
    bbk = min(bwd_block_k, pow2)
    t_pad = _round_up(t, max(bq, bk, bbq, bbk))

    def prep(x):
        x = x.reshape(b * h, t, d)
        if t_pad != t:
            x = jnp.pad(x, ((0, 0), (0, t_pad - t), (0, 0)))
        return x

    o = _flash_with_t(prep(q), prep(k), prep(v), t, bq, bk, bbq, bbk)
    return o[:, :t, :].reshape(b, h, t, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_with_t(q, k, v, t_real: int, block_q: int, block_k: int,
                  bwd_block_q: int, bwd_block_k: int):
    o, _ = _fwd_call(q, k, v, t_real=t_real, block_q=block_q, block_k=block_k)
    return o


def _flash_with_t_fwd(q, k, v, t_real, block_q, block_k,
                      bwd_block_q, bwd_block_k):
    o, lse = _fwd_call(q, k, v, t_real=t_real,
                       block_q=block_q, block_k=block_k)
    # Name the kernel outputs so remat policies can pin them: under
    # `Transformer(remat="dots")` the checkpoint_dots policy saves only
    # dot_general outputs, and without these tags the backward pass would
    # re-run the forward flash kernel just to rebuild o/lse.
    o = checkpoint_name(o, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, o, lse)


def _flash_with_t_bwd(t_real, block_q, block_k, bwd_block_q, bwd_block_k,
                      res, do):
    q, k, v, o, lse = res
    return _bwd_call(q, k, v, o, lse, do, t_real=t_real,
                     block_q=bwd_block_q, block_k=bwd_block_k)


_flash_with_t.defvjp(_flash_with_t_fwd, _flash_with_t_bwd)
